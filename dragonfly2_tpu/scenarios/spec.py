"""Declarative scenario specs.

A scenario is a plain dataclass tree — serializable, diffable, loadable
from TOML or JSON — that fully determines (together with an integer seed)
the heterogeneity and faults injected into a run. The spec carries NO
randomness itself; all sampling lives in ``engine.ScenarioEngine`` so the
same spec document can drive the pure simulator, the A/B harness, and the
multiprocess e2e loop identically.

Knob ↔ reference semantics (see PARITY.md "Scenario lab"):

- ``LinkSpec`` RTT tiers mirror the networktopology probe structure the
  reference snapshots (same-IDC / same-region / cross-region RTT bands,
  scheduler/networktopology) — the scenario's link model is what the
  probe loop *measures*;
- ``FlakySpec`` models parents whose piece serving errors or stalls —
  exercised through the child's real retry path
  (DownloadPieceFailedRequest → reschedule → blocklist), not simulated
  around it;
- ``ChurnSpec`` models peers leaving/crashing mid-download and hosts
  dropping off the announce plane (LeaveHost) and returning;
- ``SkewSpec`` models hotspot task popularity (Zipf), the regime where a
  few blobs are downloaded cluster-wide and swarms get deep.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any


@dataclasses.dataclass
class LinkSpec:
    """Per-link RTT/bandwidth model.

    RTT tiers (ms) follow the synthetic IDC structure records/synth.py
    plants; bandwidth is per-HOST NIC capacity (bytes/s) with a bimodal
    fast/slow split, an optional oversubscribed spine penalty applied to
    cross-rack transfers, and an optional handful of pathologically slow
    NICs (the tail the rule blend cannot see until piece costs pile up).
    """

    same_rack_rtt_ms: float = 0.2
    same_idc_rtt_ms: float = 0.5
    same_region_rtt_ms: float = 5.0
    cross_region_rtt_ms: float = 60.0
    rtt_jitter_sigma: float = 0.3

    base_bandwidth_bps: float = 100e6  # bytes/s of a healthy NIC
    bandwidth_jitter_sigma: float = 0.25
    slow_fraction: float = 0.0         # fraction of hosts in the slow mode
    slow_multiplier: float = 1.0       # slow-mode bandwidth = base * this
    spine_oversubscription: float = 1.0  # cross-rack bandwidth divisor
    slow_nic_count: int = 0            # hosts with a pathological NIC
    slow_nic_multiplier: float = 0.05


@dataclasses.dataclass
class ChurnSpec:
    peer_crash_rate: float = 0.0   # P(a child crashes mid-download)
    crash_progress: float = 0.5    # crash lands after this piece fraction
    host_leave_rate: float = 0.0   # P(host offline in a given epoch)
    leave_epoch_rounds: int = 20   # offline membership re-rolls every N rounds


@dataclasses.dataclass
class FlakySpec:
    parent_fraction: float = 0.0   # fraction of hosts that serve flakily
    piece_error_rate: float = 0.0  # P(piece from a flaky parent errors)
    piece_stall_rate: float = 0.0  # P(piece from a flaky parent stalls)
    stall_seconds: float = 1.0     # injected stall duration
    # Deterministic CONTENT corruption (the trust-boundary adversary): a
    # corrupting parent serves bytes that differ from the origin's, with
    # its advisory digest header rewritten to match — only verification
    # against the scheduler-attested chain catches it. Modes: "bitflip"
    # (one deterministic bit flipped) or "truncate" (deterministic tail
    # dropped).
    piece_corrupt_rate: float = 0.0  # P(piece from a flaky parent corrupts)
    corrupt_mode: str = "bitflip"    # bitflip | truncate


@dataclasses.dataclass
class SkewSpec:
    zipf_alpha: float = 0.0        # 0 = uniform task popularity


@dataclasses.dataclass
class ControlPlaneSpec:
    """Control-plane fault events (the failure-domain resilience layer's
    adversary): scheduler crashes that sever every announce stream at
    once, and host↔scheduler partitions that silently blackhole the
    announce plane (no FIN — requests vanish). Like every other spec
    knob, the EVENTS are sampled deterministically by the engine from
    (spec, seed, event identity); these fields only set the rates."""

    scheduler_crash_rate: float = 0.0   # P(the scheduler crashes in an epoch)
    crash_epoch_rounds: int = 25        # crash opportunity every N rounds
    crash_progress: float = 0.5         # e2e: kill after this piece fraction
    partition_rate: float = 0.0         # P(a host is partitioned in an epoch)
    partition_epoch_rounds: int = 20    # partition membership re-rolls every N


@dataclasses.dataclass
class WanSpec:
    """Multi-region WAN hierarchy (megascale scenario lab). `regions=0`
    disables the hierarchy — the base single-region link model applies.
    With regions, hosts partition into contiguous region blocks, each
    region gets its own seed peers, intra-region paths keep the
    ``LinkSpec`` RTT tiers, and CROSS-region paths pay the WAN tier:
    `wan_rtt_ms` latency and a bandwidth cap of `wan_bandwidth_bps`
    (modeling the analytic link-tier characterization of arXiv
    2103.10515 — parameterized tiers, not packet simulation). A
    back-to-source escalation outside `origin_region` pays
    `back_to_source_penalty_ms` on top of the origin transfer."""

    regions: int = 0
    seeds_per_region: int = 2
    zones_per_region: int = 4
    racks_per_zone: int = 16
    wan_rtt_ms: float = 80.0
    wan_jitter_sigma: float = 0.3
    wan_bandwidth_bps: float = 25e6
    origin_region: int = 0
    back_to_source_penalty_ms: float = 250.0


@dataclasses.dataclass
class TrafficSpec:
    """Diurnal Zipf traffic arrival (time-varying task popularity).
    `day_rounds=0` disables — arrivals stay flat. Otherwise the per-round
    arrival count scales sinusoidally between `trough_multiplier` and
    `peak_multiplier` over a `day_rounds`-round compressed day, task
    popularity is Zipf(`zipf_alpha`) over rotated ranks, and the hot
    ranks rotate `rotate_hot_tasks` times per day (the "what is popular
    changes through the day" regime a static Zipf cannot express)."""

    day_rounds: int = 0
    peak_multiplier: float = 3.0
    trough_multiplier: float = 0.3
    zipf_alpha: float = 1.1
    rotate_hot_tasks: int = 0


@dataclasses.dataclass
class FlashCrowdSpec:
    """Flash-crowd preheat storms: `events_per_day` bursts at
    deterministic (seed, day, event) start rounds; during a burst,
    `arrival_multiplier` x the base arrival rate slams onto `hot_tasks`
    deterministically chosen task ranks for `duration_rounds` rounds —
    the release-day preheat stampede."""

    events_per_day: int = 0
    arrival_multiplier: float = 8.0
    duration_rounds: int = 6
    hot_tasks: int = 1


@dataclasses.dataclass
class UpgradeSpec:
    """Rolling-upgrade churn waves: `waves_per_day` sweeps per compressed
    day; each sweep moves a restart window of `cohort_fraction` of the
    fleet across the host order (region blocks first — the region-by-
    region rollout shape) over `wave_rounds` rounds. Hosts in the window
    are off the announce plane (LeaveHost) and re-announce when the
    window passes them."""

    waves_per_day: int = 0
    wave_rounds: int = 30
    cohort_fraction: float = 0.05


@dataclasses.dataclass
class ScenarioSpec:
    name: str = "homogeneous"
    description: str = ""
    link: LinkSpec = dataclasses.field(default_factory=LinkSpec)
    churn: ChurnSpec = dataclasses.field(default_factory=ChurnSpec)
    flaky: FlakySpec = dataclasses.field(default_factory=FlakySpec)
    skew: SkewSpec = dataclasses.field(default_factory=SkewSpec)
    control: ControlPlaneSpec = dataclasses.field(default_factory=ControlPlaneSpec)
    # megascale scenario lab (dragonfly2_tpu/megascale): multi-region WAN
    # topology, diurnal arrival, flash crowds, rolling upgrades — all
    # default-disabled so every pre-existing builtin is bit-unchanged
    wan: WanSpec = dataclasses.field(default_factory=WanSpec)
    traffic: TrafficSpec = dataclasses.field(default_factory=TrafficSpec)
    flash: FlashCrowdSpec = dataclasses.field(default_factory=FlashCrowdSpec)
    upgrade: UpgradeSpec = dataclasses.field(default_factory=UpgradeSpec)

    # ------------------------------------------------------------- codecs

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ScenarioSpec":
        spec = cls()
        for key, value in (data or {}).items():
            if not hasattr(spec, key):
                raise ValueError(f"unknown scenario field {key!r}")
            current = getattr(spec, key)
            if dataclasses.is_dataclass(current) and isinstance(value, dict):
                for k, v in value.items():
                    if not hasattr(current, k):
                        raise ValueError(f"unknown scenario field {key}.{k}")
                    setattr(current, k, type(getattr(current, k))(v))
            else:
                setattr(spec, key, value)
        return spec

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def to_toml(self) -> str:
        """Serialize to the flat ``[section] key = value`` TOML subset
        both parsers (stdlib ``tomllib`` and the <3.11 fallback) accept —
        the round-trip the parser-agreement test pins."""
        # top-level scalars first (TOML: root keys precede any [section]),
        # then one flat section per nested spec dataclass
        scalars: list[str] = []
        sections: list[str] = []
        for field in dataclasses.fields(self):
            value = getattr(self, field.name)
            if dataclasses.is_dataclass(value):
                sections.append(f"[{field.name}]")
                for sub in dataclasses.fields(value):
                    sections.append(
                        f"{sub.name} = {_toml_value(getattr(value, sub.name))}"
                    )
                sections.append("")
            else:
                scalars.append(f"{field.name} = {_toml_value(value)}")
        return "\n".join(scalars + [""] + sections)


def _toml_value(value: Any) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, str):
        return json.dumps(value)  # TOML basic strings == JSON strings here
    if isinstance(value, float) and value == int(value) and abs(value) < 1e15:
        # tomllib keeps 1.0 a float; emit a form both parsers read as float
        return f"{value:.1f}"
    return repr(value)


def load_scenario(path: str | pathlib.Path) -> ScenarioSpec:
    """Load a spec from a ``.toml`` or ``.json`` file. TOML parsing uses
    stdlib ``tomllib`` (py3.11+) directly; on older interpreters the
    hand-rolled flat-section fallback below covers the spec grammar (the
    parser-agreement test pins that both read every builtin scenario
    identically)."""
    path = pathlib.Path(path)
    text = path.read_text()
    if path.suffix == ".toml":
        return ScenarioSpec.from_dict(_parse_toml(text))
    return ScenarioSpec.from_dict(json.loads(text))


def _parse_toml(text: str) -> dict[str, Any]:
    try:
        import tomllib  # py311+: the real parser
    except ImportError:
        return _parse_toml_fallback(text)
    return tomllib.loads(text)


def _parse_toml_fallback(text: str) -> dict[str, Any]:
    """Minimal ``[section] key = value`` parser for interpreters without
    ``tomllib`` (<3.11) — only the flat spec grammar, not general TOML."""
    root: dict[str, Any] = {}
    section = root
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            section = root.setdefault(line[1:-1].strip(), {})
            continue
        key, _, value = line.partition("=")
        section[key.strip()] = _coerce(value.strip())
    return root


def _coerce(value: str) -> Any:
    if value.startswith('"') and value.endswith('"'):
        try:
            # TOML basic strings share JSON's escape grammar — decoding
            # through json keeps the fallback byte-identical to tomllib
            # on escaped/non-ASCII content
            return json.loads(value)
        except json.JSONDecodeError:
            return value[1:-1]
    if value.startswith("'") and value.endswith("'"):
        return value[1:-1]  # TOML literal string: no escapes
    if value.lower() in ("true", "false"):
        return value.lower() == "true"
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            continue
    return value


# --------------------------------------------------------------- builtins


def builtin_scenarios() -> dict[str, ScenarioSpec]:
    """The scenario grid BENCH_scenarios.json covers: a homogeneous
    control plus the structured adversarial conditions the learned
    evaluator exists for. Severity is deliberately strong — the point is
    exploitable structure, not realism tuning."""
    return {
        "homogeneous": ScenarioSpec(
            name="homogeneous",
            description="control: uniform NICs, no faults, uniform popularity",
        ),
        "bandwidth_skew": ScenarioSpec(
            name="bandwidth_skew",
            description=(
                "bimodal rack NICs (40% at 15% speed), 4x oversubscribed "
                "spine on cross-rack paths, plus 2 pathological slow NICs"
            ),
            link=LinkSpec(
                slow_fraction=0.4,
                slow_multiplier=0.15,
                spine_oversubscription=4.0,
                slow_nic_count=2,
                slow_nic_multiplier=0.02,
            ),
        ),
        "churn": ScenarioSpec(
            name="churn",
            description=(
                "15% of children crash mid-download; 10% of hosts flap "
                "off the announce plane each epoch"
            ),
            churn=ChurnSpec(
                peer_crash_rate=0.15,
                crash_progress=0.5,
                host_leave_rate=0.10,
                leave_epoch_rounds=15,
            ),
        ),
        "flaky_parent": ScenarioSpec(
            name="flaky_parent",
            description=(
                "30% of hosts serve flakily: 25% piece error rate, 10% "
                "stall rate — exercised through the real retry path"
            ),
            flaky=FlakySpec(
                parent_fraction=0.30,
                piece_error_rate=0.25,
                piece_stall_rate=0.10,
                stall_seconds=0.5,
            ),
        ),
        "corruption": ScenarioSpec(
            name="corruption",
            description=(
                "20% of hosts serve CORRUPT bytes on 30% of pieces "
                "(deterministic bit flips under a self-consistent digest "
                "header) plus a little plain flakiness — children verify "
                "against scheduler-attested digests, report "
                "reason=corruption, and the scheduler quarantines the "
                "corrupting parents (time-decayed release)"
            ),
            flaky=FlakySpec(
                parent_fraction=0.20,
                piece_error_rate=0.05,
                piece_corrupt_rate=0.30,
                corrupt_mode="bitflip",
            ),
        ),
        "hotspot": ScenarioSpec(
            name="hotspot",
            description="Zipf(1.2) task popularity: a few blobs go cluster-wide",
            skew=SkewSpec(zipf_alpha=1.2),
        ),
        "chaos": ScenarioSpec(
            name="chaos",
            description=(
                "control-plane chaos: scheduler crashes sever every "
                "announce stream (in-flight peers re-announce their kept "
                "pieces and the scheduler adopts them), 10% of hosts "
                "silently partitioned per epoch, plus peer churn and "
                "enough flaky serving that downloads span rounds — the "
                "failure-domain resilience gauntlet"
            ),
            churn=ChurnSpec(peer_crash_rate=0.05, crash_progress=0.5),
            # flaky parents keep downloads in flight across rounds, so
            # crashes and partitions catch real partial progress instead
            # of an empty pending queue
            flaky=FlakySpec(
                parent_fraction=0.25, piece_error_rate=0.15,
                piece_stall_rate=0.05, stall_seconds=0.2,
            ),
            control=ControlPlaneSpec(
                scheduler_crash_rate=0.6,
                crash_epoch_rounds=20,
                partition_rate=0.10,
                partition_epoch_rounds=15,
            ),
        ),
    }


def megascale_scenarios() -> dict[str, ScenarioSpec]:
    """Megascale scenario-lab builtins (dragonfly2_tpu/megascale): specs
    whose WAN/traffic extensions only the event-batch engine can drive at
    fidelity. Kept out of ``builtin_scenarios`` so the BENCH_scenarios
    A/B grid (which replays every builtin through the per-peer oracle)
    is unchanged.

    - ``planet``: the scale proof — multi-region WAN, diurnal Zipf
      arrivals, flash-crowd preheat storms; NO per-piece fault families,
      so a 10^5-host run measures the engine and scheduler, not blake2b;
    - ``soak``: the compressed "24 h in production" trace — every fault
      family at once (control-plane chaos + partitions, corruption,
      churn + rolling upgrades, flash crowds) on the WAN topology;
    - ``fleet``: the sharded-control-plane soak — the chaos families
      that exercise a SchedulerFleet's ring (scheduler crashes,
      partitions, rolling-upgrade restarts) plus the flaky/churn
      families that keep downloads in flight across rounds, WITHOUT
      the corruption family, so a 10^6-host K-replica run measures
      handoff/rebalance behavior rather than blake2b.
    """
    day = 96  # compressed day: 96 rounds = one "15-minute" tick per round
    wan = WanSpec(
        regions=4, seeds_per_region=3, wan_rtt_ms=85.0,
        wan_bandwidth_bps=20e6, back_to_source_penalty_ms=250.0,
    )
    traffic = TrafficSpec(
        day_rounds=day, peak_multiplier=3.0, trough_multiplier=0.25,
        # moderate skew: the top task draws ~10% of arrivals — deep
        # swarms without every hot task slamming its peer-DAG cap (the
        # capacity-bounded swarm spill to origin is exercised by the
        # flash crowds, not the steady state)
        zipf_alpha=0.9, rotate_hot_tasks=4,
    )
    flash = FlashCrowdSpec(
        events_per_day=3, arrival_multiplier=5.0, duration_rounds=4,
        hot_tasks=4,
    )
    return {
        "planet": ScenarioSpec(
            name="planet",
            description=(
                "planet-scale day: 4 WAN regions with in-region seeds, "
                "diurnal Zipf arrivals rotating hot content, flash-crowd "
                "preheat storms — no injected faults, pure scale"
            ),
            link=LinkSpec(slow_fraction=0.3, slow_multiplier=0.25),
            wan=wan, traffic=traffic, flash=flash,
        ),
        "soak": ScenarioSpec(
            name="soak",
            description=(
                "24h-in-production soak: every fault family at once — "
                "scheduler crashes + silent partitions (chaos), corrupt "
                "parents (integrity), peer churn + rolling-upgrade waves, "
                "flash crowds — over the 4-region WAN topology"
            ),
            link=LinkSpec(
                slow_fraction=0.3, slow_multiplier=0.25,
                spine_oversubscription=2.0,
            ),
            churn=ChurnSpec(
                peer_crash_rate=0.06, crash_progress=0.5,
                host_leave_rate=0.04, leave_epoch_rounds=16,
            ),
            flaky=FlakySpec(
                parent_fraction=0.18, piece_error_rate=0.10,
                piece_stall_rate=0.05, stall_seconds=0.2,
                piece_corrupt_rate=0.10, corrupt_mode="bitflip",
            ),
            skew=SkewSpec(zipf_alpha=1.1),
            control=ControlPlaneSpec(
                scheduler_crash_rate=0.7, crash_epoch_rounds=16,
                partition_rate=0.08, partition_epoch_rounds=12,
            ),
            wan=wan, traffic=traffic, flash=flash,
            upgrade=UpgradeSpec(
                waves_per_day=1, wave_rounds=24, cohort_fraction=0.04
            ),
        ),
        "fleet": ScenarioSpec(
            name="fleet",
            description=(
                "sharded control-plane day: scheduler crashes, silent "
                "partitions and rolling-upgrade waves against K hashring "
                "replicas over the 4-region WAN; flaky parents + churn "
                "keep downloads in flight across rounds (so a replica "
                "kill catches real in-flight peers to hand off) but NO "
                "corruption family — 10^6-host fleet runs measure the "
                "ring, not blake2b"
            ),
            link=LinkSpec(
                slow_fraction=0.3, slow_multiplier=0.25,
                spine_oversubscription=2.0,
            ),
            churn=ChurnSpec(
                peer_crash_rate=0.06, crash_progress=0.5,
                host_leave_rate=0.04, leave_epoch_rounds=16,
            ),
            flaky=FlakySpec(
                parent_fraction=0.18, piece_error_rate=0.10,
                piece_stall_rate=0.05, stall_seconds=0.2,
            ),
            # milder popularity skew than the soak's (static fallback
            # when the diurnal traffic model is off): task sharding puts
            # each hot swarm wholly on ONE replica, so a zipf>=1 day is
            # a single-swarm hot-spot benchmark, not a control-plane one
            skew=SkewSpec(zipf_alpha=0.8),
            control=ControlPlaneSpec(
                scheduler_crash_rate=0.7, crash_epoch_rounds=16,
                partition_rate=0.08, partition_epoch_rounds=12,
            ),
            wan=wan,
            # the scaling cell measures the RING, so the day is a broad
            # catalog: alpha 0.5 with 12 hot-set rotations keeps
            # popularity skewed while the busiest replica's cut of the
            # day stays near 1/K, and flash storms burst over 16 tasks
            # instead of slamming one shard's band — the hottest swarm
            # also stays inside the per-task peer cap rather than
            # spilling its overflow to origin
            traffic=TrafficSpec(
                day_rounds=day, peak_multiplier=3.0,
                trough_multiplier=0.25,
                zipf_alpha=0.5, rotate_hot_tasks=12,
            ),
            flash=FlashCrowdSpec(
                events_per_day=3, arrival_multiplier=2.0,
                duration_rounds=4, hot_tasks=16,
            ),
            upgrade=UpgradeSpec(
                waves_per_day=1, wave_rounds=24, cohort_fraction=0.04
            ),
        ),
        "procday": ScenarioSpec(
            name="procday",
            description=(
                "process-planet day: the compressed day the REAL "
                "multi-process deployment (procworld) drives end to end "
                "— 12 two-hour rounds over a 3-region WAN, a certain "
                "scheduler kill every 5th round, one rolling-restart "
                "wave covering a third of the fleet, flaky parents "
                "keeping downloads in flight across kills; NO "
                "corruption family (byte identity is asserted against "
                "the attested chain, not injected against it). The "
                "SAME spec runs through run_megascale for the "
                "sim-vs-real divergence report, so every knob here is "
                "sized for a 3-daemon planet: short stalls, certain "
                "kills, coarse rounds"
            ),
            link=LinkSpec(slow_fraction=0.2, slow_multiplier=0.5),
            flaky=FlakySpec(
                # real sockets pay these stalls in wall time — keep
                # them short but present, so kill windows land on
                # genuinely in-flight transfers
                parent_fraction=0.25, piece_error_rate=0.05,
                piece_stall_rate=0.10, stall_seconds=0.05,
            ),
            control=ControlPlaneSpec(
                # crash_rate=1.0: the kill schedule is CERTAIN, so the
                # page-at-the-kill assertion is deterministic in the
                # spec alone — kills at rounds 5 and 10 of a 12-round
                # day, for sim and planet alike
                scheduler_crash_rate=1.0, crash_epoch_rounds=5,
                partition_rate=0.25, partition_epoch_rounds=6,
            ),
            wan=WanSpec(
                regions=3, seeds_per_region=1, wan_rtt_ms=85.0,
                wan_bandwidth_bps=20e6, back_to_source_penalty_ms=250.0,
            ),
            traffic=TrafficSpec(
                # 12 rounds x 120 sim-minutes: coarse enough that a real
                # round (seconds of wall time) stands in for a tick, and
                # the SLO burn windows clamp to single-round width — a
                # kill-round backlog pages AT the kill, not smeared
                day_rounds=12, peak_multiplier=2.0,
                trough_multiplier=0.5, zipf_alpha=0.8,
                rotate_hot_tasks=2,
            ),
            upgrade=UpgradeSpec(
                waves_per_day=1, wave_rounds=4, cohort_fraction=0.34
            ),
        ),
    }
