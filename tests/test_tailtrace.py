"""Tail-attribution plane (telemetry/tailtrace.py + tools/dftail.py).

Pins the PR-16 tentpole end to end: the deterministic sampler against
its vectorized twin, paired-stream digest equality, the chaos-soak
decomposition invariants (phase sums ≈ measured TTC, scheduler kills
attributed to failover, schedule_wait baseline), the bounded exemplar
memory, the client-plane trace continuity fixes (back-to-source and
re-announce spans riding the triggering envelope), the daemon's
fold-in of dead attempts, dfslo cause enrichment, and the offline
dftail verdicts (0 consistent / 1 tolerance / 2 drift)."""

import asyncio
import json
import time

import numpy as np
import pytest

from dragonfly2_tpu.client import conductor as conductor_mod
from dragonfly2_tpu.client import daemon as daemon_mod
from dragonfly2_tpu.client.conductor import PeerTaskConductor
from dragonfly2_tpu.client.daemon import Daemon
from dragonfly2_tpu.client.storage import StorageManager, TaskMetadata
from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.megascale import topology
from dragonfly2_tpu.megascale.soak import run_megascale
from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.telemetry import tailtrace
from dragonfly2_tpu.telemetry.slo import (
    SLOEngine,
    SLOSpec,
    feed_megascale_sample,
    megascale_slo_specs,
)
from dragonfly2_tpu.telemetry.tailtrace import (
    DEFAULT_TOLERANCE,
    N_PHASES,
    PHASES,
    PH_BACK_TO_SOURCE,
    PH_FAILOVER,
    PH_PARENT_FETCH,
    PH_REGISTER,
    PH_SCHEDULE_WAIT,
    PH_VERIFY,
    TailTrace,
    hash_u01_scalar,
)
from dragonfly2_tpu.telemetry.tracing import Tracer
from dragonfly2_tpu.utils import dferrors
from tools import dftail


def _tracer(regions=("r0",), **kw):
    kw.setdefault("registry", m.Registry())
    return TailTrace(regions, **kw)


def _vec(**ms):
    v = [0.0] * N_PHASES
    for name, val in ms.items():
        v[PHASES.index(name)] = val * 1e6
    return v


# ------------------------------------------------- deterministic sampler


def test_hash_u01_scalar_matches_vectorized_twin():
    """The scalar splitmix64 sampler is bit-identical to the megascale
    topology's vectorized hash — the exemplar keep/drop decision is the
    same pure function on both planes."""
    for seed in (0, 7, 2**31):
        for key in (0, 1, 63, 10_000, 2**40):
            want = float(
                topology.hash_u01(seed, "tail_exemplar", np.array([key]))[0]
            )
            assert hash_u01_scalar(seed, "tail_exemplar", key) == want
    # distinct kinds decorrelate
    a = hash_u01_scalar(7, "tail_exemplar", 5)
    b = hash_u01_scalar(7, "other_kind", 5)
    assert a != b


def test_paired_stream_digests_identical():
    t1 = _tracer(("r0", "r1"), seed=7)
    t2 = _tracer(("r0", "r1"), seed=7)
    for t in (t1, t2):
        for i in range(500):
            t.observe(
                i % 2,
                t.next_seq(),
                (1 + i % 37) * 1e6,
                _vec(parent_fetch=1 + i % 37),
                round_idx=i % 9,
            )
    assert t1.deterministic_digest() == t2.deterministic_digest()
    assert t1.report() == t2.report()
    # one observation off by one ns is visible in the digest
    t2.observe(0, t2.next_seq(), 1e6 + 1, _vec(parent_fetch=1.0))
    t1.observe(0, t1.next_seq(), 1e6, _vec(parent_fetch=1.0))
    assert t1.deterministic_digest() != t2.deterministic_digest()


# ------------------------------------------------- chaos-soak invariants


@pytest.fixture(scope="module")
def soak_report():
    """One tier-1-scale chaos soak (scheduler kills at rounds 16/32/48/80;
    kills 16 and 48 land on loaded rounds at 1500 hosts)."""
    return run_megascale(
        "soak",
        num_hosts=1500,
        num_tasks=32,
        seed=7,
        arrivals_per_round=24,
        retire_after_rounds=24,
    )


def test_soak_decomposition_sums_to_measured_ttc(soak_report):
    tail = soak_report["tail"]
    assert tail["completions"] > 0
    assert tail["phases"] == list(PHASES)
    for name, reg in tail["regions"].items():
        if not reg["completed"]:
            continue
        ratio = reg["decomp_ratio"]
        assert ratio is not None, name
        assert abs(ratio - 1.0) <= DEFAULT_TOLERANCE, (name, ratio)
    # chaos run exercised the expensive phases: scheduler kills produce
    # failover time, origin fallback produces back_to_source time
    shares = [r["phase_share"] for r in tail["regions"].values()]
    assert any(s.get("failover", 0.0) > 0.0 for s in shares)
    assert any(s.get("back_to_source", 0.0) > 0.0 for s in shares)


def test_soak_kill_windows_attributed_to_failover(soak_report):
    tail = soak_report["tail"]
    by_round = {w["round"]: w for w in tail["windows"]}
    assert sorted(by_round) == [16, 32, 48, 80]
    # the two kills that land on loaded rounds at this scale dominate by
    # MASS and by the window's slowest download; the 100k artifact pins
    # all four (trough kills need planetary arrival volume to dominate)
    for k in (16, 48):
        w = by_round[k]
        assert w["dominant_phase"] == "failover", w
        assert w["tail_dominant_phase"] == "failover", w
        assert w["slowest_ttc_ms"] > 0.0
    for w in by_round.values():
        assert w["until"] - w["round"] <= TailTrace.DEFAULT_WINDOW_ROUNDS - 1
    assert by_round[16]["until"] == 16 + TailTrace.DEFAULT_WINDOW_ROUNDS - 1
    # outside kill windows the fleet waits on the scheduler queue
    assert tail["baseline_dominant_phase"] == "schedule_wait"
    assert len(tail["digest"]) == 32
    # the offline matrices ride the report for dftail replay
    assert all(len(row) == N_PHASES for row in tail["round_phase_ms"])
    assert all(len(row) == N_PHASES + 1 for row in tail["round_slow_ms"])


def test_soak_timeline_carries_tail_hint(soak_report):
    samples = soak_report["timeline"]
    assert samples and all("tail_dominant_phase" in s for s in samples)
    phases = {s["tail_dominant_phase"] for s in samples}
    assert "failover" in phases  # the kill intervals name their burn


# ------------------------------------------------- bounded exemplar memory


def test_exemplar_memory_bound_10k_to_100k():
    """Ten times the observations, zero extra exemplar bytes: the ring
    is fixed-capacity, slowest-K replaces in place, and the per-round
    matrices grow with ROUNDS only (round_idx pinned here)."""
    t = _tracer(seed=3, slowest_k=4, sample_rate=1 / 64, exemplar_capacity=64)
    bounded = (
        "_ring_seq", "_ring_region", "_ring_round", "_ring_ttc",
        "_ring_phase", "_slow_ttc", "_slow_seq", "_slow_round",
        "_slow_phase", "_round_phase_ns", "_round_slow_ttc",
        "_round_slow_phase",
    )

    def feed(upto):
        while t._seq < upto:
            s = t.next_seq()
            t.observe(0, s, (1 + s % 101) * 1e6, _vec(parent_fetch=1 + s % 101))

    feed(10_000)
    sizes = {a: getattr(t, a).nbytes for a in bounded}
    feed(100_000)
    assert {a: getattr(t, a).nbytes for a in bounded} == sizes
    samp = t.report()["sampling"]
    assert samp["uniform_kept"] <= 64
    # counter-hashed keep decisions at rate 1/64 over 100k observations
    assert 1_000 < samp["uniform_sampled"] < 2_500
    rows = t.exemplar_rows()
    assert len(rows) <= 64 + 4
    kinds = {r["kind"] for r in rows}
    assert kinds == {"uniform", "slowest"}


# ------------------------------------------------- client-plane continuity


class _Conn:
    def __init__(self):
        self.sent = []

    async def send(self, message):
        self.sent.append(message)


class _DeadOrigin:
    def download_source(self, ts, url, headers, on_piece):
        raise dferrors.DFError("origin down")


def test_back_to_source_span_continues_scheduler_trace(tmp_path, monkeypatch):
    """The origin-fallback span rides the triggering response's wire
    envelope (NeedBackToSource/ScheduleFailure) instead of starting an
    orphan trace, and its wall time books into PH_BACK_TO_SOURCE."""
    tracer = Tracer()
    spans = tracer.export_to_memory()
    monkeypatch.setattr(conductor_mod, "default_tracer", lambda: tracer)
    storage = StorageManager(tmp_path)
    c = PeerTaskConductor(
        _Conn(), storage, msg.HostInfo(host_id="h1"),
        peer_id="p1", task_id="t1", url="http://origin/x",
    )
    c.piece_manager = _DeadOrigin()
    ts = storage.register_task(
        TaskMetadata(task_id="t1", peer_id="p1", url="http://origin/x",
                     piece_length=4 << 20)
    )
    ctx = {"trace_id": "ab" * 16, "span_id": "cd" * 8}
    asyncio.run(c._back_to_source(ts, trace_context=ctx))
    b2s = [s for s in spans if s.name == "dfdaemon.back_to_source"]
    assert len(b2s) == 1
    assert b2s[0].trace_id == ctx["trace_id"]
    assert b2s[0].parent_id == ctx["span_id"]
    assert c.phase_ns[PH_BACK_TO_SOURCE] > 0.0


def test_reannounce_span_rides_trigger_envelope(tmp_path, monkeypatch):
    """After a hashring failover the seed's re-announce continues the
    TRIGGERING scheduler's trace — the hop a tail read follows — and
    re-registers every finished piece under a fresh peer id."""
    tracer = Tracer()
    spans = tracer.export_to_memory()
    monkeypatch.setattr(daemon_mod, "default_tracer", lambda: tracer)
    storage = StorageManager(tmp_path)
    ts = storage.register_task(
        TaskMetadata(task_id="t9", peer_id="old-peer", url="http://origin/y",
                     piece_length=4 << 20)
    )
    ts.write_piece(0, 0, b"x" * 16)

    class _Seed:
        def __init__(self):
            from dragonfly2_tpu.telemetry.series import daemon_series
            self.metrics = daemon_series(m.Registry())

        def host_info(self):
            return msg.HostInfo(host_id="seed-host")

    class _Trigger:
        url = "http://origin/y"
        tag = ""
        application = ""
        trace_context = {"trace_id": "11" * 16, "span_id": "22" * 8}

    conn = _Conn()
    asyncio.run(Daemon._announce_completed(_Seed(), conn, ts, _Trigger()))
    re = [s for s in spans if s.name == "dfdaemon.reannounce"]
    assert len(re) == 1
    assert re[0].trace_id == _Trigger.trace_context["trace_id"]
    assert re[0].parent_id == _Trigger.trace_context["span_id"]
    assert len(conn.sent) == 1
    reg = conn.sent[0]
    assert isinstance(reg, msg.RegisterPeerRequest)
    assert reg.finished_pieces == [0]
    assert reg.priority == 1
    assert reg.peer_id == ts.meta.peer_id != "old-peer"


def test_daemon_observe_tail_folds_failover_and_residual(monkeypatch):
    """Dead attempts + measured recovery phases book as failover; the
    unmeasured glue becomes schedule_wait so the vector still sums to
    the measured TTC (decomp_ratio 1.0)."""
    fresh = _tracer(("local",), seed=0)
    monkeypatch.setattr(tailtrace, "_DEFAULT", fresh)

    class _Cond:
        phase_ns = _vec(register=1.0, parent_fetch=5.0, verify=0.5)

    task_t0 = time.perf_counter_ns() - int(20e6)  # measured TTC ~20ms
    Daemon._observe_tail(
        object.__new__(Daemon), _Cond(), task_t0,
        failed_attempt_ns=2e6, recovery_phases={"backoff": 1.0, "redial": 0.5},
    )
    rep = fresh.report()["regions"]["local"]
    assert rep["completed"] == 1
    assert rep["decomp_ratio"] == 1.0
    share = rep["phase_share"]
    # 2ms dead attempt + 1.5ms recovery == 3.5ms failover of ~20ms
    assert share["failover"] == pytest.approx(3.5 / 20.0, rel=0.2)
    assert share["schedule_wait"] > 0.0  # the residual landed somewhere


def test_daemon_observe_tail_scales_overlapping_workers(monkeypatch):
    """Concurrent piece workers book overlapping fetch walls, so the
    raw phase mass can EXCEED the elapsed TTC; the fold-in scales the
    vector onto the wall clock (ratio stays 1.0, relative weights
    preserved)."""
    fresh = _tracer(("local",), seed=0)
    monkeypatch.setattr(tailtrace, "_DEFAULT", fresh)

    class _Cond:
        # 4 workers × 30ms overlapping fetches inside a ~40ms download
        phase_ns = _vec(parent_fetch=120.0, verify=2.0)

    task_t0 = time.perf_counter_ns() - int(40e6)
    Daemon._observe_tail(
        object.__new__(Daemon), _Cond(), task_t0,
        failed_attempt_ns=0.0, recovery_phases={},
    )
    rep = fresh.report()["regions"]["local"]
    assert rep["decomp_ratio"] == 1.0
    share = rep["phase_share"]
    assert share["parent_fetch"] == pytest.approx(120.0 / 122.0, rel=1e-3)
    assert share["verify"] == pytest.approx(2.0 / 122.0, rel=1e-3)


# ------------------------------------------------- dfslo cause enrichment


def test_ttc_page_cause_names_dominant_phase():
    eng = SLOEngine(
        [SLOSpec("ttc_local", sli="s", objective=0.999)],
        minutes_per_unit=15.0, registry=m.Registry(),
    )
    for t in range(1, 9):
        eng.observe("s", good=100)
        eng.step(t)
    eng.set_tail_hint("failover")
    eng.observe("s", good=10, bad=90)
    eng.step(9)
    v = eng.verdict()
    assert v["state"] == "critical"
    ttc_causes = [c for c in v["causes"] if c["slo"] == "ttc_local"]
    assert ttc_causes
    assert all(c["dominant_phase"] == "failover" for c in ttc_causes)
    # non-TTC objectives never carry the hint
    assert all(
        "dominant_phase" not in c for c in v["causes"]
        if not c["slo"].startswith("ttc")
    )


def test_feed_megascale_sample_threads_tail_hint():
    eng = SLOEngine(
        megascale_slo_specs(["region-0"]),
        minutes_per_unit=15.0, registry=m.Registry(),
    )
    sample = {
        "t": 1, "pieces": 100, "corruptions": 0, "completed": 10,
        "reannounce_backlog": 0, "origin_fraction": 0.0, "breaker_open": 0,
        "ttc_ms_p95": {"region-0": 4000.0},
        "tail_dominant_phase": "retry",
    }
    feed_megascale_sample(eng, sample)
    assert eng._tail_hint == "retry"
    sample2 = dict(sample, t=2)
    del sample2["tail_dominant_phase"]
    feed_megascale_sample(eng, sample2)  # pre-tail samples clear the hint
    assert eng._tail_hint is None


# ------------------------------------------------- dftail offline replay


@pytest.fixture()
def soak_artifact(soak_report, tmp_path):
    # deep-copy: the tamper tests below mutate the doc, and the tail
    # block must not leak edits back into the module-scoped report
    doc = json.loads(json.dumps(
        {"scenario": "soak", "hosts": 1500, "tail": soak_report["tail"]}
    ))
    p = tmp_path / "report.json"
    p.write_text(json.dumps(doc))
    return p, doc


def test_dftail_reproduces_attribution_offline(soak_artifact, capsys):
    p, _ = soak_artifact
    assert dftail.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "== soak_1500 ==" in out
    assert "kill@16" in out and "baseline: schedule_wait" in out


def test_dftail_detects_window_drift(soak_artifact, capsys):
    p, doc = soak_artifact
    doc["tail"]["windows"][0]["dominant_phase"] = "verify"
    p.write_text(json.dumps(doc))
    assert dftail.main([str(p)]) == 2
    assert "DRIFT" in capsys.readouterr().out


def test_dftail_flags_tolerance_violation(soak_artifact, capsys):
    p, doc = soak_artifact
    region = next(iter(doc["tail"]["regions"]))
    doc["tail"]["regions"][region]["decomp_ratio"] = 2.0
    p.write_text(json.dumps(doc))
    assert dftail.main([str(p)]) == 1
    assert "TOLERANCE" in capsys.readouterr().out


def test_dftail_list_and_download(soak_artifact, capsys):
    p, doc = soak_artifact
    assert dftail.main([str(p), "--list"]) == 0
    listed = capsys.readouterr().out
    assert "seq=" in listed
    seq = int(doc["tail"]["exemplars"][0]["seq"])
    assert dftail.main([str(p), "--download", str(seq)]) == 0
    assert f"seq={seq}" in capsys.readouterr().out
    assert dftail.main([str(p), "--download", "999999999"]) == 2


def test_checked_in_mega_artifact_attribution(capsys):
    """The shipped BENCH_mega.json reproduces the paper's tail claim
    offline: every scheduler-kill window's slowest download is
    failover-dominated, the quiet baseline waits on the scheduler
    queue, and every region's decomposition sums to its measured TTC."""
    import pathlib

    p = pathlib.Path(__file__).resolve().parents[1] / "BENCH_mega.json"
    assert dftail.main([str(p), "--run", "soak_100000"]) == 0
    doc = json.loads(p.read_text())
    rc, verdicts = dftail.judge(doc, "soak_100000")
    assert rc == 0
    (v,) = verdicts
    assert len(v["windows"]) == 4
    assert all(
        w["tail_dominant_phase"] == "failover" for w in v["windows"]
    )
    assert v["baseline_dominant_phase"] == "schedule_wait"
    for reg in v["regions"].values():
        assert abs(reg["decomp_ratio"] - 1.0) <= DEFAULT_TOLERANCE


def test_dftail_rejects_artifact_without_tail(tmp_path, capsys):
    p = tmp_path / "old.json"
    p.write_text(json.dumps({"scenario": "soak", "hosts": 10}))
    assert dftail.main([str(p)]) == 2
    p2 = tmp_path / "broken.json"
    p2.write_text("{nope")
    assert dftail.main([str(p2)]) == 2
