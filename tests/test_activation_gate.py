"""Trust-boundary integrity, model plane (ISSUE 5): the params.sha256
manifest, STATE_BAD + last-good fallback in both registries, and the
MLEvaluator activation gate (finite-leaves + canary scoring on the
refresh worker) — a NaN-poisoned or manifest-mismatched published
version must NEVER become the serving snapshot."""

import time

import jax
import numpy as np
import pytest

from dragonfly2_tpu.models.graphsage import GraphSAGERanker
from dragonfly2_tpu.objectstorage.backends import FilesystemBackend
from dragonfly2_tpu.registry import (
    BucketModelRegistry,
    MLEvaluator,
    ModelEvaluation,
    ModelRegistry,
    ModelServer,
)
from dragonfly2_tpu.registry.registry import (
    MODEL_TYPE_GNN,
    STATE_ACTIVE,
    STATE_BAD,
    STATE_INACTIVE,
)
from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.utils import dferrors

pytestmark = pytest.mark.corruption


def _graph(n_nodes=64, n_feats=12, edges=256, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "node_feats": rng.normal(size=(n_nodes, n_feats)).astype(np.float32),
        "edge_src": rng.integers(0, n_nodes - 1, edges).astype(np.int32),
        "edge_dst": rng.integers(0, n_nodes - 1, edges).astype(np.int32),
        "edge_feats": rng.normal(size=(edges, 2)).astype(np.float32),
    }


def _gnn_params(model, graph, n_nodes=64):
    child = np.zeros(4, np.int32)
    cands = np.arange(16, dtype=np.int32).reshape(4, 4) % n_nodes
    pair = np.zeros((4, 4, 2), np.float32)
    return model.init(jax.random.key(0), graph, child, cands, pair)


def _served(registry, graph, hidden=16):
    model = GraphSAGERanker(hidden_dim=hidden)
    params = _gnn_params(model, graph, graph["node_feats"].shape[0])
    server = ModelServer(registry, "ranker", "h", MODEL_TYPE_GNN,
                         template_params=params)
    mv = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
        metadata={"hidden_dim": hidden},
    )
    registry.activate(mv.model_id, mv.version)
    assert server.refresh()
    reg_metrics = m.Registry()
    return server, MLEvaluator(server, metrics_registry=reg_metrics), params, mv


def _poison(params):
    bad = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(), params)
    jax.tree_util.tree_leaves(bad)[0].ravel()[0] = np.nan
    return bad


def _packed_buf(b=64, k=8, n_hosts=64, seed=0):
    from dragonfly2_tpu.ops import evaluator as ev
    from dragonfly2_tpu.records.features import CandidateFeatures
    from dragonfly2_tpu.state.fsm import PeerState

    rng = np.random.default_rng(seed)
    feats = CandidateFeatures.zeros(b, k)
    feats.valid[:] = True
    feats.peer_state[:] = int(PeerState.SUCCEEDED)
    feats.upload_limit[:] = 10
    feats.parent_host_id[:] = np.arange(1, b * k + 1).reshape(b, k)
    feats.child_host_id[:] = 0
    fd = feats.as_dict()
    child = rng.integers(0, n_hosts, b).astype(np.int32)
    cands = rng.integers(0, n_hosts, (b, k)).astype(np.int32)
    buf = ev.pack_eval_batch(fd, child_host_slot=child, cand_host_slot=cands)
    c = fd["piece_costs"].shape[-1]
    l = fd["parent_location"].shape[-1]  # noqa: E741
    n = fd["numeric"].shape[-1]
    return buf, (b, k, c, l, n)


# --------------------------------------------------------- activation gate


def test_nan_poisoned_version_never_becomes_serving_snapshot(tmp_path):
    """Acceptance: a NaN-poisoned published version is rejected BY THE
    REFRESH WORKER — serving stays on the last-good (params_version,
    emb_version) pair, the rejection metric increments, the version is
    marked bad (active pointer falls back), and the gate never runs on
    the schedule path."""
    graph = _graph()
    registry = ModelRegistry(tmp_path)
    server, evaluator, params, mv = _served(registry, graph)
    try:
        evaluator.refresh_embeddings(dict(graph), wait=True)
        good = evaluator.committed_versions[-1]
        assert good == (server.version, 1)
        good_params_version = server.version

        mv2 = registry.create_model_version(
            "ranker", MODEL_TYPE_GNN, "h", _poison(params), ModelEvaluation(),
            metadata={"hidden_dim": 16},
        )
        registry.activate(mv2.model_id, mv2.version)
        assert server.refresh()
        assert server.version == mv2.version  # the poison IS on the server

        # async: the gate must run on the worker, not in this caller.
        # Poll for the post-rejection COMMIT (refresh_count advances
        # strictly after _reject_version finished marking the registry).
        evaluator.refresh_embeddings(dict(graph))
        deadline = time.monotonic() + 60
        while evaluator.refresh_count < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert evaluator.refresh_count == 2
        assert evaluator.rejection_count == 1
        assert evaluator._metrics.activation_rejected.value("nonfinite_params") == 1

        # serving NEVER saw the poisoned version: the refresh that carried
        # it committed with LAST-GOOD params (emb_version advances, the
        # params_version does not)
        snap = evaluator.serving_snapshot()
        assert snap.params_version == good_params_version
        assert all(p == good_params_version
                   for p, _ in evaluator.committed_versions)

        # the registry recovered to last-good without an operator
        states = {v.version: v.state for v in registry.list_versions(mv.model_id)}
        assert states == {1: STATE_ACTIVE, 2: STATE_BAD}
        assert registry.active_version(mv.model_id).version == 1
        # a bad version can never be (re)activated
        with pytest.raises(ValueError):
            registry.activate(mv.model_id, 2)
        # ...but the trainer's NEXT publish supersedes it normally
        mv3 = registry.create_model_version(
            "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
            metadata={"hidden_dim": 16},
        )
        assert mv3.version == 3
        registry.activate(mv3.model_id, 3)
        assert server.refresh()
        evaluator.refresh_embeddings(dict(graph), wait=True)
        assert evaluator.serving_snapshot().params_version == 3
        assert evaluator.rejection_count == 1  # healthy v3 passed the gate

        # gate runs ONLY on refresh: a burst of schedule calls adds none
        # (the tick-path-latency-unchanged pin, minus wall-clock noise)
        buf, dims = _packed_buf()
        gate_runs = evaluator.gate_runs
        for _ in range(5):
            out = np.asarray(evaluator.schedule_from_packed(buf.copy(), *dims))
            assert out.shape[-1] == 2
            assert np.all(np.isfinite(out))
        assert evaluator.gate_runs == gate_runs
        assert evaluator.last_used_versions[0] == 3
    finally:
        evaluator.close()


def test_rejected_version_stays_rejected_across_refreshes(tmp_path):
    """While the server still holds a rejected version (e.g. its refresh
    loop has not yet picked up the fallback), topology refreshes keep the
    table tracking with last-good params and the gate does NOT re-run."""
    graph = _graph()
    registry = ModelRegistry(tmp_path)
    server, evaluator, params, mv = _served(registry, graph)
    try:
        evaluator.refresh_embeddings(dict(graph), wait=True)
        mv2 = registry.create_model_version(
            "ranker", MODEL_TYPE_GNN, "h", _poison(params), ModelEvaluation(),
            metadata={"hidden_dim": 16},
        )
        registry.activate(mv2.model_id, mv2.version)
        assert server.refresh()
        evaluator.refresh_embeddings(dict(graph), wait=True)
        assert evaluator.rejection_count == 1
        runs = evaluator.gate_runs
        # server NOT refreshed: it still serves the rejected version
        for _ in range(3):
            evaluator.refresh_embeddings(dict(graph), wait=True)
        assert evaluator.gate_runs == runs  # never re-gated
        assert evaluator.rejection_count == 1
        assert evaluator.serving_snapshot().params_version == 1
        assert evaluator.serving_snapshot().emb_version >= 2
    finally:
        evaluator.close()


def test_gate_with_no_last_good_stays_on_rule_fallback(tmp_path):
    """First-ever published version is poisoned: nothing commits, and
    scheduling falls back to the rule blend (no snapshot to serve)."""
    graph = _graph()
    registry = ModelRegistry(tmp_path)
    model = GraphSAGERanker(hidden_dim=16)
    params = _gnn_params(model, graph)
    server = ModelServer(registry, "ranker", "h", MODEL_TYPE_GNN,
                         template_params=params)
    mv = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", _poison(params), ModelEvaluation(),
        metadata={"hidden_dim": 16},
    )
    registry.activate(mv.model_id, mv.version)
    assert server.refresh()
    evaluator = MLEvaluator(server, metrics_registry=m.Registry())
    try:
        evaluator.refresh_embeddings(dict(graph), wait=True)
        assert evaluator.rejection_count == 1
        assert evaluator.serving_snapshot() is None
        buf, dims = _packed_buf()
        out = np.asarray(evaluator.schedule_from_packed(buf.copy(), *dims))
        assert out.shape[-1] == 2 and np.all(np.isfinite(out))
        assert evaluator.last_used_versions is None  # rule blend served
    finally:
        evaluator.close()


# ------------------------------------------------- params.sha256 manifest


def test_manifest_mismatch_never_activates_bucket(tmp_path):
    """Acceptance (bucket registry): a params blob corrupted after
    publish fails its params.sha256 manifest at load — ModelServer.refresh
    refuses it, marks the version bad, and serving stays on last-good."""
    graph = _graph()
    backend = FilesystemBackend(tmp_path / "store")
    registry = BucketModelRegistry(backend, "models")
    server, evaluator, params, mv = _served(registry, graph)
    try:
        v1_params_version = server.version
        mv2 = registry.create_model_version(
            "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation(),
            metadata={"hidden_dim": 16},
        )
        # bit-rot the published blob IN THE BUCKET (after the manifest
        # was written): sha256 now disagrees
        key = registry._key(mv2.model_id, mv2.version, "params.msgpack")
        blob = bytearray(backend.get_object(registry.bucket, key))
        blob[len(blob) // 2] ^= 0x40
        backend.put_object(registry.bucket, key, bytes(blob))
        with pytest.raises(dferrors.DataLoss, match="sha256"):
            registry.load_params(mv2.model_id, mv2.version)

        registry.activate(mv2.model_id, mv2.version)
        assert not server.refresh()  # refused, not activated
        assert server.version == v1_params_version
        states = {v.version: v.state
                  for v in registry.list_versions(mv.model_id)}
        assert states == {1: STATE_ACTIVE, 2: STATE_BAD}
        assert registry.active_version(mv.model_id).version == 1
        assert server.refresh() is False  # already on the fallback v1
        # a torn write (size mismatch) is caught before hashing
        key3 = registry._key(mv2.model_id, mv2.version, "params.msgpack")
        backend.put_object(registry.bucket, key3, bytes(blob[:100]))
        with pytest.raises(dferrors.DataLoss, match="bytes"):
            registry.load_params(mv2.model_id, mv2.version)
    finally:
        evaluator.close()


def test_bucket_bad_version_stays_bad_on_activate_cycle(tmp_path):
    """activate() must refuse a bad version and never resurrect one to
    inactive while flipping states for a new activation."""
    backend = FilesystemBackend(tmp_path / "store")
    registry = BucketModelRegistry(backend, "models")
    graph = _graph()
    model = GraphSAGERanker(hidden_dim=16)
    params = _gnn_params(model, graph)
    v1 = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation())
    v2 = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation())
    registry.activate(v1.model_id, 2)
    registry.mark_version_bad(v1.model_id, 2, reason="canary")
    # the active pointer fell back to the newest good version
    assert registry.active_version(v1.model_id).version == 1
    with pytest.raises(ValueError, match="bad"):
        registry.activate(v1.model_id, 2)
    v3 = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation())
    registry.activate(v3.model_id, 3)
    states = {v.version: v.state for v in registry.list_versions(v1.model_id)}
    assert states == {1: STATE_INACTIVE, 2: STATE_BAD, 3: STATE_ACTIVE}
    # marking the last good version bad leaves no active version
    registry.mark_version_bad(v3.model_id, 3)
    registry.mark_version_bad(v3.model_id, 1)
    assert registry.active_version(v1.model_id) is None


def test_mark_bad_fallback_skips_params_less_versions(tmp_path):
    """The recover-to-last-good pointer must land on a LOADABLE version:
    a publisher that died before uploading params leaves a not-bad but
    params-less version that activate() refuses — the bad-version
    fallback must skip it too (both registries)."""
    graph = _graph()
    model = GraphSAGERanker(hidden_dim=16)
    params = _gnn_params(model, graph)
    # bucket registry
    backend = FilesystemBackend(tmp_path / "store")
    bucket = BucketModelRegistry(backend, "models")
    b1 = bucket.create_model_version("r", MODEL_TYPE_GNN, "h", params,
                                     ModelEvaluation())
    b2 = bucket.create_model_version("r", MODEL_TYPE_GNN, "h", params,
                                     ModelEvaluation())
    backend.delete_object(bucket.bucket,
                          bucket._key(b2.model_id, 2, "params.msgpack"))
    b3 = bucket.create_model_version("r", MODEL_TYPE_GNN, "h", params,
                                     ModelEvaluation())
    bucket.activate(b3.model_id, 3)
    bucket.mark_version_bad(b3.model_id, 3, reason="canary")
    assert bucket.active_version(b1.model_id).version == 1  # skipped v2
    # fs registry
    import shutil

    fs = ModelRegistry(tmp_path / "fs")
    f1 = fs.create_model_version("r", MODEL_TYPE_GNN, "h", params,
                                 ModelEvaluation())
    f2 = fs.create_model_version("r", MODEL_TYPE_GNN, "h", params,
                                 ModelEvaluation())
    shutil.rmtree(fs.base / f2.model_id / "2" / "params")
    f3 = fs.create_model_version("r", MODEL_TYPE_GNN, "h", params,
                                 ModelEvaluation())
    fs.activate(f3.model_id, 3)
    fs.mark_version_bad(f3.model_id, 3, reason="canary")
    assert fs.active_version(f1.model_id).version == 1  # skipped v2


def test_fs_mark_version_bad_fallback(tmp_path):
    """fs ModelRegistry: same bad/fallback semantics as the bucket."""
    registry = ModelRegistry(tmp_path)
    graph = _graph()
    model = GraphSAGERanker(hidden_dim=16)
    params = _gnn_params(model, graph)
    v1 = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation())
    v2 = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation())
    registry.activate(v1.model_id, 2)
    registry.mark_version_bad(v1.model_id, 2, reason="nonfinite_params")
    assert registry.active_version(v1.model_id).version == 1
    states = {v.version: v.state for v in registry.list_versions(v1.model_id)}
    assert states == {1: STATE_ACTIVE, 2: STATE_BAD}
    with pytest.raises(ValueError, match="bad"):
        registry.activate(v1.model_id, 2)
    # marking a non-active version bad does not move the pointer
    v3 = registry.create_model_version(
        "ranker", MODEL_TYPE_GNN, "h", params, ModelEvaluation())
    registry.mark_version_bad(v3.model_id, 3)
    assert registry.active_version(v1.model_id).version == 1
