"""dfwire schema + skew harness (ISSUE 15): the ``buf breaking`` analog
over the hand-rolled codec, the N-1<->live skew replayer, and the codec
satellites (registration collisions, typed decode errors).

The breaking-gate red tests work on COPIES of the live extraction with
one injected mutation each (field rename, field type change, enum
edit, required-field add), pinning that exactly those evolutions exit
nonzero while add-field-with-default stays green — the proto3 rule the
tentpole encodes.
"""

from __future__ import annotations

import copy
import dataclasses
import json
from pathlib import Path

import pytest

# importing the servers registers every message set with the codec
import dragonfly2_tpu.manager.rpc  # noqa: F401
import dragonfly2_tpu.rpc.inference  # noqa: F401
import dragonfly2_tpu.rpc.server  # noqa: F401
from dragonfly2_tpu.rpc import wire
from tools.dflint import wirefuzz, wireschema

ROOT = Path(__file__).resolve().parents[1]
SNAPSHOT = ROOT / "tools" / "dfwire_schema.json"


@pytest.fixture(scope="module")
def live_schema() -> dict:
    return wireschema.extract()


@pytest.fixture(scope="module")
def golden() -> dict:
    return json.loads(SNAPSHOT.read_text())


# ------------------------------------------------------------ extraction


def test_snapshot_is_checked_in_and_current(live_schema, golden):
    """The golden snapshot exists and the LIVE extraction is breaking-
    free against it (compatible adds are legal mid-PR; breaks must
    regenerate with --write). Message coverage includes every codec
    registry member plus nested records."""
    changes = wireschema.diff(golden, live_schema)
    breaking = [c for c in changes if c.breaking]
    assert breaking == [], [c.render() for c in breaking]
    for name in wire._REGISTRY:
        # throwaway types other tests register in this process are not
        # part of the checked-in contract
        if name in golden["messages"]:
            assert "fields" in golden["messages"][name]
    for expected in ("RegisterPeerRequest", "NormalTaskResponse",
                     "HostInfo", "CPUStat", "V1PeerPacket",
                     "ModelInferRequest", "HealthCheckRequest"):
        assert expected in golden["messages"], expected
    assert golden["enums"]["SizeScope"] == {
        "NORMAL": 0, "SMALL": 1, "TINY": 2, "EMPTY": 3,
    }
    assert golden["codes"]["CODE_SCHED_NEED_BACK_SOURCE"] == 5001


def test_breaking_gate_green_on_clean_tree():
    assert wireschema.check_breaking() == 0


def test_normalized_types_cover_the_lattice(golden):
    fields = golden["messages"]["RegisterPeerRequest"]["fields"]
    assert fields["peer_id"] == {"type": "str", "required": True}
    assert fields["host"] == {"type": "message:HostInfo", "required": True}
    assert fields["finished_pieces"]["type"] == "optional[list[int]]"
    assert golden["messages"]["NormalTaskResponse"]["fields"][
        "candidate_parents"]["type"] == "list[message:CandidateParent]"


# --------------------------------------------------------- breaking gate


def _expect_breaking(golden, mutate, needle: str):
    old = copy.deepcopy(golden)
    mutate(old)
    # diff FROM the mutated snapshot TO the live schema: the mutation
    # plays the N-1 generation the live tree evolved away from
    changes = wireschema.diff(old, wireschema.extract())
    breaking = [c for c in changes if c.breaking]
    assert breaking, f"mutation {needle!r} was not flagged"
    assert any(needle in c.detail for c in breaking), [
        c.render() for c in breaking
    ]


def test_breaking_on_field_rename(golden):
    def mutate(old):
        fields = old["messages"]["RegisterPeerRequest"]["fields"]
        fields["peer_identifier"] = fields.pop("peer_id")

    # the live tree "renamed" peer_identifier -> peer_id: the old name
    # is removed (breaking) and the new one is added-required (breaking)
    _expect_breaking(golden, mutate, "peer_identifier")


def test_breaking_on_field_type_change(golden):
    def mutate(old):
        old["messages"]["DownloadPieceFinishedRequest"]["fields"][
            "piece_number"]["type"] = "str"

    _expect_breaking(golden, mutate, "piece_number' type changed")


def test_breaking_on_enum_edit(golden):
    def mutate(old):
        old["enums"]["SizeScope"]["EMPTY"] = 9

    _expect_breaking(golden, mutate, "SizeScope.EMPTY' value changed")


def test_breaking_on_enum_member_removed(golden):
    def mutate(old):
        del old["enums"]["SizeScope"]["TINY"]

    # live has TINY, mutated N-1 does not: live ADDED a member an N-1
    # decoder cannot parse
    _expect_breaking(golden, mutate, "SizeScope.TINY' added")


def test_breaking_on_wire_code_change(golden):
    def mutate(old):
        old["codes"]["CODE_SUCCESS"] = 0

    _expect_breaking(golden, mutate, "CODE_SUCCESS")


def test_breaking_on_required_field_add(golden):
    def mutate(old):
        del old["messages"]["RegisterPeerRequest"]["fields"]["task_id"]

    # the live tree added required task_id relative to the mutated N-1:
    # an N-1 sender omits it and the live decoder hard-errors
    _expect_breaking(golden, mutate, "task_id' added WITHOUT a default")


def test_add_field_with_default_is_compatible(golden):
    old = copy.deepcopy(golden)
    # N-1 did not know this defaulted field; the live tree adds it
    del old["messages"]["RegisterPeerRequest"]["fields"]["priority"]
    changes = wireschema.diff(old, wireschema.extract())
    assert all(not c.breaking for c in changes), [
        c.render() for c in changes if c.breaking
    ]
    assert any("priority' added with a default" in c.detail
               for c in changes)


def test_breaking_cli_exit_codes(tmp_path, golden):
    """The CLI contract the CI stage relies on: exit 0 against a clean
    snapshot, exit 1 against a mutated one, exit 1 with no snapshot."""
    clean = tmp_path / "clean.json"
    clean.write_text(json.dumps(wireschema.extract()))
    assert wireschema.check_breaking(clean) == 0
    mutated = json.loads(clean.read_text())
    mutated["messages"]["StatResponse"]["fields"]["found"]["type"] = "int"
    broken = tmp_path / "broken.json"
    broken.write_text(json.dumps(mutated))
    assert wireschema.check_breaking(broken) == 1
    assert wireschema.check_breaking(tmp_path / "missing.json") == 1


def test_write_snapshot_bumps_version_on_break(tmp_path):
    """--write records the intentional-break acknowledgement: same
    schema -> version stays; breaking diff vs the previous snapshot ->
    version bumps."""
    path = tmp_path / "snap.json"
    assert wireschema.write_snapshot(path) == 0
    assert json.loads(path.read_text())["schema_version"] == 1
    assert wireschema.write_snapshot(path) == 0  # idempotent, no bump
    assert json.loads(path.read_text())["schema_version"] == 1
    doc = json.loads(path.read_text())
    doc["messages"]["StatResponse"]["fields"]["found"]["type"] = "int"
    path.write_text(json.dumps(doc))
    assert wireschema.write_snapshot(path) == 0
    assert json.loads(path.read_text())["schema_version"] == 2


# ---------------------------------------------------------- skew replay


def test_skew_replay_against_golden_snapshot(golden):
    """Acceptance: N-1-schema frames decode against the live registry
    (and live frames satisfy the N-1 required set) for every message in
    the snapshot."""
    problems = wirefuzz.replay_skew(golden)
    assert problems == [], problems


def test_skew_replay_catches_incompatible_generations(golden):
    """Red halves of the replayer: (a) an N-1 schema missing a field
    the live side REQUIRES -> WireDecodeError surfaces as
    'INCOMPATIBLE'; (b) a live schema missing a field the N-1 side
    requires -> 'strands N-1 decoders'."""
    old = copy.deepcopy(golden)
    fields = old["messages"]["RegisterPeerRequest"]["fields"]
    del fields["task_id"]  # live requires it; N-1 frames omit it
    problems = wirefuzz.replay_skew(old)
    assert any("RegisterPeerRequest" in p and "INCOMPATIBLE" in p
               for p in problems), problems

    old2 = copy.deepcopy(golden)
    old2["messages"]["RegisterPeerRequest"]["fields"]["from_the_past"] = {
        "type": "str", "required": True,
    }
    problems2 = wirefuzz.replay_skew(old2)
    assert any("strands N-1 decoders" in p and "from_the_past" in p
               for p in problems2), problems2


def test_degrade_payload_drops_unknown_and_recurses(golden):
    from dragonfly2_tpu.cluster import messages as msg

    request = msg.RegisterPeerRequest(
        peer_id="p", task_id="t",
        host=msg.HostInfo(host_id="h", ip="1.2.3.4"),
    )
    payload = wire._to_plain(request)
    payload["field_from_the_future"] = 42
    payload["host"]["future_host_field"] = "x"
    degraded = wirefuzz.degrade_payload(payload, golden,
                                        "RegisterPeerRequest")
    assert "field_from_the_future" not in degraded
    assert "future_host_field" not in degraded["host"]
    assert degraded["peer_id"] == "p"
    assert degraded["host"]["host_id"] == "h"


# ------------------------------------------------- satellites: registry


def test_register_collision_raises_and_idempotent_reregister_is_legal():
    @dataclasses.dataclass
    class WireContractProbeMsg:
        x: int = 0

    wire.register_messages(WireContractProbeMsg)
    # same class again: no-op (server+client both import-register)
    wire.register_messages(WireContractProbeMsg)
    assert wire._REGISTRY["WireContractProbeMsg"] is WireContractProbeMsg

    @dataclasses.dataclass
    class Impostor:
        y: str = ""

    Impostor.__name__ = "WireContractProbeMsg"
    Impostor.__qualname__ = "WireContractProbeMsg"
    with pytest.raises(TypeError, match="name collision"):
        wire.register_messages(Impostor)
    # the loser did NOT alias the registry entry
    assert wire._REGISTRY["WireContractProbeMsg"] is WireContractProbeMsg


def test_register_module_collision_raises(tmp_path):
    import types as types_mod

    @dataclasses.dataclass
    class ModProbeA:
        x: int = 0

    module = types_mod.ModuleType("fake_wire_module")
    module.ModProbeA = ModProbeA
    wire.register_module(module)

    @dataclasses.dataclass
    class ModProbeB:
        y: int = 0

    ModProbeB.__name__ = "ModProbeA"
    module2 = types_mod.ModuleType("fake_wire_module_2")
    module2.ModProbeA = ModProbeB
    with pytest.raises(TypeError, match="name collision"):
        wire.register_module(module2)


# ------------------------------------------ satellites: WireDecodeError


def test_missing_required_field_raises_typed_wire_decode_error():
    import msgpack

    broken = msgpack.packb(
        {"t": "RegisterPeerRequest", "d": {"peer_id": "p1"}},
        use_bin_type=True,
    )
    with pytest.raises(wire.WireDecodeError) as exc_info:
        wire.decode(broken)
    err = exc_info.value
    assert err.message_type == "RegisterPeerRequest"
    assert err.missing == ["task_id", "host"]
    assert "incompatible schema generation" in str(err)
    # and it still IS a TypeError (pre-existing catch sites keep working)
    assert isinstance(err, TypeError)


def test_well_formed_frame_does_not_raise_despite_extra_fields():
    import msgpack

    from dragonfly2_tpu.cluster import messages as msg

    frame = msgpack.packb(
        {"t": "StatPeerRequest",
         "d": {"peer_id": "p", "new_field_from_future": 1}},
        use_bin_type=True,
    )
    assert wire.decode(frame) == msg.StatPeerRequest(peer_id="p")


# --------------------------------------------- megascale skew soak gate


def test_rolling_upgrade_soak_with_wire_skew_loses_zero_downloads(golden):
    """THE skew soak acceptance (ISSUE 15): the rolling-upgrade soak
    replayed with every control-plane exchange round-tripping the
    N-1-degraded codec (SkewProxy) produces a BIT-IDENTICAL
    deterministic report to the plain run — zero lost downloads, zero
    diverging decisions across mixed-version rounds — with zero codec
    mismatches, real frame traffic on the register/response handshake
    types, and rolling-upgrade churn actually exercised."""
    from dragonfly2_tpu.megascale.soak import (
        deterministic_view, run_megascale,
    )

    kwargs = dict(num_hosts=800, num_tasks=24, seed=7,
                  arrivals_per_round=16, retire_after_rounds=24)
    plain = run_megascale("soak", **kwargs)
    skew = run_megascale("soak", wire_skew=golden, **kwargs)
    ws = skew.pop("wire_skew")
    assert ws["mismatches"] == [], ws["mismatches"][:5]
    # the mixed-version handshake really happened, on both directions
    assert ws["frames_total"] > 1000
    for handshake in ("RegisterPeerRequest", "NormalTaskResponse",
                      "DownloadPeerFinishedRequest"):
        assert ws["frames"].get(handshake, 0) > 0, ws["frames"]
    # rolling upgrades ran, so cross-version rounds existed
    assert skew["mega"]["upgrade_host_restarts"] > 0
    # zero lost downloads: the skewed wire changed NOTHING downstream —
    # completions, failures, per-region aggregates, decision ledger,
    # SLO verdicts are all bit-identical to the plain run
    assert deterministic_view(skew) == deterministic_view(plain)
    assert skew["stats"]["completed"] > 0
    assert skew["stats"]["completed"] == plain["stats"]["completed"]


# ------------------------------------------- fleet handoff frame (ISSUE 17)


def test_handoff_frame_in_snapshot_with_defaulted_provenance(golden):
    """The PeerHandoffRequest wire message landed in the snapshot via
    add-field-with-default discipline: only the identity triple is
    required; the adoption payload and provenance fields all default,
    so an N-1 decoder that drops them still lands the peer."""
    fields = golden["messages"]["PeerHandoffRequest"]["fields"]
    required = {k for k, spec in fields.items() if spec["required"]}
    assert required == {"peer_id", "task_id", "host"}
    for optional in ("finished_pieces", "from_scheduler", "reason"):
        assert optional in fields and not fields[optional]["required"]


def test_handoff_frame_roundtrips_and_replays_skew(golden):
    """Codec roundtrip + both skew directions for the handoff frame
    specifically: a live frame degraded to the snapshot still decodes,
    and an N-1 schema that predates the message entirely passes the
    frame through whole (new-message adds are compatible)."""
    from dragonfly2_tpu.cluster import messages as msg

    request = msg.PeerHandoffRequest(
        peer_id="p1", task_id="t1",
        host=msg.HostInfo(host_id="h1", ip="10.0.0.9"),
        url="http://origin/t1", content_length=16 << 20,
        total_piece_count=4, finished_pieces=[0, 2],
        from_scheduler="scheduler-1", reason="crash",
    )
    decoded = wire.decode(wire.encode(request)[4:])  # [4:]: length header
    assert decoded == request
    # live -> N-1 degrade keeps the adoption payload intact
    payload = wire._to_plain(request)
    degraded = wirefuzz.degrade_payload(payload, golden,
                                        "PeerHandoffRequest")
    assert degraded["finished_pieces"] == [0, 2]
    assert degraded["reason"] == "crash"
    # an N-2 schema that has never heard of the message: degrade is a
    # pass-through and the structured replay stays green
    old = copy.deepcopy(golden)
    del old["messages"]["PeerHandoffRequest"]
    assert wirefuzz.degrade_payload(payload, old,
                                    "PeerHandoffRequest") == payload
    assert wirefuzz.replay_skew(old) == []


def test_fleet_soak_with_wire_skew_covers_handoff_frames(golden):
    """Skew soak over the SHARDED control plane: a K=4 fleet day with
    every exchange round-tripping the N-1 codec moves real
    PeerHandoffRequest frames, records zero codec mismatches, and is
    bit-identical to the plain fleet run — cross-version handoff loses
    zero downloads."""
    from dragonfly2_tpu.megascale.soak import (
        deterministic_view, run_megascale,
    )

    kwargs = dict(num_hosts=2000, num_tasks=24, seed=11, rounds=40,
                  fleet_replicas=4)
    plain = run_megascale("fleet", **kwargs)
    skew = run_megascale("fleet", wire_skew=golden, **kwargs)
    ws = skew.pop("wire_skew")
    assert ws["mismatches"] == [], ws["mismatches"][:5]
    assert ws["frames"].get("PeerHandoffRequest", 0) > 0, ws["frames"]
    assert deterministic_view(skew) == deterministic_view(plain)
    assert skew["stats"]["failed"] == 0
    assert skew["fleet"]["handoffs"]["crash"] > 0


# ------------------------------------------------------- property pins


def test_roundtrip_registry_is_clean():
    """Seeded structural fuzz over EVERY registered message type via the
    shared wirefuzz core (the test-side twin is test_wire_property) —
    deterministic: crc32-of-name seeds, no hash()."""
    problems = wirefuzz.roundtrip_registry()
    assert problems == [], problems[:10]
