"""Manager service layer: business logic over the Database.

Capability parity with manager/service/*.go (2,459 LoC of per-entity
logic) + the gRPC-facing parts of manager/rpcserver: user signup/signin,
cluster composites, scheduler/seed-peer registration and keepalive
active/inactive flips (manager_server_v1.go:955-1000), searcher-ranked
scheduler lists for joining daemons (ListSchedulers), model lifecycle
bridging the DB metadata mirror to the native ModelRegistry (CreateModel,
manager_server_v1.go:802-952; activate flip manager/service/model.go:
109-190), preheat job fan-out, and the dynconfig payloads schedulers and
daemons poll.
"""

from __future__ import annotations

import logging
import os
import time

from dragonfly2_tpu.manager import auth
from dragonfly2_tpu.manager.models import Database, DuplicateRecord, RecordNotFound
from dragonfly2_tpu.manager.searcher import Searcher, new_searcher
from dragonfly2_tpu.telemetry import default_registry
from dragonfly2_tpu.telemetry.series import manager_series

# scheduler/seed-peer service states (manager/models/{scheduler,seed_peer}.go)
STATE_ACTIVE = "active"
STATE_INACTIVE = "inactive"

KEEPALIVE_TIMEOUT = 60.0  # mark inactive when silent this long

logger = logging.getLogger(__name__)


class ManagerService:
    def __init__(
        self,
        db: Database | None = None,
        registry=None,
        jobs=None,
        token_authority: auth.TokenAuthority | None = None,
        searcher: Searcher | None = None,
        plugin_dir: str | None = None,
        cert_dir: str | None = None,
        enrollment_token: str | None = None,
        jobs_resolver=None,
    ):
        self.db = db or Database()
        self.registry = registry  # registry.ModelRegistry | None
        self.jobs = jobs  # cluster.jobs.JobManager | None
        # callable -> {name: scheduler-like} rebuilt from live state; the
        # launched manager resolves its DB's ACTIVE scheduler rows into
        # RemoteScheduler proxies before every job operation (schedulers
        # register/depart at runtime; an in-proc JobManager with a fixed
        # scheduler set passes None)
        self.jobs_resolver = jobs_resolver
        self.tokens = token_authority or auth.TokenAuthority()
        self.enforcer = auth.Enforcer(self.db)
        self.searcher = searcher or new_searcher(plugin_dir)
        self.metrics = manager_series(default_registry())
        # cluster CA for mTLS cert issuance (pkg/issuer); lazily created
        # on first use when a cert_dir is configured, never otherwise
        self.cert_dir = cert_dir
        self.enrollment_token = enrollment_token
        self._ca: tuple[bytes, bytes] | None = None
        self._oauth_providers: dict = {}  # name -> (config key, provider)
        self.enforcer.init_policies()
        self._ensure_root_user()

    def _ensure_root_user(self) -> None:
        """First boot creates root/dragonfly with the root role
        (rbac.go InitRBAC)."""
        if self.db.count("users") == 0:
            record = self.db.create(
                "users",
                {
                    "name": "root",
                    "email": "",
                    "encrypted_password": auth.hash_password("dragonfly"),
                    "state": "enable",
                },
            )
            self.enforcer.add_role_for_user(record["name"], auth.ROOT_ROLE)

    # ---------------------------------------------------------------- users

    def sign_up(self, name: str, password: str, email: str = "", **extra) -> dict:
        record = self.db.create(
            "users",
            {
                "name": name,
                "email": email,
                "encrypted_password": auth.hash_password(password),
                "state": "enable",
                **extra,
            },
        )
        self.enforcer.add_role_for_user(name, auth.GUEST_ROLE)
        return _redact_user(record)

    def sign_in(self, name: str, password: str) -> str:
        user = self.db.find_one("users", {"name": name})
        if user is None or user.get("state") != "enable":
            raise PermissionError("unknown or disabled user")
        if not auth.verify_password(password, user["encrypted_password"]):
            raise PermissionError("bad credentials")
        return self.tokens.issue(user["id"], name)

    # ---------------------------------------------------------- oauth signin

    def _oauth_provider(self, name: str):
        """Provider built from the `oauth` table row; cached so the state
        dict survives between signin and callback (handlers/user.go:190
        OauthSignin -> :216 OauthSigninCallback). The cache key covers the
        WHOLE record, so any CRUD update (secret rotation, endpoint change)
        rebuilds the provider instead of serving stale credentials."""
        import json as _json

        from dragonfly2_tpu.manager import oauth as oauth_mod

        record = self.db.find_one("oauth", {"name": name})
        if record is None:
            raise RecordNotFound(f"no oauth provider {name!r} configured")
        cache_key = _json.dumps(record, sort_keys=True, default=str)
        cached = self._oauth_providers.get(name)
        if cached is not None and cached[0] == cache_key:
            return cached[1]
        provider = oauth_mod.provider_from_record(record)
        self._oauth_providers[name] = (cache_key, provider)
        return provider

    def oauth_signin(self, name: str) -> str:
        """-> consent-page URL to redirect the browser to (OauthSignin)."""
        return self._oauth_provider(name).auth_code_url()

    def oauth_signin_callback(self, name: str, code: str, state: str = "") -> str:
        """Code exchange -> userinfo -> create-or-get user -> manager JWT
        (OauthSigninCallback + gin-jwt LoginHandler).

        Account linking keys on the provider's STABLE subject id stored in
        (oauth_provider, oauth_subject) — never on the display name, which
        the IdP lets users edit freely (a display name of "root" must not
        sign in as the bootstrap root account). The state parameter is
        mandatory: an absent state is a forged/replayed callback."""
        provider = self._oauth_provider(name)
        if not provider.check_state(state):
            raise PermissionError("oauth state missing, mismatched, or expired")
        token = provider.exchange(code)
        info = provider.get_user(token)
        user = self.db.find_one(
            "users", {"oauth_provider": name, "oauth_subject": info["subject"]}
        )
        if user is None:
            username = info["name"]
            if self.db.find_one("users", {"name": username}) is not None:
                # never collide with (and thereby shadow) an existing local
                # account; scope the visible name by provider+subject
                username = f"{info['name']}@{name}:{info['subject']}"
            user = self.db.create(
                "users",
                {
                    "name": username,
                    "email": info["email"],
                    "avatar": info["avatar"],
                    "oauth_provider": name,
                    "oauth_subject": info["subject"],
                    # oauth users have no local password; a random one
                    # keeps the password path closed without a schema fork
                    "encrypted_password": auth.hash_password(os.urandom(16).hex()),
                    "state": "enable",
                },
            )
            self.enforcer.add_role_for_user(username, auth.GUEST_ROLE)
        elif user.get("state") != "enable":
            raise PermissionError("user disabled")
        return self.tokens.issue(user["id"], user["name"])

    def reset_password(self, user_id: int, new_password: str) -> None:
        self.db.update("users", user_id, {"encrypted_password": auth.hash_password(new_password)})

    def get_user(self, user_id: int) -> dict:
        return _redact_user(self.db.get("users", user_id))

    def get_users(self) -> list[dict]:
        return [_redact_user(u) for u in self.db.list("users")]

    def update_user(self, user_id: int, patch: dict) -> dict:
        patch.pop("encrypted_password", None)
        return _redact_user(self.db.update("users", user_id, patch))

    # ------------------------------------------------------------- clusters

    def create_cluster(self, body: dict) -> dict:
        """The composite Cluster entity: one scheduler cluster + one
        seed-peer cluster created together (manager/service/cluster.go
        CreateCluster creates+associates both)."""
        name = body["name"]
        sc = self.db.create(
            "scheduler_clusters",
            {
                "name": f"{name}-scheduler",
                "bio": body.get("bio", ""),
                "config": body.get("scheduler_cluster_config", {}),
                "client_config": body.get("peer_cluster_config", {}),
                "scopes": body.get("scopes", {}),
                "is_default": bool(body.get("is_default", False)),
            },
        )
        spc = self.db.create(
            "seed_peer_clusters",
            {
                "name": f"{name}-seed-peer",
                "bio": body.get("bio", ""),
                "config": body.get("seed_peer_cluster_config", {}),
                "scheduler_cluster_ids": [sc["id"]],
            },
        )
        return self.db.create(
            "clusters",
            {
                "name": name,
                "bio": body.get("bio", ""),
                "scheduler_cluster_id": sc["id"],
                "seed_peer_cluster_id": spc["id"],
                "is_default": bool(body.get("is_default", False)),
            },
        )

    def delete_cluster(self, cluster_id: int) -> None:
        cluster = self.db.get("clusters", cluster_id)
        for table, key in (
            ("scheduler_clusters", "scheduler_cluster_id"),
            ("seed_peer_clusters", "seed_peer_cluster_id"),
        ):
            try:
                self.db.delete(table, cluster[key])
            except RecordNotFound:
                pass
        self.db.delete("clusters", cluster_id)

    # -------------------------------------------- schedulers and seed peers

    def register_scheduler(self, body: dict) -> dict:
        """Create-or-refresh by unique key, the UpdateScheduler/
        CreateScheduler pair the gRPC GetScheduler path uses."""
        body.setdefault("state", STATE_INACTIVE)
        try:
            return self.db.create("schedulers", body)
        except DuplicateRecord:
            existing = self.db.find_one(
                "schedulers",
                {k: body[k] for k in ("host_name", "ip", "scheduler_cluster_id")},
            )
            assert existing is not None
            return self.db.update("schedulers", existing["id"], body)

    def register_seed_peer(self, body: dict) -> dict:
        body.setdefault("state", STATE_INACTIVE)
        try:
            return self.db.create("seed_peers", body)
        except DuplicateRecord:
            existing = self.db.find_one(
                "seed_peers",
                {k: body[k] for k in ("host_name", "ip", "seed_peer_cluster_id")},
            )
            assert existing is not None
            return self.db.update("seed_peers", existing["id"], body)

    def keepalive(self, source_type: str, host_name: str, ip: str, cluster_id: int) -> None:
        """Mark the instance active and stamp it (KeepAlive stream recv,
        manager_server_v1.go:955-1000)."""
        table, key = _SOURCE_TABLES[source_type]
        record = self.db.find_one(table, {"host_name": host_name, "ip": ip, key: cluster_id})
        if record is None:
            raise RecordNotFound(f"{source_type} {host_name}/{ip} not registered")
        self.db.update(table, record["id"], {"state": STATE_ACTIVE, "keepalive_at": time.time()})

    def expire_keepalives(self, timeout: float = KEEPALIVE_TIMEOUT) -> int:
        """Sweep: instances silent > timeout flip inactive (the reference
        flips on stream disconnect; polling covers crashed hosts too)."""
        expired = 0
        deadline = time.time() - timeout
        for table in ("schedulers", "seed_peers"):
            for record in self.db.list(table, {"state": STATE_ACTIVE}, per_page=100000):
                if record.get("keepalive_at", 0) < deadline:
                    self.db.update(table, record["id"], {"state": STATE_INACTIVE})
                    expired += 1
        return expired

    def list_schedulers(self, ip: str, hostname: str, conditions: dict | None = None) -> list[dict]:
        """Searcher-ranked active schedulers for a joining daemon
        (manager_server_v1.go ListSchedulers → searcher.FindSchedulerClusters),
        flattened best-cluster-first — the daemon dynconfig payload."""
        clusters = []
        for sc in self.db.list("scheduler_clusters"):
            active = self.db.list(
                "schedulers",
                {"scheduler_cluster_id": sc["id"], "state": STATE_ACTIVE},
            )
            clusters.append({**sc, "schedulers": active})
        self.metrics.search_scheduler_cluster.labels().inc()
        try:
            ranked = self.searcher.find_scheduler_clusters(clusters, ip, hostname, conditions)
        except ValueError:
            self.metrics.search_scheduler_cluster_failure.labels().inc()
            return []
        return [s for cluster in ranked for s in cluster["schedulers"]]

    # ---------------------------------------------------------------- models

    def create_model(
        self, name: str, model_type: str, scheduler_host_id: str, params, evaluation, metadata=None
    ) -> dict:
        """CreateModel: artifacts to the registry, metadata mirrored in the
        DB (manager_server_v1.go:802-952)."""
        if self.registry is None:
            raise RuntimeError("manager has no model registry attached")
        mv = self.registry.create_model_version(
            name, model_type, scheduler_host_id, params, evaluation, metadata
        )
        return self.db.create(
            "models",
            {
                "model_id": mv.model_id,
                "name": mv.name,
                "type": mv.type,
                "version": mv.version,
                "state": mv.state,
                "evaluation": vars(mv.evaluation),
                "scheduler_host_id": scheduler_host_id,
            },
        )

    def activate_model(self, model_id: str, version: int) -> None:
        if self.registry is None:
            raise RuntimeError("manager has no model registry attached")
        self.registry.activate(model_id, version)
        for record in self.db.list("models", {"model_id": model_id}, per_page=100000):
            state = "active" if record["version"] == version else "inactive"
            self.db.update("models", record["id"], {"state": state})

    # ------------------------------------------------------------------ pki

    def _cluster_ca(self) -> tuple[bytes, bytes]:
        """Load-or-create the cluster CA under cert_dir (pkg/issuer roots).
        (cert_pem, key_pem); persisted so restarts keep issuing from the
        same root and existing leaf certs stay valid."""
        if self._ca is not None:
            return self._ca
        if self.cert_dir is None:
            raise RuntimeError("manager has no cert_dir configured; mTLS issuance is off")
        import pathlib

        from dragonfly2_tpu.utils import certs

        d = pathlib.Path(self.cert_dir)
        d.mkdir(parents=True, exist_ok=True)
        ca_cert_p, ca_key_p = d / "ca.pem", d / "ca_key.pem"
        if ca_cert_p.exists() and ca_key_p.exists():
            self._ca = (ca_cert_p.read_bytes(), ca_key_p.read_bytes())
        else:
            cert_pem, key_pem = certs.generate_ca()
            ca_cert_p.write_bytes(cert_pem)
            ca_key_p.write_bytes(key_pem)
            ca_key_p.chmod(0o600)
            self._ca = (cert_pem, key_pem)
        return self._ca

    def issue_certificate(
        self, csr_pem: bytes, validity_days: int = 365, token: str = ""
    ) -> list[bytes]:
        """Sign a service CSR with the cluster CA -> [leaf, ca] chain
        (manager-side of the security client's IssueCertificate).

        Issuance is the cluster's trust anchor, so it is gated: when the
        manager is configured with an enrollment token, a request must
        present it or the CA refuses to sign — otherwise anyone who can
        reach the RPC port could mint cluster-trusted certs. Every issued
        (and refused) CN/SAN set is logged for audit either way."""
        import hmac

        from dragonfly2_tpu.utils import certs
        from dragonfly2_tpu.utils import dflog

        log = dflog.get("manager.ca")
        cn, sans = certs.csr_identity(csr_pem)
        if self.enrollment_token:
            if not token or not hmac.compare_digest(self.enrollment_token, token):
                log.warning("refused certificate issuance cn=%r sans=%r: bad enrollment token", cn, sans)
                raise PermissionError("certificate issuance requires a valid enrollment token")
        ca_cert, ca_key = self._cluster_ca()
        leaf = certs.sign_csr(ca_cert, ca_key, csr_pem, validity_days=validity_days)
        log.info("issued certificate cn=%r sans=%r validity_days=%d", cn, sans, validity_days)
        return [leaf, ca_cert]

    # ------------------------------------------------------- observability

    def flight_recorder(self, last_n: int = 64) -> dict:
        """Flight-recorder dump for the operator (GET /api/v1/
        flight-recorder): this manager process's own recorder state plus
        every known scheduler's, collected over the same job RPC edge
        sync_peers uses (RemoteScheduler) or directly from in-proc
        services. A dead scheduler contributes an error entry, never a
        failed request — diagnosing a slow tick is exactly when parts of
        the cluster may be unhealthy."""
        from dragonfly2_tpu.telemetry import flight

        # registry_fallback=False: with an in-proc scheduler the global
        # recorder lookup would attribute ITS tick ring to the manager,
        # duplicating the per-scheduler sections below under a wrong label
        out: dict = {
            "manager": flight.dump(last_n=last_n, registry_fallback=False),
            "schedulers": {},
        }
        self._refresh_job_schedulers()
        if self.jobs is not None:
            for name, sched in self.jobs.schedulers.items():
                try:
                    if hasattr(sched, "flight_recorder"):
                        out["schedulers"][name] = sched.flight_recorder(last_n)
                    elif hasattr(sched, "flight_dump"):
                        out["schedulers"][name] = sched.flight_dump(last_n)
                except ConnectionError as e:
                    out["schedulers"][name] = {"error": str(e)}
        return out

    # ----------------------------------------------------------------- jobs

    def _refresh_job_schedulers(self) -> None:
        if self.jobs is not None and self.jobs_resolver is not None:
            try:
                self.jobs.update_schedulers(self.jobs_resolver())
            except Exception:  # noqa: BLE001 - job ops proceed on the old set
                logger.exception("job scheduler refresh failed")

    def create_job(self, body: dict) -> dict:
        job_type = body.get("type", "preheat")
        record = self.db.create(
            "jobs",
            {
                "type": job_type,
                "state": "PENDING",
                "args": body.get("args", {}),
                "user_id": body.get("user_id"),
                "result": {},
            },
        )
        self._refresh_job_schedulers()
        if self.jobs is not None and job_type == "preheat":
            from dragonfly2_tpu.cluster.jobs import PreheatRequest

            args = body.get("args", {})
            urls = args.get("urls") or ([args["url"]] if args.get("url") else [])
            result = self.jobs.create_preheat(
                PreheatRequest(
                    urls=urls,
                    tag=args.get("tag", ""),
                    application=args.get("application", ""),
                    piece_length=args.get("piece_length", 4 << 20),
                    # image-type preheat (manager/job/preheat.go PreheatArgs:
                    # type/username/password/platform/headers)
                    preheat_type=args.get("type", ""),
                    username=args.get("username", ""),
                    password=args.get("password", ""),
                    platform=args.get("platform", ""),
                    headers=args.get("headers"),
                )
            )
            record = self.db.update(
                "jobs",
                record["id"],
                {
                    "state": result.state.value,
                    "result": {"job_id": result.job_id, "task_ids": result.task_ids, **result.detail},
                },
            )
        elif self.jobs is not None and job_type == "sync_peers":
            result = self.jobs.sync_peers()
            self._merge_sync_peers(result)
            record = self.db.update(
                "jobs", record["id"], {"state": "SUCCESS", "result": result}
            )
        return record

    def get_job(self, record_id: int) -> dict:
        """Job record with LIVE state: a preheat stays PENDING until every
        fanned-out task completed on its scheduler, so GET /jobs/:id polls
        real progress (the reference's machinery group-state polling,
        test/e2e/manager/preheat.go)."""
        record = self.db.get("jobs", record_id)
        job_id = (record.get("result") or {}).get("job_id")
        # A persisted SUCCESS is terminal — never let a live recompute
        # (e.g. after a scheduler restart forgot the tasks) regress it.
        if record["state"] == "SUCCESS":
            return record
        if self.jobs is not None and record["type"] == "preheat" and job_id:
            self._refresh_job_schedulers()
            live = self.jobs.get(job_id)
            if live is None and record["result"].get("task_ids"):
                # durable record, no in-proc state: this manager restarted
                # since the job was created. Adopt the task list and poll
                # live task states — the job converges after recovery
                # instead of pending forever (VERDICT r4 next #6).
                self.jobs.adopt(job_id, record["result"]["task_ids"])
                live = self.jobs.get(job_id)
            if live is not None and live.state.value != record["state"]:
                record = self.db.update(
                    "jobs", record_id,
                    {"state": live.state.value,
                     "result": {**record["result"], **live.detail}},
                )
        return record

    def _merge_sync_peers(self, result: dict) -> None:
        """Merge the schedulers' announced hosts into the peers table
        (manager/job/sync_peers.go:230-255): upsert present hosts as
        active, flip departed ones inactive. Race-safe under the threaded
        REST server via the create/except-DuplicateRecord idiom the other
        registration paths use."""
        seen: set[tuple[str, str]] = set()
        for data in result.values():
            for h in data.get("announced_hosts", []):
                row = {
                    "host_name": h["hostname"],
                    "type": h["type"],
                    "ip": h["ip"],
                    "port": h["port"],
                    "download_port": h["download_port"],
                    "idc": h["idc"],
                    "location": h["location"],
                    "state": "active",
                }
                seen.add((h["hostname"], h["ip"]))
                try:
                    self.db.create("peers", row)
                except DuplicateRecord:
                    existing = self.db.find_one(
                        "peers", {"host_name": h["hostname"], "ip": h["ip"]}
                    )
                    if existing is not None:
                        self.db.update("peers", existing["id"], row)
        for r in self.db.list("peers", per_page=1_000_000):
            if (r["host_name"], r["ip"]) not in seen and r.get("state") == "active":
                self.db.update("peers", r["id"], {"state": "inactive"})

    # --------------------------------------------------- personal access tokens

    def create_personal_access_token(self, body: dict) -> dict:
        token = os.urandom(20).hex()
        return self.db.create(
            "personal_access_tokens",
            {
                "name": body["name"],
                "bio": body.get("bio", ""),
                "token": token,
                "scopes": body.get("scopes", []),
                "state": "active",
                "expired_at": body.get("expired_at", time.time() + 365 * 24 * 3600),
                "user_id": body.get("user_id"),
            },
        )

    # ------------------------------------------------------------ dynconfig

    def scheduler_dynconfig(self, scheduler_cluster_id: int) -> dict:
        """What a scheduler polls: its cluster config + client config +
        the cluster's seed peers (scheduler/config/dynconfig.go get)."""
        sc = self.db.get("scheduler_clusters", scheduler_cluster_id)
        seed_peers = []
        for spc in self.db.list("seed_peer_clusters", per_page=100000):
            if scheduler_cluster_id in spc.get("scheduler_cluster_ids", []):
                seed_peers += self.db.list("seed_peers", {"seed_peer_cluster_id": spc["id"]})
        return {
            "scheduler_cluster_config": sc.get("config", {}),
            "client_config": sc.get("client_config", {}),
            "seed_peers": seed_peers,
        }


_SOURCE_TABLES = {
    "scheduler": ("schedulers", "scheduler_cluster_id"),
    "seed_peer": ("seed_peers", "seed_peer_cluster_id"),
}


def _redact_user(record: dict) -> dict:
    return {k: v for k, v in record.items() if k != "encrypted_password"}
