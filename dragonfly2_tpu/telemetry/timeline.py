"""Soak timelines: behavior over simulated time, not end-of-run aggregates.

The megascale lab's reports answered "did the soak survive?" with final
counters — "pieces/s recovers after a scheduler kill" was asserted,
never measured. This module gives replay domains a deterministic
per-interval sampled gauge ring:

- :class:`TimelineRecorder` — one sample per simulated interval (the
  event clock, NOT wall time): pieces per interval, origin fraction,
  quarantine population, breaker-open count, re-announce backlog,
  per-region time-to-complete quantiles. The ring is plain data, rides
  the ``timeline`` array in BENCH_mega artifacts and the
  ``/debug/flight`` dump, and mirrors its latest sample into
  ``dragonfly_timeline_*`` Prometheus gauges for live scrapes.
- :class:`QuantileSketch` — a DDSketch-style log-bucketed streaming
  quantile sketch with a PROVABLE relative-error bound (the answer x̂
  for quantile q satisfies ``|x̂ - x_q| <= alpha * x_q`` against the
  exact quantile value x_q of the inserts), so per-region TTC
  percentiles can ride every sample without retaining per-download
  arrays. Deterministic: same inserts → same buckets → same answers.
- :func:`recovery_time` — the measurement the soak test asserts on:
  given a timeline, a fault round and a metric, how many simulated
  intervals until the metric recovers to ``threshold`` × its pre-fault
  baseline (and how deep the dip was).

Everything recorded here must be a pure function of the replay's event
clock and counters — two runs with the same (spec, seed) produce
IDENTICAL timeline arrays (pinned by tests/test_timeline.py and the
megascale determinism test).
"""

from __future__ import annotations

import math
import threading
import weakref
from collections import deque
from typing import Iterable

# ------------------------------------------------------- quantile sketch


class QuantileSketch:
    """Log-bucketed streaming quantile sketch (the DDSketch construction).

    Values land in bucket ``ceil(log_gamma(x))`` with
    ``gamma = (1 + alpha) / (1 - alpha)``; reporting the geometric
    midpoint of a bucket guarantees relative error <= ``alpha`` for
    every quantile of the positive inserts. Sub-``min_value`` and
    non-positive values collapse into a zero bucket (reported as 0.0 —
    exact for the simulated "instant completion" case). Memory is
    bounded by ``max_buckets``: when exceeded, the LOWEST buckets
    collapse into the zero bucket, so the tail quantiles the soak cares
    about (p50/p90/p99) keep their bound while tiny outliers lose
    resolution first.
    """

    __slots__ = ("alpha", "_gamma", "_log_gamma", "min_value",
                 "max_buckets", "_buckets", "_zero", "count")

    def __init__(self, relative_accuracy: float = 0.01,
                 min_value: float = 1e-6, max_buckets: int = 2048):
        if not 0.0 < relative_accuracy < 1.0:
            raise ValueError("relative_accuracy must be in (0, 1)")
        self.alpha = relative_accuracy
        self._gamma = (1.0 + relative_accuracy) / (1.0 - relative_accuracy)
        self._log_gamma = math.log(self._gamma)
        self.min_value = min_value
        self.max_buckets = max_buckets
        self._buckets: dict[int, int] = {}
        self._zero = 0
        self.count = 0

    def add(self, value: float, n: int = 1) -> None:
        self.count += n
        if value <= self.min_value:
            self._zero += n
            return
        idx = math.ceil(math.log(value) / self._log_gamma)
        self._buckets[idx] = self._buckets.get(idx, 0) + n
        if len(self._buckets) > self.max_buckets:
            self._collapse()

    def _collapse(self) -> None:
        for idx in sorted(self._buckets)[: len(self._buckets) - self.max_buckets]:
            self._zero += self._buckets.pop(idx)

    def extend(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(float(v))

    def quantile(self, q: float) -> float | None:
        """Value at quantile ``q`` in [0, 1], or None when empty."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        rank = q * (self.count - 1)
        seen = self._zero
        if rank < seen or not self._buckets:
            return 0.0
        for idx in sorted(self._buckets):
            seen += self._buckets[idx]
            if rank < seen:
                # geometric bucket midpoint: 2*g^i/(g+1) — the point whose
                # worst-case relative distance to any bucket member is alpha
                return 2.0 * self._gamma ** idx / (self._gamma + 1.0)
        idx = max(self._buckets)
        return 2.0 * self._gamma ** idx / (self._gamma + 1.0)

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "p50": _round_opt(self.quantile(0.50)),
            "p90": _round_opt(self.quantile(0.90)),
            "p99": _round_opt(self.quantile(0.99)),
        }


def _round_opt(v: float | None, nd: int = 2) -> float | None:
    return None if v is None else round(v, nd)


# ---------------------------------------------------------- the recorder


_TIMELINES: dict[str, "weakref.ref[TimelineRecorder]"] = {}
_timelines_mu = threading.Lock()


def register_timeline(name: str, recorder: "TimelineRecorder") -> None:
    """Weak named registry (mirrors flight.register_recorder) so the
    process-wide /debug/flight dump can find live timelines without a
    handle on the engine that owns them. Last registration wins."""
    with _timelines_mu:
        _TIMELINES[name] = weakref.ref(recorder)


def live_timelines() -> dict[str, "TimelineRecorder"]:
    out = {}
    with _timelines_mu:
        for name, ref in list(_TIMELINES.items()):
            rec = ref()
            if rec is None:
                del _TIMELINES[name]
            else:
                out[name] = rec
    return out


class TimelineRecorder:
    """Bounded ring of per-interval samples keyed by the EVENT clock.

    ``sample(t, values)`` appends one plain dict (``{"t": t, **values}``)
    and mirrors every scalar into the ``dragonfly_timeline_value`` gauge
    (labels: source, metric) for live scrapes; nested dicts (per-region
    sub-objects) ride the ring only. Samples must be derived from the
    replay's counters — never from wall clock — so paired-seed runs
    produce identical arrays.
    """

    __slots__ = ("name", "ring", "events", "_gauge", "_samples",
                 "_children", "__weakref__")

    def __init__(self, name: str, maxlen: int = 4096, registry=None):
        self.name = name
        self.ring: deque = deque(maxlen=maxlen)
        # annotated event marks: [{"t": ..., "event": ...}] — the fault
        # rounds recovery measurements anchor on
        self.events: list[dict] = []
        from dragonfly2_tpu.telemetry import metrics as _metrics
        from dragonfly2_tpu.telemetry.series import timeline_series

        reg = registry if registry is not None else _metrics.default_registry()
        s = timeline_series(reg)
        self._gauge = s.value
        self._samples = s.samples.labels(name)
        self._children: dict[str, object] = {}
        register_timeline(name, self)

    def sample(self, t: float, values: dict) -> None:
        entry = {"t": t}
        entry.update(values)
        self.ring.append(entry)
        self._samples.inc()
        for key, v in values.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                child = self._children.get(key)
                if child is None:
                    child = self._children[key] = self._gauge.labels(
                        self.name, key
                    )
                child.set(float(v))

    def mark_event(self, t: float, event: str) -> None:
        self.events.append({"t": t, "event": event})

    def timeline(self) -> list[dict]:
        return list(self.ring)

    def dump(self) -> dict:
        return {"name": self.name, "events": list(self.events),
                "samples": self.timeline()}


# ------------------------------------------------------ recovery measure


def recovery_time(
    timeline: list[dict],
    metric: str,
    event_t: float,
    baseline_window: int = 8,
    threshold: float = 0.9,
    horizon: int | None = None,
) -> dict:
    """Measure a fault's dip + recovery on one timeline metric.

    baseline = mean of the last ``baseline_window`` samples strictly
    before ``event_t``; the dip is the minimum over [event_t, recovery);
    recovery is the first sample at/after ``event_t`` whose value climbs
    back to ``threshold * baseline``. Returns plain data::

        {"baseline": float, "dip": float, "dip_ratio": float,
         "recovered": bool, "recovery_t": float | None,
         "recovery_intervals": float | None}

    ``recovery_intervals`` is in event-clock units (simulated intervals),
    so "recovers within N simulated minutes" is
    ``recovery_intervals * minutes_per_interval <= N``.
    """
    before = [s[metric] for s in timeline
              if s.get("t", 0) < event_t and metric in s]
    after = [(s["t"], s[metric]) for s in timeline
             if s.get("t", 0) >= event_t and metric in s]
    if horizon is not None:
        after = after[:horizon]
    base_vals = before[-baseline_window:]
    if not base_vals or not after:
        return {"baseline": None, "dip": None, "dip_ratio": None,
                "recovered": False, "recovery_t": None,
                "recovery_intervals": None}
    baseline = sum(base_vals) / len(base_vals)
    target = threshold * baseline
    dip = min(v for _, v in after)
    recovery_t = None
    for t, v in after:
        if v >= target:
            recovery_t = t
            break
        # the dip only counts until recovery; later troughs (the next
        # fault, the diurnal trough) are not THIS event's dip
    if recovery_t is not None:
        dip = min([v for t, v in after if t <= recovery_t] or [dip])
    return {
        "baseline": round(baseline, 3),
        "dip": round(dip, 3),
        "dip_ratio": round(dip / baseline, 4) if baseline else None,
        "recovered": recovery_t is not None,
        "recovery_t": recovery_t,
        "recovery_intervals": (
            round(recovery_t - event_t, 3) if recovery_t is not None else None
        ),
    }
