"""dflint red fixture: FLUSH001 (buffered column read without a valve,
in a public method and in a helper reachable dirty) and FLUSH002
(direct buffer inspection outside the valves)."""


class SchedulerService:  # the flush pass keys on the owner class name
    def __init__(self, state):
        self.state = state
        self._piece_buf: list = []

    def flush_piece_reports(self):
        self._piece_buf = []

    def stale_read(self):
        return self.state.peer_finished_count[0]  # <- FLUSH001

    def peek_buffer(self):
        return len(self._piece_buf)  # <- FLUSH002

    def covered_read(self):
        self.flush_piece_reports()
        return self.state.peer_finished_count[0]  # covered: no finding
