"""Native runtime bindings: build-on-first-use C++ kernels via ctypes.

The reference's runtime is compiled Go end to end; here the host-side hot
paths (ring lookups, DAG cycle checks, trace CSV parsing — see
native/dfnative.cpp for the reference citations) are C++ with Python
fallbacks. The shared library is compiled once with g++ into
``native/_build/`` and loaded with ctypes (no pybind11 in the image);
``DF_NATIVE=0`` disables it, and every consumer degrades to the pure
Python implementation when the toolchain or build is unavailable.
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
import subprocess
import threading

import numpy as np

logger = logging.getLogger(__name__)

_MASK64 = 0xFFFFFFFFFFFFFFFF

_SRC = pathlib.Path(__file__).resolve().parents[2] / "native" / "dfnative.cpp"
_BUILD_DIR = _SRC.parent / "_build"
_LIB_PATH = _BUILD_DIR / "libdfnative.so"

_lock = threading.Lock()
_build_lock = threading.Lock()  # serializes g++ invocations
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    with _build_lock:
        if _LIB_PATH.exists() and _LIB_PATH.stat().st_mtime >= _SRC.stat().st_mtime:
            return True  # another thread built it while we waited
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        tmp = _LIB_PATH.with_suffix(".tmp.so")
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", str(tmp), str(_SRC)]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            logger.warning("dfnative build failed to run: %s", e)
            return False
        if proc.returncode != 0:
            logger.warning("dfnative build failed:\n%s", proc.stderr)
            return False
        tmp.replace(_LIB_PATH)
        return True


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64p = ctypes.POINTER(ctypes.c_double)

    lib.df_fnv1a64.argtypes = [u8p, ctypes.c_int64]
    lib.df_fnv1a64.restype = ctypes.c_uint64
    lib.df_fnv1a64_batch.argtypes = [u8p, i64p, ctypes.c_int64, u64p]
    lib.df_fnv1a64_batch.restype = None
    lib.df_ring_pick_batch.argtypes = [u64p, ctypes.c_int64, u64p, ctypes.c_int64, i64p]
    lib.df_ring_pick_batch.restype = None
    lib.df_dag_reachable.argtypes = [u64p] + [ctypes.c_int64] * 4
    lib.df_dag_reachable.restype = ctypes.c_int32
    lib.df_dag_reachable_batch.argtypes = [u64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p, ctypes.c_int64, i32p]
    lib.df_dag_reachable_batch.restype = None
    lib.df_csv_parse_numeric.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, f64p, ctypes.c_int64,
    ]
    lib.df_csv_parse_numeric.restype = ctypes.c_int64
    return lib


def get_lib() -> ctypes.CDLL | None:
    """The loaded library, or None (callers fall back to Python).

    Never blocks a hot path on compilation: a fresh .so loads inline
    (milliseconds); a missing/stale one kicks a background build and this
    returns None until it lands. `ensure_built()` blocks for callers that
    want the native path up front (process start, tests)."""
    global _lib, _tried
    if os.environ.get("DF_NATIVE", "1") == "0":
        return None
    with _lock:
        if _tried:
            return _lib
        try:
            stale = (
                not _LIB_PATH.exists()
                or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime
            )
        except OSError:
            _tried = True
            return None
        if not stale:
            _tried = True
            try:
                _lib = _declare(ctypes.CDLL(str(_LIB_PATH)))
            except OSError as e:
                logger.warning("dfnative unavailable: %s", e)
                _lib = None
            return _lib
        # stale: build off the caller's thread; fall back meanwhile
        threading.Thread(target=_background_build, daemon=True).start()
        _tried = True
        return None


def _background_build() -> None:
    global _lib
    ok = _build()
    with _lock:
        if ok:
            try:
                _lib = _declare(ctypes.CDLL(str(_LIB_PATH)))
            except OSError as e:
                logger.warning("dfnative unavailable after build: %s", e)
                _lib = None


def ensure_built() -> bool:
    """Blocking: build+load now if needed. For process start and tests."""
    global _lib, _tried
    if os.environ.get("DF_NATIVE", "1") == "0":
        return False
    with _lock:
        if _lib is not None:
            return True
        try:
            stale = (
                not _LIB_PATH.exists()
                or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime
            )
            if stale and not _build():
                _tried = True
                return False
            _lib = _declare(ctypes.CDLL(str(_LIB_PATH)))
            _tried = True
            return True
        except OSError as e:
            logger.warning("dfnative unavailable: %s", e)
            _tried = True
            return False


def available() -> bool:
    return ensure_built()


# ------------------------------------------------------------------ helpers


def _as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def fnv1a64(data: bytes) -> int:
    """FNV-1a 64 of `data` — native when available, else pure Python.
    Both paths are the exact same function, so ring placements agree
    across mixed fleets."""
    lib = get_lib()
    if lib is not None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else (ctypes.c_uint8 * 1)()
        return int(lib.df_fnv1a64(buf, len(data)))
    h = 0xCBF29CE484222325
    for byte in data:
        h ^= byte
        h = (h * 0x100000001B3) & _MASK64
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _MASK64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _MASK64
    h ^= h >> 33
    return h


def fnv1a64_batch(keys: list[bytes]) -> np.ndarray:
    lib = get_lib()
    if lib is None:
        return np.asarray([fnv1a64(k) for k in keys], np.uint64)
    buf = b"".join(keys)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    out = np.zeros(len(keys), np.uint64)
    cbuf = (ctypes.c_uint8 * max(len(buf), 1)).from_buffer_copy(buf or b"\0")
    lib.df_fnv1a64_batch(cbuf, _as_ptr(offsets, ctypes.c_int64), len(keys), _as_ptr(out, ctypes.c_uint64))
    return out


def ring_pick_batch(ring_hashes: np.ndarray, key_hashes: np.ndarray) -> np.ndarray:
    """For each key hash, index into the sorted ring (bisect semantics)."""
    ring_hashes = np.ascontiguousarray(ring_hashes, np.uint64)
    key_hashes = np.ascontiguousarray(key_hashes, np.uint64)
    out = np.zeros(key_hashes.shape[0], np.int64)
    lib = get_lib()
    if lib is None:
        idx = np.searchsorted(ring_hashes, key_hashes, side="right")
        return idx % len(ring_hashes)
    lib.df_ring_pick_batch(
        _as_ptr(ring_hashes, ctypes.c_uint64), len(ring_hashes),
        _as_ptr(key_hashes, ctypes.c_uint64), len(key_hashes),
        _as_ptr(out, ctypes.c_int64),
    )
    return out


def dag_reachable(adj: np.ndarray, src: int, dst: int) -> bool | None:
    """Native BFS over the TaskDAG bitmatrix; None when unavailable.

    Vertex ids are bounds-checked HERE: the C++ kernel indexes the bit
    matrix unchecked, so an out-of-range id would be a heap write, not an
    error return."""
    lib = get_lib()
    if lib is None:
        return None
    adj = np.ascontiguousarray(adj, np.uint64)
    capacity, words = adj.shape
    if not (0 <= src < capacity and 0 <= dst < capacity):
        raise ValueError(f"vertex out of range [0, {capacity}): src={src} dst={dst}")
    result = lib.df_dag_reachable(_as_ptr(adj, ctypes.c_uint64), capacity, words, src, dst)
    if result < 0:
        return None  # native-side allocation failure
    return bool(result)


def dag_reachable_batch(
    adj: np.ndarray, srcs: np.ndarray, dsts: np.ndarray
) -> np.ndarray | None:
    """N reachability queries in ONE native call; None when unavailable.

    The scheduler tick asks ~15 cycle checks per pending peer — the
    per-call ctypes marshalling (pointer casts, lib lookup) costs more
    than the BFS itself, so the batch entry point amortizes it."""
    lib = get_lib()
    if lib is None:
        return None
    adj = np.ascontiguousarray(adj, np.uint64)
    srcs = np.ascontiguousarray(srcs, np.int64)
    dsts = np.ascontiguousarray(dsts, np.int64)
    if srcs.shape != dsts.shape or srcs.ndim != 1:
        raise ValueError("srcs/dsts must be equal-length 1-D arrays")
    capacity, words = adj.shape
    # bounds-check BEFORE the native call: the C++ kernel indexes the bit
    # matrix unchecked, so a stale/negative id would be a heap write
    if srcs.shape[0] and not (
        (srcs >= 0).all() and (srcs < capacity).all()
        and (dsts >= 0).all() and (dsts < capacity).all()
    ):
        raise ValueError(f"vertex out of range [0, {capacity}) in batch query")
    out = np.empty(srcs.shape[0], np.int32)
    lib.df_dag_reachable_batch(
        _as_ptr(adj, ctypes.c_uint64), capacity, words,
        _as_ptr(srcs, ctypes.c_int64), _as_ptr(dsts, ctypes.c_int64),
        srcs.shape[0], _as_ptr(out, ctypes.c_int32),
    )
    if (out < 0).any():
        return None  # native-side allocation failure
    return out.astype(bool)


def csv_parse_numeric(data: bytes, n_cols: int, skip_header: bool = True,
                      max_rows: int | None = None) -> np.ndarray | None:
    """Parse CSV bytes into an (rows, n_cols) float64 matrix; non-numeric
    fields become NaN. None when the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    if max_rows is None:
        max_rows = data.count(b"\n") + 1
    out = np.empty((max(max_rows, 1), n_cols), np.float64)
    rows = lib.df_csv_parse_numeric(
        data, len(data), n_cols, 1 if skip_header else 0,
        _as_ptr(out, ctypes.c_double), max_rows,
    )
    if rows < 0:
        return None
    return out[:rows]
