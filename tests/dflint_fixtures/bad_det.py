"""dflint red fixture: DET001 x2 (global rng + unseeded default_rng),
DET002 (wall clock), DET003 (set iteration) — in a file the test
configures as a decision module."""

import random
import time

import numpy as np


class Engine:
    def __init__(self):
        self.offline = set()

    def draw(self):
        return np.random.rand()  # <- DET001 (legacy global rng)

    def make_rng(self):
        return np.random.default_rng()  # <- DET001 (unseeded)

    def stamp(self):
        return time.time()  # <- DET002 (wall clock in decision path)

    def sweep(self):
        out = []
        for host in self.offline:  # <- DET003 (set iteration order)
            out.append(host)
        return out
