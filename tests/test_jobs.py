"""Preheat / sync-peers job tests (reference: manager+scheduler job layer)."""

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.jobs import JobManager, JobState, PreheatRequest
from dragonfly2_tpu.cluster.scheduler import SchedulerService


def seed_host(i):
    return msg.HostInfo(
        host_id=f"seed-{i}", hostname=f"seed-{i}", ip=f"10.1.0.{i}", host_type="super"
    )


def test_preheat_fans_out_by_hash_ring():
    schedulers = {"s1": SchedulerService(), "s2": SchedulerService()}
    jm = JobManager(schedulers, [seed_host(0), seed_host(1)])
    urls = [f"https://reg.example.com/layers/{i}" for i in range(12)]
    result = jm.create_preheat(PreheatRequest(urls=urls, tag="preheat"))
    assert result.state == JobState.SUCCESS
    assert len(result.task_ids) == 12
    counts = jm.sync_peers()
    total_tasks = sum(c["tasks"] for c in counts.values())
    total_peers = sum(c["peers"] for c in counts.values())
    assert total_tasks == 12
    assert total_peers == 12  # one seed registration per task
    # consistent hashing actually split the work
    assert counts["s1"]["tasks"] > 0 and counts["s2"]["tasks"] > 0
    # same urls preheat to the same schedulers (stable affinity)
    jm2 = JobManager({"s1": SchedulerService(), "s2": SchedulerService()}, [seed_host(0)])
    result2 = jm2.create_preheat(PreheatRequest(urls=urls, tag="preheat"))
    assert result2.task_ids == result.task_ids


def test_preheat_without_seeds_fails():
    jm = JobManager({"s1": SchedulerService()}, [])
    result = jm.create_preheat(PreheatRequest(urls=["https://e.com/x"]))
    assert result.state == JobState.FAILURE
    assert jm.get(result.job_id) is result
