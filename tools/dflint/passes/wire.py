"""WIRE001-WIRE004 — dfwire: static wire-contract verification of the
hand-rolled msgpack codec edge.

The reference repo's control plane is protobuf: ``buf lint`` + ``buf
breaking`` on the d7y.io api module give it message-closure and
schema-evolution safety for free. This repo's codec (rpc/wire.py) is a
dataclass-name-keyed registry with type-hint-driven conversion — no
codegen, no schema artifact — so the same guarantees have to be
machine-checked here. This pass is the ``buf lint`` half; the schema
snapshot + ``--breaking`` diff (tools/dflint/wireschema.py) is the
``buf breaking`` half; the skew replayer (tools/dflint/wirefuzz.py) is
the runtime tripwire, the PR-10/11 static-pass + runtime-backstop
pattern.

This is dflint's first CROSS-FILE pass: per-file ``run()`` collects
nothing, and everything happens in the ``finalize(contexts)`` hook the
core runner calls after all files are parsed — the producer/consumer
closure is a whole-program property.

Rules:

- ``WIRE001`` — producer/consumer closure. Four findings share the id:
  (a) a message constructed directly into a frame-sender call
  (``encode``/``write_frame``/``send``/``call``/``_call``) whose class
  is a package dataclass but never statically registered with the
  codec; (b) a registered top-level message type (not nested inside
  another message's fields) that is constructed nowhere in the package
  — a dead frame type; (c) a directly-sent registered type with no
  dispatch arm (``isinstance`` or dispatch-table key) anywhere — a
  frame nobody can consume; (d) an arm in one of the designated
  dispatch sites whose type has no live producer in the package. The
  v1 dialect's requests are produced — and its replies consumed — by
  the external v1 client generation, so those ride the argued
  ``EXTERNAL_PRODUCERS``/``EXTERNAL_CONSUMERS`` registries below (the
  D2H_ALLOWLIST idiom: every entry argues its case).
- ``WIRE002`` — codec representability. Every registered message
  field's type hint must land in the ``_to_plain``/``_from_plain``
  lattice (scalar / bytes / dataclass / enum / ``list[T]`` /
  ``tuple[T]`` or ``tuple[T, ...]`` / dict-of-scalars / Optional).
  Hints the decoder passes through unconverted — ``set``, ndarray,
  multi-element ``tuple[int, str]``, dataclass-vs-dataclass unions,
  ``dict`` values holding dataclasses/enums — are silent
  wrong-round-trip bugs and fail here before a frame ever travels.
  Nested message dataclasses are checked transitively.
- ``WIRE003`` — envelope propagation, the PR-3 "dl" re-anchor contract
  machine-checked: a serve loop that reads frames
  (``read_frame``) and routes them through a ``_dispatch*`` handler
  must re-anchor the propagated deadline budget
  (``resilience.deadline``/``deadline_s``) and continue the wire trace
  context (``trace_context``/``remote_parent``) somewhere in its
  enclosing class — otherwise every frame the handlers re-encode
  onward silently drops the budget and breaks the trace at this hop.
  Routing dispatch through the shared ``rpc/mux.dispatch_anchored``
  helper satisfies both halves at once (and is the preferred spelling
  for new request/response servers).
- ``WIRE004`` — v1-translation exhaustiveness: every member of the
  dialect's ``V1_REQUEST_TYPES`` tuple has an ``isinstance`` arm in
  ``_dispatch_v1`` (and no arm is unreachable — frames only reach it
  through that tuple's gate), and every scheduling response type the
  tick can emit (``V1_TRANSLATED_RESPONSES``) has a translation arm in
  ``to_peer_packet`` — the reference serves both protocol generations
  off one resource layer, and a response with no v1 translation is a
  v1 peer that silently never hears its scheduling verdict.

Like every dflint pass this lints a discipline, not a proof system:
producers/consumers are matched by class LEAF name (the codec's own
``__name__`` keying — satellite-enforced collision-free), and only
direct-constructor sends are producer sites. The wirefuzz roundtrip +
skew replay are the runtime backstop for what the approximation lets
through.
"""

from __future__ import annotations

import ast

from tools.dflint.core import FileContext, Finding, attr_chain
from tools.dflint.passes.collective import _functions_with_symbols, _walk_own

# frame-sender callable leaf -> positional index of the message argument
SENDER_ARG: dict[str, int] = {
    "encode": 0, "write_frame": 1, "send": 0, "call": 0, "_call": 0,
}

# Designated dispatch sites: (file suffix, function leaf name). These are
# THE consumption points of the wire protocol — rule (d) requires every
# arm here to have a live producer, and WIRE004 reads _dispatch_v1 from
# this set. A new RPC server adds its dispatch function here, which is
# what makes its arms part of the checked closure.
DISPATCH_SITES: frozenset[tuple[str, str]] = frozenset({
    ("rpc/server.py", "_dispatch"),
    ("rpc/server.py", "_dispatch_v1"),
    ("rpc/server.py", "_serve_conn"),
    ("rpc/inference.py", "_dispatch"),
    ("manager/rpc.py", "_dispatch"),
    ("rpc/mux.py", "handle_health_request"),
    ("cluster/scheduler.py", "handle"),
    ("rpc/client.py", "_read_loop"),
})

# Message types whose PRODUCER lives outside this package: the v1
# dialect's requests come from external v1-generation daemons (the
# compat surface exists exactly for peers this repo does not build), and
# the manager's CreateModel is driven by external publishers. Every
# entry argues its case; the fixture tests pin that an unargued orphan
# still fails.
EXTERNAL_PRODUCERS: dict[str, str] = {
    "V1PeerTaskRequest": "produced by external v1-generation daemons "
                         "(scheduler_client v1); tests/test_service_v1.py "
                         "drives the dialect end to end",
    "V1PieceResult": "external v1 daemons stream these "
                     "(ReportPieceResult); exercised by test_service_v1",
    "V1PeerResult": "external v1 daemons report final results; "
                    "exercised by test_service_v1",
    "V1PeerTarget": "external v1 daemons send LeaveTask; exercised by "
                    "test_service_v1",
    "V1AnnounceTaskRequest": "external dfcache-style importers announce "
                             "complete replicas; exercised by "
                             "test_service_v1",
    "CreateModelRequest": "external trainer publishers push models over "
                          "the manager edge (manager_server_v1.go:802 "
                          "parity); exercised by test_manager",
}

# Message types whose CONSUMER is the remote end of an external dialect:
# the v1 replies are decoded by v1-generation clients outside this repo.
EXTERNAL_CONSUMERS: dict[str, str] = {
    "V1RegisterResult": "decoded by external v1 clients "
                        "(RegisterPeerTask reply); pinned by "
                        "test_service_v1",
    "V1PeerPacket": "decoded by external v1 clients (the PeerPacket "
                    "scheduling stream); pinned by test_service_v1",
    "V1Task": "decoded by external v1 clients (StatTask reply); pinned "
              "by test_service_v1",
}

# The v2 scheduling responses svc.tick()/register can emit toward a
# peer — each MUST have a to_peer_packet translation arm or a v1 peer
# never hears its verdict (WIRE004). This is the design document the
# fixture pins; extend it when the tick grows a new response type.
V1_TRANSLATED_RESPONSES: tuple[str, ...] = (
    "NormalTaskResponse",
    "NeedBackToSourceResponse",
    "EmptyTaskResponse",
    "ScheduleFailure",
)

_SCALAR_HINTS = frozenset({
    "str", "int", "float", "bool", "bytes", "None", "object", "Any",
})
_LIST_HINTS = frozenset({"list", "List", "tuple", "Tuple", "Sequence"})
_DICT_HINTS = frozenset({"dict", "Dict", "Mapping"})
_BAD_HINTS = frozenset({
    "set", "Set", "frozenset", "FrozenSet", "ndarray", "Array",
    "complex", "Callable",
})
_ENUM_BASES = frozenset({"Enum", "IntEnum", "StrEnum", "IntFlag", "Flag"})
_OPTIONAL_HINTS = frozenset({"Optional"})
_UNION_HINTS = frozenset({"Union"})


class _ClassInfo:
    __slots__ = ("ctx", "node", "kind")

    def __init__(self, ctx: FileContext, node: ast.ClassDef, kind: str):
        self.ctx = ctx
        self.node = node
        self.kind = kind  # "dataclass" | "enum" | "plain"


class WirePass:
    name = "wire-contract"
    rules = ("WIRE001", "WIRE002", "WIRE003", "WIRE004")

    def __init__(
        self,
        dispatch_sites: frozenset[tuple[str, str]] | None = None,
        external_producers: dict[str, str] | None = None,
        external_consumers: dict[str, str] | None = None,
        translated_responses: tuple[str, ...] | None = None,
        dialect_suffix: str = "cluster/service_v1.py",
    ):
        self.dispatch_sites = (
            DISPATCH_SITES if dispatch_sites is None else dispatch_sites
        )
        self.external_producers = (
            EXTERNAL_PRODUCERS if external_producers is None
            else external_producers
        )
        self.external_consumers = (
            EXTERNAL_CONSUMERS if external_consumers is None
            else external_consumers
        )
        self.translated_responses = (
            V1_TRANSLATED_RESPONSES if translated_responses is None
            else translated_responses
        )
        self.dialect_suffix = dialect_suffix

    # ------------------------------------------------------------- runner

    def run(self, ctx: FileContext) -> list[Finding]:
        # every rule is a whole-program property; see finalize()
        return []

    def finalize(self, contexts: list[FileContext]) -> list[Finding]:
        facts = _Facts(contexts, self)
        findings: list[Finding] = []
        findings.extend(self._closure(facts))          # WIRE001
        findings.extend(self._representability(facts))  # WIRE002
        findings.extend(self._envelope(facts))         # WIRE003
        findings.extend(self._v1_exhaustive(facts))    # WIRE004
        return findings

    # ------------------------------------------------------------ WIRE001

    def _closure(self, facts: "_Facts") -> list[Finding]:
        findings = []
        # (a) sent-but-unregistered + (c) sent-but-unconsumed
        for ctx, node, leaf, symbol, def_line in facts.send_sites:
            info = facts.classes.get(leaf)
            if info is None or info.kind != "dataclass":
                continue  # not a package dataclass; out of scope
            if leaf not in facts.registered:
                findings.append(ctx.make_finding(
                    "WIRE001", node,
                    f"message '{leaf}' is encoded into a frame here but "
                    f"never registered with the wire codec "
                    f"(register_messages/register_module) — the remote "
                    f"decoder will reject the envelope",
                    symbol=symbol, def_line=def_line,
                ))
            elif leaf not in facts.consumed and \
                    leaf not in self.external_consumers:
                findings.append(ctx.make_finding(
                    "WIRE001", node,
                    f"message '{leaf}' is sent here but no dispatch arm "
                    f"or isinstance consumer exists anywhere in the "
                    f"package — a frame nobody can act on; add the arm "
                    f"or argue an EXTERNAL_CONSUMERS entry",
                    symbol=symbol, def_line=def_line,
                ))
        # (b) registered top-level types nobody constructs: dead frames
        for leaf, (reg_ctx, reg_node) in sorted(facts.registered.items()):
            if leaf in facts.nested_refs or leaf in self.external_producers:
                continue
            if leaf not in facts.constructed:
                findings.append(reg_ctx.make_finding(
                    "WIRE001", reg_node,
                    f"registered message type '{leaf}' is constructed "
                    f"nowhere in the package — a dead wire type; delete "
                    f"it or argue an EXTERNAL_PRODUCERS entry",
                    symbol=leaf,
                ))
        # (d) dispatch arms without a live producer
        for ctx, node, leaf, symbol, def_line in facts.dispatch_arms:
            if leaf in facts.constructed or leaf in self.external_producers:
                continue
            if leaf not in facts.classes:
                continue  # not a package class (typing gate etc.)
            findings.append(ctx.make_finding(
                "WIRE001", node,
                f"dispatch arm for '{leaf}' has no live producer in the "
                f"package — dead dispatch code; remove the arm or argue "
                f"an EXTERNAL_PRODUCERS entry",
                symbol=symbol, def_line=def_line,
            ))
        return findings

    # ------------------------------------------------------------ WIRE002

    def _representability(self, facts: "_Facts") -> list[Finding]:
        findings: list[Finding] = []
        seen: set[str] = set()
        queue = sorted(facts.registered)
        while queue:
            leaf = queue.pop()
            if leaf in seen:
                continue
            seen.add(leaf)
            info = facts.classes.get(leaf)
            if info is None or info.kind != "dataclass":
                continue
            for stmt in info.node.body:
                if not isinstance(stmt, ast.AnnAssign) or \
                        not isinstance(stmt.target, ast.Name):
                    continue
                problems, nested = _check_hint(stmt.annotation, facts)
                for nested_leaf in nested:
                    if nested_leaf not in seen:
                        queue.append(nested_leaf)
                for problem in problems:
                    findings.append(info.ctx.make_finding(
                        "WIRE002", stmt,
                        f"field '{leaf}.{stmt.target.id}': {problem}",
                        symbol=f"{leaf}.{stmt.target.id}",
                        def_line=info.node.lineno,
                    ))
        return findings

    # ------------------------------------------------------------ WIRE003

    def _envelope(self, facts: "_Facts") -> list[Finding]:
        findings = []
        for ctx, func, symbol, scope_refs in facts.serve_loops:
            if "deadline" not in scope_refs:
                findings.append(ctx.make_finding(
                    "WIRE003", func,
                    f"serve loop '{symbol}' dispatches decoded frames "
                    f"without re-anchoring the propagated deadline "
                    f"budget (rpc/wire.py \"dl\") — wrap the dispatch "
                    f"in resilience.deadline(getattr(request, "
                    f"'deadline_s', ...)) so onward frames carry the "
                    f"remaining budget",
                    symbol=symbol, def_line=func.lineno,
                ))
            if "trace" not in scope_refs:
                findings.append(ctx.make_finding(
                    "WIRE003", func,
                    f"serve loop '{symbol}' dispatches decoded frames "
                    f"without continuing the wire trace context — open "
                    f"the handler span with remote_parent=getattr("
                    f"request, 'trace_context', None) or the trace "
                    f"breaks at this hop",
                    symbol=symbol, def_line=func.lineno,
                ))
        return findings

    # ------------------------------------------------------------ WIRE004

    def _v1_exhaustive(self, facts: "_Facts") -> list[Finding]:
        findings: list[Finding] = []
        if facts.v1_request_types is None:
            return findings  # no dialect tuple in the scanned set
        tuple_ctx, tuple_node, declared = facts.v1_request_types
        arms = facts.v1_dispatch_arms
        if arms is not None:
            arm_ctx, arm_func, armed = arms
            for leaf in sorted(declared - set(armed)):
                findings.append(tuple_ctx.make_finding(
                    "WIRE004", tuple_node,
                    f"v1 request type '{leaf}' is declared in "
                    f"V1_REQUEST_TYPES but has no isinstance arm in "
                    f"_dispatch_v1 — the frame passes the gate and "
                    f"falls through untranslated",
                    symbol="V1_REQUEST_TYPES",
                ))
            for leaf, node in sorted(armed.items()):
                if leaf not in declared:
                    findings.append(arm_ctx.make_finding(
                        "WIRE004", node,
                        f"_dispatch_v1 arm for '{leaf}' is unreachable "
                        f"— frames only reach it through the "
                        f"V1_REQUEST_TYPES gate, which does not list "
                        f"this type",
                        symbol="_dispatch_v1", def_line=arm_func.lineno,
                    ))
        if facts.to_peer_packet is not None:
            pp_ctx, pp_func, translated = facts.to_peer_packet
            for leaf in self.translated_responses:
                if leaf not in translated:
                    findings.append(pp_ctx.make_finding(
                        "WIRE004", pp_func,
                        f"scheduling response '{leaf}' has no "
                        f"to_peer_packet translation arm — a v1 peer "
                        f"owed this verdict never hears it",
                        symbol="to_peer_packet", def_line=pp_func.lineno,
                    ))
        return findings


# ------------------------------------------------------- fact collection


class _Facts:
    """One whole-program scan: registered set, class index, producer and
    consumer sites, serve loops, and the v1 dialect tables."""

    def __init__(self, contexts: list[FileContext], conf: WirePass):
        self.conf = conf
        self.classes: dict[str, _ClassInfo] = {}
        # leaf -> (ctx, ClassDef) of the registration's class definition
        self.registered: dict[str, tuple[FileContext, ast.ClassDef]] = {}
        self.constructed: set[str] = set()
        self.consumed: set[str] = set()
        self.nested_refs: set[str] = set()
        # (ctx, node, leaf, symbol, def_line)
        self.send_sites: list = []
        self.dispatch_arms: list = []
        # (ctx, func, symbol, scope_refs)
        self.serve_loops: list = []
        self.v1_request_types: tuple | None = None
        self.v1_dispatch_arms: tuple | None = None
        self.to_peer_packet: tuple | None = None

        self._index_classes(contexts)
        self._resolve_registrations(contexts)
        for ctx in contexts:
            self._scan_file(ctx)
        self._collect_nested_refs()

    # -- class index ------------------------------------------------------

    def _index_classes(self, contexts: list[FileContext]) -> None:
        for ctx in contexts:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                kind = "plain"
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    chain = attr_chain(target) or ""
                    if chain.rsplit(".", 1)[-1] == "dataclass":
                        kind = "dataclass"
                for base in node.bases:
                    chain = attr_chain(base) or ""
                    if chain.rsplit(".", 1)[-1] in _ENUM_BASES:
                        kind = "enum"
                self.classes.setdefault(node.name, _ClassInfo(ctx, node, kind))

    # -- registration resolution -----------------------------------------

    def _resolve_registrations(self, contexts: list[FileContext]) -> None:
        by_suffix = {ctx.rel: ctx for ctx in contexts}

        def module_ctx(dotted: str) -> FileContext | None:
            suffix = dotted.replace(".", "/") + ".py"
            for rel, ctx in by_suffix.items():
                if rel.endswith(suffix):
                    return ctx
            return None

        for ctx in contexts:
            # import aliases: name -> dotted module path
            aliases: dict[str, str] = {}
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ImportFrom) and node.module:
                    for alias in node.names:
                        aliases[alias.asname or alias.name] = (
                            f"{node.module}.{alias.name}"
                        )
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        aliases[alias.asname or alias.name] = alias.name
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                leaf = chain.rsplit(".", 1)[-1] if chain else None
                if leaf == "register_messages":
                    for arg in node.args:
                        name = (attr_chain(arg) or "").rsplit(".", 1)[-1]
                        info = self.classes.get(name)
                        if info is not None:
                            self.registered.setdefault(
                                name, (info.ctx, info.node)
                            )
                elif leaf == "register_module":
                    target = self._registered_module(node, ctx, aliases,
                                                     module_ctx)
                    if target is None:
                        continue
                    for cnode in ast.walk(target.tree):
                        if isinstance(cnode, ast.ClassDef):
                            info = self.classes.get(cnode.name)
                            if info is not None and info.kind == "dataclass":
                                self.registered.setdefault(
                                    cnode.name, (info.ctx, info.node)
                                )

    @staticmethod
    def _registered_module(node: ast.Call, ctx: FileContext,
                           aliases: dict[str, str], module_ctx):
        if not node.args:
            return None
        arg = node.args[0]
        # the self-registration idiom: register_module(_sys.modules[__name__])
        if isinstance(arg, ast.Subscript):
            chain = attr_chain(arg.value) or ""
            if chain.rsplit(".", 1)[-1] == "modules":
                return ctx
            return None
        name = attr_chain(arg)
        if name is None:
            return None
        dotted = aliases.get(name, name)
        return module_ctx(dotted)

    # -- per-file scan ----------------------------------------------------

    def _scan_file(self, ctx: FileContext) -> None:
        designated = {
            fn for suffix, fn in self.conf.dispatch_sites
            if ctx.rel.endswith(suffix)
        }
        is_dialect = ctx.rel.endswith(self.conf.dialect_suffix)
        if is_dialect:
            self._scan_dialect_tuple(ctx)
        for func, symbol, _anc in _functions_with_symbols(ctx.tree):
            fn_leaf = symbol.rsplit(".", 1)[-1]
            refs = self._function_refs(func)
            if "read_frame" in refs["calls"] and refs["dispatch_ref"]:
                self.serve_loops.append(
                    (ctx, func, symbol, self._scope_refs(ctx, func))
                )
            arms = self._isinstance_arms(func)
            table = self._dispatch_table_keys(func)
            for leaf, node in {**arms, **table}.items():
                self.consumed.add(leaf)
                if fn_leaf in designated:
                    self.dispatch_arms.append(
                        (ctx, node, leaf, symbol, func.lineno)
                    )
            if fn_leaf == "_dispatch_v1" and fn_leaf in designated:
                self.v1_dispatch_arms = (ctx, func, arms)
            if fn_leaf == "to_peer_packet" and is_dialect:
                self.to_peer_packet = (ctx, func, set(arms))
            self._scan_sends(ctx, func, symbol)
        # module-scope construction/sends (rare, but registration files
        # construct defaults at import time)
        self._scan_constructions(ctx.tree)

    def _scan_dialect_tuple(self, ctx: FileContext) -> None:
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "V1_REQUEST_TYPES" \
                    and isinstance(node.value, ast.Tuple):
                leaves = {
                    (attr_chain(elt) or "").rsplit(".", 1)[-1]
                    for elt in node.value.elts
                }
                self.v1_request_types = (ctx, node, leaves - {""})

    def _scan_constructions(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain is not None:
                    self.constructed.add(chain.rsplit(".", 1)[-1])

    def _scan_sends(self, ctx: FileContext, func, symbol: str) -> None:
        for node in _walk_own(func):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            leaf = chain.rsplit(".", 1)[-1] if chain else None
            arg_pos = SENDER_ARG.get(leaf or "")
            if arg_pos is None or arg_pos >= len(node.args):
                continue
            arg = node.args[arg_pos]
            if not isinstance(arg, ast.Call):
                continue
            msg_chain = attr_chain(arg.func)
            if msg_chain is None:
                continue
            msg_leaf = msg_chain.rsplit(".", 1)[-1]
            if msg_leaf in self.classes:
                self.send_sites.append(
                    (ctx, arg, msg_leaf, symbol, func.lineno)
                )

    @staticmethod
    def _function_refs(func) -> dict:
        calls: set[str] = set()
        dispatch_ref = False
        for node in _walk_own(func):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain:
                    calls.add(chain.rsplit(".", 1)[-1])
            if isinstance(node, (ast.Name, ast.Attribute)):
                leaf = (attr_chain(node) or "").rsplit(".", 1)[-1]
                if leaf.startswith("_dispatch"):
                    dispatch_ref = True
        return {"calls": calls, "dispatch_ref": dispatch_ref}

    def _scope_refs(self, ctx: FileContext, func) -> set[str]:
        """{"deadline", "trace"} satisfied anywhere in the function's
        enclosing class (the re-anchor may live in the _dispatch helper
        the loop hands frames to), else in the function itself."""
        scope: ast.AST = func
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                for stmt in ast.walk(node):
                    if stmt is func:
                        scope = node
                        break
        refs: set[str] = set()
        for node in ast.walk(scope):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if node.value == "deadline_s":
                    refs.add("deadline")
                elif node.value == "trace_context":
                    refs.add("trace")
            elif isinstance(node, ast.Attribute):
                if node.attr == "deadline_s":
                    refs.add("deadline")
                elif node.attr == "trace_context":
                    refs.add("trace")
            elif isinstance(node, ast.Call):
                leaf = (attr_chain(node.func) or "").rsplit(".", 1)[-1]
                if leaf == "deadline":
                    refs.add("deadline")
            elif isinstance(node, ast.keyword) and node.arg == "remote_parent":
                refs.add("trace")
            # the blessed shared helper (rpc/mux.dispatch_anchored)
            # satisfies BOTH halves — one implementation to audit. It is
            # commonly passed as a to_thread callable, so a bare
            # reference counts, not just a direct call.
            if isinstance(node, (ast.Name, ast.Attribute)):
                if (attr_chain(node) or "").rsplit(".", 1)[-1] == \
                        "dispatch_anchored":
                    refs.update(("deadline", "trace"))
        return refs

    @staticmethod
    def _isinstance_arms(func) -> dict[str, ast.AST]:
        arms: dict[str, ast.AST] = {}
        for node in _walk_own(func):
            if not (isinstance(node, ast.Call)
                    and (attr_chain(node.func) or "") == "isinstance"
                    and len(node.args) == 2):
                continue
            second = node.args[1]
            elts = second.elts if isinstance(second, ast.Tuple) else [second]
            for elt in elts:
                chain = attr_chain(elt)
                if chain is None:
                    continue
                arms.setdefault(chain.rsplit(".", 1)[-1], node)
        return arms

    def _dispatch_table_keys(self, func) -> dict[str, ast.AST]:
        """Keys of handler-table dict literals (``{msg.X: self.handler}``)
        — a dict counts only when EVERY key resolves to a known class."""
        out: dict[str, ast.AST] = {}
        for node in _walk_own(func):
            if not isinstance(node, ast.Dict) or not node.keys:
                continue
            leaves = []
            for key in node.keys:
                chain = attr_chain(key) if key is not None else None
                leaf = chain.rsplit(".", 1)[-1] if chain else None
                if leaf is None or leaf not in self.classes:
                    leaves = []
                    break
                leaves.append((leaf, key))
            for leaf, key in leaves:
                out.setdefault(leaf, key)
        return out

    # -- nested field refs ------------------------------------------------

    def _collect_nested_refs(self) -> None:
        queue = sorted(self.registered)
        seen: set[str] = set()
        while queue:
            leaf = queue.pop()
            if leaf in seen:
                continue
            seen.add(leaf)
            info = self.classes.get(leaf)
            if info is None or info.kind != "dataclass":
                continue
            for stmt in info.node.body:
                if not isinstance(stmt, ast.AnnAssign):
                    continue
                for name in _hint_class_leaves(stmt.annotation):
                    if name in self.classes and name != leaf:
                        self.nested_refs.add(name)
                        queue.append(name)


# ------------------------------------------------ hint lattice (WIRE002)


def _hint_class_leaves(node: ast.AST) -> set[str]:
    """Every Name/Attribute leaf referenced anywhere in a type hint."""
    out: set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            # string annotations ("np.ndarray") re-parse as hints
            try:
                out |= _hint_class_leaves(
                    ast.parse(sub.value, mode="eval").body
                )
            except SyntaxError:
                pass
    return out


def _check_hint(node: ast.AST, facts: _Facts,
                inside_dict: bool = False) -> tuple[list[str], set[str]]:
    """(problems, nested dataclass leaves to check transitively).
    ``inside_dict`` marks positions the decoder passes through raw —
    a dataclass/enum there never converts back."""
    problems: list[str] = []
    nested: set[str] = set()
    if isinstance(node, ast.Constant):
        if node.value is None:
            return problems, nested
        if isinstance(node.value, str):
            try:
                parsed = ast.parse(node.value, mode="eval").body
            except SyntaxError:
                return problems, nested
            return _check_hint(parsed, facts, inside_dict)
        return problems, nested
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        parts = _flatten_union(node)
        return _check_union(parts, facts, inside_dict)
    if isinstance(node, (ast.Name, ast.Attribute)):
        leaf = (attr_chain(node) or "").rsplit(".", 1)[-1]
        return _check_leaf(leaf, node, facts, inside_dict)
    if isinstance(node, ast.Subscript):
        leaf = (attr_chain(node.value) or "").rsplit(".", 1)[-1]
        args = (
            list(node.slice.elts) if isinstance(node.slice, ast.Tuple)
            else [node.slice]
        )
        if leaf in _OPTIONAL_HINTS:
            return _check_hint(args[0], facts, inside_dict)
        if leaf in _UNION_HINTS:
            return _check_union(args, facts, inside_dict)
        if leaf in _LIST_HINTS:
            if leaf in ("tuple", "Tuple") and len(args) > 1 and not (
                len(args) == 2 and isinstance(args[1], ast.Constant)
                and args[1].value is Ellipsis
            ):
                problems.append(
                    "multi-element tuple hint — _from_plain converts "
                    "only the FIRST element type; model the record as a "
                    "nested dataclass instead"
                )
                return problems, nested
            sub_p, sub_n = _check_hint(args[0], facts, inside_dict)
            return problems + sub_p, nested | sub_n
        if leaf in _DICT_HINTS:
            if len(args) >= 2:
                sub_p, sub_n = _check_hint(args[1], facts, inside_dict=True)
                problems += sub_p
                nested |= sub_n
            return problems, nested
        if leaf in _BAD_HINTS:
            problems.append(
                f"'{leaf}' is outside the codec lattice — the decoder "
                f"passes it through unconverted (silent wrong "
                f"round-trip); use list/dict/dataclass shapes"
            )
            return problems, nested
        return problems, nested  # unknown generic: benefit of the doubt
    return problems, nested


def _flatten_union(node: ast.BinOp) -> list[ast.AST]:
    parts: list[ast.AST] = []
    for side in (node.left, node.right):
        if isinstance(side, ast.BinOp) and isinstance(side.op, ast.BitOr):
            parts.extend(_flatten_union(side))
        else:
            parts.append(side)
    return parts


def _check_union(parts: list[ast.AST], facts: _Facts,
                 inside_dict: bool) -> tuple[list[str], set[str]]:
    problems: list[str] = []
    nested: set[str] = set()
    non_none = [
        p for p in parts
        if not (isinstance(p, ast.Constant) and p.value is None)
        and (attr_chain(p) or "") != "None"
    ]
    if len(non_none) > 1:
        problems.append(
            "union of multiple payload types — _from_plain resolves "
            "Optional by taking the FIRST non-None arg, so the second "
            "alternative silently decodes as the first; split into "
            "distinct message fields"
        )
        return problems, nested
    for part in non_none:
        sub_p, sub_n = _check_hint(part, facts, inside_dict)
        problems += sub_p
        nested |= sub_n
    return problems, nested


def _check_leaf(leaf: str, node: ast.AST, facts: _Facts,
                inside_dict: bool) -> tuple[list[str], set[str]]:
    problems: list[str] = []
    nested: set[str] = set()
    if leaf in _SCALAR_HINTS:
        return problems, nested
    if leaf in _BAD_HINTS:
        problems.append(
            f"'{leaf}' is outside the codec lattice — the decoder "
            f"passes it through unconverted (silent wrong round-trip); "
            f"use list/dict/dataclass shapes"
        )
        return problems, nested
    if leaf in _LIST_HINTS or leaf in _DICT_HINTS:
        return problems, nested  # bare list/dict: scalar payload
    info = facts.classes.get(leaf)
    if info is None:
        return problems, nested  # unresolvable external: stay silent
    if info.kind == "dataclass":
        if inside_dict:
            problems.append(
                f"dataclass '{leaf}' inside a dict value — _from_plain "
                f"does not recurse into dict hints, so it decodes as a "
                f"plain dict; lift it into a typed field or a list"
            )
        else:
            nested.add(leaf)
        return problems, nested
    if info.kind == "enum":
        if inside_dict:
            problems.append(
                f"enum '{leaf}' inside a dict value — decodes as its "
                f"raw value, not the enum; lift it into a typed field"
            )
        return problems, nested
    problems.append(
        f"class '{leaf}' is neither a dataclass nor an enum — the codec "
        f"cannot reconstruct it; wrap the payload in a dataclass"
    )
    return problems, nested
