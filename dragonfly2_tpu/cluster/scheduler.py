"""Scheduler service: the v2 announce-stream business logic driving the
batched device evaluator.

Capability parity with scheduler/service/service_v2.go (AnnouncePeer
dispatch :89-204, handleRegisterPeerRequest :820 with size-scope fast
paths, piece/peer finished/failed handlers :947-1314, Reschedule :972) and
scheduler/scheduling/scheduling.go (ScheduleCandidateParents retry loop
:85-213, filter :500-571), plus the Download-record emission on completion
(service_v1.go:1418-1632).

TPU-first inversion (SURVEY.md §7 hard part (b)): instead of scoring one
peer at a time under a mutex, register/reschedule requests ACCUMULATE in a
pending queue; `tick()` gathers ALL of them into one (B, K) batch —
candidates sampled per-task from the DAG (LoadRandomPeers semantics),
probe RTTs gathered from the ProbeStore — and makes ONE device call, then
applies DAG edges and emits per-peer responses. p50 latency = tick period
+ one kernel, amortised across every concurrent request.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

import numpy as np

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.probes import ProbeStore
from dragonfly2_tpu.cluster.quarantine import QuarantineBoard
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.config.constants import CONSTANTS
from dragonfly2_tpu.graph.dag import TaskDAG
from dragonfly2_tpu.ops import evaluator as ev
from dragonfly2_tpu.ops.segment import pad_pow2
from dragonfly2_tpu.records.features import (
    host_numeric_features,
    idc_code,
    location_codes,
)
from dragonfly2_tpu.records.schema import (
    DownloadRecord,
    HostRecord,
    NetworkStat,
    ParentRecord,
    PieceRecord,
    TaskRecord,
)
from dragonfly2_tpu.records.storage import TraceStorage
from dragonfly2_tpu.state.cluster import ClusterState
from dragonfly2_tpu.telemetry.decisions import (
    ARM_CODES,
    OUTCOME_BACK_TO_SOURCE,
    OUTCOME_COMPLETED,
    OUTCOME_FAILED,
    compact_features as _ledger_features,
)
from dragonfly2_tpu.state.fsm import (
    HostType,
    InvalidTransition,
    PeerEvent,
    PeerState,
    TaskEvent,
    TaskState,
)
from dragonfly2_tpu.utils.digest import stable_hash64

logger = logging.getLogger(__name__)

# FSM display strings by raw state value: the batched apply builds one
# CandidateParent per kept parent, and constructing the PeerState enum per
# parent is measurable at B~1k rows per tick.
_STATE_DISPLAY = {int(s): s.display for s in PeerState}


@dataclasses.dataclass
class _Pending:
    peer_id: str
    blocklist: set[str]
    retries: int = 0
    enqueued_at: float = dataclasses.field(default_factory=time.monotonic)


@dataclasses.dataclass
class _PeerMeta:
    """Host-side per-peer bookkeeping beyond the SoA columns."""

    peer_id: str
    task_id: str
    host_id: str
    tag: str = ""
    application: str = ""
    registered_at: float = dataclasses.field(default_factory=time.monotonic)
    dag_slot: int = -1
    parents: dict[str, dict] = dataclasses.field(default_factory=dict)  # parent peer_id -> stats
    held_parents: set[str] = dataclasses.field(default_factory=set)  # upload slots held
    created_at_ns: int = 0


class SchedulerService:
    # flush batches at or under this many rows absorb through the scalar
    # twin (_absorb_piece_reports_small); larger ones amortise the numpy
    # machinery. Class-level so the equivalence test can force either path.
    _absorb_scalar_max = 64

    def __init__(
        self,
        config: Config | None = None,
        storage: TraceStorage | None = None,
        probes: ProbeStore | None = None,
        ml_evaluator=None,
        seed: int = 0,
        metrics_registry=None,
    ):
        from dragonfly2_tpu import native
        from dragonfly2_tpu.telemetry import default_registry
        from dragonfly2_tpu.telemetry.flight import PhaseRecorder
        from dragonfly2_tpu.telemetry.series import scheduler_series

        native.ensure_built()  # one-time; cycle checks ride the native path
        self.config = config or Config()
        sched = self.config.scheduler
        self.state = ClusterState(
            max_hosts=sched.max_hosts,
            max_tasks=sched.max_tasks,
            max_peers=getattr(sched, "max_peers", 0) or sched.max_hosts * 4,
            piece_bitset_words=getattr(sched, "piece_bitset_words", 64),
        )
        self.storage = storage
        self.probes = probes
        self.ml_evaluator = ml_evaluator
        self.rng = np.random.default_rng(seed)
        self._last_storage_flush = 0.0
        # In-product flight recorder for the tick's per-phase wall times
        # (telemetry/flight.py): ring of the last ticks the bench reads
        # its p50 breakdown from (VERDICT r3 weak #5) AND the Prometheus
        # phase histogram operators scrape — one source, so artifact and
        # production metrics cannot diverge. metrics_registry isolates the
        # PHASE series only: the dragonfly_*_jit_* families bind to the
        # process default registry at module import (ops/evaluator.py,
        # registry/serving.py) — read per-fn jit stats from flight_dump().
        reg = metrics_registry if metrics_registry is not None else default_registry()
        series = scheduler_series(reg)
        self.recorder = PhaseRecorder(
            histogram=series.schedule_phase,
            maxlen=4096,
            name="scheduler.tick",
        )
        self.tick_phases = self.recorder.ring  # same deque; legacy readers
        self.algorithm = self.config.evaluator.algorithm
        # "plugin": an externally supplied scorer replaces the linear blend
        # while every filter rule still applies (evaluator plugin.go; loader
        # contract: utils/plugins). The object must expose
        # `evaluate(feats: dict) -> (B, K) scores`.
        self.plugin_evaluator = None
        if self.algorithm == "plugin":
            from dragonfly2_tpu.utils import plugins

            evcfg = self.config.evaluator
            self.plugin_evaluator = plugins.load(
                evcfg.plugin_dir, "evaluator", evcfg.plugin_name
            )
        self._dags: dict[str, TaskDAG] = {}
        self._dag_capacity = _round_up_64(sched.max_peers_per_task)
        self._peer_meta: dict[str, _PeerMeta] = {}
        self._task_peers: dict[str, list[str]] = {}
        self._dag_slot_peer: dict[str, dict[int, str]] = {}
        # Columnar control plane (ROADMAP item 1): per-task int32 column
        # mapping DAG slot -> SoA peer row, maintained at register/leave,
        # so candidate fill resolves a sampled slot matrix to peer rows
        # with one fancy-index gather instead of two dict hops per
        # candidate. False = the per-peer loop path, kept as the
        # decision-equivalence oracle.
        self.vectorized_control = bool(getattr(sched, "vectorized_control", True))
        self._slot_pidx: dict[str, np.ndarray] = {}
        # Device-resident fused tick (ROADMAP item 2, ops/tick.py): the
        # hot columns mirror onto the device and candidate fill → feature
        # gather → scoring → selection run as ONE donated bucket-padded
        # dispatch per chunk; only DAG legality, blocklist resolution and
        # response emission stay host-side. Eligibility is decided once —
        # every input is fixed at construction: the ml and plugin arms
        # keep their own transports, and the probed-nt arm needs the
        # host-side RTT gather the fused program deliberately excludes
        # (nt WITHOUT probes zero-fills RTT on both paths, so it stays
        # eligible). fused_tick=False keeps the numpy fill + packed
        # transport as the decision-equivalence oracle.
        self.fused_tick = bool(getattr(sched, "fused_tick", True))
        self._tick_mirror = None
        self._fused_dirty_tasks: set[str] = set()
        if (
            self.fused_tick
            and self.vectorized_control
            and self.plugin_evaluator is None
            and not (self.ml_evaluator is not None and self.algorithm == "ml")
            and self.algorithm in ("default", "nt")
            and (self.probes is None or self.algorithm != "nt")
        ):
            from dragonfly2_tpu.ops.tick import TickMirror

            self._tick_mirror = TickMirror(self.state, self._dag_capacity)
        # Reverse of _PeerMeta.held_parents: parent peer_id -> children
        # holding one of its host's upload slots. _leave_peer used to scan
        # EVERY peer's held_parents to find them (~200 us per leave at 10k
        # hosts, the dominant GC cost); the reverse index makes it O(holders).
        self._children_of_parent: dict[str, set[str]] = {}
        # Buffered piece-report ingestion: piece_finished validates and
        # enqueues (peer_row, piece, length, cost_ns, parent_row) tuples;
        # stat mutation into the SoA columns happens as ONE vectorised
        # apply per tick (report_ingest phase) or at an explicit flush
        # valve (peer finish/fail, leave, GC, serving-graph reads) so no
        # reader ever observes stale columns. Single list of tuples: an
        # append is one atomic op under the GIL, so RPC threads can
        # enqueue while the tick thread swaps the buffer out. The RPC
        # server runs handlers AND tick under service.mu, but in-proc
        # drivers (simulator, bench_loop, tests) call tick() bare — the
        # small dedicated lock below covers the swap itself so a report
        # can never be lost or double-absorbed between an append and a
        # concurrent flush regardless of the driver.
        self._piece_buf: list[tuple] = []
        self._piece_buf_mu = threading.Lock()
        self._pending: dict[str, _Pending] = {}
        self._host_info: dict[str, msg.HostInfo] = {}
        # host_id -> (HostInfo identity, its HostRecord) — see _host_record
        self._host_record_cache: dict[str, tuple] = {}
        # Seed-peer trigger path (resource/seed_peer.go TriggerTask): seed
        # hosts announce with a non-normal type; first-seen tasks enqueue a
        # trigger the RPC edge pushes to one of them round-robin.
        self._seed_hosts: list[str] = []
        self._seed_rr = 0
        self.seed_triggers: list[msg.TriggerSeedRequest] = []
        # Serializes stream handlers vs the batched tick when the RPC edge
        # drives them from different threads (rpc/server.py). In-proc tests
        # and the simulator are single-threaded and unaffected.
        self.mu = threading.RLock()
        # Interval GC bookkeeping (pkg/gc/gc.go runner cadence): run_gc()
        # is called every tick by the live RPC server; each sweep fires
        # one full interval after construction (a ticker, not an eager
        # sweep — an instant host sweep would reap a freshly announced
        # idle host before its first peer registers).
        self._last_peer_gc = self._last_task_gc = self._last_host_gc = time.time()
        # Serving-graph accumulator: (child_host_slot, parent_host_slot)
        # -> [throughput_sum, piece_count], fed by every piece report.
        # The GNN ranker's quality signal travels on graph EDGES (training
        # builds edge_feats = log1p(mean throughput) from download traces,
        # records/features.py downloads_to_ranking_dataset) — serving
        # embeddings computed over an empty graph sever exactly that
        # signal, which measurably dropped the ml evaluator BELOW the rule
        # blend in the loop A/B. serving_graph_arrays() rebuilds the same
        # schema from the scheduler's own observations so MLEvaluator
        # refreshes see what the trainer saw.
        # keyed (child_slot, child_gen, parent_slot, parent_gen): the gens
        # come from _slot_gen so a recycled slot starts fresh history
        self._serving_edges: dict[tuple[int, int, int, int], list[float]] = {}
        self._serving_edge_cap = 1 << 20
        self._slot_owner: dict[int, str] = {}
        self._slot_gen: dict[int, int] = {}
        # Incremental-embed dirty frontier: host slots whose embedding
        # INPUTS changed since the last serving_graph_arrays() read — an
        # accumulated serving edge touches both endpoints, a host
        # re-announce may change its numeric features. The consumer
        # (MLEvaluator's background refresh) recomputes only these hosts'
        # k-hop in-neighborhoods when the frontier is small; structural
        # changes (host leave, slot generation bump) force a full sync
        # because the departed host's neighbors change without appearing
        # in any dirty set.
        self._dirty_host_slots: set[int] = set()
        self._serving_full_sync = True
        # Trust-boundary integrity (the digest chain the scheduler ATTESTS
        # to children): per-task piece md5s and whole-task sha256, written
        # ONLY from back-to-source reports — the origin fetch is the trust
        # anchor; parent-relayed digests are exactly what the chain
        # verifies. First writer wins: a later (possibly corrupt-parent)
        # report can never rewrite an attested digest. Distributed in
        # every NormalTaskResponse; dropped with the task's other maps.
        self._task_piece_digests: dict[str, dict[int, str]] = {}
        self._task_sha256: dict[str, str] = {}
        # chain length already sent per (task -> peer): a 10 GiB task has
        # thousands of piece md5s, and re-serializing the full map into
        # EVERY schedule/reschedule response is O(pieces x responses) on
        # the event loop — the child merges first-writer-wins, so it only
        # needs the chain again when it has GROWN since its last response
        self._chain_sent: dict[str, dict[str, int]] = {}
        self._series = series
        # Corrupt-parent quarantine: corruption-attributed piece failures
        # score against the parent HOST with time-decay; quarantined
        # hosts are skipped by the tick's candidate fill until the score
        # cools (cluster/quarantine.py).
        self.quarantine = QuarantineBoard(metrics=series)
        # Decision provenance ledger (telemetry/decisions.py): every
        # applied selection's candidate set + feature rows + scores +
        # chosen parent, joined to outcomes as terminal peer events
        # land, with the inactive arm's counterfactual shadow ranking
        # attached per tick. Resolvers bind to ClusterState only (no
        # cycle through the service); the weak name registry serves the
        # process-wide /debug/flight dump.
        self.decisions = None
        self._tick_counter = 0
        self.shadow_scoring = bool(getattr(sched, "shadow_scoring", True))
        # ml-as-shadow readiness gate: the ml packed program must be
        # compiled OFF the tick path before the shadow arm may use it —
        # warmup() warms it when a snapshot already serves; a snapshot
        # committing LATER triggers a one-shot background warm instead
        # of paying a multi-second XLA compile inside a serving tick.
        self._shadow_ml_ready = False
        self._shadow_warm_thread: threading.Thread | None = None
        # Streaming SLO engine on the WALL clock (telemetry/slo.py):
        # tick-latency, shadow-regret and breaker-census SLIs observed
        # per tick under mu, burn-rate alerts feeding the process
        # /debug/health verdict plane. The megascale lab runs its OWN
        # engine on the event clock (megascale/engine.py) — this one is
        # the live service's and never rides deterministic surfaces.
        self.slo = None
        self._slo_tick_budget_ms = float(
            getattr(sched, "slo_tick_budget_ms", 250.0)
        )
        self._slo_prev_shadow = (0, 0)  # (compared, disagree) counters
        self._slo_regret_losing = False
        if getattr(sched, "slo_enabled", True):
            from dragonfly2_tpu.telemetry.slo import SLOEngine, scheduler_slo_specs

            self.slo = SLOEngine(
                scheduler_slo_specs(self._slo_tick_budget_ms),
                name="scheduler.slo",
                minutes_per_unit=1.0,
                bucket_minutes=0.25,
                registry=reg,
            )
        if getattr(sched, "decision_ledger", True):
            from dragonfly2_tpu.telemetry.decisions import DecisionLedger

            st = self.state  # resolvers bind the state, not the service
            self.decisions = DecisionLedger(
                capacity=getattr(sched, "decision_ledger_capacity", 4096),
                k=sched.filter_parent_limit,
                limit=sched.candidate_parent_limit,
                registry=reg,
                name="scheduler.decisions",
                peer_resolver=lambda r: (
                    st._peer_id[r] if 0 <= r < st.max_peers else None
                ),
                host_resolver=lambda h: (
                    st.host_id_at(h) if h >= 0 else None
                ),
            )

    # ============================================================ messages

    def handle(self, request):
        """Dispatch one announce-stream message (service_v2.go:89-204)."""
        handlers = {
            msg.RegisterPeerRequest: self.register_peer,
            msg.DownloadPieceFinishedRequest: self.piece_finished,
            msg.DownloadPieceFailedRequest: self.piece_failed,
            msg.DownloadPeerFinishedRequest: self.peer_finished,
            msg.DownloadPeerFailedRequest: self.peer_failed,
            msg.DownloadPeerBackToSourceStartedRequest: self.back_to_source_started,
            msg.DownloadPeerBackToSourceFinishedRequest: self.back_to_source_finished,
            msg.DownloadPeerBackToSourceFailedRequest: self.back_to_source_failed,
            msg.RescheduleRequest: self.reschedule,
            msg.PeerHandoffRequest: self.peer_handoff,
        }
        handler = handlers.get(type(request))
        if handler is None:
            raise TypeError(f"unhandled message {type(request).__name__}")
        try:
            return handler(request)
        except InvalidTransition as e:
            # A protocol-illegal report (duplicate finish, failure after
            # success, …) answers with a failure response and leaves the
            # peer's state untouched — the reference logs the FSM error
            # and returns an error code (peer.go FSM.Event call sites);
            # raising here would kill the whole announce connection.
            peer_id = getattr(request, "peer_id", "")
            return msg.ScheduleFailure(peer_id, "InvalidTransition", str(e))

    def announce_host(self, host: msg.HostInfo) -> int:
        """AnnounceHost: upsert SoA host row (service_v2 AnnounceHost).

        Takes service.mu itself (reentrant under the RPC edge's dispatch
        lock): the LOCK001 sweep showed the announce path mutating
        mu-guarded state (_host_info, _serving_full_sync, the dirty
        frontier) bare when driven in-proc, racing the refresh worker's
        serving_graph_arrays read."""
        with self.mu:
            return self._announce_host_locked(host)

    def _announce_host_locked(self, host: msg.HostInfo) -> int:
        self._host_info[host.host_id] = host
        if host.host_type != "normal" and host.host_id not in self._seed_hosts:
            self._seed_hosts.append(host.host_id)
        rec = self._host_record(host)
        slot = self.state.upsert_host(
            host.host_id,
            id_hash=stable_hash64(host.host_id),
            host_type=HostType.from_name(host.host_type),
            idc=idc_code(host.idc),
            location=location_codes(host.location),
            upload_limit=host.concurrent_upload_limit,
            upload_count=host.upload_count,
            upload_failed=host.upload_failed_count,
            numeric=host_numeric_features(rec),
        )
        # Slot GENERATION bump on owner change: serving-edge accumulator
        # entries are keyed (slot, gen) so a slot recycled between
        # embedding refreshes cannot hand its previous occupant's
        # throughput history to the new host (the read-time alive filter
        # only catches slots observed dead AT refresh time).
        prev_owner = self._slot_owner.get(slot)
        if prev_owner != host.host_id:
            self._slot_owner[slot] = host.host_id
            self._slot_gen[slot] = self._slot_gen.get(slot, 0) + 1
            if prev_owner is not None:
                # RECYCLED slot: its old-generation edges vanish from the
                # serving graph, which silently changes its NEIGHBORS'
                # aggregates too — incremental embed can't see that. A
                # first-time slot has no such ghosts: its row is dirtied
                # below, future edges dirty both endpoints, and a table
                # GROWN for it is caught by the refresh's shape guard —
                # so plain joins stay on the incremental path.
                self._serving_full_sync = True
        self._dirty_host_slots.add(int(slot))  # numeric features may change
        return slot

    def leave_host(self, host_id: str) -> None:
        """LeaveHost: drop the host and every peer on it (service_v2)."""
        with self.mu:
            for peer_id, meta in list(self._peer_meta.items()):
                if meta.host_id == host_id:
                    self._leave_peer(peer_id)
            self._drop_host(host_id)

    def leave_hosts_batch(self, host_ids) -> int:
        """Bulk LeaveHost (megascale bulk API, the leave twin of
        `register_peers_batch`): one pass over the peer table groups
        departing peers by host, then each host leaves exactly as
        sequential `leave_host` calls would — same per-host peer order
        (peer-table insertion order), same side effects. The per-call
        `leave_host` scans EVERY peer per host; a rolling-upgrade churn
        wave at 10^5 hosts retires thousands of hosts per round, and the
        O(hosts x peers) rescan was the wall. Returns hosts dropped."""
        with self.mu:
            targets = [h for h in host_ids if h in self._host_info]
            if not targets:
                return 0
            target_set = set(targets)
            by_host: dict[str, list[str]] = {}
            for peer_id, meta in self._peer_meta.items():
                if meta.host_id in target_set:
                    by_host.setdefault(meta.host_id, []).append(peer_id)
            for host_id in targets:
                for peer_id in by_host.get(host_id, ()):
                    self._leave_peer(peer_id)
                self._drop_host(host_id)
            return len(targets)

    def _drop_host(self, host_id: str) -> None:
        """Host-table teardown shared by the single and batch leave paths
        (the peers must already be gone)."""
        self.state.remove_host(host_id)
        self._host_info.pop(host_id, None)
        self.quarantine.drop(host_id)
        if host_id in self._seed_hosts:
            self._seed_hosts.remove(host_id)
        # its serving edges die with it; neighbors' aggregates change
        self._serving_full_sync = True

    def _pick_seed_host(self, requester: msg.HostInfo) -> str:
        """Seed host for a cold task's trigger: plain round-robin by
        default (seed_peer.go TriggerTask); with
        `scheduler.region_aware_seeds` the round-robin is scoped to seed
        peers in the requester's region (first location element) when any
        exist, so a megascale WAN topology's origin fetches land on the
        in-region seeds instead of paying a WAN hop (ISSUE: seed peers
        per region)."""
        pool = self._seed_hosts
        if getattr(self.config.scheduler, "region_aware_seeds", False):
            region = requester.location.split("|", 1)[0]
            local = [
                h for h in self._seed_hosts
                if self._host_info.get(h) is not None
                and self._host_info[h].location.split("|", 1)[0] == region
            ]
            if local:
                pool = local
        seed_host = pool[self._seed_rr % len(pool)]
        self._seed_rr += 1
        return seed_host

    def register_peer(self, req: msg.RegisterPeerRequest):
        """handleRegisterPeerRequest (+ handleResource): upsert host/task/
        peer, size-scope dispatch, queue normal peers for scheduling.

        Takes service.mu itself (reentrant under the RPC edge and
        register_peers_batch): the register path mutates the seed-trigger
        queue, task maps and the pending queue — all mu-guarded on every
        other path."""
        with self.mu:
            return self._register_peer_locked(req)

    def _register_peer_locked(self, req: msg.RegisterPeerRequest):
        if req.host.host_id not in self._host_info:
            self.announce_host(req.host)
        host_idx = self.state.host_index(req.host.host_id)
        total_pieces = req.total_piece_count
        if total_pieces == 0 and req.content_length > 0:
            total_pieces = -(-req.content_length // req.piece_length)
        task_idx = self.state.upsert_task(
            req.task_id,
            total_pieces=max(total_pieces, 0),
            content_length=max(req.content_length, 0),
            back_to_source_limit=self.config.scheduler.retry_back_to_source_limit,
        )
        if self.state.task_state[task_idx] != int(TaskState.RUNNING):
            self.state.task_event(task_idx, TaskEvent.DOWNLOAD)

        # First peer on a task triggers a seed download so the cluster gets
        # a parent (service_v1.go:824 triggerTask -> seed_peer.go:101;
        # priority 1 = back-to-source directly, skip the seed). The queue
        # is bounded so it cannot grow without limit when no RPC edge
        # drains it (in-proc simulator).
        if (
            req.url
            and self._seed_hosts
            and req.priority != 1
            and len(self.seed_triggers) < 1024
            and not self._task_peers.get(req.task_id)
            and req.host.host_id not in self._seed_hosts
        ):
            seed_host = self._pick_seed_host(req.host)
            self.seed_triggers.append(
                msg.TriggerSeedRequest(
                    host_id=seed_host,
                    task_id=req.task_id,
                    url=req.url,
                    piece_length=req.piece_length,
                    tag=req.tag,
                    application=req.application,
                )
            )

        # Re-register of a known peer is load-not-create (service_v2
        # handleResource): keep its FSM/DAG state, just leave it queued.
        # A mid-task re-announce may carry pieces the peer fetched while
        # this scheduler wasn't listening (failover round-trip) — adopt
        # them instead of scheduling them again.
        if self.state.peer_index(req.peer_id) is not None:
            idx = self.state.peer_index(req.peer_id)
            if req.finished_pieces:
                self.state.adopt_pieces(idx, req.finished_pieces)
                if self.decisions is not None:
                    # re-announce with kept progress = failover recovery;
                    # mark it on the peer's latest recorded decision
                    self.decisions.mark_failover(req.peer_id)
            if self.state.peer_state[idx] == int(PeerState.RUNNING):
                self._pending.setdefault(
                    req.peer_id, _Pending(peer_id=req.peer_id, blocklist=set())
                )
            return None

        # Slot allocation BEFORE any state mutation: a full task DAG (hot
        # task, every slot held by a live peer) degrades to a refusal the
        # daemon answers with back-to-source — not a crashed register
        # leaving a half-created peer.
        dag = self._task_dag(req.task_id)
        slot = self._alloc_dag_slot(req.task_id, req.peer_id, dag)
        if slot < 0:
            return msg.ScheduleFailure(
                req.peer_id, "ResourceExhausted",
                f"task {req.task_id} peer DAG full ({dag.capacity})",
            )
        try:
            peer_idx = self.state.add_peer(req.peer_id, task_idx, host_idx)
        except Exception:
            # peer-table overflow (state.CapacityError) must not leak the
            # just-allocated DAG slot: nothing references it yet (no
            # _peer_meta), so _leave_peer could never reclaim it
            dag.delete_vertex(slot)
            self._dag_slot_peer.get(req.task_id, {}).pop(slot, None)
            return msg.ScheduleFailure(
                req.peer_id, "ResourceExhausted", "peer table full"
            )
        self._peer_meta[req.peer_id] = _PeerMeta(
            peer_id=req.peer_id,
            task_id=req.task_id,
            host_id=req.host.host_id,
            tag=req.tag,
            application=req.application,
            dag_slot=slot,
            created_at_ns=time.time_ns(),
        )
        self._slot_pidx[req.task_id][slot] = peer_idx
        if self._tick_mirror is not None:
            self._fused_dirty_tasks.add(req.task_id)
        self._task_peers.setdefault(req.task_id, []).append(req.peer_id)

        scope = (
            msg.SizeScope.of(req.content_length, req.piece_length)
            if req.content_length >= 0
            else msg.SizeScope.NORMAL
        )
        if scope == msg.SizeScope.EMPTY:
            self.state.peer_event(peer_idx, PeerEvent.REGISTER_EMPTY)
            return msg.EmptyTaskResponse(peer_id=req.peer_id)
        if scope == msg.SizeScope.TINY:
            # v2 semantics: tiny tasks fetch inline from a peer's download
            # port; scheduling still picks who serves it.
            self.state.peer_event(peer_idx, PeerEvent.REGISTER_TINY)
        elif scope == msg.SizeScope.SMALL:
            self.state.peer_event(peer_idx, PeerEvent.REGISTER_SMALL)
        else:
            self.state.peer_event(peer_idx, PeerEvent.REGISTER_NORMAL)
        self.state.peer_event(peer_idx, PeerEvent.DOWNLOAD)
        # Mid-task re-announce adoption (failure-domain failover): the
        # peer's kept progress becomes scheduler state — it will only be
        # scheduled for the pieces it misses, and its held pieces make it
        # a servable parent immediately. A fire-and-forget announce
        # (priority 1: a seed answering a trigger for a task it has
        # cached, daemon _announce_completed) holding EVERY piece goes
        # straight to Succeeded — it is a parent, not a download, and
        # nobody is waiting for a response. A priority-0 register stays
        # queued even when complete: its conductor blocks on the response
        # stream, so silence here would strand it for schedule_timeout.
        if req.finished_pieces:
            self.state.adopt_pieces(peer_idx, req.finished_pieces)
            total = self.state.task_total_pieces[task_idx]
            if (
                req.priority == 1
                and total > 0
                # peer_idx is a FRESH SoA row allocated in this very call:
                # buffered reports cannot name it (_leave_peer flushes
                # before any row free, so the buffer never aliases a
                # recycled index) — the count below cannot be stale
                # dflint: waive[FLUSH001] -- fresh row from this call; buffer cannot alias it (leave flushes before row free)
                and self.state.peer_finished_count[peer_idx] >= total
            ):
                self.state.peer_event(peer_idx, PeerEvent.DOWNLOAD_SUCCEEDED)
                return None  # nothing to schedule; it serves, not fetches
        self._pending[req.peer_id] = _Pending(peer_id=req.peer_id, blocklist=set())
        return None  # response arrives from tick()

    def register_peers_batch(self, reqs) -> list:
        """Bulk RegisterPeer (megascale bulk API): one lock acquisition
        and one call boundary for a whole arrival batch instead of one
        per peer — the event-batch simulation engine registers a round's
        diurnal-arrival wave through here. Semantically identical to
        sequential `register_peer` calls in list order (same slot
        allocation, same seed-trigger round-robin); returns the
        per-request responses (None = queued for the tick)."""
        with self.mu:
            return [self.register_peer(req) for req in reqs]

    def peer_handoff(self, req: msg.PeerHandoffRequest):
        """PeerHandoffRequest: adopt an in-flight peer released by another
        scheduler replica whose hashring ownership of the task moved
        (fleet crash/restart/rolling upgrade). Degrades to the exact
        failover re-announce a daemon would perform on its own — a
        RegisterPeerRequest carrying the kept pieces — so the PR-3
        adoption path (`adopt_pieces`, load-not-create) does all the
        work and an N-1 receiver that ignores the provenance fields
        still lands the peer correctly."""
        return self.register_peer(
            msg.RegisterPeerRequest(
                peer_id=req.peer_id,
                task_id=req.task_id,
                host=req.host,
                url=req.url,
                content_length=req.content_length,
                piece_length=req.piece_length,
                total_piece_count=req.total_piece_count,
                tag=req.tag,
                application=req.application,
                finished_pieces=req.finished_pieces,
            )
        )

    def reschedule(self, req: msg.RescheduleRequest):
        """RescheduleRequest (:972): drop given parents, re-queue."""
        with self.mu:
            meta = self._peer_meta.get(req.peer_id)
            if meta is None:
                return msg.ScheduleFailure(req.peer_id, "NotFound", "unknown peer")
            self._release_parent_slots(req.peer_id)
            dag = self._task_dag(meta.task_id)
            dag.delete_in_edges(meta.dag_slot)
            pending = self._pending.get(req.peer_id) or _Pending(peer_id=req.peer_id, blocklist=set())
            pending.blocklist |= set(req.candidate_parent_ids)
            pending.retries += 1
            self._pending[req.peer_id] = pending
            return None

    def piece_finished(self, req: msg.DownloadPieceFinishedRequest):
        """DownloadPieceFinished (:1102): validate + enqueue. The stat
        mutation (child bitset + cost ring, parent host upload counters,
        serving-edge accumulation) is BUFFERED and absorbed into the SoA
        columns as one vectorised batch per tick (`report_ingest` phase)
        — the reference mutates per report under a mutex
        (service_v2.go:1102); at replay rates the per-report Python/numpy
        scalar ops were the largest host-side cost between device calls.
        Only the digest-chain adoption stays inline: it needs the peer's
        FSM state AT REPORT TIME (back-to-source gate, trust-boundary
        PR), and origin reports are rare. Runs under service.mu (the
        digest chain and peer meta are mu-guarded state); the buffer
        append additionally takes _piece_buf_mu so a bare-driven tick's
        concurrent swap stays safe either way."""
        with self.mu:
            idx = self.state.peer_index(req.peer_id)
            if idx is None:
                return msg.ScheduleFailure(req.peer_id, "NotFound", "unknown peer")
            if (not req.parent_peer_id and req.digest
                    and self.state.peer_state[idx] == int(PeerState.BACK_TO_SOURCE)):
                # origin-fetched piece: its md5 joins the task's attested
                # digest chain (first writer wins — re-fetches and racing
                # seeds cannot rewrite an attested entry). Gated on the
                # scheduler's OWN record that this peer is mid-back-to-source
                # (it sent BackToSourceStarted): a peer merely omitting
                # parent_peer_id cannot forge "origin" digests and poison the
                # chain against honest parents.
                meta = self._peer_meta.get(req.peer_id)
                if meta is not None:
                    chain = self._task_piece_digests.setdefault(meta.task_id, {})
                    chain.setdefault(int(req.piece_number), req.digest)
            pidx = -1
            if req.parent_peer_id and req.peer_id in self._peer_meta:
                p = self.state.peer_index(req.parent_peer_id)
                if p is not None:
                    pidx = int(p)
            with self._piece_buf_mu:
                self._piece_buf.append(
                    (int(idx), int(req.piece_number), int(req.length),
                     float(req.cost_ns), pidx)
                )
            return None

    def pieces_finished_batch(
        self,
        peer_id: str,
        piece_numbers,
        lengths,
        costs_ns,
        parent_ids: list[str] = (),
        parent_sel=None,
    ):
        """Bulk DownloadPieceFinished ingestion: one call enqueues a whole
        wave of piece reports for `peer_id`. `parent_sel[i]` indexes
        `parent_ids` (or -1 for origin/no parent) so the per-parent id
        resolution happens once per distinct parent, not once per piece.
        The simulator's event loop reports through here; the columns
        absorb everything at the next flush exactly like per-report
        `piece_finished` calls would have. Origin digest-chain adoption is
        NOT supported on this path — callers carrying digests use
        `piece_finished`."""
        with self.mu:
            idx = self.state.peer_index(peer_id)
            if idx is None:
                return msg.ScheduleFailure(peer_id, "NotFound", "unknown peer")
            idx = int(idx)
            has_meta = peer_id in self._peer_meta
            pmap = []
            for pid in parent_ids:
                p = self.state.peer_index(pid) if has_meta else None
                pmap.append(-1 if p is None else int(p))
            if parent_sel is None:
                parent_sel = (-1,) * len(piece_numbers)
            rows = [
                (idx, int(piece), int(length), float(cost),
                 pmap[sel] if 0 <= sel < len(pmap) else -1)
                for piece, length, cost, sel in zip(
                    piece_numbers, lengths, costs_ns, parent_sel
                )
            ]
            with self._piece_buf_mu:
                self._piece_buf.extend(rows)
            return None

    def flush_piece_reports(self) -> int:
        """Absorb every buffered piece report into the SoA columns now.
        Called automatically at the tick's report_ingest phase and at
        every flush valve (peer finish/fail, leave, GC sweeps,
        serving-graph reads); public so tests and out-of-band readers can
        force column visibility."""
        with self.mu:
            return self._absorb_piece_reports()

    def _absorb_piece_reports(self) -> int:
        """One vectorised apply of the buffered reports: bitset + cost
        ring + liveness via state.record_pieces_batch, parent-host upload
        counters via one scatter-add, serving-edge/dirty-frontier
        accumulation grouped per (child_host, parent_host), and the
        capped per-(child, parent) DownloadRecord stats. Equivalent to
        the old per-report mutation applied in buffer order."""
        if not self._piece_buf:
            return 0
        with self._piece_buf_mu:
            buf = self._piece_buf
            if not buf:
                return 0
            self._piece_buf = []
        n = len(buf)
        if n <= self._absorb_scalar_max:
            return self._absorb_piece_reports_small(buf)
        cols = np.asarray(buf, np.float64)
        peer = cols[:, 0].astype(np.int64)
        piece = cols[:, 1].astype(np.int64)
        length = cols[:, 2].astype(np.int64)
        cost = cols[:, 3]
        parent = cols[:, 4].astype(np.int64)
        st = self.state
        st.record_pieces_batch(peer, piece, cost)
        hasp = parent >= 0
        if not hasp.any():
            return n
        p = parent[hasp]
        c = peer[hasp]
        plen = length[hasp]
        pcost = cost[hasp]
        phost = st.peer_host[p].astype(np.int64)
        np.add.at(st.host_upload_count, phost, 1)
        # serving-edge accumulation, grouped by (child_host, parent_host)
        chost = st.peer_host[c].astype(np.int64)
        pos = pcost > 0
        if pos.any():
            key = chost[pos] * st.max_hosts + phost[pos]
            uniq, first, inv = np.unique(
                key, return_index=True, return_inverse=True
            )
            tput_sum = np.zeros(uniq.size)
            np.add.at(tput_sum, inv, plen[pos] / (pcost[pos] / 1e9))
            cnt = np.bincount(inv, minlength=uniq.size)
            # first-occurrence order, not numeric key order: under cap
            # pressure the per-report path admitted whichever NEW pair was
            # reported first — replay that admission order exactly
            for i in np.argsort(first):
                c_slot = int(uniq[i] // st.max_hosts)
                p_slot = int(uniq[i] % st.max_hosts)
                k4 = (c_slot, self._slot_gen.get(c_slot, 0),
                      p_slot, self._slot_gen.get(p_slot, 0))
                acc = self._serving_edges.get(k4)
                if acc is None and len(self._serving_edges) < self._serving_edge_cap:
                    acc = self._serving_edges[k4] = [0.0, 0]
                if acc is not None:
                    acc[0] += float(tput_sum[i])
                    acc[1] += int(cnt[i])
                    # the edge update changes BOTH endpoints' embedding
                    # inputs — mark them for the incremental refresh
                    self._dirty_host_slots.add(c_slot)
                    self._dirty_host_slots.add(p_slot)
        # per-(child, parent) DownloadRecord stats: bytes sum vectorised,
        # PieceRecords capped at 10 per pair like the per-report path
        pair_key = c * st.max_peers + p
        order = np.argsort(pair_key, kind="stable")
        sk = pair_key[order]
        changed = np.empty(sk.size, bool)
        changed[0] = True
        np.not_equal(sk[1:], sk[:-1], out=changed[1:])
        starts = np.flatnonzero(changed)
        ends = np.empty(starts.size, np.int64)
        ends[:-1] = starts[1:]
        ends[-1] = sk.size
        now_ns = time.time_ns()
        for s, e in zip(starts, ends):
            rows = order[s:e]
            child_pid = st._peer_id[int(c[rows[0]])]
            parent_pid = st._peer_id[int(p[rows[0]])]
            if child_pid is None or parent_pid is None:
                continue
            meta = self._peer_meta.get(child_pid)
            if meta is None:
                continue
            stats = meta.parents.setdefault(parent_pid, {"pieces": [], "bytes": 0})
            stats["bytes"] += int(plen[rows].sum())
            room = 10 - len(stats["pieces"])
            for r in rows[:room] if room > 0 else ():
                stats["pieces"].append(
                    PieceRecord(length=int(plen[r]), cost=int(pcost[r]),
                                created_at=now_ns)
                )
        return n

    def _absorb_piece_reports_small(self, buf: list) -> int:
        """Scalar twin of the vectorised absorb for small flushes.

        The completion flush valves (peer finish/fail, leave) drain a
        handful of rows — one peer's last wave, ~10-30 reports — where
        the vectorised apply is pure numpy-call overhead (~0.4 ms per
        flush, the replay throughput ceiling at BENCH scale). This path
        applies the SAME column mutations in the SAME order with python
        ints/floats: bit-or accumulation per (peer, word) with popcount
        deltas, sequential cost-ring writes (last-`capacity` retention
        falls out of write order), per-row upload-count increments,
        serving-edge totals applied in first-occurrence pair order, and
        per-(child, parent) stats walked in sorted pair-key order — each
        matching the vectorised path's float op order exactly, so the
        two are bit-identical, not just approximately equivalent."""
        st = self.state
        n = len(buf)
        now = time.time()
        cap = st.piece_cost_capacity
        nwords = st.piece_bitset_words
        peer_host_col = st.peer_host
        host_of: dict[int, int] = {}
        upload_inc: dict[int, int] = {}
        bit_acc: dict[tuple[int, int], int] = {}
        ring: dict[int, list[float]] = {}
        edges: dict[tuple[int, int], list] = {}
        pairs: dict[tuple[int, int], list] = {}
        for row in buf:
            p = int(row[0])
            word, bit = divmod(int(row[1]), 64)
            pcost = float(row[3])
            if 0 <= word < nwords:
                key = (p, word)
                bit_acc[key] = bit_acc.get(key, 0) | (1 << bit)
            costs = ring.get(p)
            if costs is None:
                costs = ring[p] = []
            costs.append(pcost)
            par = int(row[4])
            if par < 0:
                continue
            plen = int(row[2])
            ph = host_of.get(par)
            if ph is None:
                ph = host_of[par] = int(peer_host_col[par])
            upload_inc[ph] = upload_inc.get(ph, 0) + 1
            if pcost > 0:
                ch = host_of.get(p)
                if ch is None:
                    ch = host_of[p] = int(peer_host_col[p])
                acc = edges.get((ch, ph))
                if acc is None:
                    acc = edges[(ch, ph)] = [0.0, 0]
                acc[0] += plen / (pcost / 1e9)
                acc[1] += 1
            rows2 = pairs.get((p, par))
            if rows2 is None:
                rows2 = pairs[(p, par)] = []
            rows2.append((plen, pcost))
        for (p, word), mask in bit_acc.items():
            before = int(st.peer_finished_bitset[p, word])
            after = before | mask
            if after != before:
                st.peer_finished_bitset[p, word] = after
                st.peer_finished_count[p] += (
                    after.bit_count() - before.bit_count()
                )
        for p, costs in ring.items():
            cur = int(st.peer_cost_cursor[p])
            m = len(costs)
            st.peer_piece_costs[p, [(cur + i) % cap for i in range(m)]] = costs
            st.peer_cost_cursor[p] = (cur + m) % cap
            st.peer_piece_cost_count[p] = min(
                int(st.peer_piece_cost_count[p]) + m, cap
            )
            st.peer_updated_at[p] = now
            st.peer_dirty[p] = True
            h = host_of.get(p)
            if h is None:
                h = host_of[p] = int(peer_host_col[p])
            if 0 <= h < st.max_hosts and st.host_alive[h]:
                st.host_updated_at[h] = now
        for ph, inc in upload_inc.items():
            st.host_upload_count[ph] += inc
        for (ch, ph), (tput, cnt) in edges.items():
            k4 = (ch, self._slot_gen.get(ch, 0), ph, self._slot_gen.get(ph, 0))
            acc = self._serving_edges.get(k4)
            if acc is None and len(self._serving_edges) < self._serving_edge_cap:
                acc = self._serving_edges[k4] = [0.0, 0]
            if acc is not None:
                acc[0] += tput
                acc[1] += cnt
                self._dirty_host_slots.add(ch)
                self._dirty_host_slots.add(ph)
        if pairs:
            now_ns = time.time_ns()
            for c, par in sorted(pairs):
                rows2 = pairs[(c, par)]
                child_pid = st._peer_id[c]
                parent_pid = st._peer_id[par]
                if child_pid is None or parent_pid is None:
                    continue
                meta = self._peer_meta.get(child_pid)
                if meta is None:
                    continue
                stats = meta.parents.setdefault(
                    parent_pid, {"pieces": [], "bytes": 0}
                )
                stats["bytes"] += sum(r[0] for r in rows2)
                room = 10 - len(stats["pieces"])
                for plen, pcost in rows2[:room] if room > 0 else ():
                    stats["pieces"].append(
                        PieceRecord(length=plen, cost=int(pcost),
                                    created_at=now_ns)
                    )
        return n

    def piece_failed(self, req: msg.DownloadPieceFailedRequest):
        """DownloadPieceFailed: parent host failure accounting + reschedule
        away from it. reason="corruption" means the child verified the
        piece's bytes against the scheduler-attested digest and they did
        NOT match — beyond the per-child blocklist, the parent HOST is
        quarantined cluster-wide (with time-decayed release) and takes a
        scoring penalty through the upload-failure feature every
        evaluator algorithm already consumes."""
        with self.mu:
            corrupt = req.reason == "corruption"
            pidx = self.state.peer_index(req.parent_peer_id)
            if pidx is not None:
                host_idx = self.state.peer_host[pidx]
                # corruption wastes a full transfer AND forces a re-fetch:
                # weight it like several plain serve failures in the scoring
                # features so a released host re-earns trust slowly
                self.state.host_upload_failed[host_idx] += 5 if corrupt else 1
                if corrupt:
                    host_id = self.state.host_id_at(int(host_idx))
                    if host_id is not None:
                        self.quarantine.report(host_id, reason="corruption")
            if corrupt:
                self._series.piece_corruption.labels().inc()
                if self.decisions is not None and req.peer_id != req.parent_peer_id:
                    # the child's decision handed it a digest-failing
                    # parent — corruption attribution on the ledger row
                    self.decisions.mark_corruption(req.peer_id)
                if req.peer_id == req.parent_peer_id:
                    # SELF-report (upload verify-on-serve found local rot):
                    # the host stops being advertised via quarantine; there
                    # is no downloading child to reschedule.
                    return None
            return self.reschedule(
                msg.RescheduleRequest(
                    peer_id=req.peer_id, candidate_parent_ids=[req.parent_peer_id]
                )
            )

    def peer_finished(self, req: msg.DownloadPeerFinishedRequest):
        """DownloadPeerFinished (:991): FSM -> Succeeded, free parent upload
        slots, emit the Download trace record."""
        with self.mu:
            idx = self.state.peer_index(req.peer_id)
            if idx is None:
                return msg.ScheduleFailure(req.peer_id, "NotFound", "unknown peer")
            self.state.peer_event(idx, PeerEvent.DOWNLOAD_SUCCEEDED)
            self._release_parent_slots(req.peer_id)
            self._pending.pop(req.peer_id, None)
            if self.decisions is not None:
                # flush valve: the cost label below reads the peer's
                # piece-cost columns, which buffered reports feed
                self._absorb_piece_reports()
                self.decisions.join_outcome(
                    req.peer_id, OUTCOME_COMPLETED,
                    bytes_=getattr(req, "content_length", 0),
                    cost_ns=self._reported_download_cost_ns(idx),
                )
            self._write_download_record(req.peer_id, "Succeeded")
            return None

    def peer_failed(self, req: msg.DownloadPeerFailedRequest):
        with self.mu:
            idx = self.state.peer_index(req.peer_id)
            if idx is None:
                return msg.ScheduleFailure(req.peer_id, "NotFound", "unknown peer")
            self.state.peer_event(idx, PeerEvent.DOWNLOAD_FAILED)
            self._release_parent_slots(req.peer_id)
            self._pending.pop(req.peer_id, None)
            if self.decisions is not None:
                self.decisions.join_outcome(req.peer_id, OUTCOME_FAILED)
            self._write_download_record(req.peer_id, "Failed")
            return None

    def back_to_source_started(self, req: msg.DownloadPeerBackToSourceStartedRequest):
        with self.mu:
            idx = self.state.peer_index(req.peer_id)
            if idx is None:
                return msg.ScheduleFailure(req.peer_id, "NotFound", "unknown peer")
            self.state.peer_event(idx, PeerEvent.DOWNLOAD_BACK_TO_SOURCE)
            task_idx = self.state.peer_task[idx]
            self.state.task_back_to_source_count[task_idx] += 1
            self._pending.pop(req.peer_id, None)
            if self.decisions is not None:
                # the peer abandoned its scheduled parents for the
                # origin — the decision's measured outcome is "escalated"
                self.decisions.join_outcome(req.peer_id, OUTCOME_BACK_TO_SOURCE)
            return None

    def back_to_source_finished(self, req: msg.DownloadPeerBackToSourceFinishedRequest):
        with self.mu:
            idx = self.state.peer_index(req.peer_id)
            if idx is None:
                return msg.ScheduleFailure(req.peer_id, "NotFound", "unknown peer")
            # capture BEFORE the FSM flips to Succeeded: digest-root adoption
            # is gated on the scheduler having seen this peer go back-to-source
            # (DOWNLOAD_SUCCEEDED is also legal from RUNNING, so a P2P peer
            # could send this message without ever fetching the origin)
            was_back_to_source = (
                self.state.peer_state[idx] == int(PeerState.BACK_TO_SOURCE)
            )
            self.state.peer_event(idx, PeerEvent.DOWNLOAD_SUCCEEDED)
            task_idx = self.state.peer_task[idx]
            if req.piece_count:
                self.state.task_total_pieces[task_idx] = req.piece_count
            if req.task_digest and was_back_to_source:
                # whole-task sha256 from the origin fetcher: the root of the
                # attested chain (first writer wins, like the piece digests)
                meta = self._peer_meta.get(req.peer_id)
                if meta is not None:
                    self._task_sha256.setdefault(meta.task_id, req.task_digest)
            # The origin download proves the task's content exists: the task
            # FSM goes Succeeded (service_v2 handleDownloadPeerBackToSource-
            # FinishedRequest) — preheat job state polls exactly this. FAILED
            # is a legal source too (fsm.py DOWNLOAD_SUCCEEDED transitions): a
            # retry that lands must recover a task an earlier attempt failed.
            if self.state.task_state[task_idx] in (
                int(TaskState.RUNNING), int(TaskState.FAILED)
            ):
                self.state.task_event(task_idx, TaskEvent.DOWNLOAD_SUCCEEDED)
            self._write_download_record(req.peer_id, "Succeeded")
            return None

    def back_to_source_failed(self, req: msg.DownloadPeerBackToSourceFailedRequest):
        with self.mu:
            idx = self.state.peer_index(req.peer_id)
            if idx is None:
                return msg.ScheduleFailure(req.peer_id, "NotFound", "unknown peer")
            self.state.peer_event(idx, PeerEvent.DOWNLOAD_FAILED)
            task_idx = self.state.peer_task[idx]
            if self.state.task_state[task_idx] == int(TaskState.RUNNING):
                self.state.task_event(task_idx, TaskEvent.DOWNLOAD_FAILED)
            self._write_download_record(req.peer_id, "Failed")
            return None

    def leave_peer(self, peer_id: str) -> None:
        with self.mu:
            self._leave_peer(peer_id)

    # ============================================================== tick

    def trigger_seed_download(
        self, task_id: str, url: str, piece_length: int = 4 << 20,
        tag: str = "", application: str = "", host_id: str = "",
        headers: dict | None = None,
    ) -> bool:
        """Enqueue a seed-peer download trigger directly (the preheat job
        edge: manager/job/preheat.go fans TriggerDownloadTask out to seed
        daemons; scheduler/job.go:152 consumes). The RPC edge pushes it
        over the chosen seed host's announce connection."""
        with self.mu:
            if len(self.seed_triggers) >= 1024:
                return False
            if not host_id and self._seed_hosts:
                host_id = self._seed_hosts[self._seed_rr % len(self._seed_hosts)]
                self._seed_rr += 1
            # No announced seed yet (preheat racing the seed daemon's
            # first announce): the trigger queues with an empty host_id —
            # the RPC drain routes it to ANY connected seed and keeps
            # retrying until the delivery TTL, so the job fails only if
            # no seed appears within the window, not if it is merely late.
            # An explicitly named seed may not have announced yet (preheat
            # right after a seed restart): the trigger is queued anyway —
            # the RPC drain re-routes to any connected seed or keeps
            # requeueing until the delivery deadline. The unannounced host
            # is deliberately NOT added to _seed_hosts, so round-robin for
            # other tasks never lands on a host that may not exist.
            self.seed_triggers.append(
                msg.TriggerSeedRequest(
                    host_id=host_id,
                    task_id=task_id,
                    url=url,
                    piece_length=piece_length,
                    tag=tag,
                    application=application,
                    headers=dict(headers or {}),
                )
            )
            return True

    def warmup(self) -> None:
        """Pre-compile the serving device programs for every batch bucket.

        Cold-start matters: XLA compiles lazily on the first tick of each
        bucket shape, and over the tunneled dev TPU a single compile can
        take tens of seconds (35 s observed for the ml-path program at
        the 256 bucket) — during which every in-flight peer waits. Safe
        to run from a background thread: the compile touches only
        zero-filled local arrays and jax's own compilation cache locking;
        no service state."""
        from dragonfly2_tpu.records.features import CandidateFeatures

        k = self.config.scheduler.filter_parent_limit
        limit = self.config.scheduler.candidate_parent_limit
        if self.plugin_evaluator is not None:
            return  # plugin path keeps the dict transport; nothing to warm
        use_ml = self.ml_evaluator is not None and self.algorithm == "ml"
        # Shadow-scoring warm: the inactive arm's program compiles here
        # too, so the first shadowed tick never pays a compile. The rule
        # twin is always warmable; the ml twin only once a snapshot has
        # committed (before that the ml entry would just fall back to
        # the rule program it cannot warm past).
        shadow_on = self.decisions is not None and self.shadow_scoring
        warm_rule_shadow = shadow_on and use_ml
        warm_ml_shadow = (
            shadow_on and not use_ml and self.ml_evaluator is not None
            and self.ml_evaluator.serving_snapshot() is not None
        )
        for bsz in _EVAL_BUCKETS:
            feats = CandidateFeatures.zeros(bsz, k, self.state.piece_cost_capacity)
            fd = feats.as_dict()
            c = fd["piece_costs"].shape[-1]
            l = fd["parent_location"].shape[-1]
            n = fd["numeric"].shape[-1]
            buf = ev.pack_eval_batch(fd)
            if use_ml:
                out = self.ml_evaluator.schedule_from_packed(
                    buf, bsz, k, c, l, n, limit=limit
                )
            else:
                algorithm = (
                    self.algorithm if self.algorithm in ("default", "nt") else "default"
                )
                out = ev.schedule_from_packed(
                    buf, bsz, k, c, l, n, algorithm=algorithm, limit=limit
                )
            np.asarray(out)  # force the compile + execution to finish
            if warm_rule_shadow or warm_ml_shadow:
                # fresh staging buffer: the call above donated buf's
                # device copy, and donated buffers are one-shot
                sbuf = ev.pack_eval_batch(fd)
                if warm_ml_shadow:
                    out = self.ml_evaluator.schedule_from_packed(
                        sbuf, bsz, k, c, l, n, limit=limit,
                        record_used=False,
                    )
                else:
                    fb = self.ml_evaluator.fallback
                    out = ev.schedule_from_packed(
                        sbuf, bsz, k, c, l, n,
                        algorithm=fb if fb in ("default", "nt") else "default",
                        limit=limit,
                    )
                np.asarray(out)
        if self._tick_mirror is not None:
            # Fused-tick warms (ops/tick.py): the fused program for every
            # bucket (+ its emit_packed variant feeding the warmed ml
            # shadow entry, when a snapshot already serves) and the
            # mirror's donated scatter signatures — all on zero-filled
            # throwaway arrays, never the live mirror, so this stays
            # background-thread safe like the rest of warmup.
            from dragonfly2_tpu.ops import tick as tk

            cols = tk.warm_cols(self.state, self._dag_capacity)
            cost_c = self.state.piece_cost_capacity
            loc_l = self.state.host_location.shape[1]
            num_n = self.state.host_numeric.shape[1]
            emit_led = self.decisions is not None
            algorithm = (
                self.algorithm if self.algorithm in ("default", "nt")
                else "default"
            )
            for bsz in _EVAL_BUCKETS:
                out = tk.fused_tick_chunk(
                    tk.warm_inputs(bsz, k), cols, bsz, k, cost_c, loc_l,
                    num_n, algorithm=algorithm, limit=limit,
                    emit_led=emit_led, emit_packed=False,
                )
                np.asarray(out)
                if warm_ml_shadow:
                    out, _sbuf = tk.fused_tick_chunk(
                        tk.warm_inputs(bsz, k), cols, bsz, k, cost_c,
                        loc_l, num_n, algorithm=algorithm, limit=limit,
                        emit_led=emit_led, emit_packed=True,
                    )
                    np.asarray(out)
            tk.warm_scatters(self.state, self._dag_capacity)
        if warm_ml_shadow:
            with self.mu:
                self._shadow_ml_ready = True
        # Drain the cost-card captures the bucket compiles just queued
        # (telemetry/costcard.py): warmup is ALREADY the designed
        # blocking cold-start phase, so the one-time duplicate compile
        # per signature lands here — never on a serving tick. On the
        # D2H_ALLOWLIST (tools/dflint/passes/jit_hygiene.py): a
        # capture/cost_analysis call in any OTHER hot function fails
        # JIT003.
        from dragonfly2_tpu.telemetry import costcard

        costcard.capture_pending()

    def _ensure_shadow_warm(self) -> None:
        """Spawn the one-shot background warm of the ml shadow entry
        (caller holds service.mu). Idempotent: a live warm thread or a
        ready flag makes this a no-op."""
        t = self._shadow_warm_thread
        if self._shadow_ml_ready or (t is not None and t.is_alive()):
            return
        t = threading.Thread(
            target=self._warm_shadow_ml, name="eval-warmup-shadow",
            daemon=True,
        )
        self._shadow_warm_thread = t
        t.start()

    def _warm_shadow_ml(self) -> None:
        """Compile the ml packed program for every bucket on a
        background thread (the warmup() discipline for a snapshot that
        committed AFTER cold start): touches only zero-filled local
        arrays + jax's compile cache, no service state; flips
        _shadow_ml_ready under mu when every bucket is warm."""
        from dragonfly2_tpu.records.features import CandidateFeatures

        try:
            k = self.config.scheduler.filter_parent_limit
            limit = self.config.scheduler.candidate_parent_limit
            for bsz in _EVAL_BUCKETS:
                feats = CandidateFeatures.zeros(
                    bsz, k, self.state.piece_cost_capacity
                )
                fd = feats.as_dict()
                c = fd["piece_costs"].shape[-1]
                l = fd["parent_location"].shape[-1]
                n = fd["numeric"].shape[-1]
                buf = ev.pack_eval_batch(fd)
                out = self.ml_evaluator.schedule_from_packed(
                    buf, bsz, k, c, l, n, limit=limit, record_used=False
                )
                np.asarray(out)  # land compile + execution off the tick
                if self._tick_mirror is not None:
                    # the first shadowed FUSED tick needs the emit_packed
                    # variant of the fused program too — warm it with the
                    # same zero-filled discipline (ops/tick.py)
                    from dragonfly2_tpu.ops import tick as tk

                    fout, _sbuf = tk.fused_tick_chunk(
                        tk.warm_inputs(bsz, k), tk.warm_cols(
                            self.state, self._dag_capacity
                        ), bsz, k, c, l, n,
                        algorithm=(
                            self.algorithm
                            if self.algorithm in ("default", "nt")
                            else "default"
                        ),
                        limit=limit,
                        emit_led=self.decisions is not None,
                        emit_packed=True,
                    )
                    np.asarray(fout)
        except Exception:  # noqa: BLE001 - shadow stays off; serving unaffected
            logger.exception("background shadow warm failed")
            return
        with self.mu:
            self._shadow_ml_ready = True

    def tick(self) -> list:
        """Run ONE batched scheduling round over every pending peer.

        scheduling.go:85-213's per-peer retry loop, inverted: back-to-source
        and retry-exhaustion decided host-side, everything else in a single
        (B, K) device call. The three control phases feeding it —
        report_ingest (buffered piece-report absorption), candidate_fill
        and apply_selection — run as columnar batch ops over the SoA
        state; the per-tick sum of EVERY host-side phase (those three
        plus pre_schedule, feature_gather and pack) is recorded as the
        `control_dispatch` phase, next to `device_call` (= dispatch +
        d2h_wait), so the control-plane-vs-device balance reads straight
        off the flight recorder with nothing left out of either side.

        Holds service.mu for the whole round — identical to how the RPC
        edge has always driven it (rpc/server.py _tick_once); taking it
        here too makes bare in-proc drivers (simulator, bench_loop,
        tests) safe against concurrent handlers, which the LOCK001 sweep
        showed they were not.
        """
        t0 = time.perf_counter()
        refresh_regret = False
        with self.mu:
            responses = self._tick_locked()
            if self.slo is not None:
                try:
                    refresh_regret = self._observe_slo(
                        (time.perf_counter() - t0) * 1e3
                    )
                except Exception:  # noqa: BLE001 - telemetry must not break the tick
                    refresh_regret = False
        if refresh_regret:
            try:
                self._refresh_slo_regret()
            except Exception:  # noqa: BLE001 - telemetry must not break the tick
                pass
        return responses

    def _observe_slo(self, tick_ms: float) -> bool:
        """Feed the live SLO engine one tick's SLIs (caller holds mu —
        the delta bookkeeping below must stay single-writer under the
        same lock that serializes ticks).

        - tick_latency: the whole-tick wall time against the configured
          budget (the PhaseRecorder ring carries the same tick's phase
          split; this is its end-to-end sum including lock wait — the
          latency a caller actually observed);
        - shadow_regret: new shadow comparisons from the decision
          ledger; disagreements count against the budget only while the
          measured fail-rate regret says the active arm is LOSING;
        - breakers: the process-wide open-breaker census.

        Stepped on the wall clock in minutes (perf_counter — the one
        DET-exempt clock; this engine never rides replay surfaces).
        Returns True when the regret sign is due for re-estimation —
        that ledger ring scan is too heavy for this critical section,
        so tick() runs it AFTER releasing mu (_refresh_slo_regret)."""
        slo = self.slo
        over = tick_ms > self._slo_tick_budget_ms
        slo.observe("tick_latency", good=0 if over else 1, bad=1 if over else 0)
        refresh = False
        led = self.decisions
        if led is not None:
            c = led.counters()
            compared, disagree = (
                c["shadow_compared"], c["shadow_top1_disagree"]
            )
            prev_c, prev_d = self._slo_prev_shadow
            d_comp, d_dis = compared - prev_c, disagree - prev_d
            self._slo_prev_shadow = (compared, disagree)
            if d_comp > 0:
                bad = d_dis if self._slo_regret_losing else 0
                slo.observe(
                    "shadow_regret", good=max(d_comp - bad, 0), bad=bad
                )
            refresh = self._tick_counter % 64 == 0
        from dragonfly2_tpu.rpc.resilience import open_breaker_census

        open_b = open_breaker_census()
        slo.observe("breakers", good=0 if open_b else 1, bad=open_b)
        slo.step(time.perf_counter() / 60.0)
        return refresh

    def _refresh_slo_regret(self) -> None:
        """Re-estimate the shadow-regret sign OUTSIDE mu: the ledger
        report walks the divergence/outcome rings (a real scan at 4096
        capacity), the ledger has its own lock, and the result is one
        GIL-atomic bool the next tick's _observe_slo reads — a one-tick
        lag in the sign is harmless, a ring scan inside the serving
        critical section is not."""
        led = self.decisions
        if led is None:
            return
        regret = led.report().get("regret_fail_rate")
        self._slo_regret_losing = regret is not None and regret > 0.0

    def _tick_locked(self) -> list:
        recorder = self.recorder
        recorder.begin()
        # replay-deterministic tick id — the decision ledger's rows and
        # per-tick divergence entries key on it, never on wall time
        self._tick_counter += 1
        # Absorb every piece report buffered since the last flush valve:
        # candidate scoring below reads the finished/cost/upload columns.
        self._absorb_piece_reports()
        recorder.mark("report_ingest")
        responses: list = []
        work: list[_Pending] = []
        for pending in list(self._pending.values()):
            decision = self._pre_schedule(pending)
            if decision is not None:
                responses.append(decision)
                self._pending.pop(pending.peer_id, None)
            else:
                work.append(pending)
        recorder.mark("pre_schedule")
        if self.storage is not None:
            # push buffered trace rows to disk on the tick cadence so
            # external readers (e2e harness, tail -f) never lag by more
            # than a tick interval past the writer's own 1s flush
            now = time.monotonic()
            if now - self._last_storage_flush > 1.0:
                self._last_storage_flush = now
                self.storage.flush()
        if not work:
            return responses

        k = self.config.scheduler.filter_parent_limit
        b = len(work)
        if self._tick_mirror is not None:
            # Device-resident fused tick: fill/gather/score/select run as
            # one donated dispatch per chunk over the column mirrors; the
            # packed-transport path below stays as the decision-
            # equivalence oracle (scheduler.fused_tick=False).
            return self._tick_fused(work, responses, k, b)
        # Candidate sampling is the same vectorised per-task draw on both
        # fill paths (shared _sample_rows helper, identical rng call
        # sequence), so the vectorised and per-peer loop fills are
        # decision-comparable given the same seed.
        if self.vectorized_control:
            fill = self._fill_candidates_vec(work, k)
        else:
            fill = self._fill_candidates_loop(
                work, self._sample_candidates(work, k)[0], k
            )
        (cand_peer_idx, cand_valid, child_peer_idx, blocklist, in_degree,
         can_add_edge, child_host_slots, cand_host_slots, cand_slots,
         cand_ids) = fill
        cand_count = cand_valid.sum(axis=1).astype(np.int64)
        recorder.mark("candidate_fill")

        avg_rtt = has_rtt = None
        if self.probes is not None and self.algorithm == "nt":
            avg_rtt, has_rtt = self.probes.gather_candidate_rtt(child_host_slots, cand_host_slots)
        feats = self.state.gather_candidates(
            child_peer_idx, cand_peer_idx, cand_valid, avg_rtt, has_rtt
        )
        fd = feats.as_dict()
        led = self.decisions
        led_feats = None
        if led is not None:
            # compact per-candidate ledger feature rows, one vectorised
            # stack for the whole batch (telemetry/decisions.py) — part
            # of feature gathering, so it stays inside this phase mark
            led_feats = _ledger_features(
                fd, in_degree, CONSTANTS.MAX_LOCATION_ELEMENTS
            )
        recorder.mark("feature_gather")

        # The jitted kernels specialize on (B, K). A raw B = len(pending)
        # would recompile on nearly every tick (SURVEY.md §7 hard part (a)),
        # so the batch is cut into chunks padded to one of three fixed
        # buckets — at most three compiled shapes per algorithm, with the
        # biggest chunk at the BASELINE eval shape (1024 tasks/call).
        # Padding rows are valid=False everywhere and fall out of selection.
        #
        # Transport: the ~25 feature arrays are packed into ONE uint8
        # buffer per chunk (ops/evaluator.pack_eval_batch), so a chunk
        # costs exactly one H2D + one dispatch + one D2H regardless of
        # field count — on the tunneled device each extra transfer is a
        # full link round-trip, and the per-field dict transport was the
        # bulk of BENCH_r03's 184 ms tick p50 (VERDICT r3 weak #5).
        limit = self.config.scheduler.candidate_parent_limit
        cost_c = fd["piece_costs"].shape[-1]
        loc_l = fd["parent_location"].shape[-1]
        num_n = fd["numeric"].shape[-1]
        use_ml = self.ml_evaluator is not None and self.algorithm == "ml"
        # Pin ONE serving snapshot for the whole tick: the background
        # refresh may commit between two chunks of the same batch, and
        # peers of one tick must be ranked against one embedding table
        # (pinning None keeps later chunks on the fallback path too).
        ml_snap = self.ml_evaluator.serving_snapshot() if use_ml else None

        # Decision-ledger context + counterfactual shadow arm. The arm
        # that actually scores this tick is attributed honestly: an ml
        # tick without a committed snapshot serves the rule fallback and
        # is recorded as such. The shadow arm is the INACTIVE one — the
        # rule blend when ml serves, the committed ml snapshot when the
        # rule does — re-scoring the same packed candidate batch;
        # nothing when no inactive arm exists (rule active, no served
        # snapshot) or on the plugin path (no packed transport).
        if self.plugin_evaluator is not None:
            arm_code = ARM_CODES["plugin"]
        elif use_ml and ml_snap is not None:
            arm_code = ARM_CODES["ml"]
        else:
            arm_code = ARM_CODES[
                self.algorithm if self.algorithm in ("default", "nt")
                else "default"
            ]
        shadow_mode = None
        shadow_alg = "default"
        shadow_snap = None
        shadow_arm_code = -1
        shadow_due = (
            self._tick_counter
            % max(int(getattr(self.config.scheduler, "shadow_every", 1)), 1)
            == 0
        )
        if (
            led is not None
            and self.shadow_scoring
            and shadow_due
            and self.plugin_evaluator is None
        ):
            if use_ml and ml_snap is not None:
                fb = self.ml_evaluator.fallback
                shadow_alg = fb if fb in ("default", "nt") else "default"
                shadow_mode = "rule"
                shadow_arm_code = ARM_CODES[shadow_alg]
            elif not use_ml and self.ml_evaluator is not None:
                shadow_snap = self.ml_evaluator.serving_snapshot()
                if shadow_snap is not None:
                    if self._shadow_ml_ready:
                        shadow_mode = "ml"
                        shadow_arm_code = ARM_CODES["ml"]
                    else:
                        # snapshot committed after warmup (or warmup
                        # never ran): compile the ml packed program on a
                        # background thread; shadow stays off until the
                        # warm lands — never a mid-tick XLA compile
                        self._ensure_shadow_warm()
        led_ctx = None
        if led is not None:
            led_ctx = {
                "tick": self._tick_counter,
                "arm": arm_code,
                "feats": led_feats,
                "child_peer_idx": child_peer_idx,
                "child_host_slots": child_host_slots,
                "cand_host_slots": cand_host_slots,
                # per-row ledger ring slot + its seq, filled by the
                # apply paths so the end-of-tick shadow drain can join
                # row-for-row (the seq guards against a mid-tick ring
                # wrap reassigning a slot to a later decision)
                "slot_of_row": np.full(b, -1, np.int64),
                "seq_of_row": np.full(b, -1, np.int64),
            }
        shadow_inflight: list[tuple[int, int, object]] = []

        def _dispatch_chunk(s: int, e: int):
            """Pack rows [s:e) and dispatch their device call WITHOUT
            blocking on the result (jax async dispatch): the returned
            value is an in-flight device array the drain step reads."""
            bsz = _bucket_rows(e - s)
            sbuf = None
            if self.plugin_evaluator is not None:
                # plugin scorers run host-side on the feature dict, so this
                # path keeps the dict transport (plugin contract stability
                # over transfer count; plugins are not the serving default)
                fd_c = {name: _pad_rows(v[s:e], bsz) for name, v in fd.items()}
                bl = _pad_rows(blocklist[s:e], bsz)
                ind = _pad_rows(in_degree[s:e], bsz)
                cae = _pad_rows(can_add_edge[s:e], bsz)
                recorder.mark("pack")
                # the plugin's host-side scoring is dispatch work for
                # attribution purposes — it replaces the device scorer
                scores = np.asarray(self.plugin_evaluator.evaluate(fd_c), np.float32)
                packed = ev.select_with_scores_packed(
                    fd_c, scores, bl, ind, cae, limit=limit
                )
            else:
                buf = ev.pack_eval_batch(
                    {name: _pad_rows(v[s:e], bsz) for name, v in fd.items()},
                    blocklist=_pad_rows(blocklist[s:e], bsz),
                    in_degree=_pad_rows(in_degree[s:e], bsz),
                    can_add_edge=_pad_rows(can_add_edge[s:e], bsz),
                    child_host_slot=_pad_rows(child_host_slots[s:e], bsz),
                    cand_host_slot=_pad_rows(cand_host_slots[s:e], bsz),
                )
                recorder.mark("pack")
                if shadow_mode is not None:
                    # The shadow arm scores the SAME packed batch from
                    # its own staging buffer, copied BEFORE the active
                    # call donates `buf`'s device allocation — donated
                    # buffers are one-shot (dfshape DON001 / the runtime
                    # DonationGuard), so reuse would be a contract
                    # violation, not an optimization. Copy wall is
                    # credited to the shadow_score phase, never to
                    # pack/dispatch.
                    t_sh = time.perf_counter()
                    sbuf = buf.copy()
                    recorder.add(
                        "shadow_score", (time.perf_counter() - t_sh) * 1e3
                    )
                    recorder.sync()
                if use_ml:
                    packed = self.ml_evaluator.schedule_from_packed(
                        buf, bsz, k, cost_c, loc_l, num_n, limit=limit,
                        snap=ml_snap,
                    )
                else:
                    algorithm = self.algorithm if self.algorithm in ("default", "nt") else "default"
                    packed = ev.schedule_from_packed(
                        buf, bsz, k, cost_c, loc_l, num_n,
                        algorithm=algorithm, limit=limit,
                    )
            recorder.mark("dispatch")
            shadow_packed = None
            if sbuf is not None:
                # Counterfactual dispatch AFTER the active chunk's async
                # dispatch (the serving call keeps priority); its D2H
                # waits for the end-of-tick drain valve (_drain_shadow).
                # Routes only already-proven bucket signatures, so the
                # retrace tripwire's observed set cannot grow.
                t_sh = time.perf_counter()
                if shadow_mode == "ml":
                    # record_used=False: a counterfactual re-score must
                    # not claim the ml version SERVED this tick
                    shadow_packed = self.ml_evaluator.schedule_from_packed(
                        sbuf, bsz, k, cost_c, loc_l, num_n, limit=limit,
                        snap=shadow_snap, record_used=False,
                    )
                else:
                    shadow_packed = ev.schedule_from_packed(
                        sbuf, bsz, k, cost_c, loc_l, num_n,
                        algorithm=shadow_alg, limit=limit,
                    )
                recorder.add(
                    "shadow_score", (time.perf_counter() - t_sh) * 1e3
                )
                recorder.sync()
            return packed, shadow_packed

        def _drain_chunk(s: int, e: int, packed, overlapped: bool) -> None:
            """Block on chunk [s:e)'s D2H, then apply its selections.
            Phase attribution is explicit (recorder.add with measured
            walls, not cursor marks): on pipelined multi-chunk ticks the
            drain runs interleaved with the NEXT chunk's pack/dispatch
            marks, and a cursor mark here would lump the apply
            bookkeeping into whichever device phase marked last. The
            packed (B, limit, 2) selection is the jit's ONLY output, so a
            chunk pays exactly one D2H transfer; with `overlapped` the
            host-side unpack+apply wall is also credited to the `overlap`
            phase — it ran while the NEXT chunk's device call was in
            flight, which is the latency the pipeline hides."""
            t_wait = time.perf_counter()
            arr = np.asarray(packed)[: e - s]
            t0 = time.perf_counter()
            recorder.add("d2h_wait", (t0 - t_wait) * 1e3)
            selected, selected_valid, selected_scores = ev.unpack_selection(arr)
            if self.vectorized_control:
                self._apply_chunk_batch(
                    work, s, e, selected, selected_valid, selected_scores,
                    cand_peer_idx, cand_slots, cand_count, responses,
                    led_ctx=led_ctx,
                )
            else:
                for row, i in enumerate(range(s, e)):
                    pending = work[i]
                    meta = self._peer_meta[pending.peer_id]
                    parents = []
                    ranked_pos = []
                    for j in range(limit):
                        if not selected_valid[row, j]:
                            break
                        pid = (
                            cand_ids[i][selected[row, j]]
                            if selected[row, j] < len(cand_ids[i]) else None
                        )
                        if pid is None:
                            continue
                        parents.append((pid, float(selected_scores[row, j])))
                        ranked_pos.append(int(selected[row, j]))
                    if not parents:
                        pending.retries += 1
                        continue  # stays pending for the next tick (retry loop)
                    response = self._apply_selection(pending, meta, parents)
                    if response is None:
                        continue  # all selections DAG-rejected; stays pending
                    responses.append(response)
                    self._pending.pop(pending.peer_id, None)
                    if led_ctx is not None:
                        self._record_loop_decision(
                            led_ctx, i, pending, meta, parents, ranked_pos,
                            cand_peer_idx, cand_count, response,
                        )
            dt = (time.perf_counter() - t0) * 1e3
            recorder.add("apply_selection", dt)
            if overlapped:
                recorder.add("overlap", dt)
            # the drain timed itself via add(); move the mark cursor so
            # the next chunk's "pack" mark doesn't inherit this wall
            recorder.sync()

        # Double-buffered dispatch: chunk i+1's pack + device call are
        # issued BEFORE blocking on chunk i's D2H, and chunk i's host-side
        # DAG bookkeeping (apply_selection) runs while chunk i+1 executes
        # on the device — at most two chunks in flight. On a tunneled
        # device each chunk's D2H is a full link round-trip; pipelining
        # overlaps round-trip i+1 with bookkeeping i instead of paying
        # them serially (BENCH_r05: device_call 84.4 ms of the 97.5 ms
        # tick was exactly this serial chain).
        stride = _chunk_stride(b)
        spans = [(s, min(s + stride, b)) for s in range(0, b, stride)]
        in_flight: tuple | None = None
        for s, e in spans:
            t0 = time.perf_counter()
            packed, shadow_packed = _dispatch_chunk(s, e)
            if shadow_packed is not None:
                # in-flight counterfactual result; drained once, at the
                # end-of-tick valve, never between chunks
                shadow_inflight.append((s, e, shadow_packed))
            if in_flight is not None:
                # this chunk's pack+dispatch ran while the previous
                # chunk's device call was in flight — overlapped host work
                recorder.add("overlap", (time.perf_counter() - t0) * 1e3)
                _drain_chunk(*in_flight, overlapped=True)
            in_flight = (s, e, packed)
        _drain_chunk(*in_flight, overlapped=False)
        if shadow_inflight and led_ctx is not None:
            self._drain_shadow(
                shadow_inflight, led_ctx["slot_of_row"],
                led_ctx["seq_of_row"], shadow_arm_code,
            )
        # Aggregate phases for the operator-facing comparison (satellite:
        # control_dispatch is a REAL recorded phase now, not bench_loop's
        # trivial-dispatch link-RTT probe): control_dispatch sums the
        # host-side control plane, device_call the device conversation.
        recorder.add("control_dispatch", (
            recorder.value("report_ingest") + recorder.value("pre_schedule")
            + recorder.value("candidate_fill") + recorder.value("feature_gather")
            + recorder.value("pack") + recorder.value("apply_selection")
        ))
        recorder.add("device_call", (
            recorder.value("dispatch") + recorder.value("d2h_wait")
        ))
        recorder.commit()
        return responses

    # ------------------------------------------------- fused device tick

    def _tick_fused(self, work: list, responses: list, k: int, b: int) -> list:
        """Device-resident tick body (ops/tick.py): the host draws the
        candidate samples and runs the legality prefilters; everything
        else — slot→peer-row resolution, validity/self/quarantine
        masking, compaction, feature gather, scoring, top-k — is ONE
        donated `fused_tick_chunk` dispatch per chunk over the column
        mirrors, pipelined exactly like the packed path (chunk i's
        decode+apply overlaps chunk i+1's device call).

        Decision equivalence with the oracle holds chunk-by-chunk
        because BOTH paths freeze their scoring inputs before the first
        dispatch: the oracle gathers features once up front, the fused
        path snapshots the mirrors once at sync — upload-slot counts and
        DAG edges mutated by an earlier chunk's apply are invisible to
        later chunks either way.

        Phase accounting (the benchwatch seam): candidate_fill is the
        host sampling+grids, legality_recheck the quarantine/blocklist/
        DAG prefilters, pack the staging-buffer build, emit the decode +
        apply + response build; fused_dispatch/d2h_wait are the device
        conversation, aggregated as fused_device_call — a NEW key, so
        r06's 0.3 ms trivial-transport device_call is never compared
        against a program that now does the whole tick. control_dispatch
        keeps meaning "all host-side work per tick" (re-derived from the
        recorder at commit), so its longitudinal comparison against r06
        stays apples-to-apples."""
        from dragonfly2_tpu.ops import tick as tk

        recorder = self.recorder
        st = self.state
        led = self.decisions
        limit = self.config.scheduler.candidate_parent_limit
        # --- candidate fill, host half: the SAME per-task-group sample
        # draw as _fill_candidates_vec (shared _sample_rows helper,
        # identical rng call sequence and skip conditions — the
        # equivalence anchor), but only the sample/in-degree grids are
        # materialized; slot resolution moves on-device.
        child_peer_idx = np.fromiter(
            (st.peer_index(p.peer_id) for p in work), np.int64, b
        ).astype(np.int32)
        child_dag_slot = np.fromiter(
            (self._peer_meta[p.peer_id].dag_slot for p in work), np.int64, b
        )
        groups = self._group_rows_by_task(work)
        samples = np.full((b, k), -1, np.int64)
        ind = np.zeros((b, k), np.int32)
        task_row = np.full(b, -1, np.int64)
        task_rows: list[tuple] = []
        for task_id, rows in groups.items():
            dag = self._task_dag(task_id)
            spx = self._slot_pidx.get(task_id)
            live = np.flatnonzero(dag.present)
            # fromiter, not asarray: _tick_fused is on the jit-hygiene
            # hot list with NO allowlisted sync leaf — the fused tick's
            # only device read-back is _drain_fused's
            r = np.fromiter(rows, np.int64, len(rows))
            task_rows.append((task_id, dag, r))
            if live.size == 0 or spx is None:
                continue
            trow = st.task_index(task_id)
            if trow is not None:
                task_row[r] = trow
            s = _sample_rows(self.rng, live, r.size, k)
            cols_r = np.arange(s.shape[1])
            samples[r[:, None], cols_r] = s
            ind[r[:, None], cols_r] = dag.in_degree[s]
        recorder.mark("candidate_fill")
        # --- legality prefilters, host half: quarantine mask (same
        # decay/release side effects, at the same logical point, as the
        # oracle's per-tick check), blocklist rows resolved to peer rows
        # at SAMPLE positions, and the DAG-legality superset over every
        # sampled slot — the device ANDs each with candidate validity,
        # which lands exactly the oracle's post-compaction batches.
        if self.quarantine.active_count():
            qmask = self._quarantined_slot_mask()
        else:
            qmask = np.zeros(st.max_hosts, bool)
        bl0 = np.zeros((b, k), bool)
        for i, pending in enumerate(work):
            if not pending.blocklist:
                continue
            bidx = {st.peer_index(x) for x in pending.blocklist}
            bidx.discard(None)
            spx = self._slot_pidx.get(self._peer_meta[pending.peer_id].task_id)
            if not bidx or spx is None:
                continue
            srow = samples[i]
            prow = np.where(srow >= 0, spx[np.clip(srow, 0, None)], -1)
            bl0[i] = np.isin(
                prow, np.fromiter(bidx, np.int64, len(bidx))
            )
        ca0 = np.zeros((b, k), bool)
        for task_id, dag, r in task_rows:
            sub = samples[r]
            rr, cc = np.nonzero(sub >= 0)
            if rr.size == 0:
                continue
            ca0[r[rr], cc] = dag.can_add_edges_pairs(
                sub[rr, cc], child_dag_slot[r][rr]
            )
        recorder.mark("legality_recheck")

        cost_c = st.piece_cost_capacity
        loc_l = st.host_location.shape[1]
        num_n = st.host_numeric.shape[1]
        algorithm = (
            self.algorithm if self.algorithm in ("default", "nt") else "default"
        )
        arm_code = ARM_CODES[algorithm]
        # Counterfactual shadow arm: fused eligibility already excludes
        # the ml/plugin active arms, so the only possible shadow is the
        # committed ml snapshot re-scoring the same candidate batch —
        # fed from the pack-identical buffer the fused program emits ON
        # DEVICE (emit_packed), through the already-warmed packed entry.
        shadow_mode = None
        shadow_snap = None
        shadow_arm_code = -1
        shadow_due = (
            self._tick_counter
            % max(int(getattr(self.config.scheduler, "shadow_every", 1)), 1)
            == 0
        )
        if (
            led is not None
            and self.shadow_scoring
            and shadow_due
            and self.ml_evaluator is not None
        ):
            shadow_snap = self.ml_evaluator.serving_snapshot()
            if shadow_snap is not None:
                if self._shadow_ml_ready:
                    shadow_mode = "ml"
                    shadow_arm_code = ARM_CODES["ml"]
                else:
                    self._ensure_shadow_warm()
        # Whole-batch result arrays the per-chunk drains fill: the apply
        # path (_apply_chunk_batch, UNCHANGED from the oracle) and the
        # ledger indexing read full-batch arrays by row.
        cand_peer_idx = np.zeros((b, k), np.int32)
        cand_slots = np.full((b, k), -1, np.int64)
        cand_host_slots = np.zeros((b, k), np.int32)
        cand_count = np.zeros(b, np.int64)
        emit_led = led is not None
        led_feats = np.zeros((b, k, 8), np.float32) if emit_led else None
        led_ctx = None
        if led is not None:
            led_ctx = {
                "tick": self._tick_counter,
                "arm": arm_code,
                "feats": led_feats,
                "child_peer_idx": child_peer_idx,
                "child_host_slots": st.peer_host[child_peer_idx].astype(np.int32),
                "cand_host_slots": cand_host_slots,
                "slot_of_row": np.full(b, -1, np.int64),
                "seq_of_row": np.full(b, -1, np.int64),
            }
        # Mirror sync: fold every dirty peer row / dirty task slot table /
        # changed host column into the device mirrors and snapshot this
        # tick's cols — part of the device conversation for attribution.
        t0 = time.perf_counter()
        cols = self._tick_mirror.sync(
            self._slot_pidx, st.task_index, self._fused_dirty_tasks, qmask
        )
        recorder.add("fused_dispatch", (time.perf_counter() - t0) * 1e3)
        recorder.sync()
        qskip_total = 0
        shadow_inflight: list[tuple[int, int, object]] = []

        def _dispatch_fused(s: int, e: int):
            """Build rows [s:e)'s staging buffer and issue the fused
            device call WITHOUT blocking (jax async dispatch); with the
            shadow arm on, its packed re-score dispatches right behind
            the serving call on the device-built buffer."""
            bsz = _bucket_rows(e - s)
            t0 = time.perf_counter()
            inbuf = tk.build_inbuf(
                bsz, samples[s:e], ind[s:e], task_row[s:e],
                child_peer_idx[s:e], bl0[s:e], ca0[s:e],
            )
            recorder.add("pack", (time.perf_counter() - t0) * 1e3)
            recorder.sync()
            t0 = time.perf_counter()
            out = tk.fused_tick_chunk(
                inbuf, cols, bsz, k, cost_c, loc_l, num_n,
                algorithm=algorithm, limit=limit,
                emit_led=emit_led, emit_packed=shadow_mode is not None,
            )
            recorder.add("fused_dispatch", (time.perf_counter() - t0) * 1e3)
            recorder.sync()
            if shadow_mode is not None:
                out, sbuf = out
                t_sh = time.perf_counter()
                shadow_packed = self.ml_evaluator.schedule_from_packed(
                    sbuf, bsz, k, cost_c, loc_l, num_n, limit=limit,
                    snap=shadow_snap, record_used=False,
                )
                shadow_inflight.append((s, e, shadow_packed))
                recorder.add(
                    "shadow_score", (time.perf_counter() - t_sh) * 1e3
                )
                recorder.sync()
            return out

        def _drain_fused(s: int, e: int, out, overlapped: bool) -> None:
            """Block on chunk [s:e)'s single D2H (the flat fused result
            buffer — the tick's ONLY device read-back; jit-hygiene
            D2H_ALLOWLIST row), decode it into the whole-batch arrays,
            then run the UNCHANGED host apply: DAG edge adds, upload
            accounting, response emission, ledger rows."""
            nonlocal qskip_total
            bsz = _bucket_rows(e - s)
            t_wait = time.perf_counter()
            arr = np.asarray(out)
            t0 = time.perf_counter()
            recorder.add("d2h_wait", (t0 - t_wait) * 1e3)
            dec = tk.decode_out(arr, bsz, k, limit, emit_led)
            m = e - s
            cand_peer_idx[s:e] = dec["cand_peer_idx"][:m]
            cand_slots[s:e] = dec["cand_slots"][:m]
            cand_host_slots[s:e] = dec["cand_host_slots"][:m]
            cand_count[s:e] = dec["cand_valid"][:m].sum(axis=1)
            if led_feats is not None:
                led_feats[s:e] = dec["led_feats"][:m]
            qskip_total += int(dec["quarantine_skipped"][0])
            selected, selected_valid, selected_scores = ev.unpack_selection(
                np.ascontiguousarray(dec["selection"][:m])
            )
            self._apply_chunk_batch(
                work, s, e, selected, selected_valid, selected_scores,
                cand_peer_idx, cand_slots, cand_count, responses,
                led_ctx=led_ctx,
            )
            dt = (time.perf_counter() - t0) * 1e3
            recorder.add("emit", dt)
            if overlapped:
                recorder.add("overlap", dt)
            recorder.sync()

        # Double-buffered dispatch, the PR-4 pipeline: chunk i+1's
        # staging build + device call issue before chunk i's D2H, chunk
        # i's decode+apply runs while chunk i+1 executes on the device.
        stride = _chunk_stride(b)
        spans = [(s, min(s + stride, b)) for s in range(0, b, stride)]
        in_flight: tuple | None = None
        for s, e in spans:
            t0 = time.perf_counter()
            out = _dispatch_fused(s, e)
            if in_flight is not None:
                recorder.add("overlap", (time.perf_counter() - t0) * 1e3)
                _drain_fused(*in_flight, overlapped=True)
            in_flight = (s, e, out)
        _drain_fused(*in_flight, overlapped=False)
        if qskip_total:
            # same counter, same tick, as the oracle's fill-time incs —
            # the skip decision just came back from the device
            self._series.quarantine_skipped.labels().inc(qskip_total)
        if shadow_inflight and led_ctx is not None:
            self._drain_shadow(
                shadow_inflight, led_ctx["slot_of_row"],
                led_ctx["seq_of_row"], shadow_arm_code,
            )
        # Phase-accounting seam (benchwatch longitudinal comparison):
        # control_dispatch stays "all host-side work per tick" — the
        # fused split's host phases — while the device conversation
        # aggregates under the NEW fused_device_call key (comparing it
        # against the trivial-transport r06 device_call would be a
        # guaranteed false regression, the program does strictly more).
        recorder.add("control_dispatch", (
            recorder.value("report_ingest") + recorder.value("pre_schedule")
            + recorder.value("candidate_fill")
            + recorder.value("legality_recheck")
            + recorder.value("pack") + recorder.value("emit")
        ))
        recorder.add("fused_device_call", (
            recorder.value("fused_dispatch") + recorder.value("d2h_wait")
        ))
        recorder.commit()
        return responses

    # ------------------------------------------------- columnar tick ops

    def _sample_candidates(self, work: list, k: int):
        """Uniform up-to-k present-DAG-slot samples for every pending
        peer, drawn per TASK group through the shared _sample_rows helper
        (the vectorised fill draws identically inside its fused per-task
        pass, so both paths see the same candidates for the same seed).
        Returns ((b, k) int32 slot matrix padded -1, {task_id: rows})."""
        b = len(work)
        out = np.full((b, k), -1, np.int32)
        groups = self._group_rows_by_task(work)
        for task_id, rows in groups.items():
            dag = self._task_dag(task_id)
            live = np.flatnonzero(dag.present)
            if live.size == 0:
                continue
            s = _sample_rows(self.rng, live, len(rows), k)
            out[np.asarray(rows)[:, None], np.arange(s.shape[1])] = s
        return out, groups

    def _group_rows_by_task(self, work: list) -> dict[str, list[int]]:
        groups: dict[str, list[int]] = {}
        for i, pending in enumerate(work):
            groups.setdefault(
                self._peer_meta[pending.peer_id].task_id, []
            ).append(i)
        return groups

    def _quarantined_slot_mask(self) -> np.ndarray:
        """Bool mask over HOST slots for this tick's candidate fill: one
        decay-aware is_quarantined check per active host (same release
        side effects as the per-candidate checks it replaces), gathered
        by the vectorised fill in one fancy index."""
        mask = np.zeros(self.state.max_hosts, bool)
        for host_id in self.quarantine.active():
            if not self.quarantine.is_quarantined(host_id):
                continue
            slot = self.state.host_index(host_id)
            if slot is not None:
                mask[slot] = True
        return mask

    def _fill_candidates_vec(self, work: list, k: int):
        """Columnar candidate fill: ONE fused pass per task group samples
        live DAG slots (dag.go GetRandomVertices semantics) and gathers
        slot->peer-row / in-degree columns; validity masking, self/
        quarantine exclusion and the stable left-compaction (matching the
        per-peer loop's skip-and-append candidate order) run as flat
        (B, K) ops; DAG legality batches once per task. Python work is
        O(tasks + blocklisted rows), not O(B x K)."""
        st = self.state
        b = len(work)
        child_peer_idx = np.fromiter(
            (st.peer_index(p.peer_id) for p in work), np.int64, b
        ).astype(np.int32)
        child_host_slots = st.peer_host[child_peer_idx].astype(np.int32)
        child_dag_slot = np.fromiter(
            (self._peer_meta[p.peer_id].dag_slot for p in work), np.int64, b
        )
        groups = self._group_rows_by_task(work)
        samples = np.full((b, k), -1, np.int64)
        pidx = np.full((b, k), -1, np.int64)
        ind = np.zeros((b, k), np.int32)
        task_rows: list[tuple] = []  # (task_id, dag, row_array) for legality
        for task_id, rows in groups.items():
            dag = self._task_dag(task_id)
            spx = self._slot_pidx.get(task_id)
            live = np.flatnonzero(dag.present)
            r = np.asarray(rows, np.int64)
            task_rows.append((task_id, dag, r))
            if live.size == 0 or spx is None:
                continue
            s = _sample_rows(self.rng, live, r.size, k)
            cols = np.arange(s.shape[1])
            rr = r[:, None]
            samples[rr, cols] = s
            pidx[rr, cols] = spx[s]
            ind[rr, cols] = dag.in_degree[s]
        valid = pidx >= 0
        safe = np.where(valid, pidx, 0)
        valid &= st.peer_alive[safe]
        valid &= pidx != child_peer_idx[:, None]
        host = st.peer_host[safe].astype(np.int64)
        if self.quarantine.active_count():
            qmask = self._quarantined_slot_mask()
            would = valid & qmask[np.clip(host, 0, st.max_hosts - 1)]
            skipped = int(would.sum())
            if skipped:
                valid &= ~would
                self._series.quarantine_skipped.labels().inc(skipped)
        # left-compact valid candidates, preserving sample order (the
        # per-peer loop appends survivors in sample order)
        order = np.argsort(~valid, axis=1, kind="stable")
        take = lambda a: np.take_along_axis(a, order, axis=1)  # noqa: E731
        cand_valid = take(valid)
        cand_peer_idx = np.where(cand_valid, take(safe), 0).astype(np.int32)
        cand_slots = np.where(cand_valid, take(np.where(valid, samples, 0)), -1)
        cand_host_slots = np.where(cand_valid, take(host), 0).astype(np.int32)
        in_degree = np.where(cand_valid, take(ind), 0).astype(np.int32)
        blocklist = np.zeros((b, k), bool)
        for i, pending in enumerate(work):
            if not pending.blocklist:
                continue
            bidx = {st.peer_index(x) for x in pending.blocklist}
            bidx.discard(None)
            if bidx:
                blocklist[i] = cand_valid[i] & np.isin(
                    cand_peer_idx[i], np.fromiter(bidx, np.int64, len(bidx))
                )
        can_add_edge = np.zeros((b, k), bool)
        for task_id, dag, r in task_rows:
            v = cand_valid[r]
            if not v.any():
                continue
            rr, cc = np.nonzero(v)
            ok = dag.can_add_edges_pairs(
                cand_slots[r][rr, cc],
                child_dag_slot[r][rr],
            )
            can_add_edge[r[rr], cc] = ok
        return (cand_peer_idx, cand_valid, child_peer_idx, blocklist,
                in_degree, can_add_edge, child_host_slots, cand_host_slots,
                cand_slots, None)

    def _fill_candidates_loop(self, work: list, samples: np.ndarray, k: int):
        """Per-peer loop fill (the pre-columnar path, kept verbatim as the
        decision-equivalence oracle): consumes the same shared candidate
        samples, then filters/marks one candidate at a time."""
        st = self.state
        b = len(work)
        cand_peer_idx = np.zeros((b, k), np.int32)
        cand_valid = np.zeros((b, k), bool)
        child_peer_idx = np.zeros(b, np.int32)
        blocklist = np.zeros((b, k), bool)
        in_degree = np.zeros((b, k), np.int32)
        can_add_edge = np.zeros((b, k), bool)
        cand_ids: list[list[str]] = []
        child_host_slots = np.zeros(b, np.int32)
        cand_host_slots = np.zeros((b, k), np.int32)
        cand_slots = np.full((b, k), -1, np.int64)
        # Cycle checks batch PER TASK, not per peer: all pending peers of
        # one task share a DAG, and the (parent_slot, child_slot) pairs
        # API pays one ctypes round-trip per task per tick — the per-peer
        # call's ~100 us marshalling was the biggest host-side tick cost
        # after the transport fix.
        task_pairs: dict[str, list[tuple[int, int, int, int]]] = {}
        # Quarantine snapshot for this tick: hosts currently excluded for
        # integrity failures. The common case (nothing quarantined) costs
        # one lock-free-ish length check; members are re-checked through
        # is_quarantined so decay-released hosts rejoin mid-snapshot.
        q_active = self.quarantine.active() if self.quarantine.active_count() else ()
        for i, pending in enumerate(work):
            meta = self._peer_meta[pending.peer_id]
            child_peer_idx[i] = st.peer_index(pending.peer_id)
            child_host_slots[i] = st.peer_host[child_peer_idx[i]]
            dag = self._task_dag(meta.task_id)
            sampled = samples[i][samples[i] >= 0]
            slot_to_peer = self._dag_slot_peer.get(meta.task_id, {})
            ids = []
            pairs = task_pairs.setdefault(meta.task_id, [])
            j = 0
            for slot in sampled:
                pid = slot_to_peer.get(int(slot))
                if pid is None or pid == pending.peer_id:
                    continue
                pidx = st.peer_index(pid)
                if pidx is None:
                    continue
                if q_active:
                    phost = st.host_id_at(int(st.peer_host[pidx]))
                    if phost in q_active and self.quarantine.is_quarantined(phost):
                        self._series.quarantine_skipped.labels().inc()
                        continue
                cand_peer_idx[i, j] = pidx
                cand_valid[i, j] = True
                blocklist[i, j] = pid in pending.blocklist
                in_degree[i, j] = dag.in_degree[slot]
                cand_host_slots[i, j] = st.peer_host[pidx]
                cand_slots[i, j] = int(slot)
                pairs.append((int(slot), meta.dag_slot, i, j))
                ids.append(pid)
                j += 1
                if j >= k:
                    break
            cand_ids.append(ids)
        for task_id, pairs in task_pairs.items():
            if not pairs:
                continue
            arr = np.asarray(pairs, np.int64)
            ok = self._task_dag(task_id).can_add_edges_pairs(arr[:, 0], arr[:, 1])
            can_add_edge[arr[:, 2], arr[:, 3]] = ok
        return (cand_peer_idx, cand_valid, child_peer_idx, blocklist,
                in_degree, can_add_edge, child_host_slots, cand_host_slots,
                cand_slots, cand_ids)

    def _apply_chunk_batch(self, work: list, s: int, e: int, selected,
                           selected_valid, selected_scores, cand_peer_idx,
                           cand_slots, cand_count, responses: list,
                           led_ctx: dict | None = None) -> None:
        """Batched selection apply for rows [s:e): DAG edges land through
        one grouped legality batch per task (graph/dag.add_edges_grouped,
        sequential-equivalent), upload-slot accounting through one
        scatter-add, and responses are emitted in row order (the same
        order the per-peer path produces, so downstream consumers see an
        identical stream). With ``led_ctx`` every APPLIED row lands in
        the decision ledger as one block record per chunk."""
        st = self.state
        limit = self.config.scheduler.candidate_parent_limit
        # pass 1: decode selections per row, group DAG edge adds per task.
        # One tolist() per array up front: the loop below touches every
        # (row, j) cell, and python-list indexing beats numpy scalar
        # indexing ~10x on this all-scalar walk (same values — tolist
        # converts float32 cells to the identical python float the old
        # per-cell float() produced).
        sel_l = np.asarray(selected)[: e - s].tolist()
        val_l = np.asarray(selected_valid)[: e - s].tolist()
        sco_l = np.asarray(selected_scores)[: e - s].tolist()
        cnt_l = np.asarray(cand_count[s:e]).tolist()
        slots_l = np.asarray(cand_slots[s:e]).tolist()
        cpi_l = np.asarray(cand_peer_idx[s:e]).tolist()
        rows_sel: list = [None] * (e - s)
        by_task: dict[str, list[int]] = {}
        for row, i in enumerate(range(s, e)):
            pending = work[i]
            meta = self._peer_meta[pending.peer_id]
            count = cnt_l[row]
            vrow = val_l[row]
            srow = sel_l[row]
            scrow = sco_l[row]
            row_slots = slots_l[row]
            row_pidx = cpi_l[row]
            pslots, ppidx, pscores, ppos = [], [], [], []
            for j in range(limit):
                if not vrow[j]:
                    break
                pos = srow[j]
                if pos >= count:
                    continue
                pslots.append(row_slots[pos])
                ppidx.append(row_pidx[pos])
                pscores.append(scrow[j])
                ppos.append(pos)
            if not pslots:
                pending.retries += 1
                continue  # stays pending for the next tick (retry loop)
            rows_sel[row] = (pending, meta, pslots, ppidx, pscores, ppos)
            by_task.setdefault(meta.task_id, []).append(row)
        # pass 2: one grouped edge-add batch per task (row order within a
        # task preserved; tasks have disjoint DAGs so cross-task order is
        # immaterial)
        accepted: dict[int, np.ndarray] = {}
        for task_id, task_rows in by_task.items():
            dag = self._task_dag(task_id)
            if len(task_rows) == 1:
                # dominant shape (~one decision per task per tick): the
                # scalar single-group twin skips the grouped batch's array
                # construction and staleness bookkeeping, same mask
                r = task_rows[0]
                accepted[r] = dag.add_edges_single(
                    rows_sel[r][2], rows_sel[r][1].dag_slot
                )
                continue
            acc = dag.add_edges_grouped(
                [np.asarray(rows_sel[r][2], np.int64) for r in task_rows],
                np.asarray([rows_sel[r][1].dag_slot for r in task_rows], np.int64),
            )
            for r, a in zip(task_rows, acc):
                accepted[r] = a.tolist()
        # pass 3: responses + upload accounting, in row order (attribute
        # lookups hoisted: this loop runs once per scheduled peer per tick
        # and its dict/array accessors showed up in the tick profile)
        peer_id_of = st._peer_id
        peer_host_col = st.peer_host
        peer_state_col = st.peer_state
        meta_get = self._peer_meta.get
        host_get = self._host_info.get
        children_of = self._children_of_parent
        pending_pop = self._pending.pop
        upload_hosts: list[int] = []
        rec_rows: list[int] = []
        rec_sel_pos: list = []
        rec_sel_scores: list = []
        rec_sel_acc: list = []
        rec_chosen: list[int] = []
        rec_peer_ids: list = []
        rec_task_ids: list = []
        rec_chosen_ids: list = []
        limit_pad = limit
        for row in range(e - s):
            entry = rows_sel[row]
            if entry is None:
                continue
            pending, meta, pslots, ppidx, pscores, ppos = entry
            acc = accepted.get(row)
            kept = []
            kept_flags = []
            for pid_idx, score, ok in zip(ppidx, pscores, acc):
                if not ok:
                    kept_flags.append(False)
                    continue
                pid = peer_id_of[pid_idx]
                pmeta = meta_get(pid) if pid is not None else None
                if pmeta is None:
                    kept_flags.append(False)
                    continue
                kept_flags.append(True)
                upload_hosts.append(int(peer_host_col[pid_idx]))
                meta.held_parents.add(pid)
                children_of.setdefault(pid, set()).add(pending.peer_id)
                host = host_get(pmeta.host_id)
                kept.append(
                    msg.CandidateParent(
                        peer_id=pid,
                        host_id=pmeta.host_id,
                        ip=host.ip if host else "",
                        port=host.port if host else 0,
                        download_port=host.download_port if host else 0,
                        state=_STATE_DISPLAY[int(peer_state_col[pid_idx])],
                        score=score,
                    )
                )
            if not kept:
                pending.retries += 1
                continue  # stays pending (all selections DAG-rejected)
            responses.append(self._finish_normal_response(pending, meta, kept))
            pending_pop(pending.peer_id, None)
            if led_ctx is not None:
                i = s + row
                pad = limit_pad - len(ppos)
                rec_rows.append(i)
                rec_sel_pos.append(ppos[:limit_pad] + [-1] * max(pad, 0))
                rec_sel_scores.append(
                    pscores[:limit_pad] + [np.nan] * max(pad, 0)
                )
                rec_sel_acc.append(
                    kept_flags[:limit_pad] + [False] * max(pad, 0)
                )
                first = next(
                    p for p, f in zip(ppos, kept_flags) if f
                )
                rec_chosen.append(first)
                rec_peer_ids.append(pending.peer_id)
                rec_task_ids.append(meta.task_id)
                rec_chosen_ids.append(kept[0].peer_id)
        if upload_hosts:
            np.add.at(
                st.host_upload_used, np.asarray(upload_hosts, np.int64), 1
            )
        if led_ctx is not None and rec_rows:
            rows = np.asarray(rec_rows, np.int64)
            slots, seqs = self.decisions.record_batch(
                led_ctx["tick"], led_ctx["arm"],
                led_ctx["child_peer_idx"][rows],
                led_ctx["child_host_slots"][rows],
                np.asarray(cand_peer_idx)[rows],
                led_ctx["cand_host_slots"][rows],
                np.asarray(cand_count)[rows],
                led_ctx["feats"][rows],
                np.asarray(rec_sel_pos, np.int64),
                np.asarray(rec_sel_scores, np.float32),
                np.asarray(rec_sel_acc, bool),
                np.asarray(rec_chosen, np.int64),
                rec_peer_ids, rec_task_ids, rec_chosen_ids,
            )
            led_ctx["slot_of_row"][rows] = slots
            led_ctx["seq_of_row"][rows] = seqs

    def _record_loop_decision(self, led_ctx: dict, i: int, pending: _Pending,
                              meta: _PeerMeta, parents: list, ranked_pos: list,
                              cand_peer_idx, cand_count, response) -> None:
        """Decision-ledger record for the per-peer oracle path: the same
        row `_apply_chunk_batch` writes on the vectorised path, built
        from the loop fill's candidate arrays. The oracle path is the
        decision-equivalence baseline, so its ledger rows must carry the
        same provenance the production path records."""
        limit = self.config.scheduler.candidate_parent_limit
        kept_ids = {cp.peer_id for cp in response.candidate_parents}
        flags = [pid in kept_ids for pid, _ in parents]
        pad = limit - len(ranked_pos)
        sel_pos = ranked_pos[:limit] + [-1] * max(pad, 0)
        sel_scores = [sc for _, sc in parents][:limit] + [np.nan] * max(pad, 0)
        sel_acc = flags[:limit] + [False] * max(pad, 0)
        chosen = next(p for p, f in zip(ranked_pos, flags) if f)
        rows = np.asarray([i], np.int64)
        slots, seqs = self.decisions.record_batch(
            led_ctx["tick"], led_ctx["arm"],
            led_ctx["child_peer_idx"][rows],
            led_ctx["child_host_slots"][rows],
            np.asarray(cand_peer_idx)[rows],
            led_ctx["cand_host_slots"][rows],
            np.asarray(cand_count)[rows],
            led_ctx["feats"][rows],
            np.asarray([sel_pos], np.int64),
            np.asarray([sel_scores], np.float32),
            np.asarray([sel_acc], bool),
            np.asarray([chosen], np.int64),
            [pending.peer_id], [meta.task_id],
            [response.candidate_parents[0].peer_id],
        )
        led_ctx["slot_of_row"][rows] = slots
        led_ctx["seq_of_row"][rows] = seqs

    def _drain_shadow(self, inflight: list, slot_of_row: np.ndarray,
                      seq_of_row: np.ndarray, shadow_arm_code: int):
        """End-of-tick drain valve for the counterfactual shadow arm's
        in-flight device results: the ONLY place shadow selections come
        back to the host. Runs strictly after the last serving chunk's
        drain — the shadow D2H can never serialize the pipelined tick —
        and its wall is credited to the `shadow_score` phase, outside
        the control_dispatch/device_call aggregates. On the jit-hygiene
        D2H_ALLOWLIST (tools/dflint/passes/jit_hygiene.py): a shadow
        read-back anywhere else on the tick path fails JIT003."""
        recorder = self.recorder
        t0 = time.perf_counter()
        limit = self.config.scheduler.candidate_parent_limit
        b = slot_of_row.shape[0]
        pos = np.full((b, limit), -1, np.int64)
        scores = np.full((b, limit), np.nan, np.float32)
        for s, e, packed in inflight:
            arr = np.asarray(packed)[: e - s]
            sel, valid, sc = ev.unpack_selection(arr)
            ll = min(limit, sel.shape[1])
            pos[s:e, :ll] = np.where(valid, sel, -1)[:, :ll]
            scores[s:e, :ll] = np.where(valid, sc, np.nan)[:, :ll]
        entry = self.decisions.record_shadow(
            slot_of_row, seq_of_row, pos, scores, shadow_arm_code,
            self._tick_counter,
        )
        recorder.add("shadow_score", (time.perf_counter() - t0) * 1e3)
        recorder.sync()
        return entry

    def _finish_normal_response(self, pending: _Pending, meta: _PeerMeta,
                                kept: list) -> msg.NormalTaskResponse:
        """Attach the attested digest chain (when it grew since this
        peer's last response) and build the NormalTaskResponse — shared
        tail of the per-peer and batched apply paths."""
        chain = self._task_piece_digests.get(meta.task_id)
        digests = {}
        if chain:
            sent = self._chain_sent.setdefault(meta.task_id, {})
            if sent.get(pending.peer_id, 0) < len(chain):
                # string keys: the wire codec's hardened unpack
                # (strict_map_key) refuses int map keys, and the
                # conductor re-ints them on receipt
                digests = {str(n): d for n, d in chain.items()}
                sent[pending.peer_id] = len(digests)
        return msg.NormalTaskResponse(
            peer_id=pending.peer_id,
            candidate_parents=kept,
            piece_digests=digests,
            task_digest=self._task_sha256.get(meta.task_id, ""),
        )

    # ============================================================ helpers

    def _pre_schedule(self, pending: _Pending):
        """Back-to-source / retry-exhaustion decisions (scheduling.go:95-159)."""
        sched = self.config.scheduler
        idx = self.state.peer_index(pending.peer_id)
        if idx is None:
            return msg.ScheduleFailure(pending.peer_id, "NotFound", "peer vanished")
        if self.state.peer_state[idx] != int(PeerState.RUNNING):
            return msg.ScheduleFailure(
                pending.peer_id, "FailedPrecondition",
                f"peer state {PeerState(int(self.state.peer_state[idx])).display} not Running",
            )
        task_idx = self.state.peer_task[idx]
        if (
            pending.retries >= sched.retry_back_to_source_limit
            and self.state.task_back_to_source_count[task_idx]
            < self.state.task_back_to_source_limit[task_idx]
        ):
            return msg.NeedBackToSourceResponse(
                pending.peer_id, f"scheduling exceeded RetryBackToSourceLimit {pending.retries}"
            )
        if pending.retries >= sched.retry_limit:
            return msg.ScheduleFailure(
                pending.peer_id, "FailedPrecondition",
                f"scheduling exceeded RetryLimit {pending.retries}",
            )
        return None

    def _apply_selection(self, pending: _Pending, meta: _PeerMeta, parents: list[tuple[str, float]]):
        dag = self._task_dag(meta.task_id)
        kept = []
        # All of this child's new edges END at its slot, so one batched
        # legality pass equals the old per-edge add_edge sequence
        # (graph/dag.py add_edges_from) at one native round-trip.
        known = [
            (pid, score, pm)
            for pid, score in parents
            if (pm := self._peer_meta.get(pid)) is not None
        ]
        accepted = dag.add_edges_from(
            np.asarray([pm.dag_slot for _, _, pm in known], np.int64),
            meta.dag_slot,
        )
        for (pid, score, pmeta), ok in zip(known, accepted):
            if not ok:
                continue
            pidx = self.state.peer_index(pid)
            self.state.host_upload_used[self.state.peer_host[pidx]] += 1
            meta.held_parents.add(pid)
            self._children_of_parent.setdefault(pid, set()).add(pending.peer_id)
            host = self._host_info.get(pmeta.host_id)
            kept.append(
                msg.CandidateParent(
                    peer_id=pid,
                    host_id=pmeta.host_id,
                    ip=host.ip if host else "",
                    port=host.port if host else 0,
                    download_port=host.download_port if host else 0,
                    state=_STATE_DISPLAY[int(self.state.peer_state[pidx])],
                    score=score,
                )
            )
        if not kept:
            pending.retries += 1
            self._pending[pending.peer_id] = pending
            return None  # caller keeps the peer pending for the next tick
        return self._finish_normal_response(pending, meta, kept)

    def _reported_download_cost_ns(self, idx) -> int:
        """The peer's download cost summed from its REPORTED piece costs
        (virtual time in replays, measured transfer time in production)
        — the decision ledger's replay-safe outcome label basis. The
        cost ring retains only the newest ``piece_cost_capacity``
        entries, so the total is the retained mean scaled to the
        finished-piece count. Caller must have flushed buffered piece
        reports (the columns this reads)."""
        st = self.state
        retained = int(min(st.peer_piece_cost_count[idx],
                           st.piece_cost_capacity))
        if retained <= 0:
            return 0
        mean = float(st.peer_piece_costs[idx, :retained].mean())
        return int(mean * max(int(st.peer_finished_count[idx]), retained))

    def _release_parent_slots(self, peer_id: str) -> None:
        """Free the upload slots this child holds on its parents' hosts.

        Tracked explicitly in meta.held_parents (not derived from DAG edges)
        so release is idempotent across reschedule/finish/leave orderings.
        """
        meta = self._peer_meta.get(peer_id)
        if meta is None:
            return
        for pid in meta.held_parents:
            pidx = self.state.peer_index(pid)
            if pidx is not None:
                host_idx = self.state.peer_host[pidx]
                self.state.host_upload_used[host_idx] = max(
                    0, int(self.state.host_upload_used[host_idx]) - 1
                )
            holders = self._children_of_parent.get(pid)
            if holders is not None:
                holders.discard(peer_id)
                if not holders:
                    del self._children_of_parent[pid]
        meta.held_parents.clear()

    def _write_download_record(self, peer_id: str, state: str) -> None:
        if self.storage is None:
            return
        # flush valve: the record reads the piece columns and the
        # per-parent stats buffered reports feed. Record-less services
        # (the bench A/B arms, most tests) skip this entirely and absorb
        # once per tick instead of once per completion.
        self._absorb_piece_reports()
        meta = self._peer_meta.get(peer_id)
        idx = self.state.peer_index(peer_id)
        if meta is None or idx is None:
            return
        task_idx = self.state.peer_task[idx]
        now_ns = time.time_ns()
        parents = []
        for pid, stats in list(meta.parents.items())[:20]:
            pmeta = self._peer_meta.get(pid)
            pidx = self.state.peer_index(pid)
            if pmeta is None or pidx is None:
                continue
            phost = self._host_info.get(pmeta.host_id)
            parents.append(
                ParentRecord(
                    id=pid,
                    tag=pmeta.tag,
                    application=pmeta.application,
                    state=_STATE_DISPLAY[int(self.state.peer_state[pidx])],
                    cost=sum(p.cost for p in stats["pieces"]),
                    upload_piece_count=len(stats["pieces"]),
                    finished_piece_count=int(self.state.peer_finished_count[pidx]),
                    host=self._host_record(phost) if phost else HostRecord(id=pmeta.host_id),
                    pieces=stats["pieces"],
                    created_at=pmeta.created_at_ns,
                    updated_at=now_ns,
                )
            )
        host = self._host_info.get(meta.host_id)
        record = DownloadRecord(
            id=peer_id,
            tag=meta.tag,
            application=meta.application,
            state=state,
            cost=now_ns - meta.created_at_ns,
            finished_piece_count=int(self.state.peer_finished_count[idx]),
            task=TaskRecord(
                id=meta.task_id,
                type="standard",
                content_length=int(self.state.task_content_length[task_idx]),
                total_piece_count=int(self.state.task_total_pieces[task_idx]),
                back_to_source_limit=int(self.state.task_back_to_source_limit[task_idx]),
                back_to_source_peer_count=int(self.state.task_back_to_source_count[task_idx]),
                state=TaskState(int(self.state.task_state[task_idx])).display,
                created_at=meta.created_at_ns,
                updated_at=now_ns,
            ),
            host=self._host_record(host) if host else HostRecord(id=meta.host_id),
            parents=parents,
            created_at=meta.created_at_ns,
            updated_at=now_ns,
        )
        self.storage.create_download(record)

    def _host_record(self, host: msg.HostInfo) -> HostRecord:
        # memoised per announcement object: a HostInfo is immutable once
        # registered (re-announce replaces the _host_info entry, which
        # misses the identity check and rebuilds), and records only ever
        # serialise the HostRecord — so sharing one instance across the
        # per-completion download records is safe and skips ~2 nested
        # dataclass builds per record on the replay critical path
        cached = self._host_record_cache.get(host.host_id)
        if cached is not None and cached[0] is host:
            return cached[1]
        rec = self._build_host_record(host)
        if len(self._host_record_cache) > 4 * self.state.max_hosts:
            self._host_record_cache.clear()
        self._host_record_cache[host.host_id] = (host, rec)
        return rec

    def _build_host_record(self, host: msg.HostInfo) -> HostRecord:
        return HostRecord(
            id=host.host_id,
            type=host.host_type,
            hostname=host.hostname,
            ip=host.ip,
            port=host.port,
            download_port=host.download_port,
            concurrent_upload_limit=host.concurrent_upload_limit,
            upload_count=host.upload_count,
            upload_failed_count=host.upload_failed_count,
            cpu=host.cpu,
            memory=host.memory,
            disk=host.disk,
            network=NetworkStat(
                tcp_connection_count=host.tcp_connection_count,
                upload_tcp_connection_count=host.upload_tcp_connection_count,
                location=host.location,
                idc=host.idc,
            ),
        )

    def _task_dag(self, task_id: str) -> TaskDAG:
        dag = self._dags.get(task_id)
        if dag is None:
            dag = TaskDAG(self._dag_capacity)
            self._dags[task_id] = dag
            # columnar twin of _dag_slot_peer: DAG slot -> SoA peer row
            self._slot_pidx[task_id] = np.full(self._dag_capacity, -1, np.int32)
            if self._tick_mirror is not None:
                self._fused_dirty_tasks.add(task_id)
        return dag

    def _alloc_dag_slot(self, task_id: str, peer_id: str, dag: TaskDAG) -> int:
        """Next free vertex slot, or -1 when every slot is held by a live
        peer (register_peer refuses the peer; the daemon back-sources)."""
        slots = self._dag_slot_peer.setdefault(task_id, {})
        free = np.flatnonzero(~dag.present)
        if free.size == 0:
            return -1
        slot = int(free[0])  # lowest free slot, like the old linear scan
        dag.ensure_vertex(slot)
        slots[slot] = peer_id
        return slot

    def _leave_peer(self, peer_id: str) -> None:
        # flush FIRST: buffered piece reports reference SoA rows by index,
        # and this is the only path that frees rows for reuse — absorbing
        # after the free could credit a recycled row
        self._absorb_piece_reports()
        meta = self._peer_meta.get(peer_id)
        if meta is None:
            return
        # Free slots this child holds, and slots children hold on THIS peer's
        # host (its out-edges die with the vertex). The reverse index
        # (_children_of_parent) names the holders directly — the previous
        # every-peer scan was ~200 us per leave at 10k hosts, the
        # dominant GC-sweep cost in the loop bench.
        self._release_parent_slots(peer_id)
        for child_pid in self._children_of_parent.pop(peer_id, ()):
            child_meta = self._peer_meta.get(child_pid)
            if child_meta is None or peer_id not in child_meta.held_parents:
                continue
            child_meta.held_parents.discard(peer_id)
            idx_self = self.state.peer_index(peer_id)
            if idx_self is not None:
                host_idx = self.state.peer_host[idx_self]
                self.state.host_upload_used[host_idx] = max(
                    0, int(self.state.host_upload_used[host_idx]) - 1
                )
        self._peer_meta.pop(peer_id, None)
        if self.decisions is not None:
            # drop the pending-join mapping so a recycled peer id can
            # never join an outcome to the departed peer's decision
            self.decisions.discard(peer_id)
        sent = self._chain_sent.get(meta.task_id)
        if sent is not None:
            sent.pop(peer_id, None)
        idx = self.state.peer_index(peer_id)
        if idx is not None and self.state.peer_state[idx] != int(PeerState.LEAVE):
            self.state.peer_event(idx, PeerEvent.LEAVE)
        dag = self._task_dag(meta.task_id)
        dag.delete_vertex(meta.dag_slot)
        self._dag_slot_peer.get(meta.task_id, {}).pop(meta.dag_slot, None)
        spx = self._slot_pidx.get(meta.task_id)
        if spx is not None and 0 <= meta.dag_slot < spx.shape[0]:
            spx[meta.dag_slot] = -1
            if self._tick_mirror is not None:
                self._fused_dirty_tasks.add(meta.task_id)
        peers = self._task_peers.get(meta.task_id)
        if peers and peer_id in peers:
            peers.remove(peer_id)
        self._pending.pop(peer_id, None)
        self.state.remove_peer(peer_id)

    # ========================================================= dynconfig

    def apply_dynconfig(self, data: dict) -> None:
        """Hot-apply manager-pushed cluster limits into the live tick
        (scheduler/config/dynconfig.go:457 Notify -> the scheduling
        config the retry loop reads). Registered as a Dynconfig observer
        by the launcher; tick() reads these fields per call, so the next
        batch after a refresh already honors the new limits."""
        cfg = data.get("scheduler_cluster_config") or {}
        int_fields = (
            "candidate_parent_limit",
            "filter_parent_limit",
            "retry_limit",
            "retry_back_to_source_limit",
        )
        float_fields = (
            "peer_ttl_seconds",
            "host_ttl_seconds",
            "piece_download_timeout_seconds",
        )
        with self.mu:
            for key in int_fields:
                if key in cfg:
                    try:
                        value = int(cfg[key])
                    except (TypeError, ValueError):
                        continue
                    if value >= 1:
                        setattr(self.config.scheduler, key, value)
            for key in float_fields:
                if key in cfg:
                    try:
                        value = float(cfg[key])
                    except (TypeError, ValueError):
                        continue
                    if value > 0:
                        setattr(self.config.scheduler, key, value)

    # ================================================================ gc

    def gc_due(self, now: float | None = None) -> bool:
        """Lock-free pre-check so the tick loop only pays a thread hop and
        the service lock when some sweep's interval has actually elapsed."""
        now = time.time() if now is None else now
        sched = self.config.scheduler
        return (
            now - self._last_peer_gc >= sched.peer_gc_interval_seconds
            or now - self._last_task_gc >= sched.task_gc_interval_seconds
            or now - self._last_host_gc >= sched.host_gc_interval_seconds
        )

    def run_gc(self, now: float | None = None, force: bool = False) -> dict[str, int]:
        """TTL sweeps over peers/tasks/hosts, each on its own interval
        (pkg/gc/gc.go:28-63 interval runners wired into the resource
        managers, scheduler/resource/{peer,task,host}_manager.go RunGC).
        Called from the live tick loop every tick; cheap no-op between
        interval boundaries. Returns per-kind reap counts for the sweeps
        that ran."""
        now = time.time() if now is None else now
        sched = self.config.scheduler
        swept: dict[str, int] = {}
        with self.mu:
            # TTL sweeps read peer/host updated_at — absorb buffered
            # reports so recent activity counts as liveness
            self._absorb_piece_reports()
            if force or now - self._last_peer_gc >= sched.peer_gc_interval_seconds:
                self._last_peer_gc = now
                swept["peers"] = self._gc_peers(now)
            if force or now - self._last_task_gc >= sched.task_gc_interval_seconds:
                self._last_task_gc = now
                swept["tasks"] = self._gc_tasks()
            if force or now - self._last_host_gc >= sched.host_gc_interval_seconds:
                self._last_host_gc = now
                swept["hosts"] = self._gc_hosts()
        return swept

    def _gc_peers(self, now: float) -> int:
        """peer_manager.go:154-220 RunGC, vectorised: FAILED peers, piece
        stalls past the download timeout, peer-TTL and host-TTL expiry all
        leave; _leave_peer does the full host-side cleanup (meta, DAG slot,
        upload slots, pending queue, SoA row)."""
        st = self.state
        sched = self.config.scheduler
        age = now - st.peer_updated_at
        pstate = st.peer_state
        downloading = (pstate == int(PeerState.RUNNING)) | (
            pstate == int(PeerState.BACK_TO_SOURCE)
        )
        host_age = now - st.host_updated_at
        peer_host_age = host_age[np.clip(st.peer_host, 0, None)]
        stale = st.peer_alive & (
            (pstate == int(PeerState.FAILED))
            | (downloading & (age > sched.piece_download_timeout_seconds))
            | (age > sched.peer_ttl_seconds)
            | (peer_host_age > sched.host_ttl_seconds)
        )
        reaped = 0
        for idx in np.nonzero(stale)[0]:
            pid = st._peer_id[idx]
            if pid is not None:
                self._leave_peer(pid)
                reaped += 1
        return reaped

    def _gc_tasks(self) -> int:
        """task_manager.go:116-134 RunGC: a task whose peers have all been
        reclaimed is reclaimed, along with its host-side DAG and slot maps
        (the dict leak the SoA free-list can't see)."""
        reaped = 0
        for task_id in list(self.state._task_by_id):
            if self._task_peers.get(task_id):
                continue
            self.state.remove_task(task_id)
            self._drop_task_maps(task_id)
            reaped += 1
        # Host-side maps can outlive the SoA row (or never have had one);
        # sweep orphans so _dags/_task_peers stay bounded too.
        for task_id in list(self._dags):
            if self.state.task_index(task_id) is None and not self._task_peers.get(task_id):
                self._drop_task_maps(task_id)
        return reaped

    def _drop_task_maps(self, task_id: str) -> None:
        self._dags.pop(task_id, None)
        self._dag_slot_peer.pop(task_id, None)
        self._slot_pidx.pop(task_id, None)
        self._task_peers.pop(task_id, None)
        self._task_piece_digests.pop(task_id, None)
        self._task_sha256.pop(task_id, None)
        self._chain_sent.pop(task_id, None)

    def _gc_hosts(self) -> int:
        """host_manager.go:146-163 RunGC: a normal host with no peers and
        no upload slots in use is reclaimed (seed/super hosts persist)."""
        st = self.state
        peers_per_host = np.bincount(
            st.peer_host[st.peer_alive], minlength=st.max_hosts
        )
        reaped = 0
        for host_id in list(self._host_info):
            idx = st.host_index(host_id)
            if idx is None:
                self._host_info.pop(host_id, None)
                continue
            if (
                peers_per_host[idx] == 0
                and int(st.host_upload_used[idx]) == 0
                and int(st.host_type[idx]) == int(HostType.NORMAL)
            ):
                self.leave_host(host_id)
                reaped += 1
        return reaped

    def snapshot_topology(self, now_ns: int | None = None) -> int:
        """Write the probe graph to trace storage (the networktopology
        Snapshot ticker, network_topology.go:124-138). Returns rows written."""
        if self.probes is None or self.storage is None:
            return 0
        now_ns = time.time_ns() if now_ns is None else now_ns
        host_info = {}
        for host_id, info in self._host_info.items():
            slot = self.state.host_index(host_id)
            if slot is None:
                continue
            host_info[slot] = {
                "id": host_id,
                "type": info.host_type,
                "hostname": info.hostname,
                "ip": info.ip,
                "port": info.port,
                "location": info.location,
                "idc": info.idc,
            }
        records = self.probes.snapshot(host_info, now_ns)
        for rec in records:
            self.storage.create_network_topology(rec)
        return len(records)

    def counts(self) -> dict:
        c = self.state.counts()
        c["pending"] = len(self._pending)
        c["tasks_with_dag"] = len(self._dags)
        c["quarantined_hosts"] = self.quarantine.active_count()
        c["tasks_with_digest_chain"] = len(self._task_piece_digests)
        return c

    def flight_dump(self, last_n: int = 64, sections=None,
                    max_bytes: int | None = None) -> dict:
        """Flight-recorder snapshot for THIS service (last-N tick phase
        breakdowns + process-wide jit compile counters + open spans +
        cost cards / timelines / the decision ledger) — served over the
        wire RPC (FlightRecorderRequest) and the manager REST surface so
        an operator can diagnose a slow tick without re-running the
        bench. `sections`/`max_bytes` bound the payload
        (telemetry/flight.DUMP_SECTIONS / DUMP_MAX_BYTES)."""
        from dragonfly2_tpu.telemetry import flight

        kwargs = {} if max_bytes is None else {"max_bytes": max_bytes}
        return flight.dump(last_n=last_n, recorder=self.recorder,
                           sections=sections, **kwargs)

    def serving_graph_arrays(self, consume_frontier: bool = True) -> dict:
        """Host graph for MLEvaluator.refresh_embeddings, built from this
        scheduler's OWN piece reports in the trainer's edge schema
        (records/features.py downloads_to_ranking_dataset: directions
        merged, edge_feats = [log1p(mean tput), log1p(count)] /
        EDGE_FEATURE_SCALE). The GNN was TRAINED with host quality
        arriving through these edges, so serving embeddings must carry
        the same signal — an empty graph demotes the ml evaluator to
        node-features-only, measurably below the rule blend.

        With `consume_frontier` (the refresh path's default) this is a
        DESTRUCTIVE read: the dirty frontier and full-sync flag pop
        exactly-once into the returned sideband. At most ONE caller per
        service may consume — a second would silently steal the frontier
        and leave its hosts stale until the next structural full sync.
        Inspection callers (debug dumps, tests, trainer exports) must
        pass consume_frontier=False, which reports the pending sideband
        without consuming it."""
        from dragonfly2_tpu.records.features import EDGE_FEATURE_SCALE

        with self.mu:
            self._absorb_piece_reports()  # edges/dirty-frontier visibility
            alive_mask = np.asarray(self.state.host_alive, bool)
            alive = np.nonzero(alive_mask)[0]
            used = int(alive.max()) + 1 if alive.size else 1
            merged: dict[tuple[int, int], list[float]] = {}
            dead_keys = []
            for full_key, (tput_sum, count) in self._serving_edges.items():
                a, gen_a, b, gen_b = full_key
                # Only edges between CURRENTLY-alive hosts in their
                # CURRENT generation: a GC'd host's slot may exceed
                # `used` (out-of-range for the padded node array), and a
                # recycled slot's old-generation entries belong to the
                # previous occupant — both are dropped and evicted.
                if (a >= alive_mask.size or b >= alive_mask.size
                        or not alive_mask[a] or not alive_mask[b]
                        or gen_a != self._slot_gen.get(a, 0)
                        or gen_b != self._slot_gen.get(b, 0)):
                    dead_keys.append(full_key)
                    continue
                for key in ((a, b), (b, a)):
                    acc = merged.setdefault(key, [0.0, 0])
                    acc[0] += tput_sum
                    acc[1] += count
            for key in dead_keys:
                del self._serving_edges[key]
            # Pop the dirty frontier atomically with the edge snapshot:
            # the caller's refresh either covers these slots or falls back
            # to a full recompute — either way they are consumed. A
            # refresh that later FAILS must re-request a full sync
            # (MLEvaluator handles that); the scheduler's contract is
            # exactly-once delivery of the frontier.
            dirty = np.fromiter(
                self._dirty_host_slots, np.int32, len(self._dirty_host_slots)
            )
            dirty.sort()
            full_sync = self._serving_full_sync
            if consume_frontier:
                self._dirty_host_slots.clear()
                self._serving_full_sync = False
        if merged:
            keys = list(merged.keys())
            edge_src = np.asarray([k[0] for k in keys], np.int32)
            edge_dst = np.asarray([k[1] for k in keys], np.int32)
            edge_feats = np.asarray(
                [[np.log1p(s / c), np.log1p(c)] for s, c in merged.values()],
                np.float32,
            ) / EDGE_FEATURE_SCALE
        else:
            edge_src = np.zeros(0, np.int32)
            edge_dst = np.zeros(0, np.int32)
            edge_feats = np.zeros((0, 2), np.float32)
        # Pad node and edge counts to power-of-two buckets so periodic
        # refreshes hit the jit cache instead of recompiling the embed
        # program for every new edge count. The last padded node row is a
        # zero-feature SINK that absorbs the padding self-edges — only
        # the sink's (never-gathered) embedding sees them.
        padded_n = pad_pow2(used + 1)
        node_feats = np.zeros((padded_n, self.state.host_numeric.shape[1]), np.float32)
        node_feats[:used] = self.state.host_numeric[:used]
        sink = padded_n - 1
        e = edge_src.shape[0]
        padded_e = pad_pow2(e)
        if padded_e != e:
            pad = padded_e - e
            edge_src = np.concatenate([edge_src, np.full(pad, sink, np.int32)])
            edge_dst = np.concatenate([edge_dst, np.full(pad, sink, np.int32)])
            edge_feats = np.concatenate([edge_feats, np.zeros((pad, 2), np.float32)])
        return {
            "node_feats": node_feats,
            "edge_src": edge_src,
            "edge_dst": edge_dst,
            "edge_feats": edge_feats,
            # Sideband for the incremental refresh (registry/serving.py
            # strips these before any jitted embed call — their varying
            # shapes must never become jit signature components):
            # host slots whose embedding inputs changed since the last
            # read, and whether structural changes force a full recompute.
            "dirty_slots": dirty,
            "full_sync": full_sync,
        }

    def task_states(self, task_ids: list[str]) -> list[int | None]:
        """Locked snapshot of per-task FSM states for cross-thread pollers
        (the manager's job-state refresh). None means the scheduler does
        not (or no longer) know the task id."""
        with self.mu:
            out: list[int | None] = []
            for task_id in task_ids:
                idx = self.state.task_index(task_id)
                out.append(None if idx is None else int(self.state.task_state[idx]))
            return out

    def list_hosts(self) -> list[dict]:
        """Announced-host snapshot for the sync_peers job (scheduler
        job.go:224 responds with its peers; the manager merges them into
        its Peer table, manager/job/sync_peers.go)."""
        with self.mu:
            out = []
            for host_id, info in self._host_info.items():
                if self.state.host_index(host_id) is None:
                    continue
                out.append(
                    {
                        "host_id": host_id,
                        "hostname": info.hostname,
                        "type": info.host_type,
                        "ip": info.ip,
                        "port": info.port,
                        "download_port": info.download_port,
                        "idc": info.idc,
                        "location": info.location,
                        "state": "active",
                    }
                )
            return out


def _round_up_64(n: int) -> int:
    return ((n + 63) // 64) * 64


def _sample_rows(rng: np.random.Generator, live: np.ndarray, m: int, k: int
                 ) -> np.ndarray:
    """(m, min(k, len(live))) independent uniform k-subsets of `live`,
    one rng draw for the whole group: random keys per row + argpartition
    (argsort when everything fits) pick k distinct slots uniformly.
    Shared by both candidate-fill paths so their rng streams match."""
    keys = rng.random((m, live.size))
    if live.size <= k:
        idx = np.argsort(keys, axis=1, kind="stable")
    else:
        idx = np.argpartition(keys, k - 1, axis=1)[:, :k]
    return live[idx].astype(np.int32)


# Fixed (B, K) batch buckets for the jitted scheduling kernels; the largest
# is the BASELINE.json eval shape (1k concurrent tasks per device call).
_EVAL_BUCKETS = (64, 256, 1024)


def _bucket_rows(n: int) -> int:
    for cap in _EVAL_BUCKETS:
        if n <= cap:
            return cap
    return _EVAL_BUCKETS[-1]


def _chunk_stride(b: int) -> int:
    """Chunk stride for the pipelined tick: the smallest bucket that cuts
    the batch into at most 4 chunks — for batches up to 4x the largest
    bucket (4096 rows); beyond that the stride stays at the largest
    bucket and the chunk count grows with the batch (ceil(b/1024), the
    pre-pipeline chunking). A batch that fits the smallest bucket stays
    one chunk (nothing to overlap); anything larger splits so the double
    buffer has at least two device calls to pipeline. Total padded rows
    never exceed the single-big-bucket split (4 x 64 = 256, 4 x 256 =
    1024), so compute cost is unchanged while per-chunk D2H round-trips
    overlap. Every chunk still pads to one of the three fixed buckets —
    the at-most-three-compiled-shapes contract holds."""
    for cap in _EVAL_BUCKETS:
        if -(-b // cap) <= 4:
            return cap
    return _EVAL_BUCKETS[-1]


def _pad_rows(a: np.ndarray, rows: int) -> np.ndarray:
    if a.shape[0] == rows:
        return a
    pad = np.zeros((rows - a.shape[0],) + a.shape[1:], a.dtype)
    return np.concatenate([a, pad])
