"""Device mesh + sharding helpers — the distributed backbone.

Where the reference scales with gRPC streams + a consistent-hash balancer
over TCP (SURVEY.md §2.6), the TPU build scales with a
`jax.sharding.Mesh`: data parallelism over the `dp` axis (batch sharded,
params replicated, XLA inserts the grad all-reduce over ICI) and graph
parallelism over the `graph` axis (edge shards aggregated with `psum` —
training/train.py:embed_graph_sharded). Multi-host extends the same mesh
across DCN via jax's multi-slice support; nothing here assumes a single
process.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
GRAPH_AXIS = "graph"
SP_AXIS = "sp"  # sequence/context parallelism (ring/ulysses attention)
TP_AXIS = "tp"  # tensor parallelism (parallel/tensor.py)
PP_AXIS = "pp"  # pipeline parallelism (parallel/pipeline.py)
EP_AXIS = "ep"  # expert parallelism (parallel/moe.py)


def make_mesh(
    n_devices: int | None = None,
    dp: int | None = None,
    graph: int = 1,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a (dp, graph, sp, tp, pp, ep) mesh. Defaults: all devices on
    the dp axis. Unused axes have size 1 — specs that don't name them are
    unaffected, so existing dp/graph/sp code is oblivious to the new axes."""
    devices = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    model = graph * sp * tp * pp * ep
    if dp is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model axes={model}")
        dp = n // model
    if dp * model != n:
        raise ValueError(f"mesh {dp}x{graph}x{sp}x{tp}x{pp}x{ep} != {n} devices")
    arr = np.asarray(devices).reshape(dp, graph, sp, tp, pp, ep)
    return Mesh(arr, (DP_AXIS, GRAPH_AXIS, SP_AXIS, TP_AXIS, PP_AXIS, EP_AXIS))


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bootstrap: `jax.distributed.initialize` with the standard
    env-var fallbacks. After this, `jax.devices()` spans every host and
    `make_mesh`/`make_hybrid_mesh` build global meshes whose collectives
    ride ICI within a slice and DCN across slices — the role the
    reference's NCCL-free gRPC/Redis backend plays for its cluster
    (SURVEY.md §2.6), minus the hand-written transport."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh(
    dcn_dp: int,
    dp: int = 1,
    graph: int = 1,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Mesh for multi-slice / multi-host topologies: `dcn_dp` data-parallel
    replicas over DCN (one per slice), every other axis within a slice over
    ICI. Gradient all-reduce then decomposes into a fast intra-slice
    reduce-scatter/all-gather plus a small cross-slice all-reduce — the
    layout the scaling playbook prescribes, with only the dp axis allowed
    to cross the slow network. Falls back to `make_mesh` ordering when the
    platform exposes no slice topology (CPU test meshes)."""
    from jax.experimental import mesh_utils

    axis_names = (DP_AXIS, GRAPH_AXIS, SP_AXIS, TP_AXIS, PP_AXIS, EP_AXIS)
    ici_shape = (dp, graph, sp, tp, pp, ep)
    dcn_shape = (dcn_dp, 1, 1, 1, 1, 1)
    devices = devices if devices is not None else jax.devices()
    slices = {getattr(d, "slice_index", None) for d in devices}
    if len(slices) <= 1 or None in slices:
        # Single slice or no slice topology (CPU test meshes): a hybrid
        # layout is meaningless, fold the dcn replicas into dp so specs
        # keep working unchanged. Real multi-slice errors must NOT take
        # this path — a flat mesh would let model axes span DCN.
        return make_mesh(
            dcn_dp * dp * graph * sp * tp * pp * ep,
            dp=dcn_dp * dp, graph=graph, sp=sp, tp=tp, pp=pp, ep=ep,
            devices=devices,
        )
    arr = mesh_utils.create_hybrid_device_mesh(ici_shape, dcn_shape, devices=devices)
    return Mesh(arr, axis_names)


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (batch) dim over dp, replicate the rest."""
    return NamedSharding(mesh, P(DP_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree):
    """device_put every leaf with its leading dim sharded over dp.

    Leaves whose batch dim is not divisible by the dp size are padded:
    bool leaves (masks) with False — so padded rows drop out of any
    masked loss/metric — and other leaves by repeating the last element,
    which keeps index leaves in-range.
    """
    dp = mesh.shape[DP_AXIS]

    def put(x):
        x = np.asarray(x)
        b = x.shape[0]
        if b % dp:
            pad = dp - (b % dp)
            if x.dtype == np.bool_:
                fill = np.zeros((pad,) + x.shape[1:], x.dtype)
            else:
                fill = np.repeat(x[-1:], pad, axis=0)
            x = np.concatenate([x, fill], axis=0)
        return jax.device_put(x, batch_sharding(mesh, x.ndim))

    return jax.tree_util.tree_map(put, tree)


def shard_stacked_batches(mesh, tree):
    """device_put a stack of batches [S, B, ...] with the BATCH dim (dim 1)
    sharded over dp — the layout `lax.scan`-based epoch loops consume (one
    device call per epoch instead of one per step). Dim-1 padding follows
    shard_batch's rules: False for masks, repeat-last otherwise."""
    dp = mesh.shape[DP_AXIS]

    def put(x):
        x = np.asarray(x)
        b = x.shape[1]
        if b % dp:
            pad = dp - (b % dp)
            if x.dtype == np.bool_:
                fill = np.zeros((x.shape[0], pad) + x.shape[2:], x.dtype)
            else:
                fill = np.repeat(x[:, -1:], pad, axis=1)
            x = np.concatenate([x, fill], axis=1)
        spec = P(None, DP_AXIS, *([None] * (x.ndim - 2)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)
