"""JIT001..JIT004 — tracer hygiene inside jitted programs and in the
serving hot path.

The repo's perf story (BENCH_r01→r06: evaluator 1.11 ms → 0.09 ms, tick
p50 97.5 → 7.1 ms) rests on two contracts:

- zero new jit signatures after warmup (the compile-shape-stability
  test): every jitted entry sees only the three fixed bucket shapes;
- exactly one designed D2H sync per chunk (the ``d2h_wait`` phase) —
  any other host sync re-serializes the pipelined tick.

Rules:

- ``JIT001`` host sync inside a jit-compiled body: ``.item()`` /
  ``.tolist()`` / ``jax.device_get`` / ``block_until_ready`` /
  ``np.asarray``/``np.array`` on a traced value, or ``float()``/
  ``int()``/``bool()`` of a traced value. Under trace these either
  fail or silently force a device round-trip per call.
- ``JIT002`` Python control flow on a traced value (``if``/``while``/
  ``assert`` conditions referencing a non-static parameter). Branching
  on tracers raises ConcretizationError or, worse, bakes one branch
  into the compiled program. ``is None`` / ``is not None`` tests are
  exempt: pytree STRUCTURE is static, so None-gating is legal jit
  style.
- ``JIT003`` host sync in a serving hot-path function that is not on
  the pass's explicit allowlist. The allowlist (``D2H_ALLOWLIST``)
  *documents* the pipeline design: the tick's single drain point, the
  warmup forcing, and the refresh worker's off-critical-path landing
  are intentional; anything new must be argued onto the list (or
  waived inline). Compile-analysis calls (``cost_analysis`` /
  ``memory_analysis`` / the cost-card ledger's ``capture_pending``)
  count as syncs here too: a cost-card capture pays a full XLA
  recompile, strictly worse than a D2H round-trip, so it may only run
  at the allowlisted warmup drain — never per tick (the
  telemetry/costcard.py capture discipline, pinned by the bad_jit
  fixture).
- ``JIT004`` dynamic shape entering a jit call: an argument sliced to
  a runtime-dependent length (``x[:n]``) at a direct call site of a
  known-jitted callable — the shape becomes a fresh signature and a
  recompile. Pad to a bucket (``pad_pow2`` / ``_pad_rows``) instead.

Static parameters (``static_argnames``) are excluded from taint; taint
propagates through simple assignments within the body (one forward
pass — an intentionally shallow, low-false-positive approximation).

``shard_map``-wrapped bodies are traced programs too (ROADMAP item-1
residual: the D2H/branching discipline must carry into meshed jits
before any sharding code lands on the serving path), so JIT001/JIT002
apply to them as well. Their static set is inferred rather than
declared: ``functools.partial`` bindings on the wrapped callable,
axis-like parameter names (``axis_name``/``axes``/``mesh``), and
parameters with constant defaults (config flags like ``use_flash``) are
static; everything else is a device shard and taints. Collective ops
(``psum``/``all_gather``/``ppermute``/``all_to_all``...) are device
ops, never host syncs — ``psum(1, axis)`` axis-size idioms stay
untainted, while ``axis_index`` results are per-device values and taint
their targets.
"""

from __future__ import annotations

import ast

from tools.dflint.core import FileContext, Finding, attr_chain

SYNC_CALL_LEAVES = {"asarray", "array", "device_get", "block_until_ready"}
SYNC_ATTR_CALLS = {"item", "tolist", "block_until_ready"}
# hot-path-only sync leaves (JIT003, never JIT001 — they are meaningless
# inside a traced body): compile-analysis calls cost a full XLA
# recompile, so a cost-card capture on the tick path is a worse stall
# than any D2H; only the warmup drain is allowlisted
COMPILE_SYNC_LEAVES = {"cost_analysis", "memory_analysis", "capture_pending"}
CAST_FUNCS = {"float", "int", "bool"}
NUMPY_ROOTS = {"np", "numpy", "onp"}
# parameter names that carry mesh topology, not array data — static in
# any traced body (shard_map bodies have no static_argnames to declare)
AXIS_PARAM_NAMES = {"axis_name", "axis", "axes", "mesh"}
# collective whose result is a per-device value: taints its target even
# though its operands are static
TRACER_SOURCE_LEAVES = {"axis_index"}

# functions whose body is the serving hot path: host syncs here must be
# explicitly allowlisted (file suffix, enclosing function name)
DEFAULT_HOT_FUNCTIONS = {
    ("cluster/scheduler.py", "tick"),
    ("cluster/scheduler.py", "_dispatch_chunk"),
    ("cluster/scheduler.py", "_drain_chunk"),
    ("cluster/scheduler.py", "_drain_shadow"),
    ("cluster/scheduler.py", "_warm_shadow_ml"),
    ("cluster/scheduler.py", "warmup"),
    ("cluster/scheduler.py", "_tick_fused"),
    ("cluster/scheduler.py", "_dispatch_fused"),
    ("cluster/scheduler.py", "_drain_fused"),
    ("registry/serving.py", "_perform_refresh"),
}

# (file suffix, enclosing function, callee leaf) -> justification.
# THIS LIST IS THE DESIGN DOCUMENT for every intentional host sync on
# the serving path (ROADMAP item-1 residual: the d2h_wait points below
# are what the tunneled-TPU re-run must re-measure).
D2H_ALLOWLIST: dict[tuple[str, str, str], str] = {
    ("cluster/scheduler.py", "_drain_chunk", "asarray"): (
        "THE designed D2H point of the pipelined tick: chunk i's packed "
        "selection is read back exactly once, timed as the d2h_wait "
        "phase, while chunk i+1's device call is already in flight"
    ),
    ("cluster/scheduler.py", "_dispatch_chunk", "asarray"): (
        "plugin scorers run HOST-side on the feature dict by contract "
        "(plugin API stability over transfer count); the asarray "
        "normalizes the plugin's host output, it does not sync a device "
        "array — plugins are not the serving default"
    ),
    ("cluster/scheduler.py", "warmup", "asarray"): (
        "warmup forces compile+execute for every bucket BEFORE serving "
        "starts; blocking here is the point — it keeps the 35 s cold "
        "compile off the first real tick"
    ),
    ("registry/serving.py", "_perform_refresh", "block_until_ready"): (
        "the refresh worker lands the embed compute on ITS thread so the "
        "committed snapshot is never an in-flight array a tick would "
        "then block on — the stall PR-4 removed"
    ),
    ("registry/serving.py", "_perform_refresh", "asarray"): (
        "host-side COO subgraph gather (numpy in, numpy out) feeding the "
        "jitted embed program; no device array is synced here"
    ),
    ("cluster/scheduler.py", "warmup", "capture_pending"): (
        "THE cost-card capture drain (telemetry/costcard.py): warmup is "
        "already the designed blocking cold-start phase, so the one-time "
        "duplicate compile per bucket signature lands here — a capture "
        "anywhere else on the serving path must fail JIT003"
    ),
    ("cluster/scheduler.py", "_warm_shadow_ml", "asarray"): (
        "the late-commit twin of warmup's forcing: when an ml snapshot "
        "commits AFTER cold start, the shadow entry compiles on this "
        "dedicated background thread (never a serving tick) and blocking "
        "on the zero-filled result is how the compile is forced to land "
        "before _shadow_ml_ready flips"
    ),
    ("cluster/scheduler.py", "_drain_shadow", "asarray"): (
        "THE counterfactual shadow-scoring drain (telemetry/decisions.py): "
        "the inactive arm's packed selections are read back ONCE, at the "
        "end-of-tick valve strictly after the last serving chunk's "
        "d2h_wait, so the shadow D2H can never re-serialize the pipelined "
        "tick — an in-tick shadow read-back anywhere else fails JIT003 "
        "(pinned by the bad_shadow fixture)"
    ),
    ("cluster/scheduler.py", "_drain_fused", "asarray"): (
        "THE single D2H of the fused tick (ops/tick.py): one flat result "
        "buffer per chunk — selection + compacted candidate columns + "
        "ledger features, int segments bitcast — read back exactly once, "
        "timed as d2h_wait, while chunk i+1's fused dispatch is already "
        "in flight (the PR-4 pipeline); any other read-back on the fused "
        "path fails JIT003 (pinned by the bad_tick fixture)"
    ),
}


class JitHygienePass:
    name = "jit-hygiene"
    rules = ("JIT001", "JIT002", "JIT003", "JIT004")

    def __init__(
        self,
        hot_functions: set[tuple[str, str]] | None = None,
        allowlist: dict[tuple[str, str, str], str] | None = None,
    ):
        self.hot_functions = (
            DEFAULT_HOT_FUNCTIONS if hot_functions is None else hot_functions
        )
        self.allowlist = D2H_ALLOWLIST if allowlist is None else allowlist

    # ------------------------------------------------------------- run

    def run(self, ctx: FileContext) -> list[Finding]:
        # function-level import: collective.py imports this module's
        # sync sets/allowlist, so the top level must stay acyclic
        from tools.dflint.passes.collective import collect_shard_map_bodies

        findings: list[Finding] = []
        jit_funcs = _collect_jit_functions(ctx.tree)
        jit_ids = {id(f) for f, _ in jit_funcs}
        for func, bindings, _axes in collect_shard_map_bodies(ctx.tree):
            if id(func) in jit_ids:
                continue
            # axis-like param names are static ONLY for shard_map bodies
            # (they carry mesh topology there); a plain jit param that
            # happens to be named `axes` keeps its taint
            jit_funcs.append((
                func,
                set(bindings) | _mesh_static_params(func) | AXIS_PARAM_NAMES,
            ))
        jit_names = {f.name for f, _ in jit_funcs}
        for func, static in jit_funcs:
            findings.extend(self._check_jit_body(ctx, func, static))
        findings.extend(self._check_hot_functions(ctx))
        findings.extend(self._check_jit_call_sites(ctx, jit_names))
        return findings

    # ------------------------------------------------------- jit bodies

    def _check_jit_body(self, ctx, func, static: set[str]) -> list[Finding]:
        tainted = {
            a.arg for a in (
                func.args.posonlyargs + func.args.args + func.args.kwonlyargs
            )
            if a.arg not in static and a.arg not in ("self", "model")
        }
        # one forward taint pass through simple assignments; axis_index
        # results are per-device values and taint even from static args
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and (
                _references(node.value, tainted)
                or _calls_tracer_source(node.value)
            ):
                for target in node.targets:
                    for name in ast.walk(target):
                        if isinstance(name, ast.Name):
                            tainted.add(name.id)
        findings = []
        symbol = func.name
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                leaf, root = _callee_leaf_root(node)
                if leaf in SYNC_ATTR_CALLS and isinstance(node.func, ast.Attribute):
                    findings.append(ctx.make_finding(
                        "JIT001", node,
                        f".{leaf}() inside jit-compiled '{func.name}' forces "
                        f"a host sync per call under trace",
                        symbol=symbol, def_line=func.lineno,
                    ))
                elif (
                    leaf in SYNC_CALL_LEAVES
                    and root in NUMPY_ROOTS | {"jax"}
                    and _references_call_args(node, tainted)
                ):
                    findings.append(ctx.make_finding(
                        "JIT001", node,
                        f"{root}.{leaf}() on a traced value inside "
                        f"jit-compiled '{func.name}' — a host "
                        f"materialization under trace",
                        symbol=symbol, def_line=func.lineno,
                    ))
                elif (
                    leaf in CAST_FUNCS and root is None
                    and _references_call_args(node, tainted)
                ):
                    findings.append(ctx.make_finding(
                        "JIT001", node,
                        f"{leaf}() of a traced value inside jit-compiled "
                        f"'{func.name}' concretizes the tracer",
                        symbol=symbol, def_line=func.lineno,
                    ))
            elif isinstance(node, (ast.If, ast.While)):
                if _branches_on_tracer(node.test, tainted):
                    findings.append(ctx.make_finding(
                        "JIT002", node,
                        f"Python {'if' if isinstance(node, ast.If) else 'while'} "
                        f"on a traced value inside jit-compiled '{func.name}' "
                        f"— use lax.cond/jnp.where (None-structure gates are "
                        f"exempt)",
                        symbol=symbol, def_line=func.lineno,
                    ))
            elif isinstance(node, ast.Assert):
                if _branches_on_tracer(node.test, tainted):
                    findings.append(ctx.make_finding(
                        "JIT002", node,
                        f"assert on a traced value inside jit-compiled "
                        f"'{func.name}' concretizes the tracer",
                        symbol=symbol, def_line=func.lineno,
                    ))
        return findings

    # ---------------------------------------------------- hot functions

    def _check_hot_functions(self, ctx) -> list[Finding]:
        hot_names = {
            name for suffix, name in self.hot_functions
            if ctx.rel.endswith(suffix)
        }
        if not hot_names:
            return []
        findings = []
        for func in _walk_functions(ctx.tree):
            if func.name not in hot_names:
                continue
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                leaf, root = _callee_leaf_root(node)
                is_sync = (
                    (leaf in SYNC_CALL_LEAVES and root in NUMPY_ROOTS | {"jax"})
                    or (leaf in SYNC_ATTR_CALLS | COMPILE_SYNC_LEAVES
                        and isinstance(node.func, ast.Attribute))
                    # bare-name capture_pending() (from-imported) is the
                    # same recompile with the module prefix dropped
                    or leaf == "capture_pending"
                )
                if not is_sync:
                    continue
                owner = _enclosing_function(func, node)
                if owner != func.name and any(
                    ctx.rel.endswith(suffix) and name == owner
                    for suffix, name in self.hot_functions
                ):
                    continue  # a nested hot function reports on its own scan
                key = None
                for suffix, name in self.hot_functions:
                    if ctx.rel.endswith(suffix) and name == owner:
                        key = (suffix, name, leaf)
                        break
                if key is not None and key in self.allowlist:
                    continue
                findings.append(ctx.make_finding(
                    "JIT003", node,
                    (
                        f"host sync '{leaf}' in serving hot path "
                        f"'{owner}' is not on the d2h allowlist — a new "
                        f"sync point re-serializes the pipelined tick; "
                        f"argue it onto tools/dflint/passes/jit_hygiene."
                        f"D2H_ALLOWLIST or waive inline"
                    ),
                    symbol=owner, def_line=func.lineno,
                ))
        return findings

    # --------------------------------------------------- jit call sites

    def _check_jit_call_sites(self, ctx, jit_names: set[str]) -> list[Finding]:
        if not jit_names:
            return []
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain is None or chain.rsplit(".", 1)[-1] not in jit_names:
                continue
            for arg in node.args:
                if _is_dynamic_slice(arg):
                    findings.append(ctx.make_finding(
                        "JIT004", arg,
                        (
                            f"runtime-length slice passed straight into "
                            f"jitted '{chain}' — each distinct length is a "
                            f"fresh compile signature; pad to a fixed "
                            f"bucket (pad_pow2/_pad_rows) first"
                        ),
                        symbol=chain,
                    ))
        return findings


# ------------------------------------------------------------- helpers


def _mesh_static_params(func) -> set[str]:
    """Params of a shard_map body that are static at trace time: constant
    defaults mark config flags (use_flash/causal/capacity), not shards."""
    static: set[str] = set()
    args = func.args
    positional = args.posonlyargs + args.args
    defaults = args.defaults
    offset = len(positional) - len(defaults)
    for i, a in enumerate(positional):
        if i >= offset and isinstance(defaults[i - offset], ast.Constant):
            static.add(a.arg)
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if isinstance(d, ast.Constant):
            static.add(a.arg)
    return static


def _calls_tracer_source(node: ast.AST) -> bool:
    for inner in ast.walk(node):
        if isinstance(inner, ast.Call):
            chain = attr_chain(inner.func)
            if chain and chain.rsplit(".", 1)[-1] in TRACER_SOURCE_LEAVES:
                return True
    return False


def _collect_jit_functions(tree) -> list[tuple[ast.FunctionDef, set[str]]]:
    """(funcdef, static param names) for every jit-compiled function:
    ``@jax.jit``, ``@jit``, ``@(functools.)partial(jax.jit, ...)``
    decorators, and ``name = jax.jit(func)`` rebinds."""
    by_name: dict[str, ast.FunctionDef] = {}
    out: list[tuple[ast.FunctionDef, set[str]]] = []
    seen: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                static = _jit_decorator_statics(dec)
                if static is not None and id(node) not in seen:
                    seen.add(id(node))
                    out.append((node, static))
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            chain = attr_chain(node.value.func)
            if chain in ("jax.jit", "jit") and node.value.args:
                target = node.value.args[0]
                if isinstance(target, ast.Name):
                    func = by_name.get(target.id)
                    if func is not None and id(func) not in seen:
                        seen.add(id(func))
                        out.append((func, _static_names(node.value)))
    return out


def _jit_decorator_statics(dec: ast.AST) -> set[str] | None:
    """static_argnames for a jit decorator, or None if not a jit."""
    chain = attr_chain(dec)
    if chain in ("jax.jit", "jit"):
        return set()
    if isinstance(dec, ast.Call):
        chain = attr_chain(dec.func)
        if chain in ("jax.jit", "jit"):
            return _static_names(dec)
        if chain in ("functools.partial", "partial") and dec.args:
            inner = attr_chain(dec.args[0])
            if inner in ("jax.jit", "jit"):
                return _static_names(dec)
    return None


def _static_names(call: ast.Call) -> set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            value = kw.value
            names = set()
            if isinstance(value, (ast.Tuple, ast.List)):
                for elt in value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        names.add(elt.value)
            elif isinstance(value, ast.Constant) and isinstance(value.value, str):
                names.add(value.value)
            return names
    return set()


# attribute reads that are STATIC under trace even on a tracer — shape
# metadata, not values; `if data.ndim > 1:` is legal jit style
STATIC_TRACER_ATTRS = {"ndim", "shape", "dtype", "size"}


def _references(node: ast.AST, names: set[str]) -> bool:
    static_value_ids = {
        id(attr.value)
        for attr in ast.walk(node)
        if isinstance(attr, ast.Attribute) and attr.attr in STATIC_TRACER_ATTRS
    }
    return any(
        isinstance(n, ast.Name) and n.id in names
        and id(n) not in static_value_ids
        for n in ast.walk(node)
    )


def _references_call_args(call: ast.Call, names: set[str]) -> bool:
    return any(_references(arg, names) for arg in call.args)


def _branches_on_tracer(test: ast.AST, tainted: set[str]) -> bool:
    """Condition references a tainted name — excluding `is (not) None`
    structure gates and `isinstance` checks (both static under jit)."""
    if isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    ):
        return False
    if isinstance(test, ast.Call):
        chain = attr_chain(test.func)
        if chain in ("isinstance", "hasattr", "callable"):
            return False
    if isinstance(test, ast.BoolOp):
        return any(_branches_on_tracer(v, tainted) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _branches_on_tracer(test.operand, tainted)
    return _references(test, tainted)


def _callee_leaf_root(node: ast.Call) -> tuple[str | None, str | None]:
    chain = attr_chain(node.func)
    if chain is None:
        if isinstance(node.func, ast.Attribute):
            return node.func.attr, None  # computed root: x[...].item()
        return None, None
    parts = chain.split(".")
    return parts[-1], parts[0] if len(parts) > 1 else None


def _is_dynamic_slice(arg: ast.AST) -> bool:
    if not isinstance(arg, ast.Subscript):
        return False
    sl = arg.slice
    if not isinstance(sl, ast.Slice):
        return False
    for bound in (sl.lower, sl.upper):
        if bound is None or isinstance(bound, ast.Constant):
            continue
        if isinstance(bound, ast.UnaryOp) and isinstance(
            bound.operand, ast.Constant
        ):
            continue
        return True
    return False


def _walk_functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _enclosing_function(outer, target) -> str:
    """Name of the innermost function within `outer` containing `target`
    (by nested def walk); falls back to outer's name."""
    best = outer.name
    for node in ast.walk(outer):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not outer:
            if any(n is target for n in ast.walk(node)):
                best = node.name
    return best
