"""Masked top-k selection.

The TPU-native replacement for the reference's sort-by-score parent
selection (evaluator_base.go:59-68 sort.Slice + scheduling.go candidate
truncation): invalid candidates are pushed to -inf so `lax.top_k` never
picks them, and validity flows back out as a mask.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


def masked_top_k(scores: jax.Array, mask: jax.Array, k: int):
    """Top-k along the last axis honoring a validity mask.

    Returns (values, indices, valid): `valid[i, j]` is False for slots that
    had fewer than j+1 valid candidates. Ties break toward lower index
    (lax.top_k is stable in that sense).
    """
    masked = jnp.where(mask, scores, NEG_INF)
    values, indices = jax.lax.top_k(masked, k)
    valid = values > NEG_INF
    return values, indices, valid
