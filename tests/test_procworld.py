"""procworld — the real-process planet harness (ISSUE 18).

Three layers:

- unit tests for the supervisor primitives (READY parsing, the
  SIGTERM→SIGKILL escalation ladder, SIGSTOP/SIGCONT, the unified
  origin server) and the replay-facing reducers (megascale sample
  schema, drift-free SLO synthesis, divergence bands);
- THE tier-1 planet smoke (marker ``procworld``): 2 real schedulers +
  3 real dfdaemons + a manager over real sockets drive a compressed
  day segment through the real client path, survive a mid-flight
  SIGKILL and a rolling-restart wave with zero lost downloads, and the
  announce-stability page fires AT the kill and clears on recovery —
  asserted from the artifact, replayed by dfslo with zero drift;
- the checked-in ``BENCH_proc.json`` replay (the BENCH_mega pattern):
  the shipped artifact reproduces its recorded verdicts offline, and
  every sim-vs-real divergence metric sits inside its declared band.
"""

from __future__ import annotations

import json
import pathlib
import signal
import subprocess
import sys
import time

import pytest

ROOT = pathlib.Path(__file__).resolve().parents[1]

# ------------------------------------------------------------- origin


def test_origin_server_superset_surface():
    """The unified origin keeps every historical attribute/alias so the
    four old per-test ``_Origin`` copies migrate by import swap."""
    import urllib.request

    from dragonfly2_tpu.procworld import OriginServer

    payload = bytes(range(256)) * 64
    origin = OriginServer(payload)
    try:
        assert origin.srv is origin._server
        url = origin.url("blob.bin")
        req = urllib.request.Request(url, method="HEAD")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert int(resp.headers["Content-Length"]) == len(payload)
        assert origin.gets == 0  # HEAD is not a GET
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.read() == payload
        ranged = urllib.request.Request(
            url, headers={"Range": "bytes=256-511"}
        )
        with urllib.request.urlopen(ranged, timeout=5) as resp:
            assert resp.status == 206
            assert resp.read() == payload[256:512]
        assert origin.gets == 2 and origin.get_count == 2
    finally:
        origin.stop()  # historical alias for close()


# --------------------------------------------------------- supervisor


def _python_child(script: str) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-u", "-c", script],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def test_managed_proc_parses_ready_ports_and_stops_clean():
    from dragonfly2_tpu.procworld import ManagedProc

    popen = _python_child(
        "import time\n"
        "print('READY 127.0.0.1 1234 PROXY 77 METRICS 88', flush=True)\n"
        "time.sleep(60)\n"
    )
    proc = ManagedProc(["fake"], popen, None, name="fake")
    proc.wait_ready(20)
    assert (proc.host, proc.port) == ("127.0.0.1", 1234)
    assert proc.ports == {"PROXY": 77, "METRICS": 88}
    proc.stop(grace=10)
    assert not proc.alive()
    assert proc.escalations == 0


def test_stop_escalation_ladder_sigkills_stubborn_child():
    """The bounded SIGTERM→SIGKILL ladder (the fix for the old tests'
    unbounded ``proc.wait()``): a child that ignores SIGTERM is KILLed
    after the grace window and the escalation is counted."""
    from dragonfly2_tpu.procworld import ManagedProc

    popen = _python_child(
        "import signal, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "print('READY 127.0.0.1 1 ', flush=True)\n"
        "time.sleep(120)\n"
    )
    proc = ManagedProc(["stubborn"], popen, None, name="stubborn")
    proc.wait_ready(20)
    t0 = time.monotonic()
    proc.stop(grace=0.5)
    assert time.monotonic() - t0 < 10, "stop() must stay bounded"
    assert not proc.alive()
    assert proc.escalations == 1


def test_pause_resume_freezes_and_thaws_child():
    from dragonfly2_tpu.procworld import ManagedProc, wait_for

    popen = _python_child(
        "import time\nprint('READY 127.0.0.1 1 ', flush=True)\n"
        "time.sleep(60)\n"
    )
    proc = ManagedProc(["pausy"], popen, None, name="pausy")
    proc.wait_ready(20)

    def state() -> str:
        return pathlib.Path(f"/proc/{proc.pid}/stat").read_text().split()[2]

    try:
        proc.pause()
        # signal delivery is asynchronous — poll the /proc state
        wait_for(lambda: state() == "T", 10, what="SIGSTOP to land")
        proc.resume()
        wait_for(lambda: state() != "T", 10, what="SIGCONT to land")
    finally:
        proc.kill()


# ------------------------------------------------- sample / synthesis


def test_quantile_nearest_rank():
    from dragonfly2_tpu.procworld import quantile

    assert quantile([], 0.95) is None
    assert quantile([5.0], 0.95) == 5.0
    assert quantile([1, 2, 3, 4], 0.50) == 3.0
    assert quantile([1, 2, 3, 4], 0.95) == 4.0


def test_build_sample_matches_megascale_timeline_schema():
    """The planet's sample carries EXACTLY the keys the megascale
    engine records (pinned against the checked-in BENCH_mega timeline):
    same schema in, same replayer out — that is the whole contract that
    lets dfslo replay a planet artifact unchanged."""
    from dragonfly2_tpu.procworld import RoundObservation, build_sample

    mega_sample = json.loads(
        (ROOT / "BENCH_mega.json").read_text()
    )["runs"][0]["timeline"][0]
    obs = RoundObservation(round_idx=1, completed=3, pieces=9,
                           origin_pieces=3, ttc_ms={"region-0": [10.0]})
    sample = build_sample(obs, minutes_per_round=120.0,
                          regions=["region-0"])
    slo_columns = {"t", "slo_verdict", "slo_alerts_firing",
                   "slo_pages_fired", "slo_tickets_fired"}
    assert set(sample) | slo_columns == set(mega_sample)


def test_synthesized_timeline_replays_with_zero_drift():
    """synthesize_timeline's recorded slo_* columns and alert log are
    reproduced bit for bit by telemetry.slo.replay_timeline — the exact
    check tools/dfslo.py performs on the artifact."""
    from dragonfly2_tpu.procworld import (
        RoundObservation, announce_page_rounds, synthesize_timeline,
    )
    from dragonfly2_tpu.telemetry.slo import replay_timeline

    regions = ["region-0", "region-1"]
    observations = []
    for r in range(1, 9):
        kill = 1 if r == 5 else 0
        observations.append(RoundObservation(
            round_idx=r, completed=10, pieces=30, origin_pieces=10,
            reannounce_backlog=3 * kill, scheduler_crash=kill,
            ttc_ms={rg: [100.0 + r, 200.0 + r] for rg in regions},
        ))
    timeline, slo_block = synthesize_timeline(
        observations, minutes_per_round=120.0, regions=regions
    )
    replay = replay_timeline(timeline, 120.0)
    for sample, col in zip(timeline, replay["samples"]):
        for key in ("slo_verdict", "slo_alerts_firing",
                    "slo_pages_fired", "slo_tickets_fired"):
            assert sample[key] == col[key], (sample["t"], key)
    assert replay["pages_fired"] == slo_block["pages_fired"]
    assert replay["tickets_fired"] == slo_block["tickets_fired"]
    assert replay["verdict_final"] == slo_block["verdict_final"]
    assert replay["alert_log"] == slo_block["alert_log"][-len(
        replay["alert_log"]):]
    # the synthetic kill paged AT the kill round
    assert announce_page_rounds(timeline, slo_block) == [5.0]


# --------------------------------------------------------- divergence


def _fake_sim_report():
    return {
        "timeline": [
            {"t": 1.0, "ttc_ms_p95": {"region-0": 4000.0}},
            {"t": 2.0, "ttc_ms_p95": {"region-0": 5000.0}},
        ],
        "mega": {"origin_bytes": 20, "p2p_bytes": 80},
        "stats": {"pieces": 1000, "completed": 100, "failed": 0},
        "failover": {"scheduler_crashes": 2, "crash_reannounced_peers": 5},
        "expected_crash_rounds": [5, 10],
        "slo": {
            "verdict_final": "ok",
            "alert_log": [
                {"t": 5.0, "slo": "announce_stability", "rule": "fast_burn",
                 "severity": "page", "event": "fired"},
            ],
        },
    }


def _fake_real_facts():
    return {
        "scenario": "procday", "seed": 7,
        "ttc_ms_p95": {"region-0": 1500.0},
        "origin_fraction": 0.4, "pieces": 300, "completed": 100,
        "lost_downloads": 0, "kills": 2, "failovers": 2,
        "kill_rounds": [5.0, 10.0],
        "slo": {
            "verdict_final": "ok",
            "alert_log": [
                {"t": 5.0, "slo": "announce_stability", "rule": "fast_burn",
                 "severity": "page", "event": "fired"},
                {"t": 10.0, "slo": "announce_stability", "rule": "fast_burn",
                 "severity": "page", "event": "fired"},
            ],
        },
    }


def test_divergence_all_within_on_agreeing_runs():
    from dragonfly2_tpu.procworld import compute_divergence

    report = compute_divergence(_fake_real_facts(), _fake_sim_report())
    assert report["all_within"], report
    metrics = report["metrics"]
    # every entry carries its band AND the argument for it — the bands
    # travel in the artifact, not in this test
    for name, entry in metrics.items():
        assert len(entry["band"]) == 2, name
        assert entry["argument"], name
        assert entry["within"] is True, (name, entry)
    assert metrics["ttc_p95_ratio_region-0"]["value"] == pytest.approx(
        1500.0 / 5000.0)
    assert metrics["origin_fraction_delta"]["value"] == pytest.approx(
        0.4 - 0.2)
    assert metrics["lost_downloads"]["value"] == 1.0


def test_divergence_flags_out_of_band_and_disagreement():
    from dragonfly2_tpu.procworld import compute_divergence

    real = _fake_real_facts()
    real["lost_downloads"] = 1          # the invariant breaks
    real["ttc_ms_p95"] = {"region-0": 9000.0}  # slower than modeled WAN
    sim = _fake_sim_report()
    sim["slo"]["verdict_final"] = "degraded"   # verdict disagreement
    report = compute_divergence(real, sim)
    assert not report["all_within"]
    m = report["metrics"]
    assert not m["lost_downloads"]["within"]
    assert not m["ttc_p95_ratio_region-0"]["within"]
    assert not m["verdict_match"]["within"]
    # a page NOT on a kill round fails the paged-at-kill agreement
    real2 = _fake_real_facts()
    real2["slo"]["alert_log"].append(
        {"t": 7.0, "slo": "announce_stability", "rule": "fast_burn",
         "severity": "page", "event": "fired"})
    report2 = compute_divergence(real2, _fake_sim_report())
    assert not report2["metrics"]["paged_at_kill"]["within"]


# ------------------------------------------------- THE planet smoke


@pytest.mark.procworld
def test_planet_day_survives_sigkill_and_rolling_restart(tmp_path):
    """THE tier-1 acceptance (ISSUE 18): 2 real scheduler processes + 3
    real dfdaemons + a manager over real sockets drive 6 rounds of the
    procday spec through the real client path (proxy-hijacked GETs,
    byte-verified against the origin digest). Round 5 SIGKILLs a
    scheduler MID-DOWNLOAD; rounds 3-6 roll a restart wave over every
    daemon. Zero lost downloads, the kill produced observable failover,
    and the announce-stability page fired AT the kill and cleared on
    recovery — all read from the artifact, which dfslo replays with
    zero drift."""
    import tools.dfslo as dfslo
    from dragonfly2_tpu.procworld import run_procday
    from tools.bench_schema import write_artifact

    t0 = time.monotonic()
    run = run_procday(
        tmp_path / "planet", rounds=6, schedulers=2, daemons=3,
        tasks_per_round=4, with_manager=True,
    )
    wall = time.monotonic() - t0
    assert wall < 420, f"planet smoke blew its time budget: {wall:.0f}s"

    st = run["stats"]
    # zero lost downloads, real P2P traffic, byte-identical completions
    # (a digest mismatch counts as lost)
    assert st["lost_downloads"] == 0, st
    assert st["completed"] > 0 and st["via_p2p"] > 0, st
    # the SIGKILL happened mid-run and daemons failed over
    assert run["kill_rounds"] == [5.0]
    assert st["kills"] == 1 and st["failovers"] >= 1, st
    # the rolling-upgrade wave restarted daemons; the killed scheduler
    # was restarted on its pinned port (recovery)
    assert st["restarts"] >= 4, run["proc"]["restarts"]
    assert run["proc"]["restarts"].get("scheduler-0", 0) >= 1
    # the page fired AT the kill and cleared on recovery — from the
    # recorded alert log, not test-local state
    assert run["page_rounds"] == [5.0], run["slo"]["alert_log"]
    cleared = [e["t"] for e in run["slo"]["alert_log"]
               if e["slo"] == "announce_stability"
               and e["severity"] == "page" and e["event"] == "cleared"]
    assert cleared == [6.0], run["slo"]["alert_log"]
    # every process exited the ladder cleanly (no lingering members)
    assert all(code is not None for code in run["proc"]
               ["exit_codes"].values())

    # the artifact replays offline through dfslo UNCHANGED: recorded
    # verdicts reproduced bit for bit (rc=2 == "it paged", not drift)
    body = write_artifact(
        tmp_path / "BENCH_proc.json", ["test"], {"scenario": "procday"},
        runs=[run],
    )
    rc, results = dfslo.judge(body)
    assert rc == 2 and len(results) == 1
    assert results[0]["paged"] and results[0]["pages_fired"] == 1
    assert not results[0]["recorded_drift"], results[0]["recorded_drift"]


# ------------------------------------------- checked-in BENCH_proc


def test_dfslo_reproduces_checked_in_bench_proc_verdicts():
    """The BENCH_mega pattern for the planet: the shipped BENCH_proc
    artifact replays offline to its recorded verdicts (pages at every
    kill round, zero drift), and the sim-vs-real divergence report it
    carries has every metric inside its declared band."""
    import tools.dfslo as dfslo

    doc = json.loads((ROOT / "BENCH_proc.json").read_text())
    rc, results = dfslo.judge(doc)
    assert len(results) == 1
    run = results[0]
    assert run["paged"] and run["pages_fired"] >= 1
    assert rc == 2
    assert not run["recorded_drift"], run["recorded_drift"]

    # the invariant and the kill evidence, from the artifact alone
    record = doc["runs"][0]
    assert record["stats"]["lost_downloads"] == 0
    assert record["stats"]["kills"] >= 1
    assert record["page_rounds"] == record["kill_rounds"]

    # the divergence report: bands + arguments carried in the artifact,
    # every compared metric within its band
    divergence = doc["divergence"]
    assert divergence["all_within"]
    assert divergence["metrics"], "empty divergence report"
    for name, entry in divergence["metrics"].items():
        assert entry["within"], (name, entry)
        assert entry["argument"], name
        lo, hi = entry["band"]
        if entry["value"] is not None:
            assert lo <= entry["value"] <= hi, (name, entry)
    assert doc["summary"]["divergence_all_within"] is True
