"""dflint red fixture: reads of donated staging buffers.

DON001 x3: one read-after-donate in the donating function itself, one
through the call-graph fixpoint (the helper forwards its parameter into
the donated position, so the CALLER's later read is the bug), and one
loop-carried reuse (buffer bound outside the loop, donated inside — the
second iteration re-donates a dead buffer).
"""

from dragonfly2_tpu.ops import evaluator as ev


def reuse_after_donate(fd, k, c, l, n):
    buf = ev.pack_eval_batch(fd)
    out = ev.schedule_from_packed(buf, 64, k, c, l, n)
    checksum = buf.sum()  # <- DON001 (buf was donated above)
    return out, checksum


def helper_forwards(staging, b, k, c, l, n):
    return ev.schedule_from_packed(staging, b, k, c, l, n)


def caller_via_fixpoint(fd, k, c, l, n):
    staging = ev.pack_eval_batch(fd)
    out = helper_forwards(staging, 64, k, c, l, n)
    return out, staging.mean()  # <- DON001 (helper donates its param)


def loop_carried_reuse(fd, k, c, l, n):
    buf = ev.pack_eval_batch(fd)  # bound outside the loop
    outs = []
    for _ in range(5):
        outs.append(ev.schedule_from_packed(buf, 64, k, c, l, n))  # <- DON001
    return outs
