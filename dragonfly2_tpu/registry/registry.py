"""Versioned model registry — the manager's model lifecycle, natively.

Capability parity with the reference's registry spread across
manager/rpcserver/manager_server_v1.go:802-952 (CreateModel: model bytes ->
object storage, metadata+evaluation -> DB), manager/types/model.go:58-75
(evaluation fields Recall/Precision/F1/MSE/MAE; object keys
``<id>/<version>/model.graphdef`` + ``<id>/config.pbtxt``) and
manager/service/model.go:109-190 (activate a version = flip DB state +
rewrite the Triton version policy).

TPU-first difference: no Triton sidecar — artifacts are orbax-saved flax
params plus a JSON manifest, laid out ``<model_id>/<version>/params/`` so
the same "activate = flip the active pointer" operation drives the
in-scheduler jit-compiled server (registry/serving.py). Storage is a
filesystem dir standing in for the object-store bucket.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import pathlib
import tempfile
import time
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from dragonfly2_tpu.utils.idgen import model_id as make_model_id

MODEL_TYPE_GNN = "gnn"
MODEL_TYPE_MLP = "mlp"
# beyond the reference's gnn|mlp enum (manager/models/model.go:19-46): the
# set-transformer ranker family (models/attention.py)
MODEL_TYPE_ATTENTION = "attention"

STATE_INACTIVE = "inactive"
STATE_ACTIVE = "active"
# Guarded activation (trust-boundary PR): a version that failed an
# integrity or canary check — a corrupt params blob, non-finite leaves, or
# an insane canary scoring pass. Bad versions can never be (re)activated;
# marking the ACTIVE version bad falls the pointer back to the newest
# good version, so serving recovers to last-good without an operator.
STATE_BAD = "bad"


@dataclasses.dataclass
class ModelEvaluation:
    """manager/types/model.go:58-64."""

    recall: float = 0.0
    precision: float = 0.0
    f1_score: float = 0.0
    mse: float = 0.0
    mae: float = 0.0


@dataclasses.dataclass
class ModelVersion:
    model_id: str
    name: str
    type: str
    version: int
    state: str
    evaluation: ModelEvaluation
    scheduler_host_id: str
    created_at: float
    metadata: dict = dataclasses.field(default_factory=dict)


class ModelRegistry:
    """Filesystem-backed registry: <base>/<model_id>/<version>/{params/, version.json}
    plus <base>/<model_id>/model.json recording the active version."""

    def __init__(self, base_dir: str | pathlib.Path):
        self.base = pathlib.Path(base_dir).absolute()
        self.base.mkdir(parents=True, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()

    # -------------------------------------------------------------- write

    def create_model_version(
        self,
        name: str,
        model_type: str,
        scheduler_host_id: str,
        params: Any,
        evaluation: ModelEvaluation,
        metadata: dict | None = None,
    ) -> ModelVersion:
        """CreateModel semantics (manager_server_v1.go:802-952): next version
        number, artifacts + evaluation stored, version starts inactive."""
        if model_type not in (MODEL_TYPE_GNN, MODEL_TYPE_MLP, MODEL_TYPE_ATTENTION):
            raise ValueError(f"unknown model type {model_type!r}")
        mid = make_model_id(name, scheduler_host_id)
        versions = self.list_versions(mid)
        next_version = max((v.version for v in versions), default=0) + 1
        vdir = self.base / mid / str(next_version)
        vdir.mkdir(parents=True, exist_ok=True)
        self._ckpt.save(vdir / "params", params)
        self._ckpt.wait_until_finished()
        mv = ModelVersion(
            model_id=mid,
            name=name,
            type=model_type,
            version=next_version,
            state=STATE_INACTIVE,
            evaluation=evaluation,
            scheduler_host_id=scheduler_host_id,
            created_at=time.time(),
            metadata=metadata or {},
        )
        _atomic_write_json(vdir / "version.json", dataclasses.asdict(mv))
        model_manifest = self.base / mid / "model.json"
        if not model_manifest.exists():
            _atomic_write_json(
                model_manifest,
                {"model_id": mid, "name": name, "type": model_type, "active_version": None},
            )
        return mv

    def activate(self, model_id: str, version: int) -> None:
        """Flip the active version pointer; exactly one version active —
        manager/service/model.go:109-151's transactional state flip."""
        vpath = self.base / model_id / str(version) / "version.json"
        if not vpath.exists():
            raise FileNotFoundError(f"{model_id} v{version} not found")
        if json.loads(vpath.read_text()).get("state") == STATE_BAD:
            raise ValueError(
                f"{model_id} v{version} is marked bad (failed an integrity "
                "or activation gate); publish a new version instead"
            )
        manifest_path = self.base / model_id / "model.json"
        manifest = json.loads(manifest_path.read_text())
        for v in self.list_versions(model_id):
            if v.state == STATE_BAD:
                continue  # bad stays bad; never resurrected to inactive
            self._set_state(model_id, v.version, STATE_ACTIVE if v.version == version else STATE_INACTIVE)
        manifest["active_version"] = version
        _atomic_write_json(manifest_path, manifest)

    def mark_version_bad(self, model_id: str, version: int, reason: str = "") -> None:
        """Record that a version failed an integrity/activation check. If
        it was the active version, the pointer falls back to the NEWEST
        remaining good version (or None) — the model-plane twin of PR 3's
        fallback-past-torn-checkpoints: serving recovers to last-good and
        the bad version can never be activated again."""
        path = self.base / model_id / str(version) / "version.json"
        if not path.exists():
            return
        data = json.loads(path.read_text())
        data["state"] = STATE_BAD
        data.setdefault("metadata", {})["bad_reason"] = reason
        _atomic_write_json(path, data)
        manifest_path = self.base / model_id / "model.json"
        if not manifest_path.exists():
            return
        manifest = json.loads(manifest_path.read_text())
        if manifest.get("active_version") != version:
            return
        # fallback must be LOADABLE, not merely not-bad: skip versions
        # whose params never landed (publisher died mid-publish), or the
        # recovered pointer would fail every load_params with not-found
        good = [
            v for v in self.list_versions(model_id)
            if v.state != STATE_BAD
            and (self.base / model_id / str(v.version) / "params").exists()
        ]
        fallback = good[-1].version if good else None
        if fallback is not None:
            self._set_state(model_id, fallback, STATE_ACTIVE)
        manifest["active_version"] = fallback
        _atomic_write_json(manifest_path, manifest)

    def delete_version(self, model_id: str, version: int) -> None:
        vdir = self.base / model_id / str(version)
        if not vdir.exists():
            return
        manifest_path = self.base / model_id / "model.json"
        if manifest_path.exists():
            manifest = json.loads(manifest_path.read_text())
            if manifest.get("active_version") == version:
                raise ValueError("cannot delete the active version")
        import shutil

        shutil.rmtree(vdir)

    def _set_state(self, model_id: str, version: int, state: str) -> None:
        path = self.base / model_id / str(version) / "version.json"
        data = json.loads(path.read_text())
        data["state"] = state
        _atomic_write_json(path, data)

    # --------------------------------------------------------------- read

    def list_models(self) -> list[dict]:
        out = []
        for manifest in sorted(self.base.glob("*/model.json")):
            out.append(json.loads(manifest.read_text()))
        return out

    def list_versions(self, model_id: str) -> list[ModelVersion]:
        out = []
        for vjson in sorted(
            (self.base / model_id).glob("*/version.json"),
            key=lambda p: int(p.parent.name),
        ):
            out.append(_version_from_json(json.loads(vjson.read_text())))
        return out

    def active_version(self, model_id: str) -> ModelVersion | None:
        manifest_path = self.base / model_id / "model.json"
        if not manifest_path.exists():
            return None
        active = json.loads(manifest_path.read_text()).get("active_version")
        if active is None:
            return None
        vjson = self.base / model_id / str(active) / "version.json"
        return _version_from_json(json.loads(vjson.read_text()))

    def load_params(self, model_id: str, version: int, template: Any = None) -> Any:
        """Restore a version's params. Template-less restores must work
        across device topologies — the trainer saves on TPU, a scheduler
        may restore on CPU (or another slice), and orbax would otherwise
        replay the *saved* shardings and fail with "Device ... was not
        found". Restoring as numpy leaves placement to the first jit call."""
        path = self.base / model_id / str(version) / "params"
        if template is not None:
            return self._ckpt.restore(path, target=template)
        with ocp.PyTreeCheckpointer() as ckpt:
            # orbax API drift: newer releases wrap the tree in a
            # CheckpointMetadata (.item_metadata, sometimes .tree below
            # it); older ones (<= 0.7.x) return the metadata tree
            # directly. Template-less restore must work on both — the
            # scheduler launcher serves registries written by trainers on
            # other topologies AND other orbax versions.
            meta = ckpt.metadata(path)
            meta = getattr(meta, "item_metadata", meta)
            tree = getattr(meta, "tree", meta)
            restore_args = jax.tree_util.tree_map(
                lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
            )
            return ckpt.restore(path, args=ocp.args.PyTreeRestore(restore_args=restore_args))

    def model_id(self, name: str, scheduler_host_id: str) -> str:
        return make_model_id(name, scheduler_host_id)



def _atomic_write_json(path: pathlib.Path, data: dict) -> None:
    """write_text truncates in place — a concurrent reader (a scheduler's
    ModelServer.refresh mid-activation) could see a half-written manifest.
    Write to a UNIQUE temp file (two concurrent writers must not rename
    each other's tmp away), fsync, and rename (atomic on POSIX)."""
    fd, tmp_name = tempfile.mkstemp(
        prefix=path.name + ".", suffix=".tmp", dir=path.parent
    )
    try:
        with os.fdopen(fd, "w") as f:
            # mkstemp creates 0600; manifests must stay readable by other
            # users (trainer/operator processes) like write_text's
            # umask-default files were
            os.fchmod(f.fileno(), 0o644)
            f.write(json.dumps(data, indent=2))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_name, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp_name)
        raise


def _version_from_json(data: dict) -> ModelVersion:
    data = dict(data)
    data["evaluation"] = ModelEvaluation(**data["evaluation"])
    return ModelVersion(**data)
