// dfnative: native runtime kernels for the host-side hot paths.
//
// The reference keeps its whole runtime in compiled Go (SURVEY.md §2 —
// scheduler DAG pkg/graph/dag, balancer pkg/balancer, CSV trace storage
// scheduler/storage); the TPU build keeps XLA for tensor math and this
// C++ layer for the host-side data structures on the request path:
//   - FNV-1a hashing + consistent-hash ring lookups (task -> scheduler
//     affinity, pkg/balancer/consistent_hashing.go:40-57)
//   - DAG reachability over uint64 bitset rows (cycle checks at DAG
//     mutation rate, pkg/graph/dag/dag.go:84-86)
//   - columnar numeric CSV parsing (the trainer's trace reader,
//     scheduler/storage/storage.go + trainer/storage)
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cmath>

extern "C" {

// ---------------------------------------------------------------- hashing

// FNV-1a 64-bit with a murmur3 fmix64 finalizer (raw FNV clusters badly
// on structured keys like "node#3", skewing ring balance). Both the
// Python and native implementations use this exact function so mixed
// fleets agree on task->scheduler affinity.
uint64_t df_fnv1a64(const uint8_t* data, int64_t len) {
    uint64_t h = 14695981039346656037ULL;
    for (int64_t i = 0; i < len; i++) {
        h ^= (uint64_t)data[i];
        h *= 1099511628211ULL;
    }
    h ^= h >> 33;
    h *= 0xff51afd7ed558ccdULL;
    h ^= h >> 33;
    h *= 0xc4ceb9fe1a85ec53ULL;
    h ^= h >> 33;
    return h;
}

// Hash n strings packed back to back; offsets has n+1 entries.
void df_fnv1a64_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                      uint64_t* out) {
    for (int64_t i = 0; i < n; i++) {
        out[i] = df_fnv1a64(buf + offsets[i], offsets[i + 1] - offsets[i]);
    }
}

// ------------------------------------------------------------------- ring

// ring: sorted vnode hashes. For each key hash, find the first vnode
// strictly greater (wrapping), i.e. Python bisect.bisect semantics.
void df_ring_pick_batch(const uint64_t* ring, int64_t n_ring,
                        const uint64_t* keys, int64_t n_keys, int64_t* out) {
    for (int64_t i = 0; i < n_keys; i++) {
        uint64_t k = keys[i];
        int64_t lo = 0, hi = n_ring;
        while (lo < hi) {
            int64_t mid = (lo + hi) / 2;
            if (ring[mid] <= k) lo = mid + 1; else hi = mid;
        }
        out[i] = lo % n_ring;
    }
}

// -------------------------------------------------------------------- DAG

// adj: capacity x words uint64 bitmatrix, adj[u] = children bitset of u.
// Returns 1 when src reaches dst (BFS over bitset rows).
int32_t df_dag_reachable(const uint64_t* adj, int64_t capacity, int64_t words,
                         int64_t src, int64_t dst) {
    if (src == dst) return 1;
    uint64_t* frontier = (uint64_t*)calloc((size_t)words, 8);
    uint64_t* visited = (uint64_t*)calloc((size_t)words, 8);
    uint64_t* next = (uint64_t*)calloc((size_t)words, 8);
    if (!frontier || !visited || !next) {
        free(frontier); free(visited); free(next);
        return -1;
    }
    frontier[src / 64] = 1ULL << (src % 64);
    visited[src / 64] = frontier[src / 64];
    int32_t found = 0;
    for (;;) {
        int any = 0;
        memset(next, 0, (size_t)words * 8);
        for (int64_t w = 0; w < words; w++) {
            uint64_t bits = frontier[w];
            while (bits) {
                int64_t b = __builtin_ctzll(bits);
                bits &= bits - 1;
                const uint64_t* row = adj + (w * 64 + b) * words;
                for (int64_t j = 0; j < words; j++) next[j] |= row[j];
            }
        }
        for (int64_t j = 0; j < words; j++) {
            next[j] &= ~visited[j];
            if (next[j]) any = 1;
        }
        if (next[dst / 64] & (1ULL << (dst % 64))) { found = 1; break; }
        if (!any) break;
        for (int64_t j = 0; j < words; j++) visited[j] |= next[j];
        uint64_t* tmp = frontier; frontier = next; next = tmp;
    }
    free(frontier); free(visited); free(next);
    return found;
}

void df_dag_reachable_batch(const uint64_t* adj, int64_t capacity, int64_t words,
                            const int64_t* srcs, const int64_t* dsts, int64_t n,
                            int32_t* out) {
    for (int64_t i = 0; i < n; i++) {
        out[i] = df_dag_reachable(adj, capacity, words, srcs[i], dsts[i]);
    }
}

// -------------------------------------------------------------------- CSV

// Parse a CSV buffer into a dense row-major double matrix of n_cols
// columns. Handles quoted fields (commas/newlines inside quotes, doubled
// quotes); non-numeric/empty fields become NaN. Rows with a different
// column count are skipped. Returns rows written (<= max_rows), or -1 on
// malformed input that prevents forward progress.
int64_t df_csv_parse_numeric(const char* buf, int64_t len, int64_t n_cols,
                             int32_t skip_header, double* out, int64_t max_rows) {
    int64_t pos = 0, rows = 0;
    double* row_vals = (double*)malloc((size_t)n_cols * 8);
    if (!row_vals) return -1;
    if (skip_header) {
        // header fields may be quoted but never contain newlines here
        while (pos < len && buf[pos] != '\n') pos++;
        if (pos < len) pos++;
    }
    while (pos < len && rows < max_rows) {
        // skip blank lines
        if (buf[pos] == '\n' || buf[pos] == '\r') { pos++; continue; }
        int64_t col = 0;
        for (;;) {
            double value = NAN;
            char tmp[64]; int64_t ti = 0;
            if (pos < len && buf[pos] == '"') {
                pos++;  // opening quote
                int64_t flen = 0;
                while (pos < len) {
                    if (buf[pos] == '"') {
                        if (pos + 1 < len && buf[pos + 1] == '"') {
                            if (ti < 63) tmp[ti++] = '"';
                            flen++; pos += 2;
                        } else { pos++; break; }
                    } else {
                        if (ti < 63) tmp[ti++] = buf[pos];
                        flen++; pos++;
                    }
                }
                if (flen > 63) ti = 0;  // too long to be numeric
            } else {
                int64_t start = pos;
                while (pos < len && buf[pos] != ',' && buf[pos] != '\n' &&
                       buf[pos] != '\r') pos++;
                int64_t flen = pos - start;
                if (flen > 0 && flen < 64) {
                    memcpy(tmp, buf + start, (size_t)flen);
                    ti = flen;
                }
            }
            if (ti > 0) {
                tmp[ti] = 0;
                char* end = nullptr;
                double d = strtod(tmp, &end);
                if (end && *end == 0) value = d;
            }
            if (col < n_cols) row_vals[col] = value;
            col++;
            if (pos >= len) break;
            if (buf[pos] == ',') { pos++; continue; }
            if (buf[pos] == '\r') { pos++; if (pos < len && buf[pos] == '\n') pos++; break; }
            pos++;  // '\n'
            break;
        }
        if (col == n_cols) {
            memcpy(out + rows * n_cols, row_vals, (size_t)n_cols * 8);
            rows++;
        }
    }
    free(row_vals);
    return rows;
}

}  // extern "C"
