"""dflint green twin of bad_fleet.py: round-robin victim selection, a
round-counter down window, sorted ring-rebalance iteration, and a
perf_counter that only measures — zero findings."""

import time


class GoodFleet:
    def __init__(self, k):
        self.k = k
        self.crashes = 0
        self.in_flight = set()
        self.down_until = {}

    def crash_victim(self):
        # round-robin over the ring: pure function of the crash counter,
        # identical across paired-seed runs
        victim = self.crashes % self.k
        self.crashes += 1
        return victim

    def shard_is_down(self, shard, round_idx):
        # down windows live on the round counter, not the wall clock
        return self.down_until.get(shard, -1) > round_idx

    def rebalance(self, owner_of):
        # sorted sweep: the handoff frame stream is byte-stable no matter
        # what PYTHONHASHSEED did to the set's internal order
        start = time.perf_counter()  # measuring the sweep, never deciding
        moved = []
        for pid in sorted(self.in_flight):
            moved.append((pid, owner_of(pid)))
        return moved, time.perf_counter() - start
