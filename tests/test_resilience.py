"""Failure-domain resilience: deadline budgets, circuit breakers, and the
hashring failover order (rpc/resilience.py + the clients/servers that wire
it). The chaos e2e lives in tests/test_chaos_failover.py; these pin the
primitives and the acceptance bound that a blackholed scheduler costs
bounded time."""

import asyncio
import socket
import threading
import time

import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.rpc import resilience, wire
from dragonfly2_tpu.rpc.client import (
    SchedulerClientPool,
    SyncSchedulerClient,
)
from dragonfly2_tpu.rpc.server import SchedulerRPCServer
from dragonfly2_tpu.telemetry import default_registry
from dragonfly2_tpu.telemetry.series import resilience_series
from dragonfly2_tpu.utils import dferrors, retry
from dragonfly2_tpu.utils.hashring import HashRing


# ------------------------------------------------------------- deadlines


def test_deadline_scope_nests_to_the_minimum():
    assert resilience.remaining() is None
    with resilience.deadline(10.0):
        outer = resilience.remaining()
        assert outer is not None and 9.0 < outer <= 10.0
        with resilience.deadline(1.0):
            inner = resilience.remaining()
            assert inner is not None and inner <= 1.0
            # a callee can only SHRINK the budget it was handed
            with resilience.deadline(60.0):
                assert resilience.remaining() <= 1.0
        assert resilience.remaining() > 1.0  # inner scope popped
    assert resilience.remaining() is None


def test_deadline_check_and_bound_timeout():
    with resilience.deadline(-1.0):  # already expired
        assert resilience.expired()
        with pytest.raises(dferrors.DeadlineExceeded):
            resilience.check("unit")
    with resilience.deadline(0.5):
        assert resilience.bound_timeout(5.0) <= 0.5
        assert resilience.bound_timeout(0.1) <= 0.1
    assert resilience.bound_timeout(5.0) == 5.0
    assert resilience.bound_timeout(None) is None


def test_wire_envelope_carries_remaining_budget():
    wire.register_messages(msg.StatTaskRequest)
    # no ambient scope, no extra bytes -> no attribute after decode
    framed = wire.encode(msg.StatTaskRequest(task_id="t"))
    assert not hasattr(wire.decode(framed[4:]), "deadline_s")
    with resilience.deadline(2.0):
        framed = wire.encode(msg.StatTaskRequest(task_id="t"))
    decoded = wire.decode(framed[4:])
    assert 0.0 < decoded.deadline_s <= 2.0
    # explicit argument wins over the ambient scope, and is clamped at 0
    with resilience.deadline(30.0):
        framed = wire.encode(msg.StatTaskRequest(task_id="t"), deadline_s=-3.0)
    assert wire.decode(framed[4:]).deadline_s == 0.0


def test_deadline_budget_decrements_across_hops():
    """Receiver re-anchors the relative budget; time spent inside the hop
    is gone from the budget its onward frames carry."""
    wire.register_messages(msg.StatTaskRequest)
    with resilience.deadline(0.5):
        hop1 = wire.decode(wire.encode(msg.StatTaskRequest(task_id="t"))[4:])
    with resilience.deadline(hop1.deadline_s):
        time.sleep(0.1)  # the hop "works" for 100ms
        hop2 = wire.decode(wire.encode(msg.StatTaskRequest(task_id="t"))[4:])
    assert hop2.deadline_s < hop1.deadline_s - 0.05


def test_server_sheds_expired_work_and_counts_it(tmp_path):
    """A sheddable frame arriving with a spent budget never reaches the
    service: scheduling requests get a DeadlineExceeded ScheduleFailure,
    stats are silently dropped, lifecycle mutations (LeavePeer) are NEVER
    shed, and dragonfly_scheduler_rpc_deadline_shed_total counts every
    shed (the tier-1 naming sweep covers the family itself)."""

    async def run():
        service = SchedulerService()
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        shed_metric = server.resilience_metrics.deadline_shed
        resched_before = shed_metric.value("RescheduleRequest")
        stat_before = shed_metric.value("StatPeerRequest")
        try:
            reader, writer = await asyncio.open_connection(host, port)
            # expired budget + scheduling request -> shed with an
            # explicit failure so the conductor fails fast
            writer.write(wire.encode(
                msg.RescheduleRequest(peer_id="peer-x"), deadline_s=0.0
            ))
            await writer.drain()
            response = await asyncio.wait_for(wire.read_frame(reader), 5)
            assert isinstance(response, msg.ScheduleFailure)
            assert response.code == "DeadlineExceeded"
            assert shed_metric.value("RescheduleRequest") == resched_before + 1
            # expired stat -> silently dropped (the caller's own budget
            # enforcement already aborted), but counted
            writer.write(wire.encode(
                msg.StatPeerRequest(peer_id="peer-x"), deadline_s=0.0
            ))
            await writer.drain()
            # expired LeavePeer -> NOT shed: lifecycle mutations execute
            # regardless of budget (dropping a leave would leak state)
            writer.write(wire.encode(
                msg.LeavePeerRequest(peer_id="peer-x"), deadline_s=0.0
            ))
            await writer.drain()
            # live budget -> dispatched normally (also proves the two
            # frames above were consumed in order without a reply)
            writer.write(wire.encode(
                msg.StatPeerRequest(peer_id="peer-x"), deadline_s=5.0
            ))
            await writer.drain()
            response = await asyncio.wait_for(wire.read_frame(reader), 5)
            assert isinstance(response, msg.StatResponse)
            assert shed_metric.value("StatPeerRequest") == stat_before + 1
            assert shed_metric.value("LeavePeerRequest") == 0
            writer.close()
        finally:
            await server.stop()

    asyncio.run(run())


def test_sync_client_enforces_ambient_deadline():
    client = SyncSchedulerClient("127.0.0.1", 1)  # never dialed
    with resilience.deadline(-1.0):
        with pytest.raises(dferrors.DeadlineExceeded):
            client.call(msg.StatTaskRequest(task_id="t"))


# -------------------------------------------------------------- breakers


def test_breaker_state_machine():
    transitions = []
    b = resilience.CircuitBreaker(
        "t:1", failure_threshold=2, open_ttl=0.05,
        on_transition=lambda target, state: transitions.append(state),
    )
    assert b.state == resilience.CLOSED
    assert b.acquire() == resilience.CLOSED
    b.record_failure()
    assert b.state == resilience.CLOSED  # below threshold
    b.record_failure()
    assert b.state == resilience.OPEN
    with pytest.raises(resilience.BreakerOpen):
        b.acquire()
    # BreakerOpen doubles as ConnectionError AND Unavailable for callers
    with pytest.raises(ConnectionError):
        b.acquire()
    time.sleep(0.06)
    assert b.state == resilience.HALF_OPEN
    assert b.acquire() == resilience.HALF_OPEN  # the single probe slot
    with pytest.raises(resilience.BreakerOpen):
        b.acquire()  # second caller does not get a probe
    b.record_failure()  # probe failed -> re-open
    assert b.state == resilience.OPEN
    time.sleep(0.06)
    assert b.acquire() == resilience.HALF_OPEN
    b.record_success()
    assert b.state == resilience.CLOSED
    assert transitions == ["open", "half_open", "open", "half_open", "closed"]


def test_breaker_board_metrics_and_drop():
    board = resilience.BreakerBoard("manager", failure_threshold=1, open_ttl=9)
    b = board.get("10.0.0.9:8002")
    b.record_failure()
    assert board.metrics.breaker_state.value("10.0.0.9:8002") == 2.0
    with pytest.raises(resilience.BreakerOpen):
        board.acquire("10.0.0.9:8002")
    assert board.metrics.breaker_fast_fail.value("10.0.0.9:8002") == 1
    board.drop("10.0.0.9:8002")
    assert "10.0.0.9:8002" not in board.targets()
    assert board.metrics.breaker_state.value("10.0.0.9:8002") == 0.0


def _blackhole_listener():
    """A listener whose accept queue is full: connects hang in the SYN/
    accept backlog — the closest a unit test gets to a blackholed host."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(0)
    fillers = []
    for _ in range(2):  # saturate the tiny backlog
        s = socket.socket()
        s.setblocking(False)
        try:
            s.connect_ex(srv.getsockname())
        except OSError:
            pass
        fillers.append(s)
    time.sleep(0.05)
    return srv, fillers


def test_blackholed_scheduler_costs_bounded_time():
    """Acceptance bound: once the breaker is open, 50 consecutive calls
    finish in under 2x ONE dial timeout total — against ~50 full dial
    timeouts without the breaker."""
    srv, fillers = _blackhole_listener()
    host, port = srv.getsockname()
    dial_timeout = 0.5
    client = SyncSchedulerClient(host, port, timeout=dial_timeout,
                                 dial_failure_ttl=30.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            client.call(msg.StatTaskRequest(task_id="t"))  # pays the dial
        first_cost = time.monotonic() - t0
        assert client.breakers.get(f"{host}:{port}").state == resilience.OPEN
        t0 = time.monotonic()
        for _ in range(50):
            with pytest.raises(ConnectionError):
                client.call(msg.StatTaskRequest(task_id="t"))
        fifty_cost = time.monotonic() - t0
        assert fifty_cost < 2 * dial_timeout, (
            f"50 calls took {fifty_cost:.2f}s with the breaker open "
            f"(first dial cost {first_cost:.2f}s)"
        )
    finally:
        client.close()
        for s in fillers:
            s.close()
        srv.close()


def test_sync_client_half_open_probe_uses_health_request(tmp_path):
    """After open_ttl the first call runs as the half-open probe: it must
    send HealthCheckRequest on the fresh socket and only then the real
    call — a recovered scheduler closes the breaker, and the real request
    still succeeds on the same connection."""

    async def run():
        service = SchedulerService()
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        try:
            client = SyncSchedulerClient(host, port, timeout=2.0,
                                         dial_failure_ttl=0.05)
            breaker = client.breakers.get(f"{host}:{port}")
            breaker.record_failure()  # threshold=1 -> open
            assert breaker.state == resilience.OPEN
            with pytest.raises(ConnectionError):
                await asyncio.to_thread(
                    client.call, msg.StatTaskRequest(task_id="t")
                )
            await asyncio.sleep(0.06)  # open_ttl elapses -> half-open
            response = await asyncio.to_thread(
                client.call, msg.StatTaskRequest(task_id="t")
            )
            assert isinstance(response, msg.StatResponse)
            assert breaker.state == resilience.CLOSED
            client.close()
        finally:
            await server.stop()

    asyncio.run(run())


# ----------------------------------------------------- hashring failover


def test_hashring_successors_order_and_coverage():
    ring = HashRing([f"10.0.0.{i}:8002" for i in range(5)])
    order = ring.successors("task-abc")
    assert order[0] == ring.pick("task-abc")
    assert sorted(order) == sorted(ring.nodes())  # all nodes, no dupes
    assert order == ring.successors("task-abc")  # deterministic
    assert ring.successors("task-abc", limit=2) == order[:2]
    # removing the primary promotes the old second — failover lands where
    # the task would live anyway after the primary leaves the ring
    primary, second = order[0], order[1]
    ring.remove(primary)
    assert ring.pick("task-abc") == second
    assert HashRing([]).successors("x") == []


def test_pool_for_task_fails_over_to_next_ring_node():
    """Primary dead -> for_task returns a connection to the next ring
    node; the primary's breaker opens so later calls skip its dial."""

    async def run():
        s1 = SchedulerRPCServer(SchedulerService(), tick_interval=0.05)
        s2 = SchedulerRPCServer(SchedulerService(), tick_interval=0.05)
        addr1 = await s1.start()
        addr2 = await s2.start()
        pool = SchedulerClientPool([addr1, addr2],
                                   breaker_failure_threshold=1)
        task_id = "task-failover-unit"
        primary = pool.primary_for_task(task_id)
        primary_server, backup_addr = (
            (s1, addr2) if primary == f"{addr1[0]}:{addr1[1]}" else (s2, addr1)
        )
        try:
            await primary_server.stop()  # kill the primary BEFORE any dial
            conn = await pool.for_task(task_id)
            assert f"{conn.host}:{conn.port}" == f"{backup_addr[0]}:{backup_addr[1]}"
            assert pool.breakers.get(primary).state == resilience.OPEN
            # with the breaker open the failover is skip-cost: 50 calls
            # must not pay 50 dial attempts
            t0 = time.monotonic()
            for _ in range(50):
                conn = await pool.for_task(task_id)
            assert time.monotonic() - t0 < 1.0
        finally:
            await pool.close()
            await s1.stop()
            await s2.stop()

    asyncio.run(run())


# ------------------------------------------------------- retry satellites


def test_retry_full_jitter_spreads_backoff():
    import random

    sleeps: list[float] = []

    def always_fail():
        raise OSError("transient")

    with pytest.raises(OSError):
        retry.run(always_fail, init_backoff=1.0, max_backoff=8.0,
                  max_attempts=5, sleep=sleeps.append,
                  rng=random.Random(7))
    assert len(sleeps) == 4
    caps = [1.0, 2.0, 4.0, 8.0]
    assert all(0.0 <= s <= cap for s, cap in zip(sleeps, caps))
    # full jitter: draws are not the deterministic ladder
    assert sleeps != caps
    other: list[float] = []
    with pytest.raises(OSError):
        retry.run(always_fail, init_backoff=1.0, max_backoff=8.0,
                  max_attempts=5, sleep=other.append,
                  rng=random.Random(8))
    assert other != sleeps


def test_retry_aborts_on_non_retryable_dferrors():
    calls = {"n": 0}

    def bad_request():
        calls["n"] += 1
        raise dferrors.InvalidArgument("malformed")

    with pytest.raises(dferrors.InvalidArgument):
        retry.run(bad_request, init_backoff=0.001, max_attempts=5)
    assert calls["n"] == 1  # no attempts burned on a caller bug

    calls["n"] = 0

    def unauthenticated():
        calls["n"] += 1
        raise dferrors.Unauthenticated("bad cert")

    with pytest.raises(dferrors.Unauthenticated):
        retry.run(unauthenticated, init_backoff=0.001, max_attempts=5)
    assert calls["n"] == 1

    # retryable DFErrors (Unavailable) still burn attempts
    calls["n"] = 0

    def unavailable():
        calls["n"] += 1
        raise dferrors.Unavailable("down")

    with pytest.raises(dferrors.Unavailable):
        retry.run(unavailable, init_backoff=0.001, max_attempts=3)
    assert calls["n"] == 3

    # the Cancel contract survives the predicate
    def cancelled():
        raise retry.Cancel(ValueError("fatal"))

    with pytest.raises(ValueError, match="fatal"):
        retry.run(cancelled, init_backoff=0.001, max_attempts=5)


def test_breaker_release_frees_probe_without_verdict():
    """A cancelled dial is not evidence against the target: release()
    must free the half-open probe slot without opening the breaker, and
    must not reset the failure count a real refusal would add to."""
    b = resilience.CircuitBreaker("t:1", failure_threshold=2, open_ttl=0.05)
    b.acquire()
    b.release()  # cancelled while CLOSED: state untouched
    assert b.state == resilience.CLOSED
    b.record_failure()
    b.record_failure()
    time.sleep(0.06)
    assert b.acquire() == resilience.HALF_OPEN
    b.release()  # probe cancelled: slot freed, breaker NOT re-opened
    assert b.acquire() == resilience.HALF_OPEN  # next caller can probe
    b.record_success()
    assert b.state == resilience.CLOSED


def test_record_outcome_classification_and_sync_probe_wedge():
    """record_outcome is the single shared classifier for all three dial
    sites: transport failures advance the breaker, anything else only
    frees the probe slot. In particular a garbled half-open probe reply
    (wire.decode TypeError) must not wedge SyncSchedulerClient's breaker
    in HALF_OPEN-with-held-probe forever."""
    board = resilience.BreakerBoard("manager", failure_threshold=1, open_ttl=0.05)
    board.get("t:9").acquire()
    board.record_outcome("t:9", TypeError("garbled frame"))
    assert board.get("t:9").state == resilience.CLOSED  # not a failure
    board.record_outcome("t:9", ConnectionRefusedError())
    assert board.get("t:9").state == resilience.OPEN
    time.sleep(0.06)
    assert board.get("t:9").acquire() == resilience.HALF_OPEN
    # probe outcome is a codec error -> slot freed, breaker NOT stuck
    board.record_outcome("t:9", TypeError("garbled frame"))
    assert board.get("t:9").acquire() == resilience.HALF_OPEN
    board.record_outcome("t:9", None)
    assert board.get("t:9").state == resilience.CLOSED

    # end to end: a server answering the half-open probe with garbage
    # must leave the sync client able to retry (no permanent BreakerOpen)
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)

    def garbled_server():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            try:
                conn.recv(4096)  # the probe frame
                conn.sendall((999999).to_bytes(4, "big") * 2)  # bad frame
                conn.close()
            except OSError:
                pass

    t = threading.Thread(target=garbled_server, daemon=True)
    t.start()
    host, port = srv.getsockname()
    client = SyncSchedulerClient(host, port, timeout=1.0, dial_failure_ttl=0.05)
    breaker = client.breakers.get(f"{host}:{port}")
    breaker.record_failure()  # open (threshold 1)
    time.sleep(0.06)  # -> half-open
    with pytest.raises(ConnectionError):
        client.call(msg.StatTaskRequest(task_id="t"))  # probe gets garbage
    # the probe settled: the slot is free, the NEXT ttl window can probe
    # again instead of BreakerOpen-forever
    assert breaker.state in (resilience.OPEN, resilience.HALF_OPEN, resilience.CLOSED)
    assert breaker.allows() or breaker.state == resilience.OPEN
    client.close()
    srv.close()


def test_register_adoption_priority_contract():
    """Mid-task re-announce adoption: a priority-0 conductor carrying
    every piece stays QUEUED (its conductor blocks on the response
    stream — silence would strand it for schedule_timeout), while a
    priority-1 fire-and-forget announce of a fully-cached task goes
    straight to Succeeded and is never scheduled."""
    from dragonfly2_tpu.state.fsm import PeerState

    svc = SchedulerService()
    host = msg.HostInfo(host_id="h-1", hostname="n", ip="10.0.0.1")
    pieces = list(range(4))
    # priority 1: the seed's completed-task announce -> adopted parent
    svc.register_peer(msg.RegisterPeerRequest(
        peer_id="seed-peer", task_id="t-1", host=host, url="http://o/x",
        content_length=4 * (4 << 20), total_piece_count=4,
        priority=1, finished_pieces=pieces,
    ))
    idx = svc.state.peer_index("seed-peer")
    assert int(svc.state.peer_state[idx]) == int(PeerState.SUCCEEDED)
    assert int(svc.state.peer_finished_count[idx]) == 4
    assert "seed-peer" not in svc._pending
    # priority 0: a conductor re-announcing all pieces after failover
    # must still get a response from the tick, so it stays pending
    svc.register_peer(msg.RegisterPeerRequest(
        peer_id="child-peer", task_id="t-1", host=host, url="http://o/x",
        content_length=4 * (4 << 20), total_piece_count=4,
        finished_pieces=pieces,
    ))
    cidx = svc.state.peer_index("child-peer")
    assert int(svc.state.peer_finished_count[cidx]) == 4  # adopted
    assert "child-peer" in svc._pending  # but not silently finalized
    # partial re-announce: adopted pieces recorded, peer queued
    svc.register_peer(msg.RegisterPeerRequest(
        peer_id="partial-peer", task_id="t-1", host=host, url="http://o/x",
        content_length=4 * (4 << 20), total_piece_count=4,
        finished_pieces=[0, 2],
    ))
    pidx = svc.state.peer_index("partial-peer")
    assert int(svc.state.peer_finished_count[pidx]) == 2
    assert "partial-peer" in svc._pending


def test_resilience_series_passes_naming_convention():
    """The new families ride the same tier-1 sweep as every other series
    (test_flight_recorder.test_metric_naming_convention_registry_walk
    walks them too); this pins idempotent re-registration."""
    from dragonfly2_tpu.telemetry import metrics as m

    reg = m.Registry()
    first = resilience_series(reg, "dfdaemon")
    again = resilience_series(reg, "dfdaemon")
    assert first.breaker_state is again.breaker_state
    for name, metric in reg._metrics.items():
        assert name.startswith("dragonfly_dfdaemon_rpc_")
        assert metric.help.strip()
