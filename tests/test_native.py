"""Native C++ kernels (native/dfnative.cpp via ctypes): build, parity
with the pure-Python fallbacks, and integration into hashring/DAG/traces."""

import numpy as np
import pytest

from dragonfly2_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="dfnative failed to build (no g++?)"
)


def _py_fnv1a64(data: bytes) -> int:
    mask = 0xFFFFFFFFFFFFFFFF
    h = 0xCBF29CE484222325
    for byte in data:
        h ^= byte
        h = (h * 0x100000001B3) & mask
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & mask
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & mask
    h ^= h >> 33
    return h


def test_fnv_matches_python_reference():
    for key in (b"", b"a", b"task-123", b"x" * 1000, bytes(range(256))):
        assert native.fnv1a64(key) == _py_fnv1a64(key)


def test_fnv_batch_matches_single():
    keys = [f"task-{i}".encode() for i in range(100)] + [b""]
    out = native.fnv1a64_batch(keys)
    assert [int(h) for h in out] == [native.fnv1a64(k) for k in keys]


def test_ring_pick_matches_bisect():
    rng = np.random.default_rng(0)
    ring = np.sort(rng.integers(0, 2**63, 500).astype(np.uint64))
    keys = rng.integers(0, 2**64, 1000, dtype=np.uint64)
    got = native.ring_pick_batch(ring, keys)
    want = np.searchsorted(ring, keys, side="right") % len(ring)
    np.testing.assert_array_equal(got, want)


def test_dag_reachable_matches_python(monkeypatch):
    from dragonfly2_tpu.graph.dag import TaskDAG

    rng = np.random.default_rng(7)
    dag = TaskDAG(capacity=128)
    for v in range(64):
        dag.ensure_vertex(v)
    for _ in range(150):
        u, v = rng.integers(0, 64, 2)
        if dag.can_add_edge(int(u), int(v)):
            dag.add_edge(int(u), int(v))

    # compare native vs the pure-Python BFS on the same adjacency
    def py_reachable(src, dst):
        monkeypatch.setattr(native, "dag_reachable", lambda *a: None)
        try:
            return TaskDAG.reachable(dag, src, dst)
        finally:
            monkeypatch.undo()

    for _ in range(200):
        s, d = map(int, rng.integers(0, 64, 2))
        assert native.dag_reachable(dag.adj, s, d) == py_reachable(s, d)

    # acyclic invariant survives the native path: no v reaches itself
    # through any edge
    for u in range(64):
        for v in dag._children(u):
            assert not dag.reachable(int(v), u)


def test_csv_parse_numeric_quoted_and_ragged():
    data = (
        b"a,b,c\n"
        b"1,2.5,3\n"
        b'4,"5,5",hello\n'  # quoted comma + non-numeric
        b"only,two\n"  # ragged -> skipped
        b'7,"8""8",9\r\n'  # escaped quote, CRLF
        b"\n"
        b"10,11,12"
    )
    mat = native.csv_parse_numeric(data, 3)
    assert mat is not None and mat.shape == (4, 3)
    np.testing.assert_allclose(mat[0], [1, 2.5, 3])
    assert mat[1][0] == 4 and np.isnan(mat[1][2])
    assert np.isnan(mat[2][1])  # 8"8 is not numeric
    np.testing.assert_allclose(mat[3], [10, 11, 12])


def test_trace_numeric_matrix_native_vs_python(tmp_path, monkeypatch):
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.storage import TraceStorage

    storage = TraceStorage(tmp_path)
    cluster = synth.make_cluster(16, seed=3)
    for rec in synth.gen_download_records(cluster, 40, num_tasks=6, max_parents=4):
        storage.create_download(rec)

    native_mat = storage.download_matrix()
    monkeypatch.setattr(native, "csv_parse_numeric", lambda *a, **k: None)
    python_mat = storage.download_matrix()
    assert native_mat.shape == python_mat.shape and native_mat.shape[0] == 40
    np.testing.assert_allclose(native_mat, python_mat, equal_nan=True)
    # column selection works and keeps order
    sub = storage.download_matrix(["finished_piece_count", "task.content_length"])
    assert sub.shape == (40, 2)


def test_hashring_native_and_python_agree(monkeypatch):
    from dragonfly2_tpu.utils.hashring import HashRing

    ring = HashRing([f"sched-{i}" for i in range(5)])
    keys = [f"task-{i}" for i in range(200)]
    batch = ring.pick_many(keys)
    singles = [ring.pick(k) for k in keys]
    assert batch == singles
    # placement must be identical with the native path disabled
    monkeypatch.setenv("DF_NATIVE", "0")
    import dragonfly2_tpu.native as nat

    monkeypatch.setattr(nat, "_tried", True)
    monkeypatch.setattr(nat, "_lib", None)
    ring_py = HashRing([f"sched-{i}" for i in range(5)])
    assert [ring_py.pick(k) for k in keys] == singles
