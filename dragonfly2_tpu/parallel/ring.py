"""Ring attention: sequence/context parallelism over the mesh `sp` axis.

The reference has no sequence models (SURVEY.md §5 "long-context:
absent") — this is new TPU-first capability: attention over sequences too
long for one chip's HBM, computed blockwise with the KV shards rotating
around the ICI ring (`lax.ppermute`) while each device keeps only its
query shard — the Ring Attention construction (see PAPERS.md), with
flash-style online-softmax accumulation so nothing materializes the full
[L, L] score matrix.

Layouts: q/k/v are [B, H, L, D] (L = per-device shard inside shard_map),
kv_mask is [B, L] key validity. `dense_attention` is the single-device
reference implementation and the parity oracle in tests.
"""

from __future__ import annotations

import functools

import jax

from dragonfly2_tpu.utils.jaxcompat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.parallel.mesh import DP_AXIS, SP_AXIS

_NEG = jnp.float32(-1e30)


def dense_attention(q, k, v, kv_mask, causal: bool = False) -> jax.Array:
    """Reference softmax attention. [B,H,L,D] x [B,L] -> [B,H,L,D].

    The q.k matmul keeps the input dtype (bf16 on the MXU) but accumulates
    in float32 — the same contract as the ring path, so the single-chip and
    sp>1 implementations are numerically interchangeable. Also the parity
    oracle and backward-recompute path for the pallas kernel (ops/flash.py),
    which is why the causal option lives here: ONE copy of the masking
    contract."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    scores = (
        jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    )
    valid = jnp.broadcast_to(kv_mask[:, None, None, :], scores.shape)
    if causal:
        ln = q.shape[2]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (ln, ln), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (ln, ln), 1)
        valid = valid & (k_pos <= q_pos)[None, None]
    scores = jnp.where(valid, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows with no valid key softmax over the -1e30 floor uniformly; zero
    # them so fully-masked rows produce 0 like the ring path
    probs = probs * valid
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)).astype(q.dtype)


def ring_attention(
    q, k, v, kv_mask, axis_name: str = SP_AXIS, use_flash: bool = False,
    q_pos=None, k_pos=None,
) -> jax.Array:
    """Blockwise attention inside shard_map: every step attends the local
    queries to the current KV block, then rotates KV one hop around the
    `axis_name` ring. Online softmax keeps running (max, sum, acc) in
    float32.

    use_flash=True computes each per-device block with the pallas kernel's
    partials mode (ops/flash.py) and merges them with the same combine —
    the [Lq, Lk] block score matrix never materializes, so long local
    shards fit where the einsum path would blow HBM. Forward-only (the
    partials kernel has no VJP); training keeps the einsum path.

    Causal mode: pass `q_pos`/`k_pos` (the GLOBAL sequence position of
    each local slot, [L] int32). Keys with k_pos > q_pos are masked as
    the KV blocks rotate — position-based, so it is correct under ANY
    sequence layout including the zigzag one `zigzag_positions` builds to
    balance causal work across the ring (einsum path only)."""
    causal = q_pos is not None
    if k_pos is not None and q_pos is None:
        raise ValueError("k_pos without q_pos: causal masking is keyed on "
                         "q_pos — passing only k_pos would silently compute "
                         "full bidirectional attention")
    if causal and use_flash:
        raise ValueError("causal ring attention uses the einsum path "
                         "(the flash partials kernel has no position mask)")
    n = jax.lax.psum(1, axis_name)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    batch, heads, q_len, dim = q.shape

    acc = jnp.zeros((batch, heads, q_len, dim), jnp.float32)
    row_max = jnp.full((batch, heads, q_len), _NEG, jnp.float32)
    row_sum = jnp.zeros((batch, heads, q_len), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    if use_flash:
        from dragonfly2_tpu.ops.flash import flash_attention_partials

        def attend_block(acc, row_max, row_sum, kb, vb, mb, kpb=None):
            acc_b, m_b, l_b = flash_attention_partials(q, kb, vb, mb)
            new_max = jnp.maximum(row_max, m_b)
            c_old = jnp.exp(row_max - new_max)
            c_new = jnp.exp(m_b - new_max)
            acc = acc * c_old[..., None] + acc_b * c_new[..., None]
            row_sum = row_sum * c_old + l_b * c_new
            return acc, new_max, row_sum
    else:
        def attend_block(acc, row_max, row_sum, kb, vb, mb, kpb=None):
            scores = (
                jnp.einsum("bhqd,bhkd->bhqk", q, kb, preferred_element_type=jnp.float32)
                * scale
            )
            key_valid = mb[:, None, None, :]
            if causal:
                key_valid = key_valid & (kpb[None, :] <= q_pos[:, None])[None, None]
            scores = jnp.where(key_valid, scores, _NEG)
            block_max = jnp.max(scores, axis=-1)
            new_max = jnp.maximum(row_max, block_max)
            correction = jnp.exp(row_max - new_max)
            probs = jnp.exp(scores - new_max[..., None]) * key_valid
            acc = acc * correction[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", probs, vb.astype(jnp.float32)
            )
            row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
            return acc, new_max, row_sum

    # ONE rotation loop for both modes: key positions ride the ring as a
    # loop-carried value; non-causal mode carries a dummy (attend_block
    # ignores kpb when causal is False).
    if causal:
        kp0 = k_pos if k_pos is not None else q_pos
    else:
        kp0 = jnp.zeros((k.shape[2],), jnp.int32)

    def body(_, carry):
        acc, row_max, row_sum, kb, vb, mb, kpb = carry
        acc, row_max, row_sum = attend_block(acc, row_max, row_sum, kb, vb, mb, kpb)
        kb, vb, mb, kpb = jax.lax.ppermute((kb, vb, mb, kpb), axis_name, perm)
        return acc, row_max, row_sum, kb, vb, mb, kpb

    # n-1 attend+rotate steps, then the final block attends WITHOUT the
    # trailing rotation — its output would be discarded, and each skipped
    # ppermute saves a full K+V+mask shard crossing the ICI ring.
    acc, row_max, row_sum, kb, vb, mb, kpb = jax.lax.fori_loop(
        0, n - 1, body, (acc, row_max, row_sum, k, v, kv_mask, kp0)
    )
    acc, row_max, row_sum = attend_block(acc, row_max, row_sum, kb, vb, mb, kpb)
    out = acc / jnp.maximum(row_sum, 1e-9)[..., None]
    return out.astype(q.dtype)


def sharded_ring_attention(mesh, q, k, v, kv_mask, use_flash: bool = False) -> jax.Array:
    """shard_map wrapper: batch over `dp`, sequence over `sp`. Global
    shapes in, global shapes out; each device holds L/sp of the sequence
    and the KV shards ride the ICI ring. `use_flash` swaps the per-device
    block computation for the pallas partials kernel (forward-only)."""
    qkv_spec = P(DP_AXIS, None, SP_AXIS, None)
    mask_spec = P(DP_AXIS, SP_AXIS)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=SP_AXIS, use_flash=use_flash),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_mask)


# ------------------------------------------------------------- causal sp


def zigzag_positions(seq_len: int, n_shards: int):
    """Zigzag context-parallel layout: split the sequence into 2n chunks
    and give shard i chunks (i, 2n-1-i), so every shard owns one early
    and one late chunk. Under a plain contiguous split, causal masking
    leaves the first shard with almost no attendable keys and the last
    with all of them — a ~2x ring-step load imbalance that the zigzag
    pairing flattens (each shard's key work sums to the same total).

    Returns (order, inverse): `x[..., order, :]` lays the sequence out in
    zigzag shard order; `y[..., inverse, :]` undoes it. `order` is also
    each zigzag slot's global position (what the causal mask needs)."""
    if seq_len % (2 * n_shards):
        raise ValueError(f"seq_len {seq_len} not divisible by 2*{n_shards}")
    chunk = seq_len // (2 * n_shards)
    order = []
    for i in range(n_shards):
        order.extend(range(i * chunk, (i + 1) * chunk))
        j = 2 * n_shards - 1 - i
        order.extend(range(j * chunk, (j + 1) * chunk))
    order = jnp.asarray(order, jnp.int32)
    inverse = jnp.zeros_like(order).at[order].set(jnp.arange(seq_len, dtype=jnp.int32))
    return order, inverse


def sharded_causal_ring_attention(mesh, q, k, v, kv_mask) -> jax.Array:
    """Causal ring attention over the `sp` axis with zigzag load
    balancing. Global [B,H,L,D] in and out (contiguous sequence order) —
    the zigzag reorder and its inverse happen here, positions ride the
    ring so masking is layout-independent."""
    n = mesh.shape[SP_AXIS]
    seq_len = q.shape[2]
    order, inverse = zigzag_positions(seq_len, n)
    qz, kz, vz = (x[:, :, order, :] for x in (q, k, v))
    maskz = kv_mask[:, order]

    qkv_spec = P(DP_AXIS, None, SP_AXIS, None)
    mask_spec = P(DP_AXIS, SP_AXIS)
    pos_spec = P(SP_AXIS)

    def local(qb, kb, vb, mb, pos):
        return ring_attention(qb, kb, vb, mb, axis_name=SP_AXIS,
                              q_pos=pos, k_pos=pos)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec, pos_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    out = fn(qz, kz, vz, maskz, order)
    return out[:, :, inverse, :]
