"""Test harness: force an 8-device virtual CPU mesh before jax loads.

Mirrors the reference's approach of unit-testing "multi-node" logic without
a cluster (SURVEY.md §4): sharding/collective code paths run on
xla_force_host_platform_device_count=8 CPU devices; numeric kernels run on
the CPU backend with fixed seeds. No TPU needed in CI.
"""

import os

# Env vars alone are not enough: in this image jax is pre-imported at
# interpreter startup (a .pth hook) with JAX_PLATFORMS already resolved, so
# the config must be updated through jax.config before first backend use.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except RuntimeError:
    # Backend already initialized (a plugin touched jax before conftest) —
    # the env vars above were then read at init and did the same job.
    pass
except AttributeError:
    # Older jax without the jax_num_cpu_devices option: the XLA_FLAGS
    # host-platform device-count flag above is the only mechanism.
    pass

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running end-to-end tests")


@pytest.fixture
def rng():
    return np.random.default_rng(0)
