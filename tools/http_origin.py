"""Shared in-process HTTP origin for the bench/scenario harnesses.

One Range-correct file server (incl. suffix ranges ``bytes=-N``, which
ad-hoc copies tended to mishandle) parameterized by a path->payload map,
with lock-guarded GET/byte counters — the single implementation behind
tools/stress.py and tools/llm_prefetch.py so range semantics cannot
drift between harnesses. (The multi-process e2e keeps its own minimal
origin because its tests monkeypatch the handler class.)
"""

from __future__ import annotations

import http.server
import threading


class HTTPOrigin:
    def __init__(self, payloads: dict[str, bytes], default: bytes | None = None):
        """`payloads` maps exact paths to bodies; `default` (if given)
        answers every other path — harnesses that only need "one blob at
        any URL" (tools/stress.py) use it alone."""
        self.payloads = dict(payloads)
        self.default = default
        self.gets = 0
        self.bytes_served = 0
        self._mu = threading.Lock()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _payload(self):
                return outer.payloads.get(
                    self.path.split("?", 1)[0], outer.default
                )

            def do_HEAD(self):
                data = self._payload()
                if data is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()

            def do_GET(self):
                data = self._payload()
                if data is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                status = 200
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    spec = rng[len("bytes="):].split(",")[0].strip()
                    lo_s, _, hi_s = spec.partition("-")
                    if lo_s == "" and hi_s:  # suffix range: last N bytes
                        data = data[-int(hi_s):] if int(hi_s) else b""
                    else:
                        lo = int(lo_s or 0)
                        hi = int(hi_s) if hi_s else len(data) - 1
                        data = data[lo : hi + 1]
                    status = 206
                with outer._mu:
                    outer.gets += 1
                    outer.bytes_served += len(data)
                self.send_response(status)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.srv.server_address[1]
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()

    def close(self) -> None:
        self.srv.shutdown()
        self.srv.server_close()
