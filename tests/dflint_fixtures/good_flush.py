"""dflint green fixture: flush-valve idioms the pass must accept —
flush-before-read, producer appends, a private helper whose only caller
flushes first, and unrelated attributes that merely share a column
name (no `.state.` hop)."""


class SchedulerService:
    def __init__(self, state):
        self.state = state
        self._piece_buf: list = []
        self.peer_finished_count = {}  # NOT a column: no .state. hop

    def flush_piece_reports(self):
        buf, self._piece_buf = self._piece_buf, []
        return len(buf)

    def enqueue(self, row):
        self._piece_buf.append(row)  # producer side: allowed

    def fresh_read(self):
        self.flush_piece_reports()
        return self.state.peer_finished_count[0]

    def entry(self):
        self.flush_piece_reports()
        return self._covered_helper()

    def _covered_helper(self):
        # only caller is `entry`, which flushes before the call
        return self.state.peer_finished_count[1]

    def unrelated(self):
        return self.peer_finished_count.get("x")
