"""Declarative scenario specs.

A scenario is a plain dataclass tree — serializable, diffable, loadable
from TOML or JSON — that fully determines (together with an integer seed)
the heterogeneity and faults injected into a run. The spec carries NO
randomness itself; all sampling lives in ``engine.ScenarioEngine`` so the
same spec document can drive the pure simulator, the A/B harness, and the
multiprocess e2e loop identically.

Knob ↔ reference semantics (see PARITY.md "Scenario lab"):

- ``LinkSpec`` RTT tiers mirror the networktopology probe structure the
  reference snapshots (same-IDC / same-region / cross-region RTT bands,
  scheduler/networktopology) — the scenario's link model is what the
  probe loop *measures*;
- ``FlakySpec`` models parents whose piece serving errors or stalls —
  exercised through the child's real retry path
  (DownloadPieceFailedRequest → reschedule → blocklist), not simulated
  around it;
- ``ChurnSpec`` models peers leaving/crashing mid-download and hosts
  dropping off the announce plane (LeaveHost) and returning;
- ``SkewSpec`` models hotspot task popularity (Zipf), the regime where a
  few blobs are downloaded cluster-wide and swarms get deep.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any


@dataclasses.dataclass
class LinkSpec:
    """Per-link RTT/bandwidth model.

    RTT tiers (ms) follow the synthetic IDC structure records/synth.py
    plants; bandwidth is per-HOST NIC capacity (bytes/s) with a bimodal
    fast/slow split, an optional oversubscribed spine penalty applied to
    cross-rack transfers, and an optional handful of pathologically slow
    NICs (the tail the rule blend cannot see until piece costs pile up).
    """

    same_rack_rtt_ms: float = 0.2
    same_idc_rtt_ms: float = 0.5
    same_region_rtt_ms: float = 5.0
    cross_region_rtt_ms: float = 60.0
    rtt_jitter_sigma: float = 0.3

    base_bandwidth_bps: float = 100e6  # bytes/s of a healthy NIC
    bandwidth_jitter_sigma: float = 0.25
    slow_fraction: float = 0.0         # fraction of hosts in the slow mode
    slow_multiplier: float = 1.0       # slow-mode bandwidth = base * this
    spine_oversubscription: float = 1.0  # cross-rack bandwidth divisor
    slow_nic_count: int = 0            # hosts with a pathological NIC
    slow_nic_multiplier: float = 0.05


@dataclasses.dataclass
class ChurnSpec:
    peer_crash_rate: float = 0.0   # P(a child crashes mid-download)
    crash_progress: float = 0.5    # crash lands after this piece fraction
    host_leave_rate: float = 0.0   # P(host offline in a given epoch)
    leave_epoch_rounds: int = 20   # offline membership re-rolls every N rounds


@dataclasses.dataclass
class FlakySpec:
    parent_fraction: float = 0.0   # fraction of hosts that serve flakily
    piece_error_rate: float = 0.0  # P(piece from a flaky parent errors)
    piece_stall_rate: float = 0.0  # P(piece from a flaky parent stalls)
    stall_seconds: float = 1.0     # injected stall duration
    # Deterministic CONTENT corruption (the trust-boundary adversary): a
    # corrupting parent serves bytes that differ from the origin's, with
    # its advisory digest header rewritten to match — only verification
    # against the scheduler-attested chain catches it. Modes: "bitflip"
    # (one deterministic bit flipped) or "truncate" (deterministic tail
    # dropped).
    piece_corrupt_rate: float = 0.0  # P(piece from a flaky parent corrupts)
    corrupt_mode: str = "bitflip"    # bitflip | truncate


@dataclasses.dataclass
class SkewSpec:
    zipf_alpha: float = 0.0        # 0 = uniform task popularity


@dataclasses.dataclass
class ControlPlaneSpec:
    """Control-plane fault events (the failure-domain resilience layer's
    adversary): scheduler crashes that sever every announce stream at
    once, and host↔scheduler partitions that silently blackhole the
    announce plane (no FIN — requests vanish). Like every other spec
    knob, the EVENTS are sampled deterministically by the engine from
    (spec, seed, event identity); these fields only set the rates."""

    scheduler_crash_rate: float = 0.0   # P(the scheduler crashes in an epoch)
    crash_epoch_rounds: int = 25        # crash opportunity every N rounds
    crash_progress: float = 0.5         # e2e: kill after this piece fraction
    partition_rate: float = 0.0         # P(a host is partitioned in an epoch)
    partition_epoch_rounds: int = 20    # partition membership re-rolls every N


@dataclasses.dataclass
class ScenarioSpec:
    name: str = "homogeneous"
    description: str = ""
    link: LinkSpec = dataclasses.field(default_factory=LinkSpec)
    churn: ChurnSpec = dataclasses.field(default_factory=ChurnSpec)
    flaky: FlakySpec = dataclasses.field(default_factory=FlakySpec)
    skew: SkewSpec = dataclasses.field(default_factory=SkewSpec)
    control: ControlPlaneSpec = dataclasses.field(default_factory=ControlPlaneSpec)

    # ------------------------------------------------------------- codecs

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "ScenarioSpec":
        spec = cls()
        for key, value in (data or {}).items():
            if not hasattr(spec, key):
                raise ValueError(f"unknown scenario field {key!r}")
            current = getattr(spec, key)
            if dataclasses.is_dataclass(current) and isinstance(value, dict):
                for k, v in value.items():
                    if not hasattr(current, k):
                        raise ValueError(f"unknown scenario field {key}.{k}")
                    setattr(current, k, type(getattr(current, k))(v))
            else:
                setattr(spec, key, value)
        return spec

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)


def load_scenario(path: str | pathlib.Path) -> ScenarioSpec:
    """Load a spec from a ``.toml`` or ``.json`` file. TOML uses stdlib
    ``tomllib`` where available (3.11+); on older interpreters a minimal
    flat ``[section] key = value`` parser covers the spec grammar."""
    path = pathlib.Path(path)
    text = path.read_text()
    if path.suffix == ".toml":
        return ScenarioSpec.from_dict(_parse_toml(text))
    return ScenarioSpec.from_dict(json.loads(text))


def _parse_toml(text: str) -> dict:
    try:
        import tomllib  # py311+

        return tomllib.loads(text)
    except ImportError:
        pass
    root: dict[str, Any] = {}
    section = root
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            section = root.setdefault(line[1:-1].strip(), {})
            continue
        key, _, value = line.partition("=")
        section[key.strip()] = _coerce(value.strip())
    return root


def _coerce(value: str) -> Any:
    if value.startswith(("'", '"')) and value.endswith(("'", '"')):
        return value[1:-1]
    if value.lower() in ("true", "false"):
        return value.lower() == "true"
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            continue
    return value


# --------------------------------------------------------------- builtins


def builtin_scenarios() -> dict[str, ScenarioSpec]:
    """The scenario grid BENCH_scenarios.json covers: a homogeneous
    control plus the structured adversarial conditions the learned
    evaluator exists for. Severity is deliberately strong — the point is
    exploitable structure, not realism tuning."""
    return {
        "homogeneous": ScenarioSpec(
            name="homogeneous",
            description="control: uniform NICs, no faults, uniform popularity",
        ),
        "bandwidth_skew": ScenarioSpec(
            name="bandwidth_skew",
            description=(
                "bimodal rack NICs (40% at 15% speed), 4x oversubscribed "
                "spine on cross-rack paths, plus 2 pathological slow NICs"
            ),
            link=LinkSpec(
                slow_fraction=0.4,
                slow_multiplier=0.15,
                spine_oversubscription=4.0,
                slow_nic_count=2,
                slow_nic_multiplier=0.02,
            ),
        ),
        "churn": ScenarioSpec(
            name="churn",
            description=(
                "15% of children crash mid-download; 10% of hosts flap "
                "off the announce plane each epoch"
            ),
            churn=ChurnSpec(
                peer_crash_rate=0.15,
                crash_progress=0.5,
                host_leave_rate=0.10,
                leave_epoch_rounds=15,
            ),
        ),
        "flaky_parent": ScenarioSpec(
            name="flaky_parent",
            description=(
                "30% of hosts serve flakily: 25% piece error rate, 10% "
                "stall rate — exercised through the real retry path"
            ),
            flaky=FlakySpec(
                parent_fraction=0.30,
                piece_error_rate=0.25,
                piece_stall_rate=0.10,
                stall_seconds=0.5,
            ),
        ),
        "corruption": ScenarioSpec(
            name="corruption",
            description=(
                "20% of hosts serve CORRUPT bytes on 30% of pieces "
                "(deterministic bit flips under a self-consistent digest "
                "header) plus a little plain flakiness — children verify "
                "against scheduler-attested digests, report "
                "reason=corruption, and the scheduler quarantines the "
                "corrupting parents (time-decayed release)"
            ),
            flaky=FlakySpec(
                parent_fraction=0.20,
                piece_error_rate=0.05,
                piece_corrupt_rate=0.30,
                corrupt_mode="bitflip",
            ),
        ),
        "hotspot": ScenarioSpec(
            name="hotspot",
            description="Zipf(1.2) task popularity: a few blobs go cluster-wide",
            skew=SkewSpec(zipf_alpha=1.2),
        ),
        "chaos": ScenarioSpec(
            name="chaos",
            description=(
                "control-plane chaos: scheduler crashes sever every "
                "announce stream (in-flight peers re-announce their kept "
                "pieces and the scheduler adopts them), 10% of hosts "
                "silently partitioned per epoch, plus peer churn and "
                "enough flaky serving that downloads span rounds — the "
                "failure-domain resilience gauntlet"
            ),
            churn=ChurnSpec(peer_crash_rate=0.05, crash_progress=0.5),
            # flaky parents keep downloads in flight across rounds, so
            # crashes and partitions catch real partial progress instead
            # of an empty pending queue
            flaky=FlakySpec(
                parent_fraction=0.25, piece_error_rate=0.15,
                piece_stall_rate=0.05, stall_seconds=0.2,
            ),
            control=ControlPlaneSpec(
                scheduler_crash_rate=0.6,
                crash_epoch_rounds=20,
                partition_rate=0.10,
                partition_epoch_rounds=15,
            ),
        ),
    }
