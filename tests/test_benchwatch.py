"""Perf observatory — bench registry gate (tools/benchwatch.py).

Tier-1 half: every checked-in BENCH_*.json (all four artifact kinds plus
the normalized trajectory) must parse against its schema and the
repo-root --check gate must be green. Unit half: regression flagging is
strict about comparability (same kind + fingerprint, strictly adjacent
rounds, quarantined values never anchor a verdict) and an injected >10%
regression exits nonzero."""

import io
import json
from pathlib import Path

import pytest

from tools import benchwatch
from tools.benchwatch import (
    SchemaError,
    check,
    detect_kind,
    find_regressions,
    load_entries,
    lower_is_better,
    normalize,
    validate,
)

ROOT = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------- tier-1 gate


def test_every_checked_in_artifact_parses_against_the_schema():
    files = benchwatch.artifact_files(ROOT)
    assert len(files) >= 8, files  # r01..r06 + mega + scenarios
    entries, errors = load_entries(files)
    assert errors == []
    assert len(entries) == len(files)
    kinds = {e["kind"] for e in entries}
    assert {"driver", "loop", "mega", "scenarios"} <= kinds


def test_checked_in_trajectory_validates():
    errors = benchwatch.validate_trajectory_file(ROOT)
    assert errors == []
    doc = json.loads((ROOT / benchwatch.TRAJECTORY_FILE).read_text())
    assert doc["schema_version"] == benchwatch.TRAJECTORY_SCHEMA_VERSION
    assert len(doc["entries"]) >= 8


def test_repo_root_check_gate_is_green():
    out = io.StringIO()
    assert check(ROOT, out=out) == 0, out.getvalue()


# ------------------------------------------------------------ regression


def _loop_artifact(pieces_per_sec=20_000.0, tick_p50=7.0, machine="x86_64"):
    return {
        "schema_version": 2,
        "cmd": "python bench_loop.py",
        "platform": {"jax": "0.4.37", "devices": ["TFRT_CPU_0"],
                     "machine": machine, "python": "3.10"},
        "summary": {"metric": "bench_loop_summary",
                    "pieces_per_sec": pieces_per_sec,
                    "tick_p50_ms": tick_p50},
        "results": [{"metric": "full_loop_pieces_per_sec",
                     "value": pieces_per_sec}],
    }


def _write(tmp_path, name, doc):
    (tmp_path / name).write_text(json.dumps(doc))


def test_injected_regression_exits_nonzero(tmp_path):
    """The acceptance gate: a crafted trajectory with a >10% drop in a
    higher-is-better metric between adjacent rounds fails --check."""
    _write(tmp_path, "BENCH_r01.json", _loop_artifact(20_000.0))
    _write(tmp_path, "BENCH_r02.json", _loop_artifact(15_000.0))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert "REGRESSION pieces_per_sec" in out.getvalue()


def test_lower_is_better_regression_direction(tmp_path):
    # pieces/s improves but tick p50 regresses 7 -> 12 ms
    _write(tmp_path, "BENCH_r01.json", _loop_artifact(20_000.0, tick_p50=7.0))
    _write(tmp_path, "BENCH_r02.json", _loop_artifact(25_000.0, tick_p50=12.0))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert "REGRESSION tick_p50_ms" in out.getvalue()
    assert "pieces_per_sec" not in out.getvalue().split("REGRESSION", 1)[1]


def test_within_threshold_changes_pass(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _loop_artifact(20_000.0))
    _write(tmp_path, "BENCH_r02.json", _loop_artifact(18_500.0))  # -7.5%
    assert check(tmp_path, out=io.StringIO()) == 0


def test_broken_round_chain_never_compares_across_the_gap(tmp_path):
    """r03 vs r01 with r02 missing: no comparison — a missing or
    corrupt intermediate round breaks the chain instead of silently
    comparing across it (the BENCH_r04-is-truncated reality)."""
    _write(tmp_path, "BENCH_r01.json", _loop_artifact(20_000.0))
    _write(tmp_path, "BENCH_r03.json", _loop_artifact(5_000.0))
    assert check(tmp_path, out=io.StringIO()) == 0


def test_platform_fingerprint_gates_comparability(tmp_path):
    """A rig move is not a regression: different machine fingerprints
    never compare."""
    _write(tmp_path, "BENCH_r01.json", _loop_artifact(20_000.0, machine="tpu-vm"))
    _write(tmp_path, "BENCH_r02.json", _loop_artifact(5_000.0, machine="x86_64"))
    assert check(tmp_path, out=io.StringIO()) == 0


def test_quarantined_values_anchor_no_verdict(tmp_path):
    """Physically invalid values (MFU > 100%, clamp-floor latencies) stay
    visible in the trajectory but are excluded from comparison — the
    BENCH_r03 corrupt-timing artifact must not make r04 look like a
    10x regression."""
    driver = {
        "n": 3, "cmd": "python bench.py", "rc": 0, "tail": "...",
        "parsed": {"metric": "m", "value": 0.01, "unit": "ms",
                   "method": "pipelined_steady_state",
                   "gnn_mfu_pct": 156.0},
    }
    entry = normalize(driver, "driver", "BENCH_r03.json")
    assert "headline_p50_ms" not in entry["metrics"]
    assert "gnn_mfu_pct" not in entry["metrics"]
    assert set(entry["quarantined_metrics"]) == {
        "headline_p50_ms", "gnn_mfu_pct"
    }
    honest = {
        "n": 4, "cmd": "python bench.py", "rc": 0, "tail": "...",
        "parsed": {"metric": "m", "value": 0.09, "unit": "ms",
                   "method": "pipelined_steady_state",
                   "gnn_mfu_pct": 24.6},
    }
    entry4 = normalize(honest, "driver", "BENCH_r04.json")
    assert entry4["metrics"]["headline_p50_ms"] == 0.09
    assert find_regressions([entry, entry4], threshold=0.10) == []


# ------------------------------------------------------------ validation


def test_schema_errors_fail_the_gate(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    _write(tmp_path, "BENCH_r02.json", {"results": [], "summary": {}})  # no cmd
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    text = out.getvalue()
    assert text.count("SCHEMA") == 2, text


def test_detect_kind_and_validate_contracts():
    assert detect_kind({"cmd": "", "rc": 0, "tail": "", "n": 1}, "x") == "driver"
    assert detect_kind({"cmd": "", "platform": {}, "summary": {},
                        "runs": []}, "x") == "mega"
    assert detect_kind({"cmd": "", "platform": {}, "summary": {},
                        "results": []}, "x") == "loop"
    assert detect_kind({"scenarios": {}}, "x") == "scenarios"
    with pytest.raises(SchemaError):
        detect_kind({"what": 1}, "x")
    with pytest.raises(SchemaError):
        validate({"cmd": "", "rc": 0, "tail": "", "parsed": {"metric": "m"}},
                 "driver", "x")  # parsed without value
    # driver with parsed == null (the r04 truncation) is LEGAL
    validate({"cmd": "", "rc": 1, "tail": "", "parsed": None}, "driver", "x")


def test_direction_table():
    assert lower_is_better("tick_p50_ms")
    assert lower_is_better("headline_p50_ms")
    assert lower_is_better("soak_100000_origin_traffic_fraction")
    assert lower_is_better("control_dispatch")
    assert not lower_is_better("pieces_per_sec")
    assert not lower_is_better("gnn_mfu_pct")
    assert not lower_is_better("ab_ml_vs_default_cost")


def test_bench_py_artifact_kind_round_trips_the_gate(tmp_path):
    """`python bench.py --artifact` writes {schema_version, cmd,
    platform, summary, record}: the `bench` kind must validate,
    normalize with the driver-record extraction (incl. quarantine
    rules), and pass --check — a freshly produced artifact failing the
    gate it feeds would be a workflow break."""
    doc = {
        "schema_version": 2,
        "cmd": "python bench.py --artifact BENCH_r07.json",
        "platform": {"jax": "0.4.37", "devices": ["TFRT_CPU_0"],
                     "machine": "x86_64", "python": "3.10"},
        "summary": {"metric": "scheduler_parent_selection_p50_ms_1024x64",
                    "value": 0.08, "gnn_mfu_pct": 30.0},
        "record": {"metric": "scheduler_parent_selection_p50_ms_1024x64",
                   "value": 0.08, "unit": "ms", "method": "control_gated_p50",
                   "trainer": {"gnn_mfu_pct": 30.0}},
    }
    assert detect_kind(doc, "BENCH_r07.json") == "bench"
    validate(doc, "bench", "BENCH_r07.json")
    entry = normalize(doc, "bench", "BENCH_r07.json")
    assert entry["metrics"]["headline_p50_ms"] == 0.08
    assert entry["metrics"]["gnn_mfu_pct"] == 30.0
    _write(tmp_path, "BENCH_r07.json", doc)
    assert check(tmp_path, out=io.StringIO()) == 0


def test_decision_metrics_direction_table(tmp_path):
    """ISSUE 13 red/green: divergence metrics (top-1 disagreement, rank
    correlation) have NO monotonic better-direction — a big swing never
    flags — while regret is a real lower-is-better verdict and must
    flag. Covers the loop summary keys and the megascale cells."""
    from tools.benchwatch import direction_exempt

    # direction table entries for the new family
    assert direction_exempt("decision_top1_disagreement")
    assert direction_exempt("decision_rank_corr")
    assert direction_exempt("soak_100000_shadow_divergence")
    assert not direction_exempt("decision_regret_ms")
    assert lower_is_better("decision_regret_ms")
    assert lower_is_better("planet_100000_decision_regret_fail_rate")
    assert lower_is_better("shadow_score")  # the tick phase, ms
    # GREEN: disagreement jumping 9x between adjacent rounds flags nothing
    a1 = _loop_artifact(20_000.0)
    a1["summary"].update({"decision_top1_disagreement": 0.05,
                          "decision_rank_corr": 0.9,
                          "decision_regret_ms": 1.0})
    a2 = _loop_artifact(20_000.0)
    a2["summary"].update({"decision_top1_disagreement": 0.45,
                          "decision_rank_corr": 0.2,
                          "decision_regret_ms": 1.0})
    _write(tmp_path, "BENCH_r01.json", a1)
    _write(tmp_path, "BENCH_r02.json", a2)
    out = io.StringIO()
    assert check(tmp_path, out=out) == 0, out.getvalue()
    entry = normalize(a2, "loop", "BENCH_r02.json")
    assert "decision_top1_disagreement" not in entry["metrics"]
    assert "decision_rank_corr" not in entry["metrics"]
    assert entry["metrics"]["decision_regret_ms"] == 1.0
    # RED: regret worsening 50% between adjacent rounds fails the gate
    a3 = _loop_artifact(20_000.0)
    a3["summary"].update({"decision_regret_ms": 1.5})
    _write(tmp_path, "BENCH_r03.json", a3)
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert "REGRESSION decision_regret_ms" in out.getvalue()
    # megascale cells: regret compares, the divergence cell is dropped
    mega = {
        "schema_version": 2, "cmd": "python bench_megascale.py",
        "platform": {"jax": "0.4.37", "devices": ["TFRT_CPU_0"],
                     "machine": "x86_64", "python": "3.10"},
        "summary": {"soak_1000": {
            "pieces_per_sec": 1000.0, "completed": 10,
            "origin_traffic_fraction": 0.05,
            "decision_top1_disagreement": 0.3,
            "decision_regret_fail_rate": 0.02,
        }},
        "runs": [{"scenario": "soak", "hosts": 1000, "stats": {},
                  "timing": {}}],
    }
    m_entry = normalize(mega, "mega", "BENCH_mega.json")
    assert m_entry["metrics"]["soak_1000_decision_regret_fail_rate"] == 0.02
    assert "soak_1000_decision_top1_disagreement" not in m_entry["metrics"]


def test_slo_metrics_direction_table(tmp_path):
    """ISSUE 14 red/green: SLO alert counts and error-budget burn are
    lower-is-better cells (an adjacent-round alert-noise increase fails
    the gate); the categorical verdict state is direction-exempt and
    never normalizes into a comparable metric."""
    from tools.benchwatch import direction_exempt

    assert lower_is_better("soak_100000_slo_pages_fired")
    assert lower_is_better("soak_100000_slo_tickets_fired")
    assert lower_is_better("soak_100000_slo_alerts_fired")
    assert lower_is_better("planet_100000_slo_budget_burn")
    assert direction_exempt("soak_100000_slo_verdict_state")
    assert not lower_is_better("pieces_per_sec")

    def mega(pages, burn, verdict):
        return {
            "schema_version": 2, "cmd": "python bench_megascale.py",
            "platform": {"jax": "0.4.37", "devices": ["TFRT_CPU_0"],
                         "machine": "x86_64", "python": "3.10"},
            "summary": {"soak_1000": {
                "pieces_per_sec": 1000.0, "completed": 10,
                "origin_traffic_fraction": 0.05,
                "slo_pages_fired": pages, "slo_tickets_fired": pages,
                "slo_alerts_fired": 2 * pages, "slo_budget_burn": burn,
                "slo_verdict_state": verdict,
            }},
            "runs": [{"scenario": "soak", "hosts": 1000, "stats": {},
                      "timing": {}}],
        }

    # GREEN: verdict category flips 0 -> 2, alerts/burn steady — passes
    _write(tmp_path, "BENCH_r01.json", mega(pages=2, burn=0.5, verdict=0))
    _write(tmp_path, "BENCH_r02.json", mega(pages=2, burn=0.5, verdict=2))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 0, out.getvalue()
    entry = normalize(mega(2, 0.5, 2), "mega", "BENCH_r02.json")
    assert "soak_1000_slo_verdict_state" not in entry["metrics"]
    assert entry["metrics"]["soak_1000_slo_pages_fired"] == 2.0
    assert entry["metrics"]["soak_1000_slo_budget_burn"] == 0.5
    # RED: alert noise doubles between adjacent rounds — the gate fails
    _write(tmp_path, "BENCH_r03.json", mega(pages=4, burn=0.8, verdict=2))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    text = out.getvalue()
    assert "REGRESSION soak_1000_slo_pages_fired" in text
    assert "REGRESSION soak_1000_slo_budget_burn" in text


def test_tail_metrics_direction_table(tmp_path):
    """ISSUE 16 red/green: worst-region tail TTC p99 is a lower-is-better
    cell (an adjacent-round tail blow-up fails the gate); phase shares
    are compositions and the decomposition ratio is a consistency audit
    (perfect = 1.0) — both direction-exempt, never normalized into a
    comparable metric."""
    from tools.benchwatch import direction_exempt

    assert lower_is_better("soak_100000_tail_ttc_p99_ms")
    assert direction_exempt("soak_100000_tail_failover_phase_share")
    assert direction_exempt("soak_100000_tail_decomp_ratio")

    def mega(p99, share, ratio):
        return {
            "schema_version": 2, "cmd": "python bench_megascale.py",
            "platform": {"jax": "0.4.37", "devices": ["TFRT_CPU_0"],
                         "machine": "x86_64", "python": "3.10"},
            "summary": {"soak_1000": {
                "pieces_per_sec": 1000.0, "completed": 10,
                "origin_traffic_fraction": 0.05,
                "tail_ttc_p99_ms": p99,
                "tail_failover_phase_share": share,
                "tail_decomp_ratio": ratio,
            }},
            "runs": [{"scenario": "soak", "hosts": 1000, "stats": {},
                      "timing": {}}],
        }

    # GREEN: failover share and ratio wobble, p99 steady — passes
    _write(tmp_path, "BENCH_r01.json", mega(p99=12000.0, share=0.1, ratio=1.0))
    _write(tmp_path, "BENCH_r02.json", mega(p99=12100.0, share=0.4, ratio=0.97))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 0, out.getvalue()
    entry = normalize(mega(12100.0, 0.4, 0.97), "mega", "BENCH_r02.json")
    assert "soak_1000_tail_failover_phase_share" not in entry["metrics"]
    assert "soak_1000_tail_decomp_ratio" not in entry["metrics"]
    assert entry["metrics"]["soak_1000_tail_ttc_p99_ms"] == 12100.0
    # RED: the tail blows up between adjacent rounds — the gate fails
    _write(tmp_path, "BENCH_r03.json", mega(p99=20000.0, share=0.4, ratio=1.0))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert "REGRESSION soak_1000_tail_ttc_p99_ms" in out.getvalue()


def test_fleet_metrics_direction_table(tmp_path):
    """ISSUE 17 red/green: aggregate pieces/s across the sharded control
    plane is a higher-is-better cell (an adjacent-round throughput drop
    fails the gate); handoff counts track ring churn, not quality — they
    swing with the fault schedule and are direction-exempt, never
    normalized into a comparable metric."""
    from tools.benchwatch import direction_exempt

    assert not lower_is_better("fleet_1000000_r4_aggregate_pieces_per_sec")
    assert not lower_is_better("fleet_1000_r1_aggregate_pieces_per_sec")
    assert direction_exempt("fleet_1000000_r4_fleet_handoffs")
    assert direction_exempt("fleet_1000_r1_fleet_handoffs")

    def mega(agg, handoffs):
        return {
            "schema_version": 2, "cmd": "python bench_megascale.py",
            "platform": {"jax": "0.4.37", "devices": ["TFRT_CPU_0"],
                         "machine": "x86_64", "python": "3.10"},
            "summary": {"fleet_1000_r4": {
                "pieces_per_sec": 1000.0, "completed": 10,
                "origin_traffic_fraction": 0.05,
                "aggregate_pieces_per_sec": agg,
                "fleet_handoffs": handoffs,
            }},
            "runs": [{"scenario": "fleet", "hosts": 1000, "stats": {},
                      "timing": {}}],
        }

    # GREEN: handoff counts swing 40 -> 900 with the fault schedule,
    # aggregate throughput steady — passes
    _write(tmp_path, "BENCH_r01.json", mega(agg=4000.0, handoffs=40))
    _write(tmp_path, "BENCH_r02.json", mega(agg=3950.0, handoffs=900))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 0, out.getvalue()
    entry = normalize(mega(3950.0, 900), "mega", "BENCH_r02.json")
    assert "fleet_1000_r4_fleet_handoffs" not in entry["metrics"]
    assert entry["metrics"]["fleet_1000_r4_aggregate_pieces_per_sec"] == 3950.0
    # RED: aggregate throughput drops >10% between adjacent rounds —
    # the fleet stopped scaling and the gate fails
    _write(tmp_path, "BENCH_r03.json", mega(agg=2500.0, handoffs=900))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert ("REGRESSION fleet_1000_r4_aggregate_pieces_per_sec"
            in out.getvalue())


def test_proc_metrics_direction_table(tmp_path):
    """ISSUE 18 red/green: the process-planet artifact kind. Lost
    downloads and stop escalations are failure accounting (lower-better:
    an adjacent-round increase fails the gate); kill/restart counts are
    chaos dosage — they swing with the scenario's crash epochs and
    upgrade waves and are direction-exempt; divergence ratios are
    ratio-to-ideal comparisons gated by the artifact's own all_within
    flag, never normalized into a comparable metric."""
    from tools.benchwatch import direction_exempt

    assert lower_is_better("proc_lost_downloads")
    assert lower_is_better("proc_escalations")
    assert lower_is_better("proc_pages_fired")
    assert not lower_is_better("proc_completed")
    assert not lower_is_better("proc_downloads_per_sec")
    assert direction_exempt("proc_kills")
    assert direction_exempt("proc_restarts")
    assert direction_exempt("sim_real_divergence")

    def proc(lost, restarts, dps=2.0):
        return {
            "schema_version": 2, "cmd": "python tools/dfproc.py",
            "platform": {"jax": "0.4.37", "devices": ["TFRT_CPU_0"],
                         "machine": "x86_64", "python": "3.10"},
            "summary": {"scenario": "procday", "completed": 144,
                        "lost_downloads": lost, "kills": 2,
                        "restarts": restarts, "escalations": 0,
                        "pages_fired": 2},
            "runs": [{"scenario": "procday", "hosts": 3, "stats": {},
                      "timing": {"downloads_per_sec": dps}}],
            "divergence": {
                "metrics": {"lost_downloads": {
                    "band": [1.0, 1.0], "within": True,
                    "argument": "exact agreement at 0"}},
                "all_within": True,
            },
        }

    # GREEN: restart count swings 10 -> 40 with the chaos schedule,
    # zero lost both rounds — passes
    _write(tmp_path, "BENCH_r01.json", proc(lost=0, restarts=10))
    _write(tmp_path, "BENCH_r02.json", proc(lost=0, restarts=40))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 0, out.getvalue()
    entry = normalize(proc(0, 40), "proc", "BENCH_r02.json")
    assert "proc_restarts" not in entry["metrics"]
    assert "proc_kills" not in entry["metrics"]
    assert entry["metrics"]["proc_lost_downloads"] == 0.0
    assert entry["metrics"]["proc_downloads_per_sec"] == 2.0
    # RED: lost downloads grew between adjacent rounds — the invariant
    # is eroding and the gate fails (zero-base rounds never anchor a
    # ratio, so the red pair starts from 1)
    _write(tmp_path, "BENCH_r02.json", proc(lost=1, restarts=40))
    _write(tmp_path, "BENCH_r03.json", proc(lost=3, restarts=40))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert "REGRESSION proc_lost_downloads" in out.getvalue()

    # schema teeth: a divergence entry without its band argument is a
    # contract violation, not a comparable artifact
    bad = proc(lost=0, restarts=10)
    del bad["divergence"]["metrics"]["lost_downloads"]["argument"]
    assert detect_kind(bad, "BENCH_proc.json") == "proc"
    with pytest.raises(SchemaError, match="argument"):
        validate(bad, "proc", "BENCH_proc.json")


def test_model_vs_measured_ratios_are_not_regression_compared(tmp_path):
    """Ratio-to-ideal metrics (perfect = 1.0) have no monotonic better
    direction — they stay out of the normalized metrics entirely."""
    art = _loop_artifact(20_000.0)
    art["summary"]["serving_h2d_bytes_model_vs_measured"] = 1.0
    entry = normalize(art, "loop", "BENCH_r01.json")
    assert "serving_h2d_bytes_model_vs_measured" not in entry["metrics"]


def test_new_writer_output_is_schema_valid(tmp_path):
    """tools/bench_schema.write_artifact output round-trips the gate."""
    from tools.bench_schema import SCHEMA_VERSION, write_artifact

    body = write_artifact(
        tmp_path / "BENCH_r09.json", ["python", "bench_loop.py"],
        {"metric": "bench_loop_summary", "pieces_per_sec": 1.0},
        results=[{"metric": "full_loop_pieces_per_sec", "value": 1.0}],
    )
    assert body["schema_version"] == SCHEMA_VERSION
    entries, errors = load_entries([tmp_path / "BENCH_r09.json"])
    assert errors == [] and entries[0]["kind"] == "loop"
    assert entries[0]["schema_version"] == SCHEMA_VERSION


def _seamed_loop_artifact(pieces_per_sec=25_000.0, tick_p50=9.0,
                          control_dispatch=6.0, seam="fused"):
    doc = _loop_artifact(pieces_per_sec, tick_p50=tick_p50)
    doc["summary"]["control_dispatch"] = control_dispatch
    doc["results"].append({"metric": "full_loop_tick_p50_ms",
                           "value": tick_p50, "phase_seam": seam})
    return doc


def test_seam_scoped_cells_never_compare_across_a_seam_change(tmp_path):
    """A phase-seam change (the fused tick moved fill/gather/score/top-k
    into one device program) redefines what a tick CONTAINS, so per-tick
    cells across the seam are "we moved rigs", not "same benchmark got
    worse" — tick_p50_ms re-enters the gate as fused_tick_p50_ms and
    the 7 -> 9 ms cross-seam delta anchors no verdict."""
    pre = _loop_artifact(20_000.0, tick_p50=7.0)
    pre["summary"]["control_dispatch"] = 6.7
    _write(tmp_path, "BENCH_r01.json", pre)
    _write(tmp_path, "BENCH_r02.json",
           _seamed_loop_artifact(25_000.0, tick_p50=9.0, control_dispatch=6.3))
    assert check(tmp_path, out=io.StringIO()) == 0


def test_control_dispatch_still_compares_across_the_seam(tmp_path):
    """control_dispatch keeps meaning "all host-side work per tick" by
    construction of the seam, so its longitudinal comparison survives
    the program-shape change — a real host-side regression under the
    fused seam still fails the gate."""
    pre = _loop_artifact(20_000.0, tick_p50=7.0)
    pre["summary"]["control_dispatch"] = 6.7
    _write(tmp_path, "BENCH_r01.json", pre)
    _write(tmp_path, "BENCH_r02.json",
           _seamed_loop_artifact(25_000.0, tick_p50=9.0, control_dispatch=9.5))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert "REGRESSION control_dispatch" in out.getvalue()


def test_seam_scoped_cells_compare_within_a_seam(tmp_path):
    """Two fused-seam rounds form a normal series: a >10% fused-tick
    regression between them fails the gate under the prefixed name."""
    _write(tmp_path, "BENCH_r01.json", _seamed_loop_artifact(tick_p50=9.0))
    _write(tmp_path, "BENCH_r02.json", _seamed_loop_artifact(tick_p50=13.0))
    out = io.StringIO()
    assert check(tmp_path, out=out) == 1
    assert "REGRESSION fused_tick_p50_ms" in out.getvalue()


def test_noise_floor_ignores_microsecond_jitter(tmp_path):
    """report_ingest 0.002 -> 0.003 ms is +50% relative but 1 us
    absolute — below the phase timer's noise floor, it anchors no
    verdict; a 5 ms absolute regression on the same family still does
    (test_lower_is_better_regression_direction)."""
    a = _loop_artifact(20_000.0, tick_p50=7.0)
    a["summary"]["report_ingest"] = 0.002
    b = _loop_artifact(20_000.0, tick_p50=7.0)
    b["summary"]["report_ingest"] = 0.003
    _write(tmp_path, "BENCH_r01.json", a)
    _write(tmp_path, "BENCH_r02.json", b)
    assert check(tmp_path, out=io.StringIO()) == 0
