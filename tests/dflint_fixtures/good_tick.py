"""dflint green fixture: the fused-tick idioms the passes must prove.

Bucketed fused dispatch (``_bucket_rows`` producer, ``_EVAL_BUCKETS``
warm iteration), fresh staging buffer per donated call, and the mirror's
attribute-rebind scatter idiom (the donated resident column is rebound
to the call's result in the same statement). All silent.
"""

from dragonfly2_tpu.cluster.scheduler import _EVAL_BUCKETS, _bucket_rows
from dragonfly2_tpu.ops import tick as tk


def warm_fused_buckets(state, cols, k, c, l, n, config):
    limit = config.scheduler.candidate_parent_limit  # config: fixed
    outs = []
    for bsz in _EVAL_BUCKETS:  # bucket-set iteration
        buf = tk.warm_inputs(bsz, k)  # fresh staging per donation
        outs.append(
            tk.fused_tick_chunk(buf, cols, bsz, k, c, l, n, limit=limit)
        )
    return outs


def dispatch_fused_chunk(samples, ind, task_row, child, bl0, ca0, cols,
                         s, e, k, c, l, n):
    bsz = _bucket_rows(e - s)  # bucket producer
    inbuf = tk.build_inbuf(
        bsz, samples[s:e], ind[s:e], task_row[s:e], child[s:e],
        bl0[s:e], ca0[s:e],
    )
    return tk.fused_tick_chunk(inbuf, cols, bsz, k, c, l, n)


def mirror_scatter_sync(mirror, idx, rows, nrows):
    nb = _bucket_rows(nrows)
    # donated resident column immediately rebound to the result: the
    # donated buffer is never read again (the TickMirror.sync idiom)
    mirror.peer_scalars = tk._scatter_rows(mirror.peer_scalars, idx, rows, nb)
    return mirror.peer_scalars
