"""Native model serving into the scheduler — the loop the reference never
closed.

The reference's intended flow (SURVEY.md §2.3): trainer trains -> manager
CreateModel -> operator activates -> scheduler's "ml" evaluator calls a
*Triton sidecar* ModelInfer (pkg/rpc/inference/client/client_v1.go:83-123)
— except the "ml" evaluator silently falls back to the rule blend
(evaluator.go:84-86) and nothing is wired. Here the whole loop is native:

- `ModelServer` watches the registry's active-version pointer and hot-swaps
  params into jit-compiled apply fns (no recompilation: same shapes).
- `MLEvaluator` = the "ml" algorithm: GraphSAGE embeddings cached per host
  slot, per-request candidate scoring is one device call, then the SAME
  filter rules as the rule-based path (ops/evaluator.select_with_scores).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
import time
import weakref
from collections import deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from dragonfly2_tpu.config.constants import CONSTANTS
from dragonfly2_tpu.models.graphsage import GraphSAGERanker
from dragonfly2_tpu.models.mlp import ProbeRTTRegressor
from dragonfly2_tpu.ops import evaluator as ev
from dragonfly2_tpu.ops.segment import gather_coo_subgraph
from dragonfly2_tpu.registry.registry import (
    MODEL_TYPE_ATTENTION,
    MODEL_TYPE_GNN,
    MODEL_TYPE_MLP,
    ModelRegistry,
)
from dragonfly2_tpu.utils import dferrors

logger = logging.getLogger(__name__)

_GRAPH_KEYS = ("node_feats", "edge_src", "edge_dst", "edge_feats")


def _graph_only(graph_arrays: dict) -> dict:
    """The four COO arrays the jitted embed programs consume. The
    scheduler's serving_graph_arrays also carries incremental-refresh
    sideband ('dirty_slots', 'full_sync') whose per-call shapes would
    retrace the jit if they ever rode along as pytree leaves."""
    return {k: graph_arrays[k] for k in _GRAPH_KEYS}


class ModelServer:
    """Serves the ACTIVE version of one registered model, reloading on
    activation flips — the native ModelInfer replacement."""

    def __init__(
        self,
        registry: ModelRegistry,
        name: str,
        scheduler_host_id: str,
        model_type: str,
        template_params: Any,
        model: Any = None,
    ):
        self.registry = registry
        self.name = name
        self.model_type = model_type
        self.model_id = registry.model_id(name, scheduler_host_id)
        self._template = template_params
        self.params: Any = None
        self.version: int | None = None
        if model is not None:
            self.model = model
        elif model_type == MODEL_TYPE_GNN:
            self.model = GraphSAGERanker()
        elif model_type == MODEL_TYPE_MLP:
            self.model = ProbeRTTRegressor()
        elif model_type == MODEL_TYPE_ATTENTION:
            from dragonfly2_tpu.models.attention import AttentionRanker

            self.model = AttentionRanker()
        else:
            raise ValueError(model_type)

    def refresh(self) -> bool:
        """Pick up a newly activated version; returns True if swapped. The
        version's metadata records its architecture (hidden_dim), so the
        served module always matches the trained one."""
        active = self.registry.active_version(self.model_id)
        if active is None or active.version == self.version:
            return False
        # Rebuild the module if the version's recorded architecture differs
        # from the served one — hidden_dim alone is not enough for families
        # with more knobs (AttentionRanker: num_heads/num_layers, whose
        # param shapes can even agree while computing different functions).
        arch = {
            key: active.metadata[key]
            for key in ("hidden_dim", "num_heads", "num_layers")
            if key in active.metadata and active.metadata[key] is not None
        }
        changed = {
            key: value
            for key, value in arch.items()
            if hasattr(self.model, key) and getattr(self.model, key) != value
        }
        new_model = self.model
        if changed:
            cls = type(self.model)
            # start from the currently-served knobs and overlay the new
            # metadata: a knob omitted from v_{n+1}'s metadata means
            # "unchanged", never "reset to class default"
            kwargs = {
                key: getattr(self.model, key)
                for key in ("hidden_dim", "num_heads", "num_layers")
                if hasattr(self.model, key)
            }
            kwargs.update({k: v for k, v in arch.items() if k in kwargs})
            new_model = cls(**kwargs)
        # Load BEFORE assigning anything: a failed params read must leave
        # the served (model, params, version) triple untouched — swapping
        # the module first and then raising would leave a mismatched pair
        # behind for callers that catch the error and keep serving.
        try:
            new_params = self.registry.load_params(
                self.model_id, active.version, template=self._template
            )
        except dferrors.DataLoss as e:
            # The version's bytes failed their integrity manifest: mark it
            # bad so the active pointer falls back to the newest GOOD
            # version (registry.mark_version_bad) — the next refresh then
            # serves last-good instead of retrying the corrupt blob
            # forever. The model-plane twin of the data plane's
            # fallback-past-torn-checkpoints.
            logger.error("refusing corrupt %s v%d: %s",
                         self.model_id, active.version, e)
            mark_bad = getattr(self.registry, "mark_version_bad", None)
            if mark_bad is not None:
                mark_bad(self.model_id, active.version, reason=str(e))
            return False
        # Commit to device ONCE here: load_params returns numpy leaves
        # (topology portability), and numpy params passed to every jitted
        # infer/schedule call would re-pay one host->device transfer PER
        # LEAF PER CALL — ~25 round-trips on the tunneled TPU, which
        # dominated the ml tick (~2 s/tick in a degraded window).
        self.model = new_model
        self.params = jax.device_put(new_params)
        self.version = active.version
        return True

    @property
    def ready(self) -> bool:
        return self.params is not None

    # ------------------------------------------------------------- infer

    def infer_mlp(self, x: jax.Array) -> jax.Array:
        """Predicted log1p(rtt_ms) for (N, F) pair features."""
        return mlp_apply(self.model, self.params, x)

    def embed_hosts(self, graph_arrays: dict) -> jax.Array:
        """(H, D) host embeddings for the current params."""
        return _gnn_embed(self.model, self.params, _graph_only(graph_arrays))

    def snapshot(self) -> tuple[Any, Any, int | None]:
        """(model, params, version) read together — callers that must not
        see a concurrent refresh() swap half-applied (the inference RPC)
        take this under their lock and run the pure apply fns on it."""
        return self.model, self.params, self.version

    def score_set(self, child_feats, parent_feats, pair_feats, mask) -> jax.Array:
        """(B, P) candidate scores from the set-transformer ranker
        (models/attention.py) — candidates attend to each other, no
        embedding cache needed."""
        return attention_score(
            self.model, self.params, child_feats, parent_feats, pair_feats, mask
        )


@functools.partial(jax.jit, static_argnames=("model",))
def mlp_apply(model, params, x):
    return model.apply(params, x)


@functools.partial(jax.jit, static_argnames=("model",))
def _gnn_embed(model, params, graph_arrays):
    return model.apply(
        params,
        graph_arrays["node_feats"],
        graph_arrays["edge_src"],
        graph_arrays["edge_dst"],
        graph_arrays["edge_feats"],
        method="embed",
    )


@functools.partial(jax.jit, static_argnames=("model",))
def attention_score(model, params, child_feats, parent_feats, pair_feats, mask):
    return model.apply(params, child_feats, parent_feats, pair_feats, mask)


@functools.partial(jax.jit, static_argnames=("model",))
def gnn_score(model, params, host_emb, child_host, cand_host, pair_feats):
    child_emb = host_emb[child_host]
    parent_emb = host_emb[cand_host]
    return model.apply(params, child_emb, parent_emb, pair_feats, method="score")


@dataclasses.dataclass(frozen=True)
class _EmbSnapshot:
    """One atomically-committed serving state: embeddings PLUS the exact
    (model, params) they were computed with. Serving reads the whole
    snapshot in one attribute load, so a params activation or an
    in-progress refresh can never pair a new scoring head with an old
    embedding table (the ModelServer.snapshot discipline, extended to
    the embedding cache)."""

    model: Any
    params: Any
    params_version: int | None
    host_emb: jax.Array
    emb_version: int


def _refresh_worker_main(eval_ref: "weakref.ref[MLEvaluator]",
                         wake: threading.Event, stop: threading.Event) -> None:
    """Background refresh loop. Holds the evaluator only through a
    weakref between drains — a strong reference in this closure would pin
    the evaluator (and its device arrays) forever and keep the finalizer
    from ever firing."""
    while True:
        wake.wait()
        if stop.is_set():
            return
        wake.clear()
        evaluator = eval_ref()
        if evaluator is None:
            return
        evaluator._drain_requests()
        del evaluator


def _signal_worker_stop(stop: threading.Event, wake: threading.Event) -> None:
    stop.set()
    wake.set()


# sentinel distinguishing "caller did not pin a snapshot" from "caller
# pinned None" in MLEvaluator.schedule_from_packed
_UNPINNED = object()


class MLEvaluator:
    """The "ml" scheduling algorithm, actually wired.

    Scores candidates with the served GraphSAGE ranker when a version is
    active; falls back to the rule blend otherwise (the reference's
    fallback, evaluator.go:76-90, except here the ml path exists).

    Embedding refresh runs OFF the serving critical path: refresh
    requests land in a latest-wins mailbox (dirty frontiers merged, never
    dropped) drained by a background worker thread; each refresh commits
    a version-stamped `_EmbSnapshot` double buffer that serving reads
    atomically. A full-graph recompute therefore never stalls a tick —
    BENCH_r05's ml arm spent 4.98 s of its 7.01 s wall blocked in
    exactly that recompute. When the scheduler's dirty frontier is small,
    the worker recomputes only the affected k-hop neighborhoods
    (`GraphSAGERanker.embed_subset`) and scatters into the committed
    table; params flips and structural graph changes fall back to the
    full recompute.
    """

    # keep at most this share of the graph on the incremental path; a
    # larger frontier recomputes everything (the gather wouldn't pay)
    INCREMENTAL_MAX_FRAC = 0.25
    # canary tolerance: the residual ensemble bounds per-row deviation at
    # ML_RESIDUAL_ALPHA * |z| * row_scale with |z| <= sqrt(K-1), so any
    # healthy version lands well inside this multiple of the rule
    # baseline's spread — exceeding it means numeric blowup, not opinion
    CANARY_SPREAD_MULT = 8.0

    def __init__(self, server: ModelServer, fallback_algorithm: str = "default",
                 metrics_registry=None):
        self.server = server
        self.fallback = fallback_algorithm
        # the ensemble's residual base: the same rule blend the fallback
        # path uses ("plugin" has no in-jit blend, so it bases on default)
        self._base_alg = (
            fallback_algorithm if fallback_algorithm in ("default", "nt")
            else "default"
        )
        self._committed: _EmbSnapshot | None = None
        # refresh mailbox: latest graph wins, dirty frontiers union
        self._req_mu = threading.Lock()
        self._request: dict | None = None
        self._compute_mu = threading.Lock()  # serializes commits in order
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker: threading.Thread | None = None
        self._need_full = False
        # stats the bench publishes: time callers spent BLOCKED inside
        # refresh_embeddings (the critical-path cost, ~0 once async) vs
        # compute time wherever it ran
        self.refresh_blocking_s = 0.0
        self.refresh_compute_s = 0.0
        self.refresh_count = 0
        self.incremental_refresh_count = 0
        # Guarded activation: every params version is gated (finite
        # leaves + canary scoring on a fixed probe batch) ON THE REFRESH
        # WORKER before it can become the committed snapshot — a rejected
        # version leaves serving on last-good and is marked bad in the
        # registry. gate_runs counts gate executions so tests can pin
        # that scheduling never pays for it.
        self._rejected_versions: set = set()
        self.gate_runs = 0
        self.rejection_count = 0
        from dragonfly2_tpu.telemetry import default_registry
        from dragonfly2_tpu.telemetry.series import serving_series

        self._metrics = serving_series(
            metrics_registry if metrics_registry is not None else default_registry()
        )
        # consistency audit trail for the refresh/serve race test: every
        # committed (params_version, emb_version) pair, and the pair the
        # last schedule call actually served from
        self.committed_versions: deque = deque(maxlen=256)
        self.last_used_versions: tuple | None = None
        # a GC'd evaluator must take its worker with it (the conftest
        # session guard fails the suite on ml-embed-refresh survivors)
        self._finalizer = weakref.finalize(
            self, _signal_worker_stop, self._stop, self._wake
        )

    # ---------------------------------------------------------- refresh

    @property
    def _host_emb(self):
        """Committed embedding table (None before the first refresh) —
        read-only compat surface; serving reads the full snapshot."""
        snap = self._committed
        return None if snap is None else snap.host_emb

    def serving_snapshot(self) -> _EmbSnapshot | None:
        """The currently committed snapshot, for callers that must pin
        ONE consistent (model, params, embeddings) across several
        schedule calls — the scheduler pins it once per tick so a
        background commit landing between two chunks of the same batch
        cannot score them against different embedding tables."""
        return self._committed

    def refresh_embeddings(self, graph_arrays: dict, wait: bool = False) -> None:
        """Recompute host-slot embeddings (call after topology/trace sync,
        and after server.refresh() swaps params).

        wait=False (the serving default) enqueues the graph for the
        background worker and returns immediately — ticks keep serving
        the previous committed snapshot until the new one lands.
        wait=True computes inline before returning: the deterministic
        path (paired A/B arms must not depend on worker timing) and the
        read-my-refresh path tests rely on.
        """
        t0 = time.perf_counter()
        try:
            if not self.server.ready:
                return
            self._merge_request(graph_arrays)
            if wait:
                self._drain_requests()
            else:
                self._ensure_worker()
                self._wake.set()
                if self._stop.is_set():
                    # closed evaluator: no worker will ever drain the
                    # mailbox — compute inline rather than silently
                    # strand the request (and the consumed dirty
                    # frontier serving_graph_arrays handed us)
                    self._drain_requests()
        finally:
            self.refresh_blocking_s += time.perf_counter() - t0

    def close(self, timeout: float = 5.0, drain: bool = False) -> None:
        """Stop the refresh worker (idempotent). With `drain`, any
        enqueued-but-unprocessed request is computed inline first so its
        work is not silently dropped; otherwise pending mailbox entries
        are discarded. The committed snapshot keeps serving either way."""
        if drain:
            self._drain_requests()
        _signal_worker_stop(self._stop, self._wake)
        # swap the worker OUT under _req_mu (dflint LOCK001), THEN join:
        # clearing after an unlocked read could null a newer worker a
        # racing _ensure_worker spawned between our read and the clear —
        # close() would return with that worker alive and unjoined. The
        # swap is atomic with the spawn check (_ensure_worker holds
        # _req_mu and sees _stop set), so whatever we swap out is the
        # only worker there will ever be.
        with self._req_mu:
            worker, self._worker = self._worker, None
        if worker is not None and worker is not threading.current_thread():
            worker.join(timeout)

    def _ensure_worker(self) -> None:
        # under _req_mu: an unsynchronized check-then-start would let two
        # concurrent wait=False refreshers spawn duplicate workers, and
        # close() would join only the last one
        with self._req_mu:
            worker = self._worker
            if worker is not None and worker.is_alive():
                return
            if self._stop.is_set():  # closed evaluators stay closed
                return
            worker = threading.Thread(
                target=_refresh_worker_main,
                args=(weakref.ref(self), self._wake, self._stop),
                name="ml-embed-refresh",
                daemon=True,
            )
            self._worker = worker
            worker.start()

    def _merge_request(self, graph_arrays: dict) -> None:
        with self._req_mu:
            prev = self._request
            req = dict(graph_arrays)
            # normalize BEFORE merging: a request without a frontier means
            # "unknown what changed" = full sync — that implicit full must
            # survive a merge with a frontier-carrying request
            if "full_sync" not in req:
                req["full_sync"] = "dirty_slots" not in req
            if prev is not None:
                # latest graph wins, but dirty frontiers UNION: dropping a
                # superseded request's frontier would leave its hosts
                # permanently stale in the incremental path
                pd = prev.get("dirty_slots")
                rd = req.get("dirty_slots")
                if pd is not None and rd is not None:
                    req["dirty_slots"] = np.union1d(pd, rd)
                req["full_sync"] = bool(
                    prev.get("full_sync", False) or req.get("full_sync", False)
                )
            self._request = req

    def _take_request(self) -> dict | None:
        with self._req_mu:
            req, self._request = self._request, None
            return req

    def _drain_requests(self) -> None:
        with self._compute_mu:
            while True:
                req = self._take_request()
                if req is None:
                    return
                try:
                    t0 = time.perf_counter()
                    self._perform_refresh(req)
                    self.refresh_compute_s += time.perf_counter() - t0
                except Exception:  # noqa: BLE001 - next refresh recovers
                    # the dropped request consumed a dirty frontier the
                    # table never absorbed — force the next refresh full
                    self._need_full = True
                    logger.exception("embedding refresh failed")

    def _perform_refresh(self, graph: dict) -> None:
        """Compute + commit one refresh (caller holds _compute_mu)."""
        model, params, version = self.server.snapshot()
        if params is None:
            return
        graph = dict(graph)
        dirty = graph.pop("dirty_slots", None)
        full_sync = bool(graph.pop("full_sync", True))
        committed = self._committed
        if version in self._rejected_versions:
            # previously rejected activation still on the server: keep
            # the embedding table tracking topology with LAST-GOOD params
            # (or stay on the rule fallback if nothing good ever landed)
            if committed is None:
                return
            model, params, version = (
                committed.model, committed.params, committed.params_version
            )
        n = graph["node_feats"].shape[0]
        emb = None
        incremental_ok = (
            not full_sync
            and not self._need_full
            and dirty is not None
            and committed is not None
            and committed.params_version == version
            and committed.host_emb.shape[0] == n
        )
        if incremental_ok and len(dirty) == 0:
            return  # nothing changed since the last sync; table is current
        if incremental_ok:
            sub = gather_coo_subgraph(
                graph["edge_src"], graph["edge_dst"], dirty,
                num_nodes=n,
                hops=getattr(model, "num_layers", 2),
                max_frac=self.INCREMENTAL_MAX_FRAC,
            )
            if sub is not None:
                edge_feats = np.asarray(graph["edge_feats"])[sub["edge_index"]]
                edge_feats = np.where(
                    sub["edge_pad"][:, None], 0.0, edge_feats
                ).astype(np.float32)
                node_feats = np.asarray(graph["node_feats"])[sub["nodes"]]
                emb = _gnn_embed_subset(
                    model, params, committed.host_emb,
                    node_feats, sub["edge_src"], sub["edge_dst"], edge_feats,
                    sub["target_local"], sub["target_global"],
                )
                self.incremental_refresh_count += 1
        if emb is None:
            emb = _gnn_embed(model, params, _graph_only(graph))
        # land the device work HERE, in the worker: committing an
        # in-flight array would make the next tick's device call inherit
        # the embed compute wait — the stall this refactor removes
        jax.block_until_ready(emb)
        if committed is None or committed.params_version != version:
            # GUARDED ACTIVATION (on this worker, never the tick path): a
            # new params version must pass finite-leaves + a canary
            # scoring pass before it can serve. A rejected version leaves
            # serving on the last-good snapshot, is marked bad in the
            # registry (so the active pointer falls back and the trainer's
            # next publish supersedes it), and never re-runs the gate.
            reason = self._activation_gate(model, params, emb)
            if reason is not None:
                self._reject_version(version, reason)
                if committed is None:
                    return  # no last-good: serving stays on the rule blend
                model, params, version = (
                    committed.model, committed.params, committed.params_version
                )
                emb = _gnn_embed(model, params, _graph_only(graph))
                jax.block_until_ready(emb)
            else:
                self._metrics.activation_accepted.labels().inc()
        snapshot = _EmbSnapshot(
            model=model,
            params=params,
            params_version=version,
            host_emb=emb,
            emb_version=(committed.emb_version + 1) if committed else 1,
        )
        self._committed = snapshot
        self.committed_versions.append(
            (snapshot.params_version, snapshot.emb_version)
        )
        self._need_full = False
        self.refresh_count += 1

    # ----------------------------------------------------- activation gate

    def _activation_gate(self, model, params, host_emb) -> str | None:
        """Decide whether a params version may serve; returns a rejection
        reason or None. Runs on the refresh worker (never a tick): checks
        every leaf and the computed embedding table for non-finite values,
        then scores a fixed deterministic probe batch and requires the ml
        ensemble's deviation from the rule baseline to stay within a sane
        multiple of the baseline's own spread — a NaN-poisoned, bit-
        rotted, or numerically exploding checkpoint fails here instead of
        activating into the serving snapshot."""
        self.gate_runs += 1
        for leaf in jax.tree_util.tree_leaves(params):
            if not bool(np.all(np.isfinite(np.asarray(leaf)))):
                return "nonfinite_params"
        emb = np.asarray(host_emb)
        if not bool(np.all(np.isfinite(emb))):
            return "nonfinite_embeddings"
        feats = _canary_probe()
        b, k = feats["valid"].shape
        n = emb.shape[0]
        child_host = np.arange(b, dtype=np.int32) % n
        cand_host = (np.arange(b * k, dtype=np.int32) % n).reshape(b, k)
        child_idc = feats["child_idc"][:, None]
        pair_feats = np.stack(
            [
                ((feats["parent_idc"] == child_idc) & (child_idc != 0)).astype(np.float32),
                np.asarray(_loc_match_fraction(
                    feats["parent_location"], feats["child_location"]
                )),
            ],
            axis=-1,
        )
        scores = np.asarray(_ensemble_scores(
            feats,
            gnn_score(model, params, host_emb, child_host, cand_host, pair_feats),
            self._base_alg,
        ))
        blend = np.asarray(ev.evaluate(feats, self._base_alg))
        valid = feats["valid"].astype(bool)
        if not bool(np.all(np.isfinite(scores[valid]))):
            return "nonfinite_scores"
        cnt = np.maximum(valid.sum(-1, keepdims=True), 1)
        mean = (blend * valid).sum(-1, keepdims=True) / cnt
        row_std = np.sqrt((((blend - mean) ** 2) * valid).sum(-1, keepdims=True) / cnt)
        scale = float(np.max(np.maximum(row_std, ML_RESIDUAL_STD_FLOOR)))
        deviation = float(np.max(np.abs(scores - blend) * valid))
        if deviation > self.CANARY_SPREAD_MULT * scale:
            return "score_spread"
        return None

    def _reject_version(self, version, reason: str) -> None:
        self.rejection_count += 1
        self._rejected_versions.add(version)
        self._metrics.activation_rejected.labels(reason).inc()
        logger.error(
            "activation gate rejected %s v%s (%s): serving stays on "
            "last-good", self.server.model_id, version, reason,
        )
        mark_bad = getattr(self.server.registry, "mark_version_bad", None)
        if mark_bad is not None and version is not None:
            try:
                # flags the version AND falls the registry's active
                # pointer back, so the server's next refresh() reloads
                # the last good version instead of the rejected one
                mark_bad(self.server.model_id, version, reason=reason)
            except Exception:  # noqa: BLE001 - gate must not kill refresh
                logger.exception("mark_version_bad failed for %s v%s",
                                 self.server.model_id, version)

    def schedule(
        self,
        feats: dict,
        child_host_slot: np.ndarray | None = None,
        cand_host_slot: np.ndarray | None = None,
        blocklist=None,
        in_degree=None,
        can_add_edge=None,
        limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
    ) -> dict:
        snap = self._committed  # one atomic read: model+params+emb agree
        if snap is not None and child_host_slot is not None:
            # ONE fused device call per chunk (pair features + embedding
            # gathers + scoring + masked selection). Dispatching these as
            # separate eager/jit calls cost 4 round trips per tick — over
            # a tunneled device that made the ml path ~10x slower than the
            # rule blend, which needs exactly one dispatch.
            self.last_used_versions = (snap.params_version, snap.emb_version)
            return _ml_schedule(
                snap.model,
                snap.params,
                snap.host_emb,
                child_host_slot,
                cand_host_slot,
                feats,
                blocklist,
                in_degree,
                can_add_edge,
                limit,
                algorithm=self._base_alg,
            )
        return ev.schedule_candidate_parents(
            feats, blocklist, in_degree, can_add_edge, algorithm=self.fallback, limit=limit
        )

    def schedule_packed(
        self,
        feats: dict,
        child_host_slot: np.ndarray | None = None,
        cand_host_slot: np.ndarray | None = None,
        blocklist=None,
        in_degree=None,
        can_add_edge=None,
        limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
    ):
        """Serving-path twin of `schedule`: one fused device call whose only
        output is the packed (B, limit, 2) selection (ops/evaluator.py
        `_pack_selection`) — one D2H per tick chunk."""
        snap = self._committed
        if snap is not None and child_host_slot is not None:
            self.last_used_versions = (snap.params_version, snap.emb_version)
            return _ml_schedule_packed(
                snap.model,
                snap.params,
                snap.host_emb,
                child_host_slot,
                cand_host_slot,
                feats,
                blocklist,
                in_degree,
                can_add_edge,
                limit,
                algorithm=self._base_alg,
            )
        return ev.schedule_candidate_parents_packed(
            feats, blocklist, in_degree, can_add_edge, algorithm=self.fallback, limit=limit
        )

    def schedule_from_packed(
        self, buf, b, k, c, l, n,
        limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
        snap: Any = _UNPINNED,
        record_used: bool = True,
    ):
        """Single-buffer-transport twin of `schedule_packed` (the tick's
        one-H2D contract; ops/evaluator.pack_eval_batch). Falls back to
        the linear blend over the same buffer until a snapshot commits.
        `snap` pins an explicit snapshot (serving_snapshot) for the whole
        call sequence — the scheduler passes one per tick so every chunk
        of a multi-chunk batch scores against the same committed table
        (pinning None pins the FALLBACK: a commit landing mid-tick must
        not flip later chunks onto the ml path either). `record_used=
        False` keeps `last_used_versions` untouched — the shadow-scoring
        path uses it, because a counterfactual re-score must not claim
        "this ml version SERVED" (last_used_versions is the refresh/serve
        race audit trail and the rule-blend-served sentinel)."""
        if snap is _UNPINNED:
            snap = self._committed
        if snap is not None:
            if record_used:
                self.last_used_versions = (
                    snap.params_version, snap.emb_version
                )
            return _ml_schedule_from_packed(
                snap.model, snap.params, snap.host_emb,
                buf, b, k, c, l, n, limit, algorithm=self._base_alg,
            )
        return ev.schedule_from_packed(
            buf, b, k, c, l, n, algorithm=self.fallback, limit=limit
        )


@functools.lru_cache(maxsize=1)
def _canary_probe() -> dict:
    """Fixed probe batch for the activation gate: one small deterministic
    synthetic cluster's download records replayed as scoring requests
    (the same records/synth + features pipeline the trainer and the
    evaluator differential tests use). Cached — the gate scores the SAME
    batch for every version, so rejections are reproducible and the
    per-gate cost is one tiny device call, not a data pipeline."""
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.features import downloads_to_eval_batch

    cluster = synth.make_cluster(16, seed=0)
    records = synth.gen_download_records(cluster, 8)
    return downloads_to_eval_batch(
        records, batch_tasks=8, batch_candidates=8
    ).as_dict()


@jax.jit
def _loc_match_fraction(parent_loc, child_loc):
    child = child_loc[:, None, :]
    elem_eq = (parent_loc == child) & (parent_loc != 0) & (child != 0)
    prefix = jnp.cumprod(elem_eq.astype(jnp.int32), axis=-1)
    return prefix.sum(-1).astype(jnp.float32) / CONSTANTS.MAX_LOCATION_ELEMENTS


# The served model REFINES the rule blend instead of replacing it: final
# score = blend + ALPHA * z(gnn) * max(std(blend_row), STD_FLOOR). The
# learned logits are z-scored within each candidate row (scale-free), then
# bounded by the row's own blend spread, so the model can reorder
# candidates the blend finds comparable but can never promote one the
# blend rules out — and a cold/weak model degrades to the blend, not to
# noise. (Full-scale A/B, BENCH r5 loop leg: the pure-model scorer landed
# between random and the blend; the residual form is how the learned
# signal adds to the engineered priors rather than competing with them.
# The reference never reached this question — its ml path is dead code,
# evaluator.go:84-86.)
ML_RESIDUAL_ALPHA = 0.5
ML_RESIDUAL_STD_FLOOR = 0.02


def _ensemble_scores(feats: dict, gnn_logits: jax.Array,
                     algorithm: str = "default") -> jax.Array:
    valid = feats["valid"].astype(jnp.float32)
    cnt = jnp.maximum(valid.sum(-1, keepdims=True), 1.0)

    def _masked_moments(x):
        mean = (x * valid).sum(-1, keepdims=True) / cnt
        var = (((x - mean) ** 2) * valid).sum(-1, keepdims=True) / cnt
        return mean, var

    # the residual base is the CONFIGURED rule blend (the evaluator's
    # fallback algorithm), not a hardcoded "default": an nt cluster must
    # keep its probe/RTT prior when the model comes online
    blend = ev.evaluate(feats, algorithm)
    g_mean, g_var = _masked_moments(gnn_logits)
    z = (gnn_logits - g_mean) * jax.lax.rsqrt(g_var + 1e-6)
    _, b_var = _masked_moments(blend)
    scale = jnp.maximum(jnp.sqrt(b_var), ML_RESIDUAL_STD_FLOOR)
    return blend + ML_RESIDUAL_ALPHA * z * scale


@functools.partial(jax.jit, static_argnames=("model", "limit", "algorithm"))
def _ml_schedule(
    model, params, host_emb, child_host, cand_host, feats,
    blocklist, in_degree, can_add_edge, limit, algorithm="default",
):
    """Fused ml-path schedule: everything from raw candidate features to
    the selected parents in one compiled program."""
    child_idc = feats["child_idc"][..., None]
    pair_feats = jnp.stack(
        [
            ((feats["parent_idc"] == child_idc) & (child_idc != 0)).astype(jnp.float32),
            _loc_match_fraction(feats["parent_location"], feats["child_location"]),
        ],
        axis=-1,
    )
    scores = _ensemble_scores(
        feats,
        gnn_score(model, params, host_emb, child_host, cand_host, pair_feats),
        algorithm,
    )
    return ev.select_with_scores(
        feats, scores, blocklist, in_degree, can_add_edge, limit=limit
    )


@functools.partial(jax.jit, static_argnames=("model", "limit", "algorithm"))
def _ml_schedule_packed(
    model, params, host_emb, child_host, cand_host, feats,
    blocklist, in_degree, can_add_edge, limit, algorithm="default",
):
    """`_ml_schedule` with the packed single-output selection contract."""
    child_idc = feats["child_idc"][..., None]
    pair_feats = jnp.stack(
        [
            ((feats["parent_idc"] == child_idc) & (child_idc != 0)).astype(jnp.float32),
            _loc_match_fraction(feats["parent_location"], feats["child_location"]),
        ],
        axis=-1,
    )
    scores = _ensemble_scores(
        feats,
        gnn_score(model, params, host_emb, child_host, cand_host, pair_feats),
        algorithm,
    )
    return ev.select_with_scores_packed(
        feats, scores, blocklist, in_degree, can_add_edge, limit=limit
    )


@functools.partial(
    jax.jit, static_argnames=("model", "b", "k", "c", "l", "n", "limit", "algorithm"),
    # like ev.schedule_from_packed: the packed H2D staging buffer is
    # consumed exactly once per chunk, so its device allocation is
    # donated; params and the embedding table stay live across calls
    donate_argnums=(3,),
)
def _ml_schedule_from_packed(model, params, host_emb, buf, b, k, c, l, n, limit,
                             algorithm="default"):
    """`_ml_schedule_packed` over the single-buffer transport
    (ops/evaluator.pack_eval_batch): the whole ml tick is one H2D + one
    dispatch + one D2H like the linear-blend path — only the (device-
    resident) embedding table and params stay out of the buffer."""
    f = ev.unpack_eval_batch(buf, b, k, c, l, n)
    child_idc = f["child_idc"][..., None]
    pair_feats = jnp.stack(
        [
            ((f["parent_idc"] == child_idc) & (child_idc != 0)).astype(jnp.float32),
            _loc_match_fraction(f["parent_location"], f["child_location"]),
        ],
        axis=-1,
    )
    scores = _ensemble_scores(f, gnn_score(
        model, params, host_emb, f["child_host_slot"], f["cand_host_slot"], pair_feats
    ), algorithm)
    return ev.select_with_scores_packed(
        f, scores, f["blocklist"], f["in_degree"], f["can_add_edge"], limit=limit
    )


@functools.partial(jax.jit, static_argnames=("model",))
def _gnn_embed_subset(model, params, table, node_feats, edge_src, edge_dst,
                      edge_feats, target_local, target_global):
    """Incremental refresh program: embed a gathered dirty-frontier
    subgraph (ops/segment.gather_coo_subgraph) and scatter the fresh rows
    into the device-resident table. `table` is NOT donated: the previous
    snapshot may be serving a concurrent tick while the worker computes
    — the scatter allocates the new table, the old one stays valid until
    the commit swaps the snapshot."""
    return model.apply(
        params, node_feats, edge_src, edge_dst, edge_feats,
        table, target_local, target_global,
        method="embed_subset",
    )


# Flight-recorder instrumentation (telemetry/flight.py) on the ml serving
# entry points: the fused ml tick call and the embedding refresh — the
# programs whose silent retraces used to be invisible until a 35 s compile
# landed mid-tick. The tick entry point is block=False so the pipelined
# tick's async dispatch survives (see ops/evaluator.py); the refresh
# programs keep the blocking dispatch/device split — they run on the
# background worker where blocking is free.
from dragonfly2_tpu.telemetry.flight import instrument_jit as _instrument_jit  # noqa: E402

_ml_schedule_from_packed = _instrument_jit(
    _ml_schedule_from_packed, "ml.schedule_from_packed", service="scheduler",
    block=False,
    # costcards=True: every SERVING_JIT_REGISTRY entry carries an XLA
    # cost card per compiled signature (telemetry/costcard.py); the
    # pending note stores avals only, so it cannot pin a params/table
    # snapshot, and the capture drains off the hot path
    costcards=True,
)
_gnn_embed = _instrument_jit(_gnn_embed, "ml.embed_hosts", service="scheduler",
                             costcards=True)
_gnn_embed_subset = _instrument_jit(
    _gnn_embed_subset, "ml.embed_subset", service="scheduler", costcards=True
)
