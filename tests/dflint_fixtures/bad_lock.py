"""dflint red fixture: LOCK001 must trip exactly once — `count` is
mutated under `_mu` in `locked_bump` and bare in `racy_bump`. `unshared`
is never guarded anywhere, so it must NOT trip (single-threaded idiom)."""

import threading


class Board:
    def __init__(self):
        self._mu = threading.Lock()
        self.count = 0
        self.unshared = 0

    def locked_bump(self):
        with self._mu:
            self.count += 1

    def racy_bump(self):
        self.count += 1  # <- the one expected LOCK001

    def single_threaded(self):
        self.unshared += 1
