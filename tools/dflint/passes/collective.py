"""COLL001/COLL002 — collective hygiene for meshed jits.

ROADMAP items 1 and 2 (pod-scale sharded serving, fused on-device tick)
will put ``psum``/``all_gather``/``ppermute`` collectives and
``shard_map`` bodies on the serving path. Two disciplines must hold
BEFORE that code lands, so it lands gated:

- ``COLL001`` — axis-name hygiene. Every collective's axis name must be
  declared in the ``MESH_AXES`` registry below (one source of truth,
  mirroring ``parallel/mesh.py``'s axis constants), and inside a
  ``shard_map`` body whose partition specs name a resolvable axis set,
  every collective must use axes from that set — a collective over an
  axis its own in/out specs never partition is either dead communication
  or a partition bug (the 2103.10515 communication model only prices
  declared axes).
- ``COLL002`` — D2H discipline inside meshed bodies. A host sync
  (``np.asarray`` / ``.item()`` / ``block_until_ready`` ...) inside a
  ``shard_map`` body re-serializes EVERY device in the mesh, not just
  one chip's dispatch queue; it rides the same justified
  ``D2H_ALLOWLIST`` as the jit-hygiene pass (argue it on, or waive
  inline with a reason).

Axis names resolve statically from, in order: string literals, the
known ``parallel/mesh.py`` axis constants (``DP_AXIS`` ...), same-file
module/function-level constant assignments, ``functools.partial``
bindings on the shard_map'd callable, and parameter defaults.
Unresolvable axes (a bare forwarded parameter) stay silent — the wrapper
that BINDS the axis is where the check lands, which every wrapper in
``parallel/`` does via partial or default.
"""

from __future__ import annotations

import ast

from tools.dflint.core import FileContext, Finding, attr_chain
from tools.dflint.passes.jit_hygiene import (
    D2H_ALLOWLIST,
    NUMPY_ROOTS,
    SYNC_ATTR_CALLS,
    SYNC_CALL_LEAVES,
)

# THE mesh-axis registry: every collective axis in the tree must be one
# of these (parallel/mesh.py axis constants; keep the two in sync — the
# fixture tests pin that an unregistered axis trips COLL001).
MESH_AXES: dict[str, str] = {
    "dp": "data parallelism — batch sharded, grads all-reduced over ICI",
    "graph": "graph parallelism — edge shards psum-combined (train.py)",
    "sp": "sequence/context parallelism (ring/ulysses attention)",
    "tp": "tensor parallelism — hidden dim sharded (parallel/tensor.py)",
    "pp": "pipeline parallelism — stage hops over ppermute",
    "ep": "expert parallelism — token/expert all_to_all (parallel/moe.py)",
}

# mirror of parallel/mesh.py's exported constants, so importing files
# resolve Name references without a cross-file import graph
KNOWN_AXIS_CONSTANTS: dict[str, str] = {
    "DP_AXIS": "dp", "GRAPH_AXIS": "graph", "SP_AXIS": "sp",
    "TP_AXIS": "tp", "PP_AXIS": "pp", "EP_AXIS": "ep",
}

# collective leaf -> positional index of the axis-name argument
COLLECTIVE_AXIS_ARG: dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "ppermute": 1, "pshuffle": 1, "all_to_all": 1,
    "axis_index": 0, "axis_size": 0,
}


class CollectivePass:
    name = "collective-hygiene"
    rules = ("COLL001", "COLL002")

    def __init__(
        self,
        mesh_axes: dict[str, str] | None = None,
        allowlist: dict[tuple[str, str, str], str] | None = None,
    ):
        self.mesh_axes = MESH_AXES if mesh_axes is None else mesh_axes
        self.allowlist = D2H_ALLOWLIST if allowlist is None else allowlist

    # ------------------------------------------------------------- run

    def run(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        resolver = _AxisResolver(ctx.tree)
        wrapped = collect_shard_map_bodies(ctx.tree)
        spec_axes = {id(func): axes for func, _, axes in wrapped}
        bindings = {id(func): b for func, b, _ in wrapped}
        body_ids = set(spec_axes)
        # 1) registry check on every collective in the file
        for func, symbol, ancestors in _functions_with_symbols(ctx.tree):
            # a nested closure resolves through its enclosing functions'
            # params/partial-bindings too (ring/ulysses body closures)
            scope_chain = [func, *ancestors]
            for node in _walk_own(func):
                if not isinstance(node, ast.Call):
                    continue
                leaf, axis_node = _collective_axis(node)
                if leaf is None:
                    continue
                axes = None
                for scope in scope_chain:
                    axes = resolver.resolve(
                        axis_node, scope, bindings.get(id(scope), {})
                    )
                    if axes is not None:
                        break
                if axes is None:
                    continue  # forwarded param without a binding: silent
                for axis in axes:
                    if axis not in self.mesh_axes:
                        findings.append(ctx.make_finding(
                            "COLL001", node,
                            (
                                f"collective '{leaf}' over axis "
                                f"'{axis}' not declared in MESH_AXES — "
                                f"register the mesh axis (tools/dflint/"
                                f"passes/collective.py) or fix the name"
                            ),
                            symbol=symbol, def_line=func.lineno,
                        ))
                    elif _spec_violation(
                        axis, scope_chain, body_ids, spec_axes
                    ):
                        findings.append(ctx.make_finding(
                            "COLL001", node,
                            (
                                f"collective '{leaf}' over axis "
                                f"'{axis}' inconsistent with the "
                                f"enclosing shard_map's partition specs "
                                f"({sorted(_declared_axes(scope_chain, body_ids, spec_axes))}) "
                                f"— the body communicates over an axis "
                                f"its specs never partition"
                            ),
                            symbol=symbol, def_line=func.lineno,
                        ))
        # 2) D2H discipline inside shard_map bodies
        for func, _bindings, _axes in wrapped:
            findings.extend(self._check_body_syncs(ctx, func))
        return findings

    def _check_body_syncs(self, ctx, func) -> list[Finding]:
        findings = []
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            leaf, root = _leaf_root(node)
            is_sync = (
                (leaf in SYNC_CALL_LEAVES and root in NUMPY_ROOTS | {"jax"})
                or (leaf in SYNC_ATTR_CALLS
                    and isinstance(node.func, ast.Attribute))
            )
            if not is_sync:
                continue
            key = None
            for (suffix, fname, sleaf), _reason in self.allowlist.items():
                if ctx.rel.endswith(suffix) and fname == func.name \
                        and sleaf == leaf:
                    key = (suffix, fname, sleaf)
                    break
            if key is not None:
                continue
            findings.append(ctx.make_finding(
                "COLL002", node,
                (
                    f"host sync '{leaf}' inside shard_map body "
                    f"'{func.name}' stalls every device in the mesh — "
                    f"argue it onto D2H_ALLOWLIST "
                    f"(tools/dflint/passes/jit_hygiene.py) or waive "
                    f"inline"
                ),
                symbol=func.name, def_line=func.lineno,
            ))
        return findings


# -------------------------------------------------- shard_map detection


def collect_shard_map_bodies(tree) -> list[tuple[ast.AST, dict, set[str]]]:
    """(funcdef, partial kwarg bindings, axes named by in/out specs) for
    every function the file wraps in ``shard_map``. Shared with the
    jit-hygiene pass, which applies its tracer checks to these bodies."""
    by_name: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
    resolver = _AxisResolver(tree)
    out = []
    seen: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain is None or chain.rsplit(".", 1)[-1] != "shard_map":
            continue
        if not node.args:
            continue
        target, bindings = _unwrap_partial(node.args[0])
        if not isinstance(target, ast.Name):
            continue
        func = by_name.get(target.id)
        if func is None or id(func) in seen:
            continue
        seen.add(id(func))
        axes: set[str] = set()
        for kw in node.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                axes |= _spec_axes(kw.value, resolver)
        for pos_arg in node.args[2:4]:  # positional in_specs/out_specs
            axes |= _spec_axes(pos_arg, resolver)
        out.append((func, bindings, axes))
    return out


def _unwrap_partial(node: ast.AST) -> tuple[ast.AST, dict]:
    """``partial(f, x=1)`` -> (Name f, {'x': <node 1>}); plain names pass
    through with no bindings."""
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        if chain in ("functools.partial", "partial") and node.args:
            bindings = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            return node.args[0], bindings
    return node, {}


def _spec_axes(node: ast.AST, resolver: "_AxisResolver") -> set[str]:
    """Axis names inside P(...) partition-spec expressions (literal or
    resolvable through local/module constants)."""
    axes: set[str] = set()
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        chain = attr_chain(call.func)
        if chain is None or chain.rsplit(".", 1)[-1] not in ("P", "PartitionSpec"):
            continue
        for arg in call.args:
            resolved = resolver.resolve(arg, None)
            if resolved:
                axes |= resolved
    # a Name that is itself a spec variable (edge_spec = P(...)) resolves
    # through the constant table when the resolver knows its P(...) value
    if isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Name):
                axes |= resolver.spec_var_axes(elt.id)
    elif isinstance(node, ast.Name):
        axes |= resolver.spec_var_axes(node.id)
    return axes


# ------------------------------------------------------ axis resolution


class _AxisResolver:
    """Static axis-name resolution over one file: module + function-local
    constant assignments, known mesh constants, parameter defaults."""

    def __init__(self, tree):
        self.tree = tree
        self.assigns: dict[str, ast.AST] = {}
        # recursion guard: mutually-referential assignments (A = (B,),
        # B = (A,)) must degrade to unresolvable, not RecursionError
        self._stack: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                # last assignment wins; good enough for constant tables
                self.assigns[node.targets[0].id] = node.value

    def resolve(
        self, node: ast.AST | None, func, bindings: dict | None = None
    ) -> set[str] | None:
        """Set of axis names, or None when unresolvable. `func` supplies
        parameter defaults (and may be None for spec contexts);
        `bindings` are functools.partial keyword bindings on the wrapped
        callable, which override defaults."""
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            if isinstance(node.value, str):
                return {node.value}
            return set() if node.value is None else None
        if isinstance(node, (ast.Tuple, ast.List)):
            out: set[str] = set()
            for elt in node.elts:
                resolved = self.resolve(elt, func, bindings)
                if resolved is None:
                    return None
                out |= resolved
            return out
        if isinstance(node, ast.Name):
            if node.id in KNOWN_AXIS_CONSTANTS:
                return {KNOWN_AXIS_CONSTANTS[node.id]}
            if node.id in self._stack:
                return None  # assignment cycle: unresolvable
            if bindings and node.id in bindings:
                return self.resolve(bindings[node.id], None)
            default = _param_default(func, node.id) if func is not None else None
            if default is not None:
                return self.resolve(default, None)
            value = self.assigns.get(node.id)
            if value is not None and not isinstance(value, ast.Name):
                self._stack.add(node.id)
                try:
                    return self.resolve(value, func, bindings)
                finally:
                    self._stack.discard(node.id)
            return None
        if isinstance(node, ast.Attribute):
            leaf = node.attr
            if leaf in KNOWN_AXIS_CONSTANTS:
                return {KNOWN_AXIS_CONSTANTS[leaf]}
            return None
        return None

    def spec_var_axes(self, name: str) -> set[str]:
        """Axes of a variable assigned a P(...) spec expression."""
        value = self.assigns.get(name)
        if value is None:
            return set()
        return _spec_axes(value, self)


def _param_default(func, name: str) -> ast.AST | None:
    if func is None:
        return None
    args = func.args
    positional = args.posonlyargs + args.args
    defaults = args.defaults
    offset = len(positional) - len(defaults)
    for i, a in enumerate(positional):
        if a.arg == name and i >= offset:
            return defaults[i - offset]
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if a.arg == name and d is not None:
            return d
    return None


# -------------------------------------------------------------- helpers


def _collective_axis(node: ast.Call) -> tuple[str | None, ast.AST | None]:
    chain = attr_chain(node.func)
    if chain is None:
        return None, None
    leaf = chain.rsplit(".", 1)[-1]
    if leaf not in COLLECTIVE_AXIS_ARG:
        return None, None
    # collectives must be QUALIFIED through jax.lax / lax — a bare name
    # would alias user helpers called `psum`; the precision-over-recall
    # tradeoff (a `from jax.lax import psum` import style goes unchecked)
    # matches the rest of dflint, and the tree only uses jax.lax.*
    parts = chain.split(".")
    if len(parts) < 2 or parts[-2] not in ("lax", "jax"):
        return None, None
    pos = COLLECTIVE_AXIS_ARG[leaf]
    axis_node = None
    if pos < len(node.args):
        axis_node = node.args[pos]
    else:
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis_node = kw.value
    return leaf, axis_node


def _leaf_root(node: ast.Call) -> tuple[str | None, str | None]:
    chain = attr_chain(node.func)
    if chain is None:
        if isinstance(node.func, ast.Attribute):
            return node.func.attr, None
        return None, None
    parts = chain.split(".")
    return parts[-1], parts[0] if len(parts) > 1 else None


def _declared_axes(scope_chain, body_ids, spec_axes) -> set[str]:
    """Partition-spec axes of the innermost shard_map body on the scope
    chain (empty set when none resolves)."""
    for scope in scope_chain:
        if id(scope) in body_ids and spec_axes[id(scope)]:
            return spec_axes[id(scope)]
    return set()


def _spec_violation(axis, scope_chain, body_ids, spec_axes) -> bool:
    declared = _declared_axes(scope_chain, body_ids, spec_axes)
    return bool(declared) and axis not in declared


def _functions_with_symbols(tree):
    """Every funcdef in the file (module, method, nested) with a dotted
    symbol and its enclosing-function chain (innermost first); callers
    pair this with `_walk_own` so each node is scanned under exactly one
    function."""
    def visit(node, prefix, ancestors):
        for stmt in ast.iter_child_nodes(node):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                symbol = f"{prefix}{stmt.name}"
                yield stmt, symbol, ancestors
                yield from visit(stmt, f"{symbol}.", [stmt, *ancestors])
            elif isinstance(stmt, ast.ClassDef):
                yield from visit(stmt, f"{prefix}{stmt.name}.", ancestors)
            else:
                yield from visit(stmt, prefix, ancestors)

    yield from visit(tree, "", [])


def _walk_own(func):
    """Walk a function's subtree, pruning nested function bodies (they
    are visited as their own functions)."""
    stack = [iter(ast.iter_child_nodes(func))]
    while stack:
        try:
            node = next(stack[-1])
        except StopIteration:
            stack.pop()
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.append(iter(ast.iter_child_nodes(node)))
