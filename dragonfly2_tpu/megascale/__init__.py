"""Megascale scenario lab: vectorized event-batch simulation at
10^5–10^6 hosts.

The per-peer ``cluster/simulator.ClusterSimulator`` — retained unchanged
as the decision-equivalence oracle — advances one piece per Python loop
iteration; this package advances ALL in-flight downloads one event batch
per round as numpy ops over columnar peer state, feeding the scheduler's
bulk APIs (``pieces_finished_batch``, ``register_peers_batch``,
``leave_hosts_batch``):

- ``engine``:   ``EventBatchEngine`` (the oracle's vectorized twin) +
                ``megascale_service`` (a scheduler sized for the scale);
- ``topology``: region/WAN host populations, the vectorized
                counter-hashed uniform sampler, and ``WanCostModel``
                (parameterized RTT/bandwidth tiers per topology relation
                — the analytic model of arXiv 2103.10515);
- ``soak``:     the compressed 24h-in-production run (every fault family
                at once) behind the ``soak`` scenario builtin;
- ``fleet``:    ``SchedulerFleet`` (K task-sharded scheduler replicas
                behind one consistent hashring, cross-scheduler peer
                handoff on ring rebalance) + ``FleetEventBatchEngine``
                (the fleet-routed engine) behind the ``fleet`` builtin.

``bench_megascale.py`` is the CLI; ``BENCH_mega.json`` the artifact.
"""

from dragonfly2_tpu.megascale.engine import (  # noqa: F401
    EventBatchEngine,
    MegaStats,
    megascale_service,
)
from dragonfly2_tpu.megascale.topology import (  # noqa: F401
    WanCostModel,
    hash_u01,
    make_region_cluster,
)
from dragonfly2_tpu.megascale.soak import run_megascale  # noqa: F401
from dragonfly2_tpu.megascale.fleet import (  # noqa: F401
    FleetEventBatchEngine,
    SchedulerFleet,
    megascale_fleet,
)
