"""Train-state checkpointing (orbax) — the capability the reference lacks
entirely (SURVEY.md §5: "no ML checkpointing (no training)"), layered the
way its data plane does resume: restartable state on disk + versioned
artifacts in the registry (registry/).
"""

from __future__ import annotations

import logging
import pathlib
import shutil
from typing import Any

import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


class TrainCheckpointer:
    """Step-indexed checkpoints of {params, opt_state, step, metadata}."""

    def __init__(self, directory: str | pathlib.Path, max_to_keep: int = 3):
        self.directory = pathlib.Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )
        self._closed = False

    def save(self, step: int, state: Any) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        self._mngr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    def restore(self, step: int | None = None, template: Any = None) -> Any:
        """Restore `step`, or — with no step given — the NEWEST checkpoint
        that actually loads. A save interrupted mid-write (trainer crash,
        SIGKILL between array files and the commit) can leave a step
        directory that lists but does not restore; falling back to the
        previous intact step is what makes `save` crash-safe end to end,
        mirroring how the data plane reloads only verified pieces. An
        EXPLICIT step still raises on corruption — the caller asked for
        that exact state, and silently handing back an older one would
        corrupt whatever invariant they were restoring under."""
        if step is not None:
            if template is not None:
                return self._mngr.restore(step, args=ocp.args.StandardRestore(template))
            return self._mngr.restore(step)
        last_err: Exception | None = None
        for candidate in sorted(self._mngr.all_steps(), reverse=True):
            try:
                return self.restore(candidate, template=template)
            except Exception as e:  # noqa: BLE001 - torn checkpoint, try older
                last_err = e
                logger.warning(
                    "checkpoint step %d failed to restore (%s); "
                    "falling back to the previous step", candidate, e,
                )
        if last_err is not None:
            # checkpoints EXIST but none restores: that is a systematic
            # problem (template/pytree mismatch, format skew), not a torn
            # write — swallowing it into a None 'no checkpoint' would
            # silently restart an expensive run from step 0
            raise last_err
        return None  # genuinely nothing saved yet

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._mngr.close()

    def clear(self) -> None:
        """Completed-run cleanup: close the manager and delete the saved
        state, so the NEXT training run starts from scratch instead of
        'resuming' past its final epoch and publishing stale params."""
        self.close()
        shutil.rmtree(self.directory, ignore_errors=True)


def params_to_bytes(params: Any) -> bytes:
    """Serialize a params pytree for the wire (the CreateModel stream,
    manager_server_v1.go:802-952 — the reference ships model.graphdef
    bytes; here it is msgpack'd arrays)."""
    from flax import serialization

    return serialization.msgpack_serialize(params)


def params_from_bytes(blob: bytes) -> Any:
    from flax import serialization

    return serialization.msgpack_restore(blob)
