"""Training-loop tests: convergence on planted signal, DP sharding
equivalence, checkpoint roundtrip (SURVEY.md §4 numeric tier)."""

import jax
import numpy as np
import pytest

from dragonfly2_tpu.config.config import TrainerConfig
from dragonfly2_tpu.models import GraphSAGERanker
from dragonfly2_tpu.parallel import make_mesh
from dragonfly2_tpu.records import synth
from dragonfly2_tpu.records.features import (
    downloads_to_ranking_dataset,
    topology_to_pairs,
)
from dragonfly2_tpu.training import (
    TrainCheckpointer,
    embed_graph_sharded,
    train_gnn,
    train_mlp,
)
from dragonfly2_tpu.training.train import train_attention
from dragonfly2_tpu.training.data import edge_bucket, graph_arrays


@pytest.fixture(scope="module")
def cluster():
    return synth.make_cluster(80, seed=11)


@pytest.fixture(scope="module")
def mlp_data(cluster):
    topos = synth.gen_network_topology_records(cluster, 300)
    return topology_to_pairs(topos)


@pytest.fixture(scope="module")
def rank_data(cluster):
    records = synth.gen_download_records(cluster, 200, num_tasks=16)
    return downloads_to_ranking_dataset(records)


def test_mlp_learns_rtt_structure(mlp_data):
    x, y = mlp_data
    cfg = TrainerConfig(epochs=8, batch_size=64, hidden_dim=32, learning_rate=3e-3)
    res = train_mlp(x, y, cfg, seed=0)
    assert res.losses[-1] < res.losses[0] * 0.5
    # better than predicting the mean (variance baseline)
    assert res.eval_metrics["mse"] < float(np.var(y)) * 0.7
    assert res.samples_per_sec > 0


def test_every_family_reports_an_analytic_flop_floor(mlp_data, rank_data):
    """All three trainers carry a positive matmul-only FLOP floor so
    flops_basis (the ONE MFU policy) never falls back to 'none' or to an
    invalid cost_analysis value on a backend that misreports."""
    from dragonfly2_tpu.training.train import flops_basis

    x, y = mlp_data
    ds, graph = rank_data
    cfg = TrainerConfig(epochs=1, batch_size=64, hidden_dim=32)
    for res in (
        train_mlp(x, y, cfg, seed=0),
        train_gnn(ds, graph, cfg, seed=0),
        train_attention(ds, cfg, seed=0),
    ):
        assert res.analytic_flops_per_sample > 0
        src, flops = flops_basis(res)
        # with a positive floor the basis IS the floor, always
        assert src.startswith("analytic_matmul_floor"), src
        assert flops == res.analytic_flops_per_sample


def test_mlp_dp_sharded_matches_semantics(mlp_data):
    x, y = mlp_data
    cfg = TrainerConfig(epochs=2, batch_size=64, hidden_dim=32)
    mesh = make_mesh(8)
    res = train_mlp(x, y, cfg, mesh=mesh, seed=0)
    assert np.isfinite(res.losses).all()
    assert res.losses[-1] < res.losses[0]


def test_gnn_learns_to_rank(rank_data):
    ds, graph = rank_data
    cfg = TrainerConfig(epochs=6, batch_size=64, hidden_dim=32, learning_rate=3e-3)
    res = train_gnn(ds, graph, cfg, seed=0)
    assert res.losses[-1] < res.losses[0]
    # top-1 picks should beat random (1/valid-candidates ~ 0.25 relevance rate)
    assert res.eval_metrics["precision"] > 0.3


def test_gnn_sharded_embed_matches_replicated(rank_data):
    ds, graph = rank_data
    cfg = TrainerConfig(epochs=1, batch_size=32, hidden_dim=32)
    mesh = make_mesh(8, graph=2)
    res = train_gnn(ds, graph, cfg, mesh=mesh, seed=0)
    model = GraphSAGERanker(hidden_dim=32)
    ga = graph_arrays(graph, pad_edges_to=edge_bucket(graph.edge_src.shape[0], 512))
    ref = model.apply(
        res.params, ga["node_feats"], ga["edge_src"], ga["edge_dst"], ga["edge_feats"],
        method="embed",
    )
    sharded = embed_graph_sharded(model, res.params, ga, mesh)
    np.testing.assert_allclose(
        np.asarray(ref, np.float32), np.asarray(sharded, np.float32), rtol=2e-2, atol=2e-2
    )


def test_checkpoint_roundtrip(tmp_path, mlp_data):
    x, y = mlp_data
    cfg = TrainerConfig(epochs=1, batch_size=64, hidden_dim=16)
    res = train_mlp(x, y, cfg, seed=0)
    ckpt = TrainCheckpointer(tmp_path / "ckpt")
    state = {"params": res.params, "step": res.steps}
    ckpt.save(res.steps, state)
    assert ckpt.latest_step() == res.steps
    restored = ckpt.restore(template=state)
    leaves_a = [np.asarray(v) for v in __import__("jax").tree_util.tree_leaves(res.params)]
    leaves_b = [np.asarray(v) for v in __import__("jax").tree_util.tree_leaves(restored["params"])]
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(a, b)
    ckpt.close()


def test_checkpoint_restore_survives_interrupted_save(tmp_path):
    """Crash-safety satellite: a save interrupted mid-write (SIGKILL
    between the array files and the commit) leaves a torn step directory.
    restore() must fall back to the previous INTACT step instead of
    loading — or dying on — the half-written one; asking for the torn
    step EXPLICITLY still raises."""
    import pathlib

    import numpy as np

    ckpt = TrainCheckpointer(tmp_path / "ckpt", max_to_keep=5)
    state1 = {"params": {"w": np.arange(8, dtype=np.float32)}, "step": 1}
    state2 = {"params": {"w": np.arange(8, dtype=np.float32) * 2}, "step": 2}
    ckpt.save(1, state1)
    ckpt.save(2, state2)
    assert ckpt.latest_step() == 2

    # simulate the interrupt: gut step 2's payload files, keeping the
    # directory so the manager still lists the step
    step_dir = pathlib.Path(tmp_path / "ckpt" / "2")
    assert step_dir.is_dir()
    for path in sorted(step_dir.rglob("*"), reverse=True):
        if path.is_file():
            path.write_bytes(b"")  # torn write: zero-length payloads

    restored = ckpt.restore(template=state1)
    assert restored is not None, "restore() found no intact checkpoint"
    assert int(restored["step"]) == 1, (
        f"restore() returned step {restored['step']} from a torn checkpoint"
    )
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]), state1["params"]["w"]
    )
    with pytest.raises(Exception):
        ckpt.restore(step=2, template=state1)  # explicit step stays loud
    ckpt.close()


def test_train_resumes_from_checkpoint(tmp_path, mlp_data):
    """Kill-and-restart resume: a second train call with the same
    checkpointer picks up at the next epoch instead of restarting, and a
    fully-trained checkpoint yields no further epochs."""
    from dragonfly2_tpu.training.checkpoint import TrainCheckpointer

    x, y = mlp_data
    cfg = TrainerConfig(epochs=2, batch_size=64, hidden_dim=16, learning_rate=3e-3)

    ck = TrainCheckpointer(tmp_path / "ck")
    first = train_mlp(x, y, cfg, seed=0, checkpointer=ck)
    assert ck.latest_step() == 1  # saved after epochs 0 and 1
    steps_per_epoch = first.steps // 2

    # "crash" after epoch 1 of a 4-epoch run: resume trains only 2 more
    cfg4 = TrainerConfig(epochs=4, batch_size=64, hidden_dim=16, learning_rate=3e-3)
    resumed = train_mlp(x, y, cfg4, seed=0, checkpointer=ck)
    assert resumed.steps == 2 * steps_per_epoch
    assert ck.latest_step() == 3

    # already complete: nothing to train, params come from the checkpoint
    again = train_mlp(x, y, cfg4, seed=0, checkpointer=ck)
    assert again.steps == 0
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(again.params)[0]),
        np.asarray(jax.tree_util.tree_leaves(resumed.params)[0]),
    )


def test_train_attention_ulysses_strategy(rank_data):
    """sp_strategy='ulysses' swaps ring for all-to-all attention in the
    trainer; loss must stay finite on a dp x sp mesh."""
    ds, _ = rank_data
    mesh = make_mesh(8, dp=4, sp=2)
    cfg = TrainerConfig(epochs=1, batch_size=16, hidden_dim=32)
    res = train_attention(ds, cfg, mesh=mesh, seed=0, sp_strategy="ulysses")
    assert res.steps > 0 and np.isfinite(res.losses).all()
    with pytest.raises(ValueError):
        train_attention(ds, cfg, mesh=mesh, sp_strategy="bogus")


def test_trainer_service_checkpoint_lifecycle(tmp_path):
    """checkpoint_dir set -> checkpoints are written during training but
    CLEARED on success, so a later train_finish on fresh traces trains
    from scratch instead of "resuming" past its final epoch and
    republishing stale params with zero steps."""
    from dragonfly2_tpu.cluster.trainer_service import GNN_MODEL_NAME, TrainerService
    from dragonfly2_tpu.records.storage import HostTraceStorage, TraceStorage
    from dragonfly2_tpu.registry import ModelRegistry

    cluster = synth.make_cluster(16, seed=3)
    records = synth.gen_download_records(cluster, 60, num_tasks=4)
    store = TraceStorage(tmp_path / "traces")
    for r in records:
        store.create_download(r)

    svc = TrainerService(
        HostTraceStorage(tmp_path / "trainer"),
        ModelRegistry(tmp_path / "registry"),
        TrainerConfig(
            epochs=2, batch_size=16, hidden_dim=16,
            checkpoint_dir=str(tmp_path / "ck"),
        ),
    )
    svc.train_mlp_chunk("h1", store.open_download())
    outcome = svc.train_finish("h1")
    assert outcome.gnn is not None and outcome.gnn_result.steps > 0
    # success cleared the checkpoint state
    assert not (tmp_path / "ck" / GNN_MODEL_NAME).exists()

    # a second upload cycle must actually train on the new data
    svc.train_mlp_chunk("h1", store.open_download())
    outcome2 = svc.train_finish("h1")
    assert outcome2.gnn is not None and outcome2.gnn_result.steps > 0
    assert outcome2.gnn.version == outcome.gnn.version + 1


def test_gnn_roofline_bound_structure():
    """The MFU bound analysis the bench artifact publishes (gnn_bound):
    internally consistent roofline — the thin-feature layer-0 adjacency
    matmul and the embedding gathers are memory-bound, the ceiling is a
    real bound (< 100%), and a pure-bandwidth stage can never be labeled
    compute-bound."""
    from dragonfly2_tpu.training.train import gnn_roofline_bound

    b = gnn_roofline_bound(
        n_nodes=10_000, node_feat_dim=12, edge_feat_dim=2,
        hidden=256, batch=4096, parents=20, pair_feat_dim=2,
    )
    assert 0 < b["mfu_ceiling_pct"] < 100
    assert abs(b["ridge_flops_per_byte"] - 197e12 / 819e9) < 1
    by_name = {s["stage"]: s for s in b["stages"]}
    # AI = 2*F flops per adjacency byte with F=12 -> deeply memory-bound
    assert by_name["sage_0.adj_matmul"]["bound"] == "memory"
    assert by_name["sage_0.adj_matmul"]["ai_flops_per_byte"] < 30
    assert by_name["emb_gather"]["bound"] == "memory"
    assert by_name["emb_gather"]["gflops"] == 0.0
    # every stage's time bound respects its own flops and bytes
    for s in b["stages"]:
        t_flops = s["gflops"] * 1e9 / 197e12 * 1e6
        t_bytes = s["mbytes"] * 1e6 / 819e9 * 1e6
        assert s["time_us_lb"] >= max(t_flops, t_bytes) - 0.1
    # the segment-sum (serving) path is pure bandwidth: zero-flop stages
    seg = gnn_roofline_bound(
        n_nodes=10_000, node_feat_dim=12, edge_feat_dim=2,
        hidden=256, batch=4096, parents=20, pair_feat_dim=2, dense_adj=False,
    )
    seg_stages = {s["stage"]: s for s in seg["stages"]}
    assert seg_stages["sage_0.segment_sum"]["gflops"] == 0.0
    assert seg_stages["sage_0.segment_sum"]["bound"] == "memory"
    assert seg["mfu_ceiling_pct"] < 100
    assert "statement" in b and "memory-bound" in b["statement"]
