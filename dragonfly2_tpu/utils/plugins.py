"""Plugin loader for evaluator / searcher / source overrides.

Capability parity with internal/dfplugin/dfplugin.go:43-81, which
plugin.Open()s `d7y-<type>-plugin-<name>.so` from the plugin dir and pulls
a `DragonflyPluginInit` symbol. Python equivalent: import
`df_<type>_plugin_<name>.py` from the plugin dir (or any importable module
path) and call its `dragonfly_plugin_init(options) -> object`. Same
attribute contract, no .so machinery.
"""

from __future__ import annotations

import importlib
import importlib.util
import pathlib
import sys
from typing import Any

PLUGIN_INIT = "dragonfly_plugin_init"

# Mirrors dfplugin's PluginType enum (resource/scheduler/manager).
PLUGIN_TYPES = ("evaluator", "searcher", "source", "resource")


def plugin_module_name(plugin_type: str, name: str) -> str:
    if plugin_type not in PLUGIN_TYPES:
        raise ValueError(f"unknown plugin type {plugin_type!r}")
    return f"df_{plugin_type}_plugin_{name}"


def load(plugin_dir: str | pathlib.Path, plugin_type: str, name: str, options: dict | None = None) -> Any:
    """Load a plugin from `<plugin_dir>/df_<type>_plugin_<name>.py`, falling
    back to an installed module of the same name."""
    module_name = plugin_module_name(plugin_type, name)
    path = pathlib.Path(plugin_dir) / f"{module_name}.py"
    if path.exists():
        spec = importlib.util.spec_from_file_location(module_name, path)
        assert spec is not None and spec.loader is not None
        module = importlib.util.module_from_spec(spec)
        # Registered before exec so plugin-defined classes are picklable /
        # re-importable (importlib contract).
        sys.modules[module_name] = module
        try:
            spec.loader.exec_module(module)
        except BaseException:
            sys.modules.pop(module_name, None)
            raise
    else:
        try:
            module = importlib.import_module(module_name)
        except ModuleNotFoundError:
            raise FileNotFoundError(
                f"plugin {module_name} not found in {plugin_dir} or on sys.path"
            ) from None
    init = getattr(module, PLUGIN_INIT, None)
    if init is None:
        raise AttributeError(f"plugin {module_name} lacks {PLUGIN_INIT}()")
    return init(options or {})
