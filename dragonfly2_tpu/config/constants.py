"""Workload constants mirrored from the reference's config defaults.

Reference: /root/reference/scheduler/config/constants.go (filter/candidate
limits :33-37, probe queue length :111-112, storage defaults :183-190,
trainer interval :197-201) and scheduler/scheduling/scheduling.go:128,156
(retry limits). These bound the shapes of every batched kernel: candidate
axes are padded to FILTER_PARENT_LIMIT, trace records carry at most
MAX_PARENTS_PER_RECORD parents x MAX_PIECES_PER_PARENT pieces
(scheduler/storage/types.go:169,218,293).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Constants:
    # --- scheduling (scheduler/config/constants.go:33-37) ---
    FILTER_PARENT_LIMIT: int = 15
    CANDIDATE_PARENT_LIMIT: int = 4
    # scheduling.go retry loop (:128,:156)
    RETRY_LIMIT: int = 5
    RETRY_BACK_TO_SOURCE_LIMIT: int = 3
    RETRY_INTERVAL_SECONDS: float = 0.05

    # --- resource GC (scheduler/config/constants.go:75-91 + pkg/gc) ---
    PEER_GC_INTERVAL_SECONDS: float = 10.0
    PEER_TTL_SECONDS: float = 24 * 3600.0
    PIECE_DOWNLOAD_TIMEOUT_SECONDS: float = 30 * 60.0
    TASK_GC_INTERVAL_SECONDS: float = 30 * 60.0
    HOST_GC_INTERVAL_SECONDS: float = 6 * 3600.0
    HOST_TTL_SECONDS: float = 3600.0

    # --- evaluator (evaluator.go:42-61) ---
    MAX_SCORE: float = 1.0
    MIN_SCORE: float = 0.0
    MAX_LOCATION_ELEMENTS: int = 5  # maxElementLen
    NORMAL_DISTRIBUTION_LEN: int = 30  # piece-cost sample count for 3-sigma
    MIN_AVAILABLE_COST_LEN: int = 2
    BAD_NODE_MEAN_MULTIPLIER: float = 20.0
    BAD_NODE_SIGMA: float = 3.0

    # --- evaluator weights (evaluator_base.go:28-46) ---
    W_FINISHED_PIECE: float = 0.2
    W_UPLOAD_SUCCESS: float = 0.2
    W_FREE_UPLOAD: float = 0.15
    W_HOST_TYPE: float = 0.15
    W_IDC: float = 0.15
    W_LOCATION: float = 0.15

    # --- network-topology evaluator weights (evaluator_network_topology.go:30-51) ---
    NT_W_FINISHED_PIECE: float = 0.2
    NT_W_UPLOAD_SUCCESS: float = 0.2
    NT_W_FREE_UPLOAD: float = 0.15
    NT_W_PROBE: float = 0.12
    NT_W_HOST_TYPE: float = 0.11
    NT_W_IDC: float = 0.11
    NT_W_LOCATION: float = 0.11
    PING_TIMEOUT_NS: int = 1_000_000_000  # defaultPingTimeout = 1s

    # --- probes (constants.go:111-112, probes.go:39) ---
    PROBE_QUEUE_LENGTH: int = 5
    EWMA_WEIGHT: float = 0.1  # defaultMovingAverageWeight: new = 0.1*old + 0.9*sample
    FIND_PROBED_HOSTS_LIMIT: int = 50

    # --- trace storage (constants.go:183-190, types.go:169,218,293) ---
    MAX_PARENTS_PER_RECORD: int = 20
    MAX_PIECES_PER_PARENT: int = 10
    MAX_DEST_HOSTS_PER_RECORD: int = 5
    STORAGE_MAX_SIZE_MB: int = 100
    STORAGE_MAX_BACKUPS: int = 10

    # --- trainer cadence (constants.go:197-201, announcer.go:40) ---
    TRAIN_INTERVAL_SECONDS: int = 7 * 24 * 3600
    TRAIN_UPLOAD_TIMEOUT_SECONDS: int = 3600
    TRAIN_UPLOAD_CHUNK_BYTES: int = 128 * 1024 * 1024

    # --- TPU-native batch shapes (BASELINE.json configs[2]) ---
    EVAL_BATCH_TASKS: int = 1024
    EVAL_BATCH_CANDIDATES: int = 64
    PIECE_COST_CAPACITY: int = 32  # >= NORMAL_DISTRIBUTION_LEN, ring buffer per peer


CONSTANTS = Constants()
