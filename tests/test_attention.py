"""Attention ranker + ring attention: numerics parity on the virtual
8-device mesh, model behavior, and training convergence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dragonfly2_tpu.models.attention import AttentionRanker
from dragonfly2_tpu.parallel import ring
from dragonfly2_tpu.parallel.mesh import make_mesh


def _qkv(batch=2, heads=4, length=16, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, heads, length, dim)
    q = rng.standard_normal(shape).astype(np.float32)
    k = rng.standard_normal(shape).astype(np.float32)
    v = rng.standard_normal(shape).astype(np.float32)
    mask = rng.random((batch, length)) < 0.8
    mask[:, 0] = True  # at least one valid key per row
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(mask)


def test_ring_attention_matches_dense():
    """Ring attention over sp shards must equal single-device dense
    attention (the blockwise online softmax is exact, not approximate)."""
    q, k, v, mask = _qkv()
    dense = ring.dense_attention(q, k, v, mask)
    for sp in (2, 4, 8):
        mesh = make_mesh(sp, dp=1, sp=sp)
        out = ring.sharded_ring_attention(mesh, q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=1e-5)


def test_ring_attention_dp_and_sp_together():
    q, k, v, mask = _qkv(batch=4, length=8)
    mesh = make_mesh(8, dp=4, sp=2)
    out = ring.sharded_ring_attention(mesh, q, k, v, mask)
    dense = ring.dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense), atol=1e-5)


def test_ring_attention_fully_masked_rows_are_zero():
    q, k, v, mask = _qkv(batch=2, length=8)
    mask = jnp.zeros_like(mask)  # nothing valid
    mesh = make_mesh(8, dp=2, sp=4)
    out = ring.sharded_ring_attention(mesh, q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)
    dense = ring.dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(dense), 0.0, atol=1e-6)


def test_ring_attention_grads_match_dense():
    q, k, v, mask = _qkv(batch=2, length=8)
    mesh = make_mesh(2, dp=1, sp=2)

    def loss_dense(q):
        return jnp.sum(ring.dense_attention(q, k, v, mask) ** 2)

    def loss_ring(q):
        return jnp.sum(ring.sharded_ring_attention(mesh, q, k, v, mask) ** 2)

    g_dense = jax.grad(loss_dense)(q)
    g_ring = jax.grad(loss_ring)(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), atol=1e-4)


def test_attention_ranker_shapes_and_masking():
    model = AttentionRanker(hidden_dim=32, num_heads=4, num_layers=1)
    rng = np.random.default_rng(0)
    n, p, f = 6, 8, 18
    child = rng.standard_normal((n, f)).astype(np.float32)
    parents = rng.standard_normal((n, p, f)).astype(np.float32)
    pair = rng.standard_normal((n, p, 2)).astype(np.float32)
    mask = np.ones((n, p), bool)
    mask[:, 5:] = False
    params = model.init(jax.random.key(0), child, parents, pair, mask)
    scores = model.apply(params, child, parents, pair, mask)
    assert scores.shape == (n, p)
    assert np.all(np.asarray(scores)[:, 5:] < -1e29)  # masked out
    assert np.all(np.isfinite(np.asarray(scores)[:, :5]))


def test_attention_ranker_learns_planted_signal():
    """Training on synth traces must beat random top-1 parent selection
    (the planted host-quality signal, records/synth.py)."""
    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.features import downloads_to_ranking_dataset
    from dragonfly2_tpu.training.train import train_attention

    cluster = synth.make_cluster(32, seed=5)
    records = synth.gen_download_records(cluster, 300, num_tasks=24, max_parents=8)
    ds, _ = downloads_to_ranking_dataset(records, max_parents=8)
    result = train_attention(
        ds, TrainerConfig(hidden_dim=32, batch_size=32, epochs=8), seed=0
    )
    # Single-batch losses are noisy; compare epoch means. The listwise CE
    # is lower-bounded by the target distribution's entropy (~1.43 on this
    # trace), so "learned" = last epoch mean strictly below first.
    spe = result.steps // 8
    losses = np.asarray(result.losses)
    assert losses[-spe:].mean() < losses[:spe].mean()
    assert result.eval_metrics["regret"] < 0.35, result.eval_metrics


def test_attention_ranker_trains_on_dp_sp_mesh():
    """Full train loop with batches over dp and ring attention over sp."""
    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.features import downloads_to_ranking_dataset
    from dragonfly2_tpu.training.train import train_attention

    cluster = synth.make_cluster(24, seed=2)
    records = synth.gen_download_records(cluster, 96, num_tasks=12, max_parents=8)
    ds, _ = downloads_to_ranking_dataset(records, max_parents=8)
    mesh = make_mesh(8, dp=4, sp=2)
    result = train_attention(
        ds, TrainerConfig(hidden_dim=32, batch_size=16, epochs=2), mesh=mesh, seed=0
    )
    assert result.steps > 0 and np.isfinite(result.losses).all()


def test_ranker_with_flash_attention_matches_dense():
    """The Pallas flash kernel is a drop-in attention_fn for the ranker:
    same scores as the dense path (interpret mode on CPU)."""
    from dragonfly2_tpu.ops.flash import flash_attention

    rng = np.random.default_rng(3)
    n, p, f = 4, 16, 6
    child = rng.standard_normal((n, f)).astype(np.float32)
    parents = rng.standard_normal((n, p, f)).astype(np.float32)
    pair = rng.standard_normal((n, p, 2)).astype(np.float32)
    mask = rng.random((n, p)) < 0.8
    mask[:, 0] = True

    model = AttentionRanker(hidden_dim=16, num_heads=2, num_layers=2)
    params = model.init(jax.random.key(0), child, parents, pair, mask)
    dense_scores = model.apply(params, child, parents, pair, mask)
    flash_scores = model.apply(
        params, child, parents, pair, mask, attention_fn=flash_attention
    )
    # bf16 matmul accumulation inside the kernel: parity at half precision,
    # not f32 (same tolerance family as tests/test_flash.py)
    np.testing.assert_allclose(
        np.asarray(flash_scores), np.asarray(dense_scores), atol=5e-2, rtol=5e-2
    )


def test_ring_attention_flash_blocks_match_dense():
    """Flash-in-ring: per-device blocks computed by the pallas partials
    kernel, merged across KV rotations, must equal dense attention."""
    from dragonfly2_tpu.parallel.ring import sharded_ring_attention

    q, k, v, mask = _qkv(batch=2, heads=2, length=32, dim=8, seed=5)
    dense = ring.dense_attention(q, k, v, mask)
    for sp in (2, 4):
        mesh = make_mesh(sp, dp=1, sp=sp)
        out = sharded_ring_attention(mesh, q, k, v, mask, use_flash=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(dense, np.float32),
            atol=5e-2, rtol=5e-2,
        )


def test_causal_ring_attention_zigzag_parity():
    """Zigzag causal ring attention == dense causal attention,
    layout-independent (positions ride the ring with the KV blocks)."""
    from dragonfly2_tpu.parallel.ring import (
        dense_attention,
        sharded_causal_ring_attention,
        zigzag_positions,
    )

    mesh8 = make_mesh(8, dp=2, sp=4)
    b, h, L, d = 2, 2, 64, 16
    rng = np.random.default_rng(3)
    q = rng.normal(size=(b, h, L, d)).astype(np.float32)
    k = rng.normal(size=(b, h, L, d)).astype(np.float32)
    v = rng.normal(size=(b, h, L, d)).astype(np.float32)
    mask = np.ones((b, L), bool)
    mask[1, -7:] = False  # ragged tail on one sequence

    want = np.asarray(dense_attention(q, k, v, mask, causal=True))
    got = np.asarray(sharded_causal_ring_attention(mesh8, q, k, v, mask))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    # zigzag order/inverse are a permutation pair
    order, inverse = zigzag_positions(L, 4)
    x = np.arange(L)
    assert (np.asarray(order)[np.asarray(inverse)] == x).all()
    assert (np.asarray(inverse)[np.asarray(order)] == x).all()


def test_causal_ring_rejects_flash():
    from dragonfly2_tpu.parallel.ring import ring_attention

    q = np.zeros((1, 1, 8, 4), np.float32)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, np.ones((1, 8), bool), use_flash=True,
                       q_pos=np.arange(8, dtype=np.int32))
