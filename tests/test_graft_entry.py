"""Driver contract: entry() compiles single-device; dryrun_multichip runs a
fully sharded train step on the virtual 8-device mesh."""

import sys
import pathlib

import jax

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (16, 8)
    assert jax.numpy.isfinite(out).all()


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_odd():
    graft.dryrun_multichip(3)  # graph axis falls back to 1
