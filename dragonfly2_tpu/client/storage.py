"""On-disk piece store for the daemon data plane.

Capability parity with client/daemon/storage (storage_manager.go:52-129
ifaces, local_storage.go): per-task data file + metadata sidecar, piece
writes at offsets with per-piece digests, FinishedPieces tracking,
reuse lookup by task id (RegisterTask dedup / FindCompletedTask),
partial-completion resume (FindPartialCompletedTask :545), TTL +
disk-usage GC, and persistent-task reload on restart (ReloadPersistentTask
:674). Single 'simple'-style strategy: one contiguous data file per task.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import threading
import time

from dragonfly2_tpu.utils import dferrors
from dragonfly2_tpu.utils.container import Bitset
from dragonfly2_tpu.utils.digest import md5_from_bytes, sha256_from_reader


class _BoundedReader:
    """Read-at-most-N wrapper so the whole-task digest covers exactly
    content_length bytes even if the data file grew past it."""

    def __init__(self, f, limit: int):
        self._f = f
        self._left = limit

    def read(self, n: int = -1) -> bytes:
        if self._left <= 0:
            return b""
        n = self._left if n < 0 else min(n, self._left)
        data = self._f.read(n)
        self._left -= len(data)
        return data


@dataclasses.dataclass
class PieceMetadata:
    number: int
    offset: int
    length: int
    digest: str = ""
    cost_ns: int = 0


@dataclasses.dataclass
class TaskMetadata:
    task_id: str
    peer_id: str
    url: str = ""
    content_length: int = -1
    piece_length: int = 4 << 20
    total_pieces: int = -1
    # whole-task sha256, computed at mark_done (the root of the digest
    # chain the scheduler distributes; "" until the task completes)
    digest: str = ""
    done: bool = False
    created_at: float = 0.0
    accessed_at: float = 0.0
    pieces: dict[int, PieceMetadata] = dataclasses.field(default_factory=dict)

    def finished_count(self) -> int:
        return len(self.pieces)


class TaskStorage:
    """One task's on-disk state: `<dir>/<task_id>/data` + `metadata.json`."""

    def __init__(self, base: pathlib.Path, meta: TaskMetadata):
        self.dir = base / meta.task_id
        self.dir.mkdir(parents=True, exist_ok=True)
        self.data_path = self.dir / "data"
        self.meta_path = self.dir / "metadata.json"
        self.pieces_path = self.dir / "pieces.jsonl"
        self.meta = meta
        self._lock = threading.RLock()
        # notified on every piece commit and on mark_done: the upload
        # server's long-poll piece listing (GET /pieces/<task>?wait_after=N)
        # blocks on it so children learn about new pieces push-style — the
        # role the reference's per-parent SyncPieceTasks stream plays
        # (client/daemon/peertask_piecetask_synchronizer.go)
        self.piece_cond = threading.Condition(self._lock)
        self._bitset = Bitset()
        for n in meta.pieces:
            self._bitset.set(n)
        if not self.data_path.exists():
            self.data_path.touch()
        if not self.meta_path.exists():
            self._flush_meta()

    # -------------------------------------------------------------- pieces

    def write_piece(
        self, number: int, offset: int, data: bytes, digest: str = "", cost_ns: int = 0,
        verified: bool = False,
    ) -> PieceMetadata:
        """Write piece bytes at their offset; validates the digest first
        (pieceManager digest check before commit). `verified=True` means
        the caller computed `digest` from THIS buffer moments ago
        (piece_manager's fetch paths) — skip re-hashing the same up-to-
        4 MiB buffer on the download hot path."""
        if digest and not verified:
            actual = md5_from_bytes(data)
            if actual != digest:
                raise dferrors.InvalidArgument(
                    f"piece {number} digest mismatch: got {actual} want {digest}"
                )
        with self._lock:
            with open(self.data_path, "r+b") as f:
                f.seek(offset)
                f.write(data)
            piece = PieceMetadata(
                number=number, offset=offset, length=len(data),
                digest=digest or md5_from_bytes(data), cost_ns=cost_ns,
            )
            self.meta.pieces[number] = piece
            self._bitset.set(number)
            self.meta.accessed_at = time.time()
            # O(1) durability per piece: append to the journal instead of
            # rewriting every accumulated entry (which is O(n^2) per task).
            with open(self.pieces_path, "a") as f:
                f.write(json.dumps(dataclasses.asdict(piece)) + "\n")
            self.piece_cond.notify_all()
            return piece

    def read_piece(self, number: int) -> bytes:
        with self._lock:
            piece = self.meta.pieces.get(number)
            if piece is None:
                raise dferrors.NotFound(f"piece {number} not stored")
            self.meta.accessed_at = time.time()
            with open(self.data_path, "rb") as f:
                f.seek(piece.offset)
                return f.read(piece.length)

    def read_range(self, offset: int, length: int) -> bytes:
        with self._lock:
            self.meta.accessed_at = time.time()
            with open(self.data_path, "rb") as f:
                f.seek(offset)
                return f.read(length)

    def has_piece(self, number: int) -> bool:
        return self._bitset.test(number)

    def finished_pieces(self) -> list[int]:
        with self._lock:
            return sorted(self.meta.pieces)

    def set_peer_id(self, peer_id: str) -> None:
        """The daemon re-registers a held task under a FRESH peer id on
        failover/restart re-announce; record it so later self-reports
        (verify-on-serve rot) name a peer the scheduler actually knows —
        a stale id would make quarantine silently no-op."""
        with self._lock:
            self.meta.peer_id = peer_id
            self._flush_meta()

    def evict_piece(self, number: int) -> bool:
        """Un-commit one piece (its bytes failed a LATER integrity check:
        verify-on-serve rot, or a whole-task digest mismatch attributed at
        mark_done). The piece leaves the finished set and the task drops
        out of `done`, so the conductor's resume/download path re-fetches
        it instead of serving or re-serving bad bytes forever. The bytes
        stay in the data file (harmless — unfinished ranges are never
        served) and the piece journal is rewritten without the entry.
        True iff THIS call removed the piece — concurrent detectors of the
        same rot use it to collapse to one self-report."""
        return bool(self.evict_pieces((number,)))

    def evict_pieces(self, numbers) -> list[int]:
        """Batch evict_piece: one journal rewrite + one metadata flush no
        matter how many pieces fall (mark_done recovery can evict
        thousands on a big task — per-piece rewrites would be O(n^2)
        journal bytes). Returns the numbers actually removed."""
        with self._lock:
            evicted = [n for n in numbers if self.meta.pieces.pop(n, None) is not None]
            if not evicted:
                return evicted
            for n in evicted:
                self._bitset.clear(n)
            self.meta.done = False
            self.meta.digest = ""
            with open(self.pieces_path, "w") as f:
                for piece in self.meta.pieces.values():
                    f.write(json.dumps(dataclasses.asdict(piece)) + "\n")
            self._flush_meta()
            return evicted

    def verify_piece(self, number: int) -> bool:
        """Re-hash a stored piece's bytes against its recorded digest
        (verify-on-serve / fsck). False = local disk rot or a torn write;
        the caller decides whether to 503, self-report, or just flag."""
        with self._lock:
            piece = self.meta.pieces.get(number)
            if piece is None:
                return False
            with open(self.data_path, "rb") as f:
                f.seek(piece.offset)
                data = f.read(piece.length)
        if len(data) != piece.length:
            return False
        return not piece.digest or md5_from_bytes(data) == piece.digest

    def compute_digest(self) -> str:
        """Whole-task sha256 over the first content_length bytes of the
        data file ("" when the length is unknown)."""
        with self._lock:
            length = self.meta.content_length
            if length < 0:
                return ""
            with open(self.data_path, "rb") as f:
                return sha256_from_reader(_BoundedReader(f, length))

    def mark_done(
        self,
        content_length: int | None = None,
        total_pieces: int | None = None,
        expected_digest: str | None = None,
    ) -> None:
        """Completion commit with integrity cross-checks. The caller's
        (content_length, total_pieces) claim is verified against the
        actual FinishedPieces state — a missed piece used to yield a
        silently short file — and the whole-task sha256 is computed and
        (when the scheduler attested one) verified before `done` flips.
        Raises TaskIntegrityError / PieceCorrupted; the task then stays
        resumable instead of serving a hole or corrupt bytes."""
        with self._lock:
            length = self.meta.content_length if content_length is None else content_length
            total = self.meta.total_pieces if total_pieces is None else total_pieces
            if total is not None and total > 0:
                missing = [n for n in range(total) if n not in self.meta.pieces]
                if missing:
                    raise dferrors.TaskIntegrityError(
                        f"task {self.meta.task_id}: {len(missing)} of {total} "
                        f"pieces missing at mark_done (first hole: piece "
                        f"{missing[0]})"
                    )
                if length is not None and length >= 0:
                    stored = sum(p.length for p in self.meta.pieces.values()
                                 if p.number < total)
                    if stored != length:
                        raise dferrors.TaskIntegrityError(
                            f"task {self.meta.task_id}: stored piece bytes "
                            f"{stored} != content_length {length}"
                        )
            if content_length is not None:
                self.meta.content_length = content_length
            if total_pieces is not None:
                self.meta.total_pieces = total_pieces
            digest = self.compute_digest()
            if expected_digest and digest and digest != expected_digest:
                raise dferrors.PieceCorrupted(
                    f"task {self.meta.task_id}: whole-task sha256 {digest} "
                    f"!= attested {expected_digest}"
                )
            self.meta.digest = digest
            self.meta.done = True
            self._flush_meta()
            self.piece_cond.notify_all()

    def wait_for_pieces(self, known_count: int, timeout: float) -> bool:
        """Block until this task holds MORE than `known_count` pieces or
        is done (True), or the timeout passes (False) — the long-poll
        primitive behind push-style piece announcements."""
        deadline = time.monotonic() + timeout
        with self.piece_cond:
            while (
                len(self.meta.pieces) <= known_count and not self.meta.done
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.piece_cond.wait(remaining)
            return True

    def size_on_disk(self) -> int:
        try:
            return self.data_path.stat().st_size
        except OSError:
            return 0

    # ---------------------------------------------------------- metadata io

    def _flush_meta(self) -> None:
        """Task-level fields only; piece entries live in the append-only
        journal (pieces.jsonl)."""
        d = dataclasses.asdict(self.meta)
        d.pop("pieces", None)
        tmp = self.meta_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(d))
        tmp.replace(self.meta_path)

    @staticmethod
    def load(base: pathlib.Path, task_dir: pathlib.Path) -> "TaskStorage | None":
        meta_path = task_dir / "metadata.json"
        try:
            d = json.loads(meta_path.read_text())
            pieces = {
                int(k): PieceMetadata(**v) for k, v in d.pop("pieces", {}).items()
            }
            meta = TaskMetadata(**{**d, "pieces": pieces})
        except (OSError, json.JSONDecodeError, TypeError, ValueError):
            return None
        # replay the append-only piece journal (a torn final line from a
        # crash mid-append is dropped)
        try:
            for line in (task_dir / "pieces.jsonl").read_text().splitlines():
                try:
                    piece = PieceMetadata(**json.loads(line))
                except (json.JSONDecodeError, TypeError):
                    continue
                meta.pieces[piece.number] = piece
        except OSError:
            pass
        return TaskStorage(base, meta)


class StorageManager:
    """All tasks on this daemon + GC policy.

    GC parity (local_storage + storage manager): TTL on last access, and
    a high/low-watermark disk-usage sweep evicting least-recently-used
    completed tasks first.
    """

    def __init__(
        self,
        data_dir: str | pathlib.Path,
        task_ttl: float = 24 * 3600.0,
        disk_gc_threshold_bytes: int = 0,  # 0 = unlimited
    ):
        self.base = pathlib.Path(data_dir)
        self.base.mkdir(parents=True, exist_ok=True)
        self.task_ttl = task_ttl
        self.disk_gc_threshold_bytes = disk_gc_threshold_bytes
        self._tasks: dict[str, TaskStorage] = {}
        self._lock = threading.RLock()
        self.reload()

    # ------------------------------------------------------------ lifecycle

    def register_task(self, meta: TaskMetadata) -> TaskStorage:
        with self._lock:
            ts = self._tasks.get(meta.task_id)
            if ts is None:
                meta.created_at = meta.created_at or time.time()
                meta.accessed_at = time.time()
                ts = TaskStorage(self.base, meta)
                self._tasks[meta.task_id] = ts
            return ts

    def get(self, task_id: str) -> TaskStorage | None:
        with self._lock:
            return self._tasks.get(task_id)

    def find_completed_task(self, task_id: str) -> TaskStorage | None:
        ts = self.get(task_id)
        return ts if ts is not None and ts.meta.done else None

    def find_partial_completed_task(self, task_id: str) -> TaskStorage | None:
        """Resume point: task exists with some pieces but not done
        (storage_manager.go:545)."""
        ts = self.get(task_id)
        if ts is not None and not ts.meta.done and ts.meta.finished_count() > 0:
            return ts
        return None

    def delete_task(self, task_id: str) -> bool:
        with self._lock:
            ts = self._tasks.pop(task_id, None)
        if ts is None:
            return False
        import shutil

        shutil.rmtree(ts.dir, ignore_errors=True)
        return True

    def tasks(self) -> list[TaskStorage]:
        with self._lock:
            return list(self._tasks.values())

    def reload(self) -> int:
        """Reload persisted tasks after restart (ReloadPersistentTask).

        The disk scan runs unlocked (it is slow and touches no shared
        state); each check-then-insert takes the task-table lock so a
        reload racing live registrations cannot clobber a TaskStorage a
        download is already writing through (dflint LOCK001)."""
        loaded = 0
        for task_dir in self.base.iterdir() if self.base.exists() else []:
            if not task_dir.is_dir():
                continue
            ts = TaskStorage.load(self.base, task_dir)
            if ts is None:
                continue
            with self._lock:
                if ts.meta.task_id not in self._tasks:
                    self._tasks[ts.meta.task_id] = ts
                    loaded += 1
        return loaded

    # ------------------------------------------------------------------ gc

    def run_gc(self) -> int:
        """TTL sweep + disk watermark sweep; returns tasks reclaimed."""
        now = time.time()
        reclaimed = 0
        for ts in self.tasks():
            if now - ts.meta.accessed_at > self.task_ttl:
                if self.delete_task(ts.meta.task_id):
                    reclaimed += 1
        if self.disk_gc_threshold_bytes > 0:
            usage = sum(ts.size_on_disk() for ts in self.tasks())
            if usage > self.disk_gc_threshold_bytes:
                # Evict least-recently-used completed tasks down to 80%.
                target = int(self.disk_gc_threshold_bytes * 0.8)
                for ts in sorted(self.tasks(), key=lambda t: t.meta.accessed_at):
                    if usage <= target:
                        break
                    if ts.meta.done:
                        usage -= ts.size_on_disk()
                        if self.delete_task(ts.meta.task_id):
                            reclaimed += 1
        return reclaimed
