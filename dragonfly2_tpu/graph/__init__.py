from dragonfly2_tpu.graph.dag import TaskDAG, DAGError, batch_can_add_edge, batch_reachable

__all__ = ["TaskDAG", "DAGError", "batch_can_add_edge", "batch_reachable"]
