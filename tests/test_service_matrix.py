"""Scheduler-service behavior matrix (VERDICT r3 #4/#8).

The reference spends ~8.2k lines enumerating scheduler-service behavior
(scheduler/service/service_v1_test.go, service_v2_test.go) as tables of
(request, entity state) -> outcome. This file is the same investment in
table-driven form, derived from ONE source of truth — the FSM transition
tables in state/fsm.py — so any mutation in a handler branch (skipped
legality check, wrong destination state, dropped failure response)
diverges from the recomputed expectation and fails:

- announce-oneof x peer-FSM-state product: every report handler against
  every forced pre-state, expected outcome recomputed from
  PEER_TRANSITIONS;
- size-scope register matrix (service_v1.go:1005-1110 /
  handleRegisterPeerRequest fast paths);
- a model-based random-walk: thousands of random report sequences
  replayed against a shadow FSM model, service state must track it
  exactly;
- unknown-peer probes for every handler.
"""

import itertools

import numpy as np
import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.state.fsm import (
    PEER_TRANSITIONS,
    InvalidTransition,
    PeerEvent,
    PeerState,
    TaskState,
    peer_transition,
)


def host(i: int, host_type: str = "normal") -> msg.HostInfo:
    return msg.HostInfo(
        host_id=f"mh-{i}", hostname=f"mh-{i}", ip=f"10.2.{i // 256}.{i % 256}",
        host_type=host_type,
    )


def register(svc, peer_id: str, task_id: str = "t-1", i: int = 1, **kw):
    return svc.register_peer(msg.RegisterPeerRequest(
        peer_id=peer_id, task_id=task_id, host=host(i),
        url=f"https://o.example/{task_id}", **kw,
    ))


# Each report handler drives exactly one peer FSM event (service_v2.go
# handlers); outcomes below are RECOMPUTED from PEER_TRANSITIONS.
HANDLER_EVENTS = [
    (msg.DownloadPeerFinishedRequest, PeerEvent.DOWNLOAD_SUCCEEDED),
    (msg.DownloadPeerFailedRequest, PeerEvent.DOWNLOAD_FAILED),
    (msg.DownloadPeerBackToSourceStartedRequest, PeerEvent.DOWNLOAD_BACK_TO_SOURCE),
    (msg.DownloadPeerBackToSourceFinishedRequest, PeerEvent.DOWNLOAD_SUCCEEDED),
    (msg.DownloadPeerBackToSourceFailedRequest, PeerEvent.DOWNLOAD_FAILED),
]

# LEAVE rows are excluded: a peer in LEAVE has left the SoA table in the
# real service (leave_peer frees the row), so the matrix covers it via
# the unknown-peer probes instead.
PRE_STATES = [s for s in PeerState if s != PeerState.LEAVE]


@pytest.mark.parametrize(
    "req_cls,event", HANDLER_EVENTS, ids=[c.__name__ for c, _ in HANDLER_EVENTS]
)
@pytest.mark.parametrize("pre", PRE_STATES, ids=[s.name for s in PRE_STATES])
def test_report_handler_against_every_peer_state(req_cls, event, pre):
    """handler x pre-state: legal transitions land in the FSM's
    destination state with no failure response; illegal ones answer
    ScheduleFailure(InvalidTransition) and leave the state untouched."""
    svc = SchedulerService()
    register(svc, "p-1")
    idx = svc.state.peer_index("p-1")
    svc.state.peer_state[idx] = int(pre)

    sources, dest = PEER_TRANSITIONS[event]
    response = svc.handle(req_cls(peer_id="p-1"))
    if pre in sources:
        assert svc.state.peer_state[idx] == int(dest), (pre, event)
        assert not isinstance(response, msg.ScheduleFailure), (pre, event)
    else:
        assert isinstance(response, msg.ScheduleFailure), (pre, event)
        assert response.code == "InvalidTransition"
        assert svc.state.peer_state[idx] == int(pre), "illegal event mutated state"


@pytest.mark.parametrize(
    "req_cls",
    [cls for cls, _ in HANDLER_EVENTS]
    + [msg.DownloadPieceFinishedRequest, msg.DownloadPieceFailedRequest,
       msg.RescheduleRequest],
    ids=lambda c: c.__name__,
)
def test_every_handler_answers_unknown_peer(req_cls):
    svc = SchedulerService()
    if req_cls is msg.DownloadPieceFinishedRequest:
        req = req_cls(peer_id="ghost", piece_number=0, length=1, cost_ns=1)
    elif req_cls is msg.DownloadPieceFailedRequest:
        req = req_cls(peer_id="ghost", parent_peer_id="also-ghost")
    else:
        req = req_cls(peer_id="ghost")
    response = svc.handle(req)
    assert isinstance(response, msg.ScheduleFailure)
    assert response.peer_id == "ghost"


# --------------------------------------------------------- size scopes

SCOPE_CASES = [
    # (content_length, piece_length, scope, post-register peer state)
    (0, 4 << 20, msg.SizeScope.EMPTY, PeerState.RECEIVED_EMPTY),
    (1, 4 << 20, msg.SizeScope.TINY, PeerState.RUNNING),
    (128, 4 << 20, msg.SizeScope.TINY, PeerState.RUNNING),
    (129, 4 << 20, msg.SizeScope.SMALL, PeerState.RUNNING),
    (4 << 20, 4 << 20, msg.SizeScope.SMALL, PeerState.RUNNING),
    ((4 << 20) + 1, 4 << 20, msg.SizeScope.NORMAL, PeerState.RUNNING),
    (10 << 20, 1 << 20, msg.SizeScope.NORMAL, PeerState.RUNNING),
    (-1, 4 << 20, msg.SizeScope.NORMAL, PeerState.RUNNING),  # unknown length
]


@pytest.mark.parametrize(
    "content_length,piece_length,scope,state", SCOPE_CASES,
    ids=[f"len{c}_piece{p}" for c, p, _, _ in SCOPE_CASES],
)
def test_register_size_scope_matrix(content_length, piece_length, scope, state):
    """handleRegisterPeerRequest size-scope fast paths (service_v1.go:
    1005-1110): EMPTY answers inline and never queues; every other scope
    runs the scheduling path with the scope recorded in the FSM route."""
    assert msg.SizeScope.of(content_length, piece_length) == scope or content_length < 0
    svc = SchedulerService()
    response = register(
        svc, "p-s", content_length=content_length, piece_length=piece_length
    )
    idx = svc.state.peer_index("p-s")
    assert svc.state.peer_state[idx] == int(state)
    if scope == msg.SizeScope.EMPTY:
        assert isinstance(response, msg.EmptyTaskResponse)
        assert "p-s" not in svc._pending
    else:
        assert response is None
        assert "p-s" in svc._pending
    # piece math: total pieces derived when length is known
    if content_length > 0:
        tidx = svc.state.task_index("t-1")
        want = -(-content_length // piece_length)
        assert svc.state.task_total_pieces[tidx] == want


# ------------------------------------------------- model-based random walk

def _apply_model(state: PeerState, event: PeerEvent) -> tuple[PeerState, bool]:
    """Shadow FSM: (next state, legal?)."""
    try:
        return peer_transition(state, event), True
    except InvalidTransition:
        return state, False


@pytest.mark.parametrize("seed", range(8))
def test_random_report_walk_tracks_fsm_model(seed):
    """Thousands of random report frames against one peer: after every
    frame the service's SoA state must equal the shadow FSM model, and
    failure responses must appear exactly on the model's illegal steps.
    Any handler that forgets a legality check, maps to the wrong event,
    or mutates state on the error path diverges within a few steps."""
    rng = np.random.default_rng(seed)
    svc = SchedulerService()
    register(svc, "p-w")
    idx = svc.state.peer_index("p-w")
    model = PeerState(int(svc.state.peer_state[idx]))

    frames = [cls for cls, _ in HANDLER_EVENTS]
    events = {cls: ev for cls, ev in HANDLER_EVENTS}
    for _ in range(400):
        cls = frames[rng.integers(len(frames))]
        response = svc.handle(cls(peer_id="p-w"))
        model, legal = _apply_model(model, events[cls])
        assert svc.state.peer_state[idx] == int(model)
        assert legal == (not isinstance(response, msg.ScheduleFailure))


# ------------------------------------------------------ piece accounting

def test_piece_accounting_matrix():
    """piece_finished/piece_failed bookkeeping: child bitset dedups by
    piece number, parent host upload counters move on success, failure
    counters + blocklist + DAG detach on failure (service_v1.go:1159-1282
    handlePieceSuccess/handlePieceFailure)."""
    svc = SchedulerService()
    svc.announce_host(host(0, "super"))
    register(svc, "parent-1", i=1)
    svc.handle(msg.DownloadPeerBackToSourceStartedRequest(peer_id="parent-1"))
    svc.handle(msg.DownloadPeerBackToSourceFinishedRequest(peer_id="parent-1", piece_count=8))
    register(svc, "child-1", i=2)
    responses = svc.tick()
    assert any(isinstance(r, msg.NormalTaskResponse) for r in responses)

    cidx = svc.state.peer_index("child-1")
    pidx = svc.state.peer_index("parent-1")
    phost = svc.state.peer_host[pidx]
    upload_before = int(svc.state.host_upload_count[phost])
    for piece, repeat in ((0, 1), (1, 1), (1, 2)):  # piece 1 reported twice
        for _ in range(repeat):
            svc.handle(msg.DownloadPieceFinishedRequest(
                peer_id="child-1", piece_number=piece, length=1 << 20,
                cost_ns=5_000_000, parent_peer_id="parent-1",
            ))
    svc.flush_piece_reports()  # buffered ingestion: make columns visible
    assert svc.state.peer_finished_count[cidx] == 2  # deduped bitset
    assert int(svc.state.host_upload_count[phost]) == upload_before + 4

    failed_before = int(svc.state.host_upload_failed[phost])
    svc.handle(msg.DownloadPieceFailedRequest(
        peer_id="child-1", parent_peer_id="parent-1"
    ))
    assert int(svc.state.host_upload_failed[phost]) == failed_before + 1
    assert "parent-1" in svc._pending["child-1"].blocklist


# -------------------------------------- no-FSM-event handlers x states
#
# piece_finished / piece_failed / reschedule fire NO peer FSM event
# (service_v1.go:1159-1282 handlePieceSuccess/Failure mutate accounting
# only): for a known peer they must succeed from EVERY live pre-state
# and leave the FSM state exactly as they found it.

NO_EVENT_REQUESTS = [
    ("piece_finished", lambda: msg.DownloadPieceFinishedRequest(
        peer_id="p-1", piece_number=0, length=1 << 20, cost_ns=1_000_000)),
    ("piece_failed", lambda: msg.DownloadPieceFailedRequest(
        peer_id="p-1", parent_peer_id="ghost-parent")),
    ("reschedule", lambda: msg.RescheduleRequest(
        peer_id="p-1", candidate_parent_ids=["ghost-parent"])),
]


@pytest.mark.parametrize("name,make", NO_EVENT_REQUESTS, ids=[n for n, _ in NO_EVENT_REQUESTS])
@pytest.mark.parametrize("pre", PRE_STATES, ids=[s.name for s in PRE_STATES])
def test_no_event_handler_against_every_peer_state(name, make, pre):
    svc = SchedulerService()
    register(svc, "p-1")
    idx = svc.state.peer_index("p-1")
    svc.state.peer_state[idx] = int(pre)
    response = svc.handle(make())
    assert not isinstance(response, msg.ScheduleFailure), (name, pre, response)
    assert svc.state.peer_state[idx] == int(pre), (name, pre)
    if name == "reschedule":
        # re-queued with the parent blocklisted, whatever the state
        assert "ghost-parent" in svc._pending["p-1"].blocklist


@pytest.mark.parametrize("pre", PRE_STATES, ids=[s.name for s in PRE_STATES])
def test_leave_peer_from_every_state(pre):
    """LeavePeer frees the SoA row from EVERY live state (resource
    peer manager delete; service_v1.go:457 LeaveTask): the peer id
    resolves to nothing afterwards and the row count drops."""
    svc = SchedulerService()
    register(svc, "p-1")
    idx = svc.state.peer_index("p-1")
    svc.state.peer_state[idx] = int(pre)
    svc.leave_peer("p-1")  # the RPC edge routes LeavePeerRequest here
    assert svc.state.peer_index("p-1") is None, pre
    assert svc.state.counts()["peers"] == 0, pre
    # idempotent: leaving again is a no-op, not a crash
    svc.leave_peer("p-1")


# ------------------------------------------------- task FSM product

TASK_B2S_CASES = [
    # (pre task state, request, expected post task state)
    (TaskState.RUNNING, msg.DownloadPeerBackToSourceFinishedRequest, TaskState.SUCCEEDED),
    (TaskState.FAILED, msg.DownloadPeerBackToSourceFinishedRequest, TaskState.SUCCEEDED),
    (TaskState.SUCCEEDED, msg.DownloadPeerBackToSourceFinishedRequest, TaskState.SUCCEEDED),
    (TaskState.RUNNING, msg.DownloadPeerBackToSourceFailedRequest, TaskState.FAILED),
    (TaskState.SUCCEEDED, msg.DownloadPeerBackToSourceFailedRequest, TaskState.SUCCEEDED),
    (TaskState.FAILED, msg.DownloadPeerBackToSourceFailedRequest, TaskState.FAILED),
]


@pytest.mark.parametrize(
    "pre,req_cls,post", TASK_B2S_CASES,
    ids=[f"{p.name}-{c.__name__}" for p, c, _ in TASK_B2S_CASES],
)
def test_back_to_source_drives_task_fsm(pre, req_cls, post):
    """Back-to-source outcomes drive the TASK FSM: a landed origin fetch
    proves content exists (SUCCEEDED, recovering FAILED tasks); a failed
    one fails a RUNNING task but never regresses a SUCCEEDED one
    (service_v2 handleDownloadPeerBackToSource* + fsm.py transitions)."""
    svc = SchedulerService()
    register(svc, "p-1")
    svc.handle(msg.DownloadPeerBackToSourceStartedRequest(peer_id="p-1"))
    tidx = svc.state.task_index("t-1")
    svc.state.task_state[tidx] = int(pre)
    svc.handle(req_cls(peer_id="p-1"))
    assert svc.state.task_state[tidx] == int(post), (pre, req_cls.__name__)


# ------------------------------------ trace-record content assertions
#
# service_v1_test.go pins the CONTENT of the Download records the
# handlers emit, not just that they emit; these do the same for the
# success, peer-failure, and back-to-source-failure paths.

def _svc_with_storage(tmp_path):
    from dragonfly2_tpu.records.storage import TraceStorage

    storage = TraceStorage(tmp_path / "matrix-data")
    return SchedulerService(storage=storage), storage


def test_peer_finished_record_content(tmp_path):
    svc, storage = _svc_with_storage(tmp_path)
    svc.announce_host(host(1))
    svc.announce_host(host(2))
    register(svc, "parent-1", i=1, tag="mt", application="ma",
             content_length=4 << 20, piece_length=1 << 20, total_piece_count=4)
    svc.handle(msg.DownloadPeerBackToSourceStartedRequest(peer_id="parent-1"))
    svc.handle(msg.DownloadPeerBackToSourceFinishedRequest(
        peer_id="parent-1", piece_count=4, content_length=4 << 20))
    register(svc, "child-1", i=2, tag="mt", application="ma",
             content_length=4 << 20, piece_length=1 << 20, total_piece_count=4)
    assert any(isinstance(r, msg.NormalTaskResponse) for r in svc.tick())
    for piece in range(3):
        svc.handle(msg.DownloadPieceFinishedRequest(
            peer_id="child-1", piece_number=piece, length=1 << 20,
            cost_ns=7_000_000, parent_peer_id="parent-1"))
    svc.handle(msg.DownloadPeerFinishedRequest(peer_id="child-1"))
    storage.flush()
    records = {r.id: r for r in storage.list_downloads()}
    rec = records["child-1"]
    assert rec.state == "Succeeded"
    assert rec.tag == "mt" and rec.application == "ma"
    assert rec.finished_piece_count == 3
    assert rec.cost > 0
    assert rec.task.id == "t-1"
    assert rec.task.total_piece_count == 4
    # the child's register re-entered the task FSM Running; the b2s
    # completion had marked it Succeeded before that
    assert rec.task.state in ("Running", "Succeeded")
    # the serving parent rides along with its piece history
    parents = {p.id: p for p in rec.parents}
    assert "parent-1" in parents
    p = parents["parent-1"]
    assert p.upload_piece_count == 3
    assert len(p.pieces) == 3
    assert all(piece.cost == 7_000_000 for piece in p.pieces)
    assert p.host.id == "mh-1"


def test_peer_failed_record_content(tmp_path):
    svc, storage = _svc_with_storage(tmp_path)
    svc.announce_host(host(1))
    register(svc, "p-f", i=1)
    svc.handle(msg.DownloadPeerFailedRequest(peer_id="p-f"))
    storage.flush()
    rec = {r.id: r for r in storage.list_downloads()}["p-f"]
    assert rec.state == "Failed"
    assert rec.finished_piece_count == 0
    assert rec.host.id == "mh-1"
    # peer FSM reflects the failure too
    assert svc.state.peer_state[svc.state.peer_index("p-f")] == int(PeerState.FAILED)


def test_back_to_source_failed_record_content(tmp_path):
    svc, storage = _svc_with_storage(tmp_path)
    svc.announce_host(host(1))
    register(svc, "p-b", i=1)
    svc.handle(msg.DownloadPeerBackToSourceStartedRequest(peer_id="p-b"))
    svc.handle(msg.DownloadPeerBackToSourceFailedRequest(peer_id="p-b"))
    storage.flush()
    rec = {r.id: r for r in storage.list_downloads()}["p-b"]
    assert rec.state == "Failed"
    assert rec.task.state == "Failed"  # origin fetch failure fails the task
    # back-to-source attempt was counted on the task record
    assert rec.task.back_to_source_peer_count == 1


def test_register_idempotence_across_states():
    """Re-register of a known peer is load-not-create for every live
    state (service_v2 handleResource): no FSM event fires, no duplicate
    row appears, and only RUNNING peers re-enter the pending queue."""
    for pre in (PeerState.RUNNING, PeerState.SUCCEEDED, PeerState.FAILED):
        svc = SchedulerService()
        register(svc, "p-1")
        idx = svc.state.peer_index("p-1")
        svc.state.peer_state[idx] = int(pre)
        svc._pending.pop("p-1", None)
        register(svc, "p-1")
        assert svc.state.counts()["peers"] == 1, pre
        assert svc.state.peer_state[idx] == int(pre), pre
        assert ("p-1" in svc._pending) == (pre == PeerState.RUNNING), pre
