"""Multi-process e2e — the reference's kind-cluster tier with real OS
processes: `python -m dragonfly2_tpu.cmd` launches scheduler + trainer as
separate processes, dfget-style downloads run against them from this
process, traces stream to the trainer over its socket, and the registry
fills with trained models. (SURVEY.md §4: e2e tests exec dfget in pods
against a live cluster; here pods are subprocesses.)"""

import asyncio
import hashlib
import os
import signal
import threading

import pytest

# the hand-rolled _spawn/_stop/_Origin these tests grew are now the
# procworld supervisor primitives (same contracts, plus log capture and
# the bounded escalation ladder)
from dragonfly2_tpu.procworld import OriginServer as _Origin
from dragonfly2_tpu.procworld import spawn_cmd as _spawn
from dragonfly2_tpu.procworld import stop_proc as _stop


@pytest.mark.slow
def test_processes_schedule_download_train(tmp_path):
    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.records.storage import TraceStorage
    from dragonfly2_tpu.registry import ModelRegistry
    from dragonfly2_tpu.rpc.client import TrainerClient

    payload = os.urandom(3 * (1 << 20) + 12345)
    digest = hashlib.sha256(payload).hexdigest()
    origin = _Origin(payload)

    sched_dir = tmp_path / "sched-data"
    sched, s_host, s_port = _spawn(
        ["scheduler", "--data-dir", str(sched_dir)], tmp_path
    )
    trainer, t_host, t_port = _spawn(
        [
            "trainer",
            "--data-dir", str(tmp_path / "trainer-data"),
            "--registry-dir", str(tmp_path / "registry"),
            "--epochs", "2",
        ],
        tmp_path,
    )
    try:
        async def drive():
            url = f"http://127.0.0.1:{origin.port}/blob.bin"
            # first peer back-sources, second pulls from it over P2P
            d1 = Daemon(
                tmp_path / "peer1", [(s_host, s_port)],
                ip="127.0.0.1", hostname="proc-peer-1",
            )
            await d1.start()
            ts1 = await d1.download(url, piece_length=1 << 20)
            await d1.export_file(ts1, str(tmp_path / "out1.bin"))
            gets_after_first = origin.gets

            d2 = Daemon(
                tmp_path / "peer2", [(s_host, s_port)],
                ip="127.0.0.1", hostname="proc-peer-2",
            )
            await d2.start()
            ts2 = await d2.download(
                url, piece_length=1 << 20, back_source_allowed=False
            )
            await d2.export_file(ts2, str(tmp_path / "out2.bin"))
            await d2.stop()
            await d1.stop()
            return gets_after_first

        gets_after_first = asyncio.run(drive())
        for name in ("out1.bin", "out2.bin"):
            got = hashlib.sha256((tmp_path / name).read_bytes()).hexdigest()
            assert got == digest, f"{name} corrupt"
        assert origin.gets == gets_after_first, "second peer hit the origin"

        # the scheduler process recorded download traces on disk
        storage = TraceStorage(sched_dir)
        assert storage.list_downloads(), "no traces written by scheduler proc"

        # stream them to the trainer process; registry fills with models
        async def train():
            client = TrainerClient(t_host, t_port)
            return await client.train(
                "sched-proc", "127.0.0.1", "sched-node",
                datasets={"download": storage.open_download()},
                chunk_size=1 << 20,
            )

        response = asyncio.run(train())
        assert response.ok, response.description
        registry = ModelRegistry(tmp_path / "registry")
        assert any(m["type"] == "gnn" for m in registry.list_models())
    finally:
        _stop(sched)
        _stop(trainer)
        origin.close()


@pytest.mark.slow
def test_manager_and_dfdaemon_launchers(tmp_path):
    import json
    import urllib.request

    manager, m_host, m_port = _spawn(
        ["manager", "--db", str(tmp_path / "manager.db")], tmp_path
    )
    sched, s_host, s_port = _spawn(["scheduler"], tmp_path)
    daemon, d_host, d_port = _spawn(
        [
            "dfdaemon",
            "--data-dir", str(tmp_path / "daemon-data"),
            "--scheduler", f"{s_host}:{s_port}",
        ],
        tmp_path,
    )
    try:
        # sign in as the default root user, then hit an RBAC-guarded route
        signin = urllib.request.Request(
            f"http://{m_host}:{m_port}/api/v1/users/signin",
            data=json.dumps({"name": "root", "password": "dragonfly"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(signin, timeout=5) as resp:
            token = json.loads(resp.read())["token"]
        schedulers = urllib.request.Request(
            f"http://{m_host}:{m_port}/api/v1/schedulers",
            headers={"Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(schedulers, timeout=5) as resp:
            assert resp.status == 200
            json.loads(resp.read())
        assert d_port > 0  # daemon bound its upload listener
    finally:
        _stop(daemon)
        _stop(sched)
        _stop(manager)


def test_scheduler_serves_inference_rpc(tmp_path):
    """`cmd scheduler --registry-dir` exposes trained models over the
    KServe-v2-shaped inference RPC: publish+activate an MLP into the
    registry, boot the scheduler process, score through the wire."""
    import jax
    import numpy as np

    from dragonfly2_tpu.cluster.trainer_service import MLP_MODEL_NAME
    from dragonfly2_tpu.models.mlp import ProbeRTTRegressor
    from dragonfly2_tpu.registry import ModelEvaluation, ModelRegistry
    from dragonfly2_tpu.registry.registry import MODEL_TYPE_MLP
    from dragonfly2_tpu.rpc.inference import InferenceClient

    registry_dir = tmp_path / "registry"
    reg = ModelRegistry(registry_dir)
    model = ProbeRTTRegressor(hidden_dim=8)
    x = np.ones((4, 8), np.float32)
    params = model.init(jax.random.key(0), x)
    mv = reg.create_model_version(
        MLP_MODEL_NAME, MODEL_TYPE_MLP, "sched-1", params, ModelEvaluation(),
        metadata={"hidden_dim": 8},  # the trainer always records this —
        # refresh() rebuilds the served module from it
    )
    reg.activate(mv.model_id, mv.version)

    proc, _, _ = _spawn(
        ["scheduler", "--registry-dir", str(registry_dir),
         "--scheduler-host-id", "sched-1"],
        tmp_path,
    )
    try:
        parts = proc.ready_line.split()
        assert "INFER" in parts, proc.ready_line
        ih, ip = parts[parts.index("INFER") + 1], int(parts[parts.index("INFER") + 2])

        async def run():
            client = await InferenceClient(ih, ip).connect()
            try:
                assert await client.server_live()
                assert await client.model_ready(MLP_MODEL_NAME)
                out = await client.model_infer(MLP_MODEL_NAME, {"features": x})
                expected = np.asarray(model.apply(params, x))
                np.testing.assert_allclose(out["rtt"], expected, rtol=1e-5)
            finally:
                await client.close()

        asyncio.run(run())
    finally:
        _stop(proc)


def test_metrics_and_debug_endpoints(tmp_path):
    """--metrics-port serves /metrics, /debug/stacks, /debug/profile
    (InitMonitor + per-service Prometheus server parity)."""
    import urllib.request

    proc, _, _ = _spawn(["scheduler", "--metrics-port", "0"], tmp_path)
    try:
        parts = proc.ready_line.split()
        mport = int(parts[parts.index("METRICS") + 1])
        base = f"http://127.0.0.1:{mport}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            body = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
        with urllib.request.urlopen(f"{base}/debug/stacks", timeout=5) as resp:
            stacks = resp.read().decode()
            assert "Thread" in stacks or "File" in stacks
        with urllib.request.urlopen(f"{base}/debug/profile?seconds=0.3", timeout=10) as resp:
            prof = resp.read().decode()
            assert "samples over" in prof
    finally:
        _stop(proc)


def test_dfdaemon_proxy_listeners(tmp_path):
    """--proxy/--sni-proxy serve the daemon's proxy listeners (the
    reference daemon's proxy + SNI servers, daemon.go:525-604)."""
    import urllib.request

    sched, s_host, s_port = _spawn(["scheduler"], tmp_path)
    origin = _Origin(b"layer-bytes" * 1000)
    daemon, _, _ = _spawn(
        ["dfdaemon", "--data-dir", str(tmp_path / "d"),
         "--scheduler", f"{s_host}:{s_port}",
         "--proxy", "--sni-proxy",
         "--proxy-rule", r"127\.0\.0\.1.*\.bin",
         "--registry-mirror", f"http://127.0.0.1:{origin.port}"],
        tmp_path,
    )
    try:
        parts = daemon.ready_line.split()
        pport = int(parts[parts.index("PROXY") + 1])
        assert "SNI" in parts
        # reverse-proxy mode: a relative request is mirrored to the origin
        req = urllib.request.Request(f"http://127.0.0.1:{pport}/v2/some/blob")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.read() == origin.payload
        # --proxy-rule hijack: an absolute-URI GET matching the rule is
        # served out of the P2P mesh (daemon downloads the task), marked
        # by the via header
        proxied = urllib.request.Request(
            f"http://127.0.0.1:{origin.port}/layer.bin",
        )
        proxied.set_proxy(f"127.0.0.1:{pport}", "http")
        with urllib.request.urlopen(proxied, timeout=30) as resp:
            assert resp.read() == origin.payload
            assert resp.headers.get("X-Dragonfly-Via") == "p2p"
    finally:
        _stop(daemon)
        _stop(sched)
        origin.close()


@pytest.mark.slow
def test_full_system_loops_through_launchers(tmp_path):
    """The whole control loop with ONLY launcher wiring: manager (REST +
    RPC) + trainer + scheduler (--manager keepalive, --trainer announce
    cadence) + daemons downloading. Without any manual streaming, traces
    must flow scheduler -> trainer on the cadence, models must appear in
    the registry, and the manager must list the scheduler."""
    import json
    import time
    import urllib.request

    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.registry import ModelRegistry

    origin = _Origin(os.urandom(1 << 20))
    manager, m_host, m_port = _spawn(
        ["manager", "--db", str(tmp_path / "m.db")], tmp_path
    )
    m_rpc_port = int(manager.ready_line.split()[manager.ready_line.split().index("RPC") + 1])
    trainer, t_host, t_port = _spawn(
        ["trainer", "--data-dir", str(tmp_path / "t-data"),
         "--registry-dir", str(tmp_path / "registry"), "--epochs", "2"],
        tmp_path,
    )
    sched, s_host, s_port = _spawn(
        ["scheduler", "--data-dir", str(tmp_path / "s-data"),
         "--manager", f"{m_host}:{m_rpc_port}", "--keepalive-interval", "0.5",
         "--trainer", f"{t_host}:{t_port}", "--announce-interval", "3",
         # NO --scheduler-host-id: the announce-side and serving-side
         # defaults must agree, or trained models are never servable
         "--registry-dir", str(tmp_path / "registry")],
        tmp_path,
    )
    try:
        async def downloads():
            d1 = Daemon(tmp_path / "p1", [(s_host, s_port)], hostname="loop-1")
            d2 = Daemon(tmp_path / "p2", [(s_host, s_port)], hostname="loop-2")
            await d1.start(); await d2.start()
            url = f"http://127.0.0.1:{origin.port}/blob.bin"
            await d1.download(url, piece_length=256 * 1024)
            await d2.download(url, piece_length=256 * 1024, back_source_allowed=False)
            await d1.stop(); await d2.stop()

        asyncio.run(downloads())

        # announce cadence fires on its own; registry fills with models
        # (no probe loop in this rig -> no networktopology dataset -> the
        # MLP regressor has nothing to train on; the GNN ranker trains
        # from the download traces alone)
        registry = ModelRegistry(tmp_path / "registry")
        deadline = time.monotonic() + 60
        models = []
        while time.monotonic() < deadline:
            models = registry.list_models()
            if any(m["type"] == "gnn" for m in models):
                break
            time.sleep(1)
        assert any(m["type"] == "gnn" for m in models), (
            f"registry after cadence: {[m['type'] for m in models]}"
        )

        # ...and the scheduler's own inference endpoint serves it under
        # the DEFAULT identity (train->publish->auto-activate->serve with
        # no ids configured anywhere)
        from dragonfly2_tpu.cluster.trainer_service import GNN_MODEL_NAME
        from dragonfly2_tpu.rpc.inference import InferenceClient

        parts = sched.ready_line.split()
        ih = parts[parts.index("INFER") + 1]
        ip_ = int(parts[parts.index("INFER") + 2])

        async def wait_ready():
            client = await InferenceClient(ih, ip_).connect()
            try:
                for _ in range(30):
                    if await client.model_ready(GNN_MODEL_NAME):
                        return True
                    await asyncio.sleep(1)
                return False
            finally:
                await client.close()

        assert asyncio.run(wait_ready()), "trained model never became servable"

        # the manager saw registration + keepalives: scheduler listed active
        signin = urllib.request.Request(
            f"http://{m_host}:{m_port}/api/v1/users/signin",
            data=json.dumps({"name": "root", "password": "dragonfly"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(signin, timeout=5) as resp:
            token = json.loads(resp.read())["token"]
        req = urllib.request.Request(
            f"http://{m_host}:{m_port}/api/v1/schedulers",
            headers={"Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            rows = json.loads(resp.read())
        assert rows, "scheduler never registered with the manager"
        assert any(r.get("state") == "active" for r in rows), rows
    finally:
        _stop(sched)
        _stop(trainer)
        _stop(manager)
        origin.close()


@pytest.mark.slow
def test_sigterm_under_load_bounded_exit_and_clean_restart(tmp_path):
    """SIGTERM while the scheduler is under real load (VERDICT r3 weak #7):
    an in-flight download streaming pieces from a throttled origin, a
    connected inference client, and live manager keepalives. The process
    must exit within the grace window (rc 0, no SIGKILL), the daemon's
    task storage must reload uncorrupted on restart, and the same URL
    must complete against a fresh scheduler afterwards."""
    import time as _time

    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.rpc.inference import InferenceClient

    payload = os.urandom(2 * (1 << 20) + 999)
    digest = hashlib.sha256(payload).hexdigest()

    # throttle GETs so the download is provably in flight at kill time
    origin = _Origin(payload, delay_s=0.15)

    manager, m_host, m_port = _spawn(
        ["manager", "--db", str(tmp_path / "m.db")], tmp_path
    )
    m_rpc = int(manager.ready_line.split()[manager.ready_line.split().index("RPC") + 1])
    sched, s_host, s_port = _spawn(
        ["scheduler", "--data-dir", str(tmp_path / "s-data"),
         "--manager", f"{m_host}:{m_rpc}", "--keepalive-interval", "0.3",
         "--registry-dir", str(tmp_path / "registry")],
        tmp_path,
    )
    parts = sched.ready_line.split()
    ih = parts[parts.index("INFER") + 1]
    ip_ = int(parts[parts.index("INFER") + 2])
    daemon_dir = tmp_path / "peer-restart"
    try:
        async def load_and_kill():
            d = Daemon(daemon_dir, [(s_host, s_port)], hostname="sigterm-peer")
            await d.start()
            url = f"http://127.0.0.1:{origin.port}/blob.bin"
            dl = asyncio.ensure_future(d.download(url, piece_length=128 * 1024))
            # wait until pieces are actually flowing
            for _ in range(100):
                if origin.gets > 2:
                    break
                await asyncio.sleep(0.1)
            assert origin.gets > 2, "download never started"
            infer = await InferenceClient(ih, ip_).connect()
            assert await infer.server_live()

            t0 = _time.monotonic()
            sched.send_signal(signal.SIGTERM)
            rc = await asyncio.to_thread(sched.wait, 10)
            exit_s = _time.monotonic() - t0
            assert rc == 0, f"scheduler exited rc={rc} under load"
            assert exit_s < 10, f"exit took {exit_s:.1f}s"

            dl.cancel()
            try:
                await dl
            except (Exception, asyncio.CancelledError):
                pass
            await infer.close()
            await d.stop(leave=False)

        asyncio.run(load_and_kill())

        # fresh scheduler; SAME daemon data dir must reload cleanly and
        # complete the interrupted URL (partial-resume/persistent reload,
        # storage_manager.go:545,674 semantics)
        origin.delay_s = 0.0
        sched2, s2_host, s2_port = _spawn(
            ["scheduler", "--data-dir", str(tmp_path / "s2-data")], tmp_path
        )
        try:
            async def resume():
                d = Daemon(daemon_dir, [(s2_host, s2_port)], hostname="sigterm-peer")
                await d.start()  # persistent-task reload runs here
                url = f"http://127.0.0.1:{origin.port}/blob.bin"
                ts = await d.download(url, piece_length=128 * 1024)
                await d.export_file(ts, str(tmp_path / "resumed.bin"))
                await d.stop()

            asyncio.run(resume())
            got = hashlib.sha256((tmp_path / "resumed.bin").read_bytes()).hexdigest()
            assert got == digest, "resumed download corrupt after SIGTERM"
        finally:
            _stop(sched2)
    finally:
        _stop(sched)
        _stop(manager)
        origin.close()


@pytest.mark.slow
def test_sigkill_mid_download_restart_adopts_reannounced_pieces(tmp_path):
    """SIGKILL (no grace, the crash SIGTERM handling can't see) lands on
    the ONLY scheduler while a child dfdaemon's download is in flight.
    The supervisor restarts the scheduler on its pinned port with empty
    in-memory state; the seed daemon's keepalive loop re-announces its
    finished pieces (the PR-3 crash-recovery path), the restarted
    scheduler ADOPTS them, and the child completes byte-identical with
    ZERO additional origin GETs — every recovered byte came from the
    seed's kept pieces, not a back-to-source refetch."""
    import concurrent.futures
    import time as _time

    from dragonfly2_tpu.procworld import ProcessPlanet, wait_for
    from dragonfly2_tpu.procworld.planet import _fetch_via_proxy, _scrape
    from dragonfly2_tpu.telemetry.metrics import Registry

    payload = os.urandom(2 * (1 << 20) + 333)
    digest = hashlib.sha256(payload).hexdigest()
    origin = _Origin(payload)
    # the test_chaos_failover headroom, via the launcher's --config path:
    # the recovering child must not escalate to back-to-source while the
    # restarted scheduler is still adopting the seed's re-announced copy
    cfg = tmp_path / "sched.yaml"
    cfg.write_text(
        "scheduler:\n  retry_back_to_source_limit: 50\n  retry_limit: 60\n"
    )
    try:
        with ProcessPlanet(tmp_path, registry=Registry()) as planet:
            planet.spawn_scheduler(
                "scheduler-0", extra=("--config", str(cfg)))
            addrs = planet.scheduler_addresses()
            seed = planet.spawn_daemon("seed-0", addrs, host_type="super")
            child0 = planet.spawn_daemon("child-0", addrs)
            child1 = planet.spawn_daemon("child-1", addrs)
            url = origin.url()

            # seed back-sources the payload once and announces it
            got, _, _ = _fetch_via_proxy(url, int(seed.ports["PROXY"]))
            assert got == digest
            gets_after_seed = origin.gets
            assert gets_after_seed > 0

            # child-0's download is submitted, then SIGKILL lands while
            # its transfer is in flight (real TTC through the proxy path
            # is ~1s; the kill cuts the announce stream mid-task)
            with concurrent.futures.ThreadPoolExecutor(1) as pool:
                fut = pool.submit(
                    _fetch_via_proxy, url, int(child0.ports["PROXY"]))
                _time.sleep(0.1)
                planet.kill("scheduler-0")
                fresh = planet.restart("scheduler-0")  # same pinned port
                try:
                    fut.result(timeout=60)
                except Exception:
                    pass  # the kill window caught the transfer — expected

            # the seed's keepalive loop redials the restarted scheduler on
            # its own (2s probe cadence); child-1 must not register before
            # the seed is back, or the one-shot first-peer seed trigger
            # fires into the void
            wait_for(
                lambda: _scrape(fresh.ports["METRICS"]).get(
                    "dragonfly_scheduler_announce_host_total", 0) >= 1,
                30, what="seed redial after scheduler restart",
            )

            # a fresh peer against the restarted (empty-state) scheduler:
            # its register triggers the super-host seed, the seed finds
            # the completed task on disk and re-announces every finished
            # piece (PR-3), the scheduler ADOPTS the seed as parent, and
            # child-1 completes P2P
            got, _, _ = _fetch_via_proxy(url, int(child1.ports["PROXY"]))
            assert got == digest, "post-restart download corrupt"
            reann = _scrape(seed.ports["METRICS"]).get(
                "dragonfly_dfdaemon_seed_task_reannounce_total", 0)
            assert reann >= 1, "seed never re-announced kept pieces"
            # zero origin re-fetches: recovery rode the adopted pieces
            assert origin.gets == gets_after_seed, (
                f"origin refetched after restart: {origin.gets} vs "
                f"{gets_after_seed}"
            )
    finally:
        origin.close()


@pytest.mark.slow
def test_bucket_registry_shared_across_processes(tmp_path):
    """Trainer process on "host A" publishes models into a SIGNED S3
    bucket; a scheduler process on "host B" serves them — the two share
    ONLY the bucket endpoint, no filesystem (VERDICT r3 missing #2
    done-criterion: the e2e passes with --registry-dir pointing at a
    bucket URL; reference upload path manager_server_v1.go:880-952)."""
    from test_remote_sources import ACCESS, REGION, SECRET, _S3Handler, _Store, _serve

    from dragonfly2_tpu.cluster.trainer_service import GNN_MODEL_NAME
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.storage import TraceStorage
    from dragonfly2_tpu.registry import open_registry
    from dragonfly2_tpu.rpc.client import TrainerClient
    from dragonfly2_tpu.rpc.inference import InferenceClient

    store = _Store()
    handler = type("H", (_S3Handler,), {"store": store})
    srv, addr = _serve(handler)
    url = (
        f"s3://models?endpoint={addr}"
        f"&access_key={ACCESS}&secret_key={SECRET}&region={REGION}"
    )

    # traces a scheduler would have streamed (synthetic download records)
    cluster = synth.make_cluster(16, seed=3)
    records = synth.gen_download_records(cluster, 60, num_tasks=4)
    tstore = TraceStorage(tmp_path / "traces")
    for r in records:
        tstore.create_download(r)

    trainer, t_host, t_port = _spawn(
        ["trainer", "--data-dir", str(tmp_path / "t-data"),
         "--registry-dir", url, "--epochs", "2"],
        tmp_path,
    )
    sched = None
    try:
        async def train():
            client = TrainerClient(t_host, t_port)
            return await client.train(
                "sched-b", "127.0.0.1", "sched-node",
                datasets={"download": tstore.open_download()},
                chunk_size=1 << 20,
            )

        response = asyncio.run(train())
        assert response.ok, response.description

        # the bucket (not any local dir) holds the published model
        reg = open_registry(url)
        assert any(m["type"] == "gnn" for m in reg.list_models())
        assert not (tmp_path / "models").exists(), "registry leaked to disk"

        sched, _, _ = _spawn(
            ["scheduler", "--registry-dir", url,
             "--scheduler-host-id", "sched-b"],
            tmp_path,
        )
        parts = sched.ready_line.split()
        ih = parts[parts.index("INFER") + 1]
        ip_ = int(parts[parts.index("INFER") + 2])

        async def serve_check():
            client = await InferenceClient(ih, ip_).connect()
            try:
                for _ in range(20):
                    if await client.model_ready(GNN_MODEL_NAME):
                        return True
                    await asyncio.sleep(0.5)
                return False
            finally:
                await client.close()

        assert asyncio.run(serve_check()), "bucket model never became servable"
    finally:
        if sched is not None:
            _stop(sched)
        _stop(trainer)
        srv.shutdown()


@pytest.mark.slow
def test_manager_restart_durability(tmp_path):
    """Control-plane durability across a manager restart (VERDICT r3
    missing #5): the deliberate redesign is ONE sqlite file in WAL mode
    instead of MySQL/Postgres + Redis (database.go:185, internal/job) —
    this e2e pins what that must mean in practice: users, clusters,
    applications, and PATs survive a SIGTERM + reboot on the same --db,
    a registered scheduler is re-listed and its keepalives re-activate
    it, while in-proc job queues are (documented) NOT durable."""
    import json
    import urllib.request

    db = tmp_path / "durable.db"

    def api(m_host, m_port, token, path, data=None, method=None):
        req = urllib.request.Request(
            f"http://{m_host}:{m_port}{path}",
            data=json.dumps(data).encode() if data is not None else None,
            headers={"Content-Type": "application/json",
                     "Authorization": f"Bearer {token}"},
            method=method,
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            body = resp.read()
            return json.loads(body) if body else None

    def signin(m_host, m_port):
        req = urllib.request.Request(
            f"http://{m_host}:{m_port}/api/v1/users/signin",
            data=json.dumps({"name": "root", "password": "dragonfly"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())["token"]

    manager, m_host, m_port = _spawn(["manager", "--db", str(db)], tmp_path)
    m_rpc = int(manager.ready_line.split()[manager.ready_line.split().index("RPC") + 1])
    sched, s_host, s_port = _spawn(
        ["scheduler", "--manager", f"{m_host}:{m_rpc}",
         "--keepalive-interval", "0.3"],
        tmp_path,
    )
    try:
        token = signin(m_host, m_port)
        cluster = api(m_host, m_port, token, "/api/v1/clusters",
                      {"name": "durable-c1"})
        app = api(m_host, m_port, token, "/api/v1/applications",
                  {"name": "durable-app", "url": "https://a.example"})
        pat = api(m_host, m_port, token, "/api/v1/personal-access-tokens",
                  {"name": "ci-token", "scopes": ["job"]})
        assert cluster["id"] and app["id"] and pat.get("token")
        # scheduler registered + keepalives -> active
        import time as _time

        deadline = _time.monotonic() + 10
        rows = []
        while _time.monotonic() < deadline:
            rows = api(m_host, m_port, token, "/api/v1/schedulers")
            if rows and any(r.get("state") == "active" for r in rows):
                break
            _time.sleep(0.3)
        assert rows and any(r.get("state") == "active" for r in rows), rows

        _stop(manager)  # SIGTERM; WAL sqlite must land everything
        manager2, m2_host, m2_port = _spawn(["manager", "--db", str(db)], tmp_path)
        try:
            token2 = signin(m2_host, m2_port)
            names = {c["name"] for c in api(m2_host, m2_port, token2, "/api/v1/clusters")}
            assert "durable-c1" in names
            apps = {a["name"] for a in api(m2_host, m2_port, token2, "/api/v1/applications")}
            assert "durable-app" in apps
            pats = api(m2_host, m2_port, token2, "/api/v1/personal-access-tokens")
            assert any(p["name"] == "ci-token" for p in pats)
            # the scheduler row survived; it goes active again only once
            # keepalives reach the NEW manager process (different port, so
            # the old scheduler can't — a fresh scheduler re-registers)
            rows2 = api(m2_host, m2_port, token2, "/api/v1/schedulers")
            assert rows2, "scheduler registration rows lost across restart"
        finally:
            _stop(manager2)
    finally:
        _stop(sched)
        if manager.poll() is None:
            _stop(manager)


def test_preheat_survives_manager_kill_and_restart(tmp_path):
    """Cross-process preheat + control-plane recovery (VERDICT r4 next
    #6): TWO launched schedulers registered with one launched manager, a
    seed daemon serving both, a REST preheat job fanned out over the
    RemoteScheduler job edge (the reference's machinery bus hop,
    manager/job/preheat.go -> internal/job) — then the manager is KILLED
    mid-preheat and restarted on the same --db and RPC port. The durable
    job record must converge to SUCCESS on the new process: it re-adopts
    the task list and polls live task states from the schedulers, which
    kept downloading while the manager was gone."""
    import json
    import socket
    import time as _time
    import urllib.request

    from dragonfly2_tpu.client.daemon import Daemon

    payload = os.urandom(1 << 20)

    # keep seed downloads in flight at kill time
    origin = _Origin(payload, delay_s=0.1)

    # fixed manager RPC port so schedulers reconnect to the RESTARTED
    # manager (their --manager flag pins host:port)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    m_rpc_port = s.getsockname()[1]
    s.close()

    db = tmp_path / "preheat.db"
    manager, m_host, m_port = _spawn(
        ["manager", "--db", str(db), "--rpc-port", str(m_rpc_port)], tmp_path
    )
    scheds = []
    for i in (1, 2):
        sched, s_host, s_port = _spawn(
            ["scheduler", "--data-dir", str(tmp_path / f"s{i}-data"),
             "--manager", f"{m_host}:{m_rpc_port}",
             "--hostname", f"preheat-sched-{i}",
             "--keepalive-interval", "0.3"],
            tmp_path,
        )
        scheds.append((sched, s_host, s_port))

    def api(port, token, path, data=None, method=None):
        req = urllib.request.Request(
            f"http://{m_host}:{port}{path}",
            data=json.dumps(data).encode() if data is not None else None,
            headers={"Content-Type": "application/json",
                     "Authorization": f"Bearer {token}"},
            method=method,
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = resp.read()
            return json.loads(body) if body else None

    def signin(port):
        req = urllib.request.Request(
            f"http://{m_host}:{port}/api/v1/users/signin",
            data=json.dumps({"name": "root", "password": "dragonfly"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())["token"]

    async def run_seed_daemon(stop_event):
        daemon = Daemon(
            tmp_path / "seed", [(h, p) for _, h, p in scheds],
            hostname="seed-1", host_type="super",
        )
        await daemon.start()
        try:
            await stop_event.wait()
        finally:
            await daemon.stop()

    loop_holder = {}
    seed_thread = None
    try:
        # seed daemon on its own loop thread, announcing to BOTH schedulers
        def seed_main():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            loop_holder["loop"] = loop
            loop_holder["stop"] = asyncio.Event()
            loop.run_until_complete(run_seed_daemon(loop_holder["stop"]))

        seed_thread = threading.Thread(target=seed_main, daemon=True)
        seed_thread.start()
        deadline = _time.monotonic() + 10
        while "stop" not in loop_holder and _time.monotonic() < deadline:
            _time.sleep(0.05)

        token = signin(m_port)
        # wait for both schedulers to register active (keepalive cadence)
        deadline = _time.monotonic() + 15
        while _time.monotonic() < deadline:
            rows = api(m_port, token, "/api/v1/schedulers")
            if len([r for r in rows if r.get("state") == "active"]) >= 2:
                break
            _time.sleep(0.3)

        urls = [f"http://127.0.0.1:{origin.port}/blob-{i}.bin" for i in range(4)]
        job = api(m_port, token, "/api/v1/jobs",
                  {"type": "preheat", "args": {"urls": urls}})
        assert job["state"] in ("PENDING", "SUCCESS"), job
        record_id = job["id"]

        # kill the manager MID-preheat (throttled origin keeps the seed
        # downloads in flight); the schedulers and seed keep working
        manager.kill()
        manager.wait(timeout=10)

        manager2, _, m2_port = _spawn(
            ["manager", "--db", str(db), "--rpc-port", str(m_rpc_port)],
            tmp_path,
        )
        try:
            token2 = signin(m2_port)
            got = None
            deadline = _time.monotonic() + 60
            while _time.monotonic() < deadline:
                got = api(m2_port, token2, f"/api/v1/jobs/{record_id}")
                if got["state"] == "SUCCESS":
                    break
                _time.sleep(0.5)
            assert got and got["state"] == "SUCCESS", got
            # the origin actually served the seed fetches
            assert origin.gets >= 4, origin.gets
        finally:
            _stop(manager2)
    finally:
        if seed_thread is not None and "stop" in loop_holder:
            loop_holder["loop"].call_soon_threadsafe(loop_holder["stop"].set)
            seed_thread.join(timeout=10)
        for sched, _, _ in scheds:
            _stop(sched)
        if manager.poll() is None:
            _stop(manager)
        origin.close()


def test_mtls_launchers_end_to_end(tmp_path):
    """Launcher-level mTLS (VERDICT r1 item 4): manager issues the cluster
    CA, scheduler certifies + serves mutual TLS, a dfget download rides the
    encrypted edge, and a plaintext connection to the scheduler fails."""
    import asyncio
    import hashlib

    from dragonfly2_tpu.utils import certs

    if not certs._HAVE_CRYPTO:
        # without the cryptography package the scheduler --tls-issue spawn
        # dies before this test's try/finally, leaking the origin listener
        # into the session (the conftest leak guard flags it)
        pytest.skip("mTLS launcher e2e needs the 'cryptography' package")

    from dragonfly2_tpu.client.daemon import Daemon
    from dragonfly2_tpu.manager.rpc import obtain_certificate

    origin = _Origin(bytes(i % 251 for i in range(90_000)))
    manager, m_host, m_port = _spawn(
        ["manager", "--cert-dir", str(tmp_path / "ca")], tmp_path
    )
    # manager READY line: "READY host rest_port RPC rpc_port"
    parts = manager.ready_line.split()
    rpc_port = int(parts[parts.index("RPC") + 1])
    sched, s_host, s_port = _spawn(
        [
            "scheduler",
            "--tls-dir", str(tmp_path / "sched-tls"),
            "--tls-issue",
            "--manager", f"{m_host}:{rpc_port}",
        ],
        tmp_path,
    )
    try:
        async def drive():
            mat = await obtain_certificate(
                m_host, rpc_port, "daemon-1", tmp_path / "daemon-tls"
            )
            d = Daemon(
                tmp_path / "tls-peer", [(s_host, s_port)], hostname="tls-peer",
                ssl_context=mat.client_context(),
            )
            await d.start()
            url = f"http://127.0.0.1:{origin.port}/blob.bin"
            ts = await d.download(url, piece_length=16 * 1024)
            with open(ts.data_path, "rb") as f:
                assert hashlib.sha256(f.read()).hexdigest() == hashlib.sha256(
                    origin.payload
                ).hexdigest()
            await d.stop()

            # plaintext stream must die at the TLS edge
            from dragonfly2_tpu.cluster import messages as msg
            from dragonfly2_tpu.rpc import wire

            try:
                reader, writer = await asyncio.open_connection(s_host, s_port)
                wire.write_frame(writer, msg.StatTaskRequest(task_id="x"))
                await writer.drain()
                data = await asyncio.wait_for(reader.read(4), timeout=5)
                assert data == b"", "plaintext client was answered over a TLS port"
            except (ConnectionError, OSError):
                pass  # reset is equally a rejection

        asyncio.run(drive())
    finally:
        _stop(sched)
        _stop(manager)
        origin.close()
