"""Perf observatory — soak timelines (telemetry/timeline.py).

Pins the three measurement primitives the megascale soak assertions rest
on: the quantile sketch's PROVABLE relative-error bound, the timeline
recorder's plain-data ring + gauge mirror, and the recovery_time
measurement (dip + intervals-to-recover) the soak test anchors on the
scheduler-kill rounds."""

import numpy as np
import pytest

from dragonfly2_tpu.telemetry.timeline import (
    QuantileSketch,
    TimelineRecorder,
    live_timelines,
    recovery_time,
)


# -------------------------------------------------------- quantile sketch


def _exact_quantile(sorted_vals: np.ndarray, q: float) -> float:
    # the sketch's rank convention: value at rank q * (n - 1)
    return float(sorted_vals[int(q * (len(sorted_vals) - 1))])


@pytest.mark.parametrize("alpha", [0.01, 0.05])
def test_sketch_relative_error_bound(alpha):
    """THE bound: for every queried quantile, the sketch's answer is
    within alpha relative error of the exact empirical quantile — the
    DDSketch log-bucket guarantee, tested against lognormal data whose
    tail spans four orders of magnitude (the TTC-like shape)."""
    rng = np.random.default_rng(7)
    values = np.exp(rng.normal(loc=3.0, scale=1.5, size=20_000))
    sketch = QuantileSketch(relative_accuracy=alpha)
    sketch.extend(values)
    hi = np.sort(values)
    for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 0.999):
        exact = _exact_quantile(hi, q)
        got = sketch.quantile(q)
        assert got is not None
        rel = abs(got - exact) / exact
        assert rel <= alpha + 1e-9, (q, exact, got, rel)


def test_sketch_edge_cases_and_zero_bucket():
    s = QuantileSketch()
    assert s.quantile(0.5) is None
    s.add(0.0)
    s.add(-5.0)   # non-positive values collapse to the zero bucket
    s.add(100.0)
    assert s.count == 3
    assert s.quantile(0.0) == 0.0
    assert s.quantile(1.0) == pytest.approx(100.0, rel=0.011)
    with pytest.raises(ValueError):
        s.quantile(1.5)
    with pytest.raises(ValueError):
        QuantileSketch(relative_accuracy=0.0)


def test_sketch_bucket_collapse_keeps_tail_accuracy():
    """Memory stays bounded: beyond max_buckets the LOWEST buckets
    collapse into zero, so tail quantiles keep their bound."""
    s = QuantileSketch(relative_accuracy=0.01, max_buckets=64)
    rng = np.random.default_rng(3)
    values = np.exp(rng.uniform(-10, 10, size=5000))  # huge dynamic range
    s.extend(values)
    assert len(s._buckets) <= 64
    exact = _exact_quantile(np.sort(values), 0.99)
    assert s.quantile(0.99) == pytest.approx(exact, rel=0.011)


def test_sketch_determinism():
    def build():
        s = QuantileSketch()
        for i in range(1000):
            s.add((i * 37 % 997) + 0.5)
        return s.to_dict()

    assert build() == build()


# ------------------------------------------------------ timeline recorder


def test_recorder_ring_gauges_and_registry():
    from dragonfly2_tpu.telemetry.metrics import Registry

    reg = Registry()
    rec = TimelineRecorder("test.timeline", maxlen=4, registry=reg)
    for t in range(6):
        rec.sample(t, {"pieces": t * 10, "origin_fraction": 0.1,
                       "nested": {"a": 1}})
    rec.mark_event(3, "scheduler_crash")
    tl = rec.timeline()
    assert len(tl) == 4  # bounded ring
    assert tl[-1] == {"t": 5, "pieces": 50, "origin_fraction": 0.1,
                      "nested": {"a": 1}}
    dump = rec.dump()
    assert dump["events"] == [{"t": 3, "event": "scheduler_crash"}]
    text = reg.expose()
    # scalars mirror into the gauge; nested dicts ride the ring only
    assert ('dragonfly_timeline_value{source="test.timeline",'
            'metric="pieces"} 50.0') in text
    assert 'metric="nested"' not in text
    assert ('dragonfly_timeline_samples_total{source="test.timeline"} 6.0'
            in text)
    # the weak named registry serves the /debug/flight surface
    assert live_timelines().get("test.timeline") is rec


# -------------------------------------------------------- recovery_time


def _tl(values, start=0):
    return [{"t": start + i, "pieces": v} for i, v in enumerate(values)]


def test_recovery_time_measures_dip_and_recovery():
    # baseline 100, kill at t=8 dips to 40, recovers (>= 90) at t=11
    tl = _tl([100] * 8 + [40, 60, 80, 95, 100])
    r = recovery_time(tl, "pieces", event_t=8, baseline_window=4,
                      threshold=0.9)
    assert r["baseline"] == 100.0
    assert r["dip"] == 40
    assert r["dip_ratio"] == 0.4
    assert r["recovered"] and r["recovery_t"] == 11
    assert r["recovery_intervals"] == 3


def test_recovery_time_never_recovers_within_horizon():
    tl = _tl([100] * 8 + [40] * 10)
    r = recovery_time(tl, "pieces", event_t=8, baseline_window=4,
                      threshold=0.9, horizon=6)
    assert not r["recovered"]
    assert r["recovery_t"] is None and r["recovery_intervals"] is None
    assert r["dip"] == 40


def test_recovery_time_instant_recovery_and_empty_edges():
    # value never drops below threshold: recovery at the event sample
    tl = _tl([100] * 12)
    r = recovery_time(tl, "pieces", event_t=6, baseline_window=4)
    assert r["recovered"] and r["recovery_intervals"] == 0
    # no pre-event samples -> unmeasurable, not a crash
    r2 = recovery_time(tl, "pieces", event_t=0, baseline_window=4)
    assert r2["baseline"] is None and not r2["recovered"]
    # dip only counts until recovery: a later trough (next fault) is
    # not THIS event's dip
    tl3 = _tl([100] * 8 + [95, 100, 0, 0])
    r3 = recovery_time(tl3, "pieces", event_t=8, baseline_window=4)
    assert r3["recovered"] and r3["recovery_t"] == 8
    assert r3["dip"] == 95
