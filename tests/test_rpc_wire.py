"""Wire codec: dataclass<->msgpack roundtrips and stream framing."""

import asyncio

import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.rpc import wire

wire.register_module(msg)


def test_roundtrip_nested():
    req = msg.RegisterPeerRequest(
        peer_id="p1",
        task_id="t1",
        host=msg.HostInfo(host_id="h1", ip="10.0.0.1", idc="idc-a"),
        content_length=1234,
    )
    out = wire.decode(wire.encode(req)[4:])
    assert out == req
    assert isinstance(out.host, msg.HostInfo)


def test_roundtrip_lists_and_bytes():
    resp = msg.NormalTaskResponse(
        peer_id="p1",
        candidate_parents=[
            msg.CandidateParent("pp", "hh", "1.2.3.4", 80, 81, "Running", 0.9)
        ],
    )
    out = wire.decode(wire.encode(resp)[4:])
    assert out.candidate_parents[0].download_port == 81

    train = msg.TrainRequest(
        host_id="h", ip="i", hostname="n", dataset="download", chunk=b"\x00\xffdata"
    )
    out = wire.decode(wire.encode(train)[4:])
    assert out.chunk == b"\x00\xffdata"


def test_unknown_type_rejected():
    class NotRegistered:
        pass

    with pytest.raises(TypeError):
        wire.encode(NotRegistered())


def test_stream_framing():
    async def run():
        reader = asyncio.StreamReader()
        messages = [
            msg.ProbeStartedRequest(host_id="h", count=3),
            msg.ProbeFinishedRequest(
                host_id="h", results=[msg.ProbeResult(host_id="d", rtt_ns=5)]
            ),
        ]
        for item in messages:
            reader.feed_data(wire.encode(item))
        reader.feed_eof()
        got = []
        while True:
            item = await wire.read_frame(reader)
            if item is None:
                break
            got.append(item)
        return messages, got

    messages, got = asyncio.run(run())
    assert got == messages
