"""Tensor parallelism: Megatron-style column/row-parallel feed-forward.

No analogue in the reference (it has no tensor compute, SURVEY.md §2.6);
this is the TPU-native scaling axis for wide model layers. The classic
two-matmul block needs exactly ONE collective:

    y = gelu(x @ W1 + b1) @ W2 + b2
        W1 [F, H] column-sharded over `tp` -> each device owns H/tp of the
        hidden; gelu is elementwise so it needs no exchange.
        W2 [H, F] row-sharded over `tp` -> partial [.., F] products,
        summed with one psum over the ICI ring.

Used standalone via `sharded_tp_ffn` (global shapes in/out) or composed
inside a larger shard_map with `tp_ffn`.
"""

from __future__ import annotations

import functools

import jax

from dragonfly2_tpu.utils.jaxcompat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.parallel.mesh import DP_AXIS, TP_AXIS


def tp_ffn(x, w1, b1, w2, b2, axis_name: str = TP_AXIS) -> jax.Array:
    """Inside shard_map: x [..., F] replicated over tp; w1 [F, H/tp],
    b1 [H/tp], w2 [H/tp, F] are the local shards; b2 [F] replicated.
    Returns the full [..., F] output on every device (one psum)."""
    h = jnp.dot(x, w1, preferred_element_type=jnp.float32) + b1
    h = jax.nn.gelu(h).astype(x.dtype)
    partial = jnp.dot(h, w2, preferred_element_type=jnp.float32)
    out = jax.lax.psum(partial, axis_name)
    return (out + b2).astype(x.dtype)


def sharded_tp_ffn(mesh, x, w1, b1, w2, b2) -> jax.Array:
    """shard_map wrapper: batch over dp, hidden over tp. Weights come in
    at global shape (W1 [F, H], W2 [H, F]) and are sharded on their
    hidden dim; x/output are batch-sharded and tp-replicated."""
    fn = shard_map(
        functools.partial(tp_ffn, axis_name=TP_AXIS),
        mesh=mesh,
        in_specs=(
            P(DP_AXIS),  # x: batch rows over dp, features replicated
            P(None, TP_AXIS),  # w1 columns over tp
            P(TP_AXIS),  # b1 follows w1's hidden shard
            P(TP_AXIS, None),  # w2 rows over tp
            P(),  # b2 replicated
        ),
        out_specs=P(DP_AXIS),
        check_vma=False,
    )
    return fn(x, w1, b1, w2, b2)
