"""Megascale run harness: one entry point the soak tests and
``bench_megascale.py`` share, so the artifact and the test suite measure
the same replay.

``run_megascale`` builds a scale-sized scheduler + event-batch engine
for a named megascale scenario ("planet" | "soak" | any builtin), drives
it for a number of rounds (default: one full compressed day plus a drain
tail), and returns the report dict — SimStats + MegaStats counters,
per-region completion percentiles, origin-traffic fraction,
quarantine/failover event counts, engine step-phase p50s, and peak RSS.
Everything except the ``timing`` sub-object is deterministic in
(scenario, hosts, seed); the determinism test pins that.
"""

from __future__ import annotations

import dataclasses
import time

from dragonfly2_tpu.megascale.engine import EventBatchEngine, megascale_service
from dragonfly2_tpu.scenarios.spec import builtin_scenarios, megascale_scenarios


def peak_rss_mb() -> float | None:
    """VmHWM from /proc (peak resident set of this process), in MiB."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    return None


def resolve_scenario(name: str):
    mega = megascale_scenarios()
    if name in mega:
        return mega[name]
    return builtin_scenarios()[name]


def run_megascale(
    scenario: str = "soak",
    num_hosts: int = 50_000,
    num_tasks: int = 96,
    seed: int = 7,
    rounds: int | None = None,
    arrivals_per_round: int | None = None,
    algorithm: str = "default",
    retire_after_rounds: int | None = 24,
    probe_every: int = 0,
    drain_rounds: int = 12,
    max_peers_per_task: int | None = None,
    wire_skew: dict | None = None,
    fleet_replicas: int | None = None,
) -> dict:
    """One megascale replay. `arrivals_per_round` defaults to ~1.5 total
    downloads per host spread over the day; `rounds` defaults to one
    compressed day plus `drain_rounds` of trailing arrivals-light rounds
    so in-flight downloads finish. Returns the report dict.

    `wire_skew` (a golden wire-schema dict, tools/dfwire_schema.json)
    turns on the mixed-version soak mode: every message-shaped
    control-plane exchange round-trips the real codec degraded to the
    N-1 snapshot (tools/dflint/wirefuzz.SkewProxy) — the rolling-upgrade
    soak then replays the whole compressed day over cross-version frames
    and the report grows a `wire_skew` block (frame counts per type +
    any codec mismatches) the skew gate asserts empty.

    `fleet_replicas` switches the control plane to a SchedulerFleet of
    that many task-sharded scheduler replicas behind one hashring
    (megascale/fleet.py) driven by the FleetEventBatchEngine; the report
    grows a deterministic `fleet` block (per-shard counts/digests/tail,
    handoff counters, crash-victim recovery) and a wall-derived
    `timing.fleet` block (modeled parallel wall + aggregate pieces/s).
    `fleet_replicas=1` is bit-identical to the plain run except for the
    extra fleet columns — the K=1 equivalence oracle test pins that."""
    spec = resolve_scenario(scenario)
    day = spec.traffic.day_rounds or 96
    if rounds is None:
        rounds = day + drain_rounds
    # a short run must still mostly be a LOADED run: clamp the drain
    # tail so an explicit --rounds below the default drain length does
    # not silently degrade into an all-idle replay
    drain_rounds = min(drain_rounds, max(rounds // 4, 1))
    if arrivals_per_round is None:
        arrivals_per_round = max(1, int(num_hosts * 1.5) // max(day, 1))
    # live-peer bound: arrivals x (retirement window + in-flight slack),
    # plus flash-crowd bursts and seed registrations
    window = (retire_after_rounds or rounds) + 16
    peak = arrivals_per_round * max(
        spec.traffic.peak_multiplier, 1.0
    ) + arrivals_per_round * spec.flash.arrival_multiplier * (
        1 if spec.flash.events_per_day else 0
    )
    max_live = int(peak * window) + 8192
    if max_peers_per_task is None:
        # hottest-swarm bound: top Zipf task share x arrivals x live
        # window, next power of two, clamped — a hot task past this cap
        # spills its overflow to origin (the refused-registration path),
        # exactly the tradeoff a production per-task peer limit makes
        hottest = int(arrivals_per_round * 0.15 * window * 2)
        max_peers_per_task = min(8192, max(2048, 1 << hottest.bit_length()))
    if fleet_replicas is not None:
        from dragonfly2_tpu.megascale.fleet import megascale_fleet

        svc = megascale_fleet(
            num_hosts, num_tasks=num_tasks, max_live_peers=max_live,
            algorithm=algorithm, seed=seed,
            max_peers_per_task=max_peers_per_task, replicas=fleet_replicas,
        )
    else:
        svc = megascale_service(
            num_hosts, num_tasks=num_tasks, max_live_peers=max_live,
            algorithm=algorithm, seed=seed,
            max_peers_per_task=max_peers_per_task,
        )
    driver = svc
    if wire_skew is not None:
        # Deliberate tooling import inside the opt-in skew mode ONLY
        # (ISSUE 15 places the skew harness with the rest of dfwire in
        # tools/dflint/): production replays never enter this branch,
        # so a deployment without the repo's tools/ tree is unaffected.
        from tools.dflint.wirefuzz import SkewProxy

        driver = SkewProxy(svc, wire_skew)
    # pre-compile the eval-bucket device programs during setup: a lazy
    # XLA compile mid-day lands its seconds on whichever shard first
    # ticks the new batch shape, skewing the fleet's per-shard capacity
    # ledger with one-off cold-start noise (production replicas warm
    # their caches before joining the serving ring for the same reason)
    svc.warmup()
    t0 = time.perf_counter()
    if fleet_replicas is not None:
        from dragonfly2_tpu.megascale.fleet import FleetEventBatchEngine

        sim = FleetEventBatchEngine(
            driver, fleet=svc, num_hosts=num_hosts, num_tasks=num_tasks,
            seed=seed, scenario=spec, retire_after_rounds=retire_after_rounds,
        )
    else:
        sim = EventBatchEngine(
            driver, num_hosts=num_hosts, num_tasks=num_tasks, seed=seed,
            scenario=spec, retire_after_rounds=retire_after_rounds,
        )
    setup_s = time.perf_counter() - t0

    t1 = time.perf_counter()
    for r in range(rounds):
        sim.run_round(arrivals_per_round if r < rounds - drain_rounds else 1)
        if probe_every and (r + 1) % probe_every == 0:
            sim.run_probe_round(sources=8)
    wall = time.perf_counter() - t1

    st = sim.stats
    # Scheduler-kill recovery, measured from timeline data rather than
    # asserted from end aggregates: per kill round, the pieces-per-round
    # dip and the simulated time until the rate recovers to >=90% of its
    # pre-kill baseline (telemetry/timeline.recovery_time).
    from dragonfly2_tpu.telemetry.timeline import recovery_time

    tl = sim.timeline.timeline()
    recovery = [
        {
            "round": r,
            "sim_minutes": round(r * sim.minutes_per_round, 2),
            **recovery_time(tl, "pieces", r, baseline_window=8,
                            threshold=0.9),
        }
        for r in sim._crash_rounds
    ]
    for entry in recovery:
        if entry["recovery_intervals"] is not None:
            entry["recovery_sim_minutes"] = round(
                entry["recovery_intervals"] * sim.minutes_per_round, 2
            )
    report = {
        "scenario": scenario,
        "hosts": num_hosts,
        "tasks": num_tasks,
        "seed": seed,
        "rounds": rounds,
        "arrivals_per_round": arrivals_per_round,
        "algorithm": algorithm,
        "stats": dataclasses.asdict(st),
        "mega": dataclasses.asdict(sim.mega),
        **sim.region_report(),
        "fault_schedule_digest": sim.fault_schedule_digest(),
        # the per-round soak timeline (deterministic, event-clocked) +
        # its annotated fault events and the measured kill recovery
        "timeline": tl,
        "timeline_events": list(sim.timeline.events),
        # pure preview of the kill schedule (scenarios/engine.crash_rounds)
        # — must equal the rounds the timeline actually marked, or the
        # engine and the annotation have drifted
        "expected_crash_rounds": (
            sim.engine.crash_rounds(rounds) if sim.engine is not None else []
        ),
        "minutes_per_round": sim.minutes_per_round,
        "recovery": recovery,
        "fault_families": {
            # the soak acceptance gate: every family nonzero in one run
            "chaos": st.injected_scheduler_crashes + st.injected_partition_drops,
            "corruption": st.injected_corruptions,
            "churn": st.injected_crashes + st.injected_host_leaves,
            "flash_crowds": sim.mega.flash_arrivals,
        },
        "quarantine": {
            "corruption_reports": st.injected_corruptions,
            "quarantined_hosts_final": svc.quarantine.active_count(),
        },
        "failover": {
            "scheduler_crashes": st.injected_scheduler_crashes,
            "crash_reannounced_peers": st.crash_reannounced_peers,
            "partition_drops": st.injected_partition_drops,
        },
        "scheduler_counts": svc.counts(),
        # decision provenance (telemetry/decisions.py): deterministic
        # counters + divergence/regret aggregates and the ledger's
        # deterministic-column digest — the paired-seed determinism test
        # pins the digest identical across runs (wall-clock columns are
        # excluded from it by construction)
        "decisions": _decision_report(svc),
        # SLO engine output (telemetry/slo.py): final verdict, alert
        # fire/clear log on the event clock, per-objective budget
        # remaining — deterministic in (scenario, hosts, seed), so it
        # rides deterministic_view and the paired-seed test pins it;
        # tools/dfslo.py reproduces the same block offline from the
        # `timeline` array above
        "slo": _slo_report(sim),
        # tail attribution (telemetry/tailtrace.py): per-region TTC
        # decomposition quantiles, phase shares, dominant-phase
        # histogram, kill-window attribution over the crash rounds,
        # exemplars and the paired-seed-pinned digest — deterministic,
        # so it rides deterministic_view; tools/dftail.py recomputes
        # the window/dominant view offline from this block alone
        "tail": _tail_report(sim),
        "timing": {
            "setup_s": round(setup_s, 2),
            "wall_s": round(wall, 2),
            "pieces_per_sec": round(st.pieces / max(wall, 1e-9), 1),
            "events_per_sec": round(sim.mega.piece_events / max(wall, 1e-9), 1),
            "step_phases_p50_ms": sim.recorder.phase_p50s(),
            "tick_phases_p50_ms": svc.recorder.phase_p50s(),
            "peak_rss_mb": peak_rss_mb(),
        },
        # compiler-measured cost cards for the serving programs this run
        # compiled (telemetry/costcard.py; platform-dependent like
        # `timing`, so deterministic_view strips it)
        "costcards": _drained_costcards(),
    }
    if fleet_replicas is not None:
        # sharded-control-plane block (megascale/fleet.py): handoff
        # counters, per-shard counts/decision digests/tail attribution,
        # the crash-victim schedule with per-victim recovery measured on
        # the victim shard's own piece series — deterministic, rides
        # deterministic_view; the wall-derived scaling numbers (modeled
        # parallel wall, aggregate pieces/s — the 1-vs-K artifact) go
        # under `timing` with the other clock-dependent fields
        report["fleet"] = sim.fleet_report()
        report["timing"]["fleet"] = sim.fleet_timing(wall)
    if wire_skew is not None:
        # mixed-version wire evidence: which frame types actually crossed
        # the skewed codec, and any round-trip mismatch (must be empty —
        # the skew soak gate asserts on it)
        report["wire_skew"] = driver.report()
    return report


def _tail_report(sim) -> dict:
    """The megascale run's tail block (telemetry/tailtrace.report),
    windowed over the rounds the scheduler actually died plus the
    per-round phase matrix — the offline basis tools/dftail.py replays
    the window attribution from."""
    report = sim.tail.report(crash_rounds=sim._crash_rounds)
    report["round_phase_ms"] = sim.tail.round_phase_matrix_ms()
    report["round_slow_ms"] = sim.tail.round_slow_matrix_ms()
    report["crash_rounds"] = [int(r) for r in sim._crash_rounds]
    return report


def _slo_report(sim) -> dict:
    """The megascale run's SLO block: the engine's flattened report
    (telemetry/slo.slo_report) — verdict, pages/tickets fired, budget
    burn, the alert transition log keyed by event-clock round."""
    from dragonfly2_tpu.telemetry.slo import slo_report

    return slo_report(sim.slo)


def _decision_report(svc) -> dict | None:
    """Deterministic decision-ledger block for the megascale report:
    the ledger's flattened report MINUS the wall-derived TTC keys (the
    paired-seed determinism test compares this block), plus the
    deterministic-column digest."""
    led = getattr(svc, "decisions", None)
    if led is None:
        return None
    r = led.report()
    return {
        key: r[key] for key in (
            "decisions", "joined", "shadow_compared", "shadow_top1_disagree",
            "top1_disagreement", "rank_corr", "n_disagreements",
            "regret_fail_rate", "regret_fail_rate_by_arm",
        )
    } | {"columns_digest": led.deterministic_digest()}


def _drained_costcards() -> dict:
    """Drain pending cost-card captures and return the ledger dump —
    the report assembly is the megascale run's off-hot-path drain
    point (the engine's tick path never compiles cost analyses)."""
    from dragonfly2_tpu.telemetry import costcard

    costcard.capture_pending()
    return costcard.ledger().dump()


def deterministic_view(report: dict) -> dict:
    """The report minus wall-clock/platform-dependent fields (same
    contract as scenarios/ab.deterministic_view). The `timeline` array
    STAYS — its samples are event-clocked by construction, and the
    determinism test pinning this view is what keeps them that way.
    `wire_skew` is excluded too: the block is deterministic but only a
    skew-mode run carries it, and the documented contract is that a
    skew run's view compares EQUAL to the plain run's."""
    return {
        k: v for k, v in report.items()
        if k not in ("timing", "costcards", "wire_skew")
    }
