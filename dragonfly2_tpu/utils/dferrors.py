"""Coded errors crossing service boundaries.

Capability parity with internal/dferrors (gRPC-status-shaped errors the
reference threads through streams) plus the common codes the services
raise. Host-side control-plane code raises these; the message layer
(cluster/messages.py ScheduleFailure) carries code+message across the
in-proc or socket boundary.
"""

from __future__ import annotations

import enum


class Code(enum.Enum):
    OK = "OK"
    CANCELLED = "Cancelled"
    INVALID_ARGUMENT = "InvalidArgument"
    NOT_FOUND = "NotFound"
    ALREADY_EXISTS = "AlreadyExists"
    PERMISSION_DENIED = "PermissionDenied"
    RESOURCE_EXHAUSTED = "ResourceExhausted"
    FAILED_PRECONDITION = "FailedPrecondition"
    UNAVAILABLE = "Unavailable"
    UNAUTHENTICATED = "Unauthenticated"
    INTERNAL = "Internal"
    DEADLINE_EXCEEDED = "DeadlineExceeded"
    DATA_LOSS = "DataLoss"


class DFError(Exception):
    code: Code = Code.INTERNAL

    def __init__(self, message: str = "", code: Code | None = None):
        if code is not None:
            self.code = code
        super().__init__(message or self.code.value)
        self.message = message

    def to_wire(self) -> dict:
        return {"code": self.code.value, "message": self.message}

    @staticmethod
    def from_wire(d: dict) -> "DFError":
        try:
            code = Code(d.get("code", Code.INTERNAL.value))
        except ValueError:  # unknown code from a newer/corrupt peer
            code = Code.INTERNAL
        cls = _BY_CODE.get(code, DFError)
        return cls(d.get("message", ""), code=code)


class InvalidArgument(DFError):
    code = Code.INVALID_ARGUMENT


class NotFound(DFError):
    code = Code.NOT_FOUND


class AlreadyExists(DFError):
    code = Code.ALREADY_EXISTS


class PermissionDenied(DFError):
    code = Code.PERMISSION_DENIED


class ResourceExhausted(DFError):
    code = Code.RESOURCE_EXHAUSTED


class FailedPrecondition(DFError):
    code = Code.FAILED_PRECONDITION


class Unavailable(DFError):
    code = Code.UNAVAILABLE


class Unauthenticated(DFError):
    code = Code.UNAUTHENTICATED


class DeadlineExceeded(DFError):
    code = Code.DEADLINE_EXCEEDED


class DataLoss(DFError):
    """Bytes crossing a trust boundary failed an integrity check."""

    code = Code.DATA_LOSS


class PieceCorrupted(DataLoss):
    """A fetched piece's digest does not match its attested digest — the
    parent served corrupt bytes (or they were corrupted in flight). The
    bytes are never committed; the failure report carries
    reason="corruption" so the scheduler can quarantine the parent."""


class TaskIntegrityError(DataLoss):
    """A task's stored state is internally inconsistent at completion
    time: piece holes in the finished bitset, summed piece lengths that
    disagree with the content length, or a whole-task digest mismatch."""


_BY_CODE = {cls.code: cls for cls in DFError.__subclasses__()}
