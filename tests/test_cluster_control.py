"""Scheduler service tests: the announce-stream protocol against a live
service with in-memory state (SURVEY.md §4 tier 1: multi-node logic driven
without a cluster)."""

import numpy as np
import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.probes import ProbeStore
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.records.storage import TraceStorage
from dragonfly2_tpu.state.fsm import PeerState


def host(i, seed=False, idc="idc-a"):
    return msg.HostInfo(
        host_id=f"host-{i}",
        hostname=f"node-{i}",
        ip=f"10.0.0.{i}",
        host_type="super" if seed else "normal",
        idc=idc,
        location=f"na|zone-1|rack-{i % 4}",
    )


def register(svc, peer_id, task_id, h, pieces=4):
    return svc.register_peer(
        msg.RegisterPeerRequest(
            peer_id=peer_id,
            task_id=task_id,
            host=h,
            url="https://e.com/blob",
            content_length=pieces * (4 << 20),
            total_piece_count=pieces,
        )
    )


def seeded_service(storage=None, config=None):
    svc = SchedulerService(config=config, storage=storage)
    # a seed peer that has succeeded -> eligible parent
    register(svc, "seed-peer", "task-1", host(0, seed=True))
    svc.peer_finished(msg.DownloadPeerFinishedRequest(peer_id="seed-peer", piece_count=4))
    svc.tick()  # flush seed's own (now moot) pending entry
    return svc


def test_register_and_schedule_from_seed():
    svc = seeded_service()
    assert register(svc, "child-1", "task-1", host(1)) is None
    responses = svc.tick()
    normal = [r for r in responses if isinstance(r, msg.NormalTaskResponse)]
    assert len(normal) == 1
    assert normal[0].peer_id == "child-1"
    parents = normal[0].candidate_parents
    assert parents and parents[0].peer_id == "seed-peer"
    assert parents[0].state == "Succeeded"
    # DAG edge exists: seed -> child
    meta_child = svc._peer_meta["child-1"]
    meta_seed = svc._peer_meta["seed-peer"]
    assert svc._task_dag("task-1").has_edge(meta_seed.dag_slot, meta_child.dag_slot)
    # parent's host upload slot consumed
    seed_host_idx = svc.state.host_index("host-0")
    assert svc.state.host_upload_used[seed_host_idx] == 1


def test_double_register_is_idempotent():
    """Re-register of a live peer is load-not-create (service_v2
    handleResource), not an FSM violation."""
    svc = seeded_service()
    register(svc, "child-1", "task-1", host(1))
    register(svc, "child-1", "task-1", host(1))  # duplicate
    assert svc.counts()["peers"] == 2  # seed + child, not 3
    responses = svc.tick()
    assert sum(isinstance(r, msg.NormalTaskResponse) for r in responses) == 1


def test_empty_scope_fast_path():
    svc = SchedulerService()
    resp = svc.register_peer(
        msg.RegisterPeerRequest(
            peer_id="p-empty", task_id="t-empty", host=host(5), content_length=0
        )
    )
    assert isinstance(resp, msg.EmptyTaskResponse)
    idx = svc.state.peer_index("p-empty")
    assert svc.state.peer_state[idx] == int(PeerState.RECEIVED_EMPTY)


def test_reschedule_blocklists_parent():
    svc = seeded_service()
    register(svc, "child-1", "task-1", host(1))
    svc.tick()
    svc.reschedule(
        msg.RescheduleRequest(peer_id="child-1", candidate_parent_ids=["seed-peer"])
    )
    responses = svc.tick()
    # only candidate is blocklisted -> no NormalTaskResponse for child-1
    assert not any(
        isinstance(r, msg.NormalTaskResponse) and r.peer_id == "child-1" for r in responses
    )
    assert "child-1" in svc._pending


def test_retries_escalate_to_back_to_source_then_failure():
    svc = SchedulerService()  # no parents at all
    register(svc, "lonely", "task-x", host(2))
    responses = []
    for _ in range(10):
        responses += svc.tick()
        if responses:
            break
    # with zero candidates, retries grow until back-to-source is offered
    b2s = [r for r in responses if isinstance(r, msg.NeedBackToSourceResponse)]
    assert b2s and b2s[0].peer_id == "lonely"
    # simulate the peer going back to source and finishing
    svc.back_to_source_started(msg.DownloadPeerBackToSourceStartedRequest(peer_id="lonely"))
    svc.back_to_source_finished(
        msg.DownloadPeerBackToSourceFinishedRequest(peer_id="lonely", piece_count=4)
    )
    idx = svc.state.peer_index("lonely")
    assert svc.state.peer_state[idx] == int(PeerState.SUCCEEDED)


def test_retry_limit_failure_when_b2s_exhausted():
    cfg = Config()
    cfg.scheduler.retry_back_to_source_limit = 1
    svc = SchedulerService(config=cfg)
    register(svc, "lonely", "task-x", host(2), pieces=4)
    # consume the task's back-to-source budget
    t = svc.state.task_index("task-x")
    svc.state.task_back_to_source_count[t] = svc.state.task_back_to_source_limit[t]
    failures = []
    for _ in range(10):
        failures += [r for r in svc.tick() if isinstance(r, msg.ScheduleFailure)]
        if failures:
            break
    assert failures and "RetryLimit" in failures[0].description


def test_piece_and_peer_finished_bookkeeping(tmp_path):
    storage = TraceStorage(tmp_path)
    svc = seeded_service(storage=storage)
    register(svc, "child-1", "task-1", host(1))
    svc.tick()
    for piece in range(4):
        svc.piece_finished(
            msg.DownloadPieceFinishedRequest(
                peer_id="child-1",
                piece_number=piece,
                length=4 << 20,
                cost_ns=50_000_000,
                parent_peer_id="seed-peer",
            )
        )
    # piece reports buffer until the next tick/flush valve (columnar
    # report_ingest); force column visibility before asserting
    svc.flush_piece_reports()
    child_idx = svc.state.peer_index("child-1")
    assert svc.state.peer_finished_count[child_idx] == 4
    seed_host_idx = svc.state.host_index("host-0")
    assert svc.state.host_upload_count[seed_host_idx] == 4
    assert svc.state.host_upload_used[seed_host_idx] == 1

    svc.peer_finished(msg.DownloadPeerFinishedRequest(peer_id="child-1", piece_count=4))
    assert svc.state.peer_state[child_idx] == int(PeerState.SUCCEEDED)
    assert svc.state.host_upload_used[seed_host_idx] == 0  # slot released

    records = storage.list_downloads()
    child_records = [r for r in records if r.id == "child-1"]
    assert len(child_records) == 1
    rec = child_records[0]
    assert rec.state == "Succeeded"
    assert rec.task.id == "task-1"
    assert len(rec.parents) == 1 and rec.parents[0].id == "seed-peer"
    assert len(rec.parents[0].pieces) == 4
    assert rec.parents[0].pieces[0].cost == 50_000_000


def test_piece_failed_reschedules_and_counts():
    svc = seeded_service()
    register(svc, "child-1", "task-1", host(1))
    svc.tick()
    svc.piece_failed(
        msg.DownloadPieceFailedRequest(peer_id="child-1", parent_peer_id="seed-peer")
    )
    seed_host_idx = svc.state.host_index("host-0")
    assert svc.state.host_upload_failed[seed_host_idx] == 1
    assert "child-1" in svc._pending
    assert "seed-peer" in svc._pending["child-1"].blocklist


def test_reschedule_releases_upload_slots():
    """Dropping parents must free their hosts' upload slots; repeated
    reschedules must not leak (code-review regression)."""
    svc = seeded_service()
    register(svc, "child-1", "task-1", host(1))
    svc.tick()
    seed_host_idx = svc.state.host_index("host-0")
    assert svc.state.host_upload_used[seed_host_idx] == 1
    for _ in range(3):
        svc.reschedule(msg.RescheduleRequest(peer_id="child-1"))
        svc.tick()
    # slot count reflects at most the current edge, never accumulates
    assert svc.state.host_upload_used[seed_host_idx] <= 1
    svc.peer_finished(msg.DownloadPeerFinishedRequest(peer_id="child-1", piece_count=4))
    assert svc.state.host_upload_used[seed_host_idx] == 0


def test_leave_parent_releases_its_upload_slots():
    svc = seeded_service()
    register(svc, "child-1", "task-1", host(1))
    svc.tick()
    seed_host_idx = svc.state.host_index("host-0")
    assert svc.state.host_upload_used[seed_host_idx] == 1
    svc.leave_peer("seed-peer")
    assert svc.state.host_upload_used[seed_host_idx] == 0
    # child's held set no longer references the gone parent
    assert "seed-peer" not in svc._peer_meta["child-1"].held_parents


def test_snapshot_topology_includes_network_fields(tmp_path):
    from dragonfly2_tpu.cluster.probes import ProbeStore
    import numpy as np

    storage = TraceStorage(tmp_path)
    probes = ProbeStore(max_pairs=64, max_hosts=32)
    svc = SchedulerService(storage=storage, probes=probes)
    svc.announce_host(host(0, idc="idc-x"))
    svc.announce_host(host(1, idc="idc-y"))
    src = svc.state.host_index("host-0")
    dst = svc.state.host_index("host-1")
    probes.enqueue(np.array([src]), np.array([dst]), np.array([3e6], np.float32))
    assert svc.snapshot_topology(now_ns=5) == 1
    rec = storage.list_network_topologies()[0]
    assert rec.host.network.idc == "idc-x"
    assert rec.dest_hosts[0].network.idc == "idc-y"
    assert rec.host.network.location.startswith("na|")


def test_leave_host_drops_peers():
    svc = seeded_service()
    register(svc, "child-1", "task-1", host(1))
    svc.tick()
    svc.leave_host("host-1")
    assert svc.state.peer_index("child-1") is None
    assert svc.state.host_index("host-1") is None
    assert "child-1" not in svc._peer_meta


def test_nt_algorithm_uses_probe_store():
    cfg = Config()
    cfg.evaluator.algorithm = "nt"
    probes = ProbeStore(max_pairs=256, max_hosts=64)
    svc = SchedulerService(config=cfg, probes=probes)
    svc.algorithm = "nt"
    register(svc, "seed-peer", "task-1", host(0, seed=True))
    svc.peer_finished(msg.DownloadPeerFinishedRequest(peer_id="seed-peer", piece_count=4))
    svc.tick()
    register(svc, "child-1", "task-1", host(1))
    # probe parent-host -> child-host direction
    src = svc.state.host_index("host-0")
    dst = svc.state.host_index("host-1")
    probes.enqueue(np.array([src]), np.array([dst]), np.array([2e6], np.float32))
    responses = svc.tick()
    normal = [r for r in responses if isinstance(r, msg.NormalTaskResponse)]
    assert normal and normal[0].candidate_parents[0].peer_id == "seed-peer"


def test_plugin_evaluator_algorithm(tmp_path):
    """algorithm="plugin" loads an external scorer via utils/plugins and
    routes it through select_with_scores — the evaluator plugin path the
    reference loads from a .so (evaluator plugin.go, dfplugin.go:43-81).
    The plugin ranks by reversed candidate order, so with two eligible
    succeeded parents the one the default blend would rank lower wins."""
    (tmp_path / "df_evaluator_plugin_rev.py").write_text(
        "import numpy as np\n"
        "class Rev:\n"
        "    def evaluate(self, feats):\n"
        "        k = feats['valid'].shape[1]\n"
        "        return np.broadcast_to(\n"
        "            np.arange(k, 0, -1, dtype=np.float32), feats['valid'].shape\n"
        "        )\n"
        "def dragonfly_plugin_init(options):\n"
        "    return Rev()\n"
    )
    cfg = Config()
    cfg.evaluator.algorithm = "plugin"
    cfg.evaluator.plugin_dir = str(tmp_path)
    cfg.evaluator.plugin_name = "rev"
    svc = SchedulerService(config=cfg)
    assert svc.plugin_evaluator is not None

    register(svc, "seed-peer", "task-1", host(0, seed=True))
    svc.peer_finished(
        msg.DownloadPeerFinishedRequest(peer_id="seed-peer", piece_count=4)
    )
    svc.tick()
    assert register(svc, "child-1", "task-1", host(1)) is None
    responses = svc.tick()
    normal = [r for r in responses if isinstance(r, msg.NormalTaskResponse)]
    assert len(normal) == 1 and normal[0].peer_id == "child-1"
    parents = normal[0].candidate_parents
    # filter rules still apply: only the succeeded seed peer is eligible,
    # and its score comes from the plugin's constant-per-column ramp
    assert parents and parents[0].peer_id == "seed-peer"


def test_tick_bucketing_schedules_all_pending():
    """The tick pads its batch to fixed (64/256/1024) buckets so the jitted
    kernels compile at most three shapes; crossing a bucket boundary must
    not change scheduling results or drop pending peers."""
    from dragonfly2_tpu.cluster.scheduler import _bucket_rows, _pad_rows

    assert _bucket_rows(1) == 64 and _bucket_rows(64) == 64
    assert _bucket_rows(65) == 256 and _bucket_rows(1000) == 1024
    padded = _pad_rows(np.ones((3, 2), np.float32), 8)
    assert padded.shape == (8, 2) and padded[3:].sum() == 0

    svc = seeded_service()
    n = 70  # crosses the 64-row bucket into the 256 one
    for i in range(n):
        register(svc, f"child-{i}", "task-1", host(1 + (i % 200)))
    # A single tick may legitimately skip children (random candidate
    # sampling can miss the seed; parent upload slots bound attach rate) —
    # they stay pending and retry. Across a few ticks every child must be
    # scheduled, with none lost to the bucket-padding rows.
    scheduled: set[str] = set()
    for _ in range(20):
        for r in svc.tick():
            if isinstance(r, msg.NormalTaskResponse):
                scheduled.add(r.peer_id)
                assert r.candidate_parents, r.peer_id
        if len(scheduled) == n:
            break
    assert scheduled == {f"child-{i}" for i in range(n)}


def test_trigger_seed_download_named_vs_roundrobin():
    """A preheat may race the seed daemons' first announce: with no seed
    announced yet, BOTH the unnamed and the named trigger QUEUE (the
    unnamed one with an empty host_id — the RPC drain routes it to any
    seed that connects within the delivery TTL, so the job fails only if
    no seed ever appears, not if it is merely late). Neither may leak an
    unannounced host into the round-robin seed set used for other
    tasks."""
    svc = SchedulerService()
    # no seeds at all: both queue — unnamed with host_id="" for late
    # routing, named with the (not-yet-announced) requested host
    assert svc.trigger_seed_download("t-a", "http://o/f")
    assert svc.trigger_seed_download("t-b", "http://o/f", host_id="seed-not-yet")
    assert [t.host_id for t in svc.seed_triggers] == ["", "seed-not-yet"]
    assert svc._seed_hosts == []

    # once a real seed announces, round-robin only ever picks it
    register(svc, "seed-peer", "task-1", host(0, seed=True))
    assert svc.trigger_seed_download("t-c", "http://o/f")
    assert svc.seed_triggers[-1].host_id == host(0, seed=True).host_id
