"""TTL in-memory cache with a janitor thread.

Capability parity with the reference's pkg/cache (pkg/cache/cache.go: Set
:114, Add :155, Get :169, GetWithExpiration :186, Scan :88, Delete :227,
DeleteExpired :253, Keys :273, OnEvicted :288, Save/Load :298-372, Flush
:403, janitor :414-437). Backs dynconfig's on-disk fallback and any
host-side lookup state; device-resident state lives in state/ and
cluster/probes.py instead.
"""

from __future__ import annotations

import pickle
import threading
import time
import weakref
from typing import Any, Callable, Iterable

NO_EXPIRATION = 0.0


class CacheKeyExists(KeyError):
    pass


class Cache:
    """Thread-safe TTL cache. `default_expiration<=0` means never expire."""

    def __init__(self, default_expiration: float = NO_EXPIRATION, cleanup_interval: float = 0.0):
        self._default = default_expiration
        self._lock = threading.RLock()
        self._items: dict[str, tuple[Any, float]] = {}  # key -> (value, deadline or 0)
        self._on_evicted: Callable[[str, Any], None] | None = None
        self._janitor: threading.Thread | None = None
        self._stop = threading.Event()
        if cleanup_interval > 0:
            # Janitor holds only a weakref so an abandoned cache can be
            # collected (the reference uses runtime.SetFinalizer for the
            # same reason, pkg/cache/cache.go:451-467); the loop exits when
            # the cache dies or close() is called.
            self._janitor = threading.Thread(
                target=_janitor_loop,
                args=(weakref.ref(self), self._stop, cleanup_interval),
                daemon=True,
            )
            self._janitor.start()

    # ------------------------------------------------------------- writes

    def set(self, key: str, value: Any, ttl: float | None = None) -> None:
        deadline = self._deadline(ttl)
        with self._lock:
            self._items[key] = (value, deadline)

    def set_default(self, key: str, value: Any) -> None:
        self.set(key, value, None)

    def add(self, key: str, value: Any, ttl: float | None = None) -> None:
        """Set only if absent (or expired); raises CacheKeyExists otherwise."""
        with self._lock:
            if self._get_locked(key) is not None:
                raise CacheKeyExists(key)
            self._items[key] = (value, self._deadline(ttl))

    def delete(self, key: str) -> None:
        with self._lock:
            item = self._items.pop(key, None)
        if item is not None and self._on_evicted is not None:
            self._on_evicted(key, item[0])

    def flush(self) -> None:
        with self._lock:
            self._items.clear()

    # -------------------------------------------------------------- reads

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            item = self._get_locked(key)
        return default if item is None else item[0]

    def contains(self, key: str) -> bool:
        with self._lock:
            return self._get_locked(key) is not None

    def get_with_expiration(self, key: str) -> tuple[Any, float | None] | None:
        """Returns (value, deadline-or-None) for live keys, else None."""
        with self._lock:
            item = self._get_locked(key)
        if item is None:
            return None
        value, deadline = item
        return value, (deadline if deadline > 0 else None)

    def keys(self) -> list[str]:
        now = time.monotonic()
        with self._lock:
            return [k for k, (_, d) in self._items.items() if d <= 0 or d > now]

    def scan(self, prefix: str, limit: int = -1) -> list[str]:
        """Live keys with the given prefix (pkg/cache Scan — how the
        reference enumerates `networktopology:src:*` style keyspaces)."""
        out: list[str] = []
        for k in self.keys():
            if k.startswith(prefix):
                if 0 <= limit <= len(out):
                    break
                out.append(k)
        return out

    def item_count(self) -> int:
        with self._lock:
            return len(self._items)

    def items(self) -> dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            return {k: v for k, (v, d) in self._items.items() if d <= 0 or d > now}

    # --------------------------------------------------------- maintenance

    def on_evicted(self, fn: Callable[[str, Any], None] | None) -> None:
        self._on_evicted = fn

    def delete_expired(self) -> None:
        now = time.monotonic()
        evicted: list[tuple[str, Any]] = []
        with self._lock:
            for k in list(self._items):
                v, d = self._items[k]
                if 0 < d <= now:
                    del self._items[k]
                    evicted.append((k, v))
        if self._on_evicted is not None:
            for k, v in evicted:
                self._on_evicted(k, v)

    def close(self) -> None:
        self._stop.set()

    # --------------------------------------------------------- persistence

    def save_file(self, path: str) -> None:
        """Persist live items. Deadlines are converted to remaining TTL so a
        later load re-arms them against the new clock."""
        now = time.monotonic()
        with self._lock:
            dump = {
                k: (v, (d - now) if d > 0 else NO_EXPIRATION)
                for k, (v, d) in self._items.items()
                if d <= 0 or d > now
            }
        with open(path, "wb") as f:
            pickle.dump(dump, f)

    def load_file(self, path: str) -> None:
        with open(path, "rb") as f:
            dump = pickle.load(f)
        now = time.monotonic()
        with self._lock:
            for k, (v, ttl) in dump.items():
                if k not in self._items:
                    self._items[k] = (v, now + ttl if ttl > 0 else NO_EXPIRATION)

    # ------------------------------------------------------------ internal

    def _deadline(self, ttl: float | None) -> float:
        if ttl is None:
            ttl = self._default
        return time.monotonic() + ttl if ttl > 0 else NO_EXPIRATION

    def _get_locked(self, key: str):
        item = self._items.get(key)
        if item is None:
            return None
        _, deadline = item
        if 0 < deadline <= time.monotonic():
            return None
        return item

def _janitor_loop(cache_ref, stop: threading.Event, interval: float) -> None:
    while not stop.wait(interval):
        cache = cache_ref()
        if cache is None:
            return
        cache.delete_expired()
        del cache


def new_cache(default_expiration: float = NO_EXPIRATION, cleanup_interval: float = 0.0) -> Cache:
    return Cache(default_expiration, cleanup_interval)
