#!/usr/bin/env python
"""dfproc — run the real-process planet day and emit BENCH_proc.json.

Boots K real scheduler processes, M real dfdaemons, and a manager over
real sockets (procworld.ProcessPlanet), drives the compressed scenario
day through the real client path with process-level chaos (SIGKILL at
the spec's kill rounds, SIGSTOP partitions, rolling restarts), then
runs the SAME spec through the megascale simulator and writes the
sim-vs-real divergence report next to the planet's timeline+SLO run —
one artifact, bench_schema v2, replayable by ``tools/dfslo.py``
unchanged:

    python tools/dfproc.py --out BENCH_proc.json
    python tools/dfproc.py --scenario procday --rounds 12 --daemons 3
    python tools/dfslo.py BENCH_proc.json          # offline re-verdict

Exit codes: 0 = zero lost downloads AND every divergence metric inside
its declared band; 1 = a divergence band violated; 2 = lost downloads
or a planet failure (the invariant, not a tolerance).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from dragonfly2_tpu.procworld import (  # noqa: E402
    compute_divergence,
    real_facts,
    run_procday,
)
from tools.bench_schema import write_artifact  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="procday")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--schedulers", type=int, default=2)
    ap.add_argument("--daemons", type=int, default=3)
    ap.add_argument("--rounds", type=int, default=None,
                    help="default: the scenario's full compressed day")
    ap.add_argument("--tasks-per-round", type=int, default=4)
    ap.add_argument("--workdir", default=None,
                    help="planet state dir (default: a fresh temp dir)")
    ap.add_argument("--out", default="BENCH_proc.json")
    ap.add_argument("--sim-hosts", type=int, default=300,
                    help="host count for the divergence-side sim run")
    ap.add_argument("--no-sim", action="store_true",
                    help="skip the simulator leg (no divergence block)")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="dfproc-")
    print(f"dfproc: planet workdir {workdir}", flush=True)
    run = run_procday(
        workdir, scenario=args.scenario, seed=args.seed,
        schedulers=args.schedulers, daemons=args.daemons,
        rounds=args.rounds, tasks_per_round=args.tasks_per_round,
    )
    st = run["stats"]
    print(
        f"dfproc: {st['completed']} completed, {st['lost_downloads']} lost, "
        f"{st['kills']} kills, {st['failovers']} failovers, "
        f"{st['restarts']} restarts in {run['timing']['wall_s']}s",
        flush=True,
    )

    divergence = None
    if not args.no_sim:
        from dragonfly2_tpu.megascale.soak import run_megascale

        print("dfproc: running the same spec through the simulator…",
              flush=True)
        sim = run_megascale(
            args.scenario, num_hosts=args.sim_hosts, num_tasks=24,
            seed=args.seed, rounds=run["rounds"], arrivals_per_round=16,
        )
        divergence = compute_divergence(real_facts(run), sim)
        for name in sorted(divergence["metrics"]):
            m = divergence["metrics"][name]
            flag = "ok" if m["within"] else "OUT-OF-BAND"
            print(f"  {name}: real={m['real']} sim={m['sim']} "
                  f"value={m['value']} band={m['band']} {flag}")

    summary = {
        "scenario": run["scenario"],
        "completed": st["completed"],
        "lost_downloads": st["lost_downloads"],
        "kills": st["kills"],
        "restarts": st["restarts"],
        "escalations": st["escalations"],
        "pages_fired": run["slo"].get("pages_fired", 0),
        "verdict_final": run["slo"].get("verdict_final"),
        "divergence_all_within": (
            divergence["all_within"] if divergence else None
        ),
    }
    extra = {"proc": run.pop("proc")}
    if divergence is not None:
        extra["divergence"] = divergence
    write_artifact(args.out, sys.argv, summary, runs=[run], extra=extra)
    print(f"dfproc: wrote {args.out}", flush=True)

    if st["lost_downloads"] > 0:
        print("dfproc: LOST DOWNLOADS — the invariant failed", flush=True)
        return 2
    if divergence is not None and not divergence["all_within"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
