"""Streaming SLO engine, multi-window burn-rate alerting, and the health
verdict plane (ISSUE 14): window math, alert state machines, megascale
SLI derivation + offline replay (tools/dfslo.py), the /debug/health
merge, and the live scheduler wiring."""

import json
import pathlib

import pytest

from dragonfly2_tpu.telemetry import metrics as m
from dragonfly2_tpu.telemetry.slo import (
    DEFAULT_BURN_RULES,
    HEALTH_MAX_BYTES,
    MEGASCALE_TTC_P95_MS,
    SEVERITY_PAGE,
    SEVERITY_TICKET,
    BurnRateRule,
    SLOEngine,
    SLOSpec,
    _SlidingCounter,
    feed_megascale_sample,
    health_verdict,
    megascale_slo_specs,
    parse_health_query,
    replay_timeline,
    scheduler_slo_specs,
    slo_report,
)

ROOT = pathlib.Path(__file__).resolve().parents[1]


def _engine(objective=0.999, minutes_per_unit=15.0, **spec_kw):
    return SLOEngine(
        [SLOSpec("x", sli="s", objective=objective, **spec_kw)],
        minutes_per_unit=minutes_per_unit,
        registry=m.Registry(),
    )


def _warm(eng, rounds=8, good=100):
    for t in range(1, rounds + 1):
        eng.observe("s", good=good)
        eng.step(t)
    return rounds


# ------------------------------------------------------------ window math


def test_sliding_counter_window_sums_and_pruning():
    c = _SlidingCounter(bucket_minutes=15.0, max_minutes=60.0)
    for t, (g, b) in enumerate([(10, 0), (10, 0), (10, 5), (10, 0)]):
        c.observe(t * 15.0, g, b)
    assert c.totals(60.0, 45.0) == (40, 5)
    assert c.totals(30.0, 45.0) == (20, 5)
    # a window narrower than one bucket still reads the current bucket
    assert c.totals(5.0, 45.0) == (10, 0)
    # pruning: buckets older than max_minutes drop on append
    c.observe(200.0, 1, 0)
    assert c.totals(1000.0, 200.0) == (1, 0)


def test_spec_validation():
    with pytest.raises(ValueError):
        SLOSpec("bad", sli="s", objective=1.5)
    with pytest.raises(ValueError):
        SLOSpec("bad", sli="s", objective=0.99, window_minutes=0)
    with pytest.raises(ValueError):
        SLOEngine(
            [SLOSpec("dup", sli="s", objective=0.9),
             SLOSpec("dup", sli="s", objective=0.9)],
            registry=m.Registry(),
        )
    assert SLOSpec("b", sli="s", objective=0.99).budget == pytest.approx(0.01)


# ------------------------------------------------- burn-rate state machine


def test_page_fires_only_when_both_windows_burn():
    """The multi-window property: a hot long window alone (spike already
    past) must NOT page — both windows at/above the factor fire it, and
    the short window draining clears it."""
    eng = _engine()
    t = _warm(eng)
    # spike: one interval burns far past 14.4x on both windows -> page
    eng.observe("s", good=50, bad=50)
    out = eng.step(t + 1)
    assert out["verdict"] == "critical"
    fired = [e for e in eng.alert_log if e["event"] == "fired"]
    assert {(e["rule"], e["severity"]) for e in fired} == {
        ("fast_burn", SEVERITY_PAGE), ("slow_burn", SEVERITY_TICKET)
    }
    # next interval is clean: the 5m short window drains -> page clears
    # even though the 1h long window still contains the whole spike
    eng.observe("s", good=100)
    out = eng.step(t + 2)
    assert not any(
        c["severity"] == SEVERITY_PAGE for c in eng.verdict()["causes"]
    )
    cleared = [e for e in eng.alert_log if e["event"] == "cleared"]
    assert ("fast_burn",) in {(e["rule"],) for e in cleared}
    assert eng.pages_fired == 1


def test_min_events_abstains_on_thin_windows():
    eng = _engine(min_events=64)
    _warm(eng, rounds=2, good=4)
    eng.observe("s", bad=4)  # 100% errors, but only 12 events in window
    out = eng.step(3)
    assert out["verdict"] == "ok" and out["alerts_firing"] == 0


def test_budget_accounting():
    eng = _engine(objective=0.9, window_minutes=24 * 60.0)
    _warm(eng, rounds=4, good=90)
    eng.observe("s", good=0, bad=18)  # 18 bad of 378 total; allowed 37.8
    eng.step(5)
    ev = eng.dump()["evaluations"]["x"]
    assert ev["budget_remaining"] == pytest.approx(1 - 18 / 37.8, abs=1e-3)
    assert ev["error_rate"] == pytest.approx(18 / 378, abs=1e-4)
    report = slo_report(eng)
    assert report["budget_burn"] == pytest.approx(18 / 37.8, abs=1e-3)
    assert report["verdict_final"] in ("ok", "degraded", "critical")


def test_ticket_only_is_degraded():
    rules = (BurnRateRule("slow_burn", SEVERITY_TICKET, 360.0, 30.0, 6.0),)
    eng = SLOEngine(
        [SLOSpec("x", sli="s", objective=0.99, burn_rules=rules)],
        minutes_per_unit=15.0, registry=m.Registry(),
    )
    t = _warm(eng, rounds=2)
    eng.observe("s", good=50, bad=50)
    out = eng.step(t + 1)
    assert out["verdict"] == "degraded" and out["tickets_fired"] == 1
    causes = eng.verdict()["causes"]
    assert causes and causes[0]["severity"] == SEVERITY_TICKET


def test_engine_determinism_same_feed_same_alert_timeline():
    def run():
        eng = _engine()
        feed = [(100, 0)] * 8 + [(60, 40)] + [(100, 0)] * 6 + [(70, 30)]
        for t, (g, b) in enumerate(feed, start=1):
            eng.observe("s", good=g, bad=b)
            eng.step(t)
        return eng.dump()

    assert run() == run()


def test_metrics_exported():
    reg = m.Registry()
    eng = SLOEngine(
        [SLOSpec("x", sli="s", objective=0.999)],
        name=None, minutes_per_unit=15.0, registry=reg,
    )
    t = _warm(eng)
    eng.observe("s", good=10, bad=90)
    eng.step(t + 1)
    text = reg.expose()
    assert 'dragonfly_slo_verdict_state{source="slo"} 2.0' in text
    assert 'dragonfly_slo_alerts_fired_total{source="slo",slo="x",rule="fast_burn",severity="page"} 1.0' in text
    assert 'dragonfly_slo_budget_remaining{source="slo",slo="x"}' in text
    assert 'dragonfly_slo_sli_events_total{source="slo",sli="s",outcome="bad"} 90.0' in text
    assert 'window="short"' in text and 'window="long"' in text


# --------------------------------------------------- megascale derivation


def _clean_sample(t, regions=("region-0", "region-1")):
    return {
        "t": float(t),
        "pieces": 1000, "completed": 60, "corruptions": 0,
        "origin_fraction": 0.05, "reannounce_backlog": 0,
        "breaker_open": 0,
        "ttc_ms_p95": {r: 4000.0 for r in regions},
    }


def test_megascale_feed_clean_day_zero_alerts():
    eng = SLOEngine(
        megascale_slo_specs(["region-0", "region-1"]),
        minutes_per_unit=15.0, registry=m.Registry(),
    )
    for t in range(1, 30):
        feed_megascale_sample(eng, _clean_sample(t))
    assert eng.pages_fired == 0 and eng.tickets_fired == 0
    assert eng.verdict()["state"] == "ok"
    assert not eng.alert_log


def test_megascale_feed_kill_spike_pages_and_clears():
    """A re-announce spike (the scheduler-kill signature) fires the
    announce_stability fast-burn page at the spike interval and clears
    it within one interval of the short window draining."""
    samples = [_clean_sample(t) for t in range(1, 30)]
    samples[14]["reannounce_backlog"] = 50  # t=15: kill wipes peers
    result = replay_timeline(samples, minutes_per_unit=15.0)
    fired = [e for e in result["alert_log"]
             if e["event"] == "fired" and e["severity"] == SEVERITY_PAGE]
    assert [e["t"] for e in fired] == [15.0]
    assert fired[0]["slo"] == "announce_stability"
    cleared = [e for e in result["alert_log"]
               if e["event"] == "cleared" and e["rule"] == "fast_burn"]
    assert cleared and cleared[0]["t"] <= 17.0
    assert result["paged"] and result["pages_fired"] == 1
    # the verdict columns ride every sample; the spike interval is the
    # critical one
    by_t = {c["t"]: c for c in result["samples"]}
    assert by_t[15.0]["slo_verdict"] == 2
    assert by_t[14.0]["slo_verdict"] == 0


def test_megascale_ttc_and_breaker_slis():
    eng = SLOEngine(
        megascale_slo_specs(["region-0"]),
        minutes_per_unit=15.0, registry=m.Registry(),
    )
    for t in range(1, 10):
        s = _clean_sample(t, regions=("region-0",))
        if t >= 5:
            s["ttc_ms_p95"]["region-0"] = MEGASCALE_TTC_P95_MS * 3
            s["breaker_open"] = 3
        feed_megascale_sample(eng, s)
    d = eng.dump()
    assert d["evaluations"]["ttc_region-0"]["bad_events"] > 0
    assert d["evaluations"]["breaker_health"]["bad_events"] > 0
    # sustained breaker-open intervals page the breaker SLO
    assert any(
        e["slo"] == "breaker_health" and e["event"] == "fired"
        for e in eng.alert_log
    )


def test_replay_timeline_deterministic_and_pure():
    samples = [_clean_sample(t) for t in range(1, 20)]
    samples[9]["reannounce_backlog"] = 40
    r1 = replay_timeline(samples, 15.0)
    r2 = replay_timeline(samples, 15.0)
    assert r1 == r2
    # replay ignores any recorded slo_* columns (pure function of the
    # raw sample columns): pre-annotated samples replay identically
    annotated = [dict(s, slo_verdict=1, slo_pages_fired=9) for s in samples]
    assert replay_timeline(annotated, 15.0) == r1


# ------------------------------------------------------ the verdict plane


def test_parse_health_query():
    assert parse_health_query("") == {}
    assert parse_health_query("last_n=4&max_bytes=4096") == {
        "last_n": 4, "max_bytes": 4096,
    }
    assert parse_health_query("max_bytes=10")["max_bytes"] == 1024  # floor
    with pytest.raises(ValueError):
        parse_health_query("last_n=banana")
    with pytest.raises(ValueError):
        parse_health_query("max_bytes=nope")


def test_health_verdict_merges_worst_wins_and_caps():
    import gc

    ok_eng = SLOEngine(
        [SLOSpec("fine", sli="s", objective=0.9)],
        name="test.hv-ok", minutes_per_unit=15.0, registry=m.Registry(),
    )
    ok_eng.observe("s", good=100)
    ok_eng.step(1)
    bad_eng = _engine()
    bad_eng.name = "test.hv-bad"
    from dragonfly2_tpu.telemetry.slo import register_engine

    register_engine("test.hv-bad", bad_eng)
    t = _warm(bad_eng)
    for i in range(400):  # fire/clear churn to grow the alert log
        bad_eng.observe("s", good=10, bad=90) if i % 2 == 0 \
            else bad_eng.observe("s", good=100)
        bad_eng.step(t + 1 + i)
    # leave the page FIRING so the merged verdict is critical
    bad_eng.observe("s", good=10, bad=90)
    bad_eng.step(t + 401)
    assert bad_eng.verdict()["state"] == "critical"
    try:
        body = health_verdict(last_n=256, max_bytes=None)
        assert body["state"] == "critical"  # worst of {ok, critical} wins
        assert "test.hv-ok" in body["sources"]
        assert "test.hv-bad" in body["sources"]
        assert body["slos"]["test.hv-ok"]["state"] == "ok"
        # the hard cap sheds the alert log oldest-first with a marker
        capped = health_verdict(last_n=512, max_bytes=2048)
        size = len(json.dumps(capped, separators=(",", ":"), default=str))
        assert size <= 2048, size
        assert capped["truncated"]["dropped_alert_log"] > 0
        assert capped["state"] == "critical"  # verdict survives shedding
        roomy = health_verdict(max_bytes=HEALTH_MAX_BYTES)
        assert "truncated" not in roomy
    finally:
        del ok_eng, bad_eng
        gc.collect()


# --------------------------------------------------- live scheduler wiring


def test_scheduler_tick_feeds_live_slo_engine():
    """The live service keeps tick-latency/shadow-regret/breaker SLIs on
    the wall clock: ticks observe into scheduler.slo, the engine rides
    the flight dump's slo section, and a healthy loop reads ok."""
    from dragonfly2_tpu.cluster import messages as msg
    from dragonfly2_tpu.cluster.scheduler import SchedulerService

    svc = SchedulerService(metrics_registry=m.Registry())
    assert svc.slo is not None
    h = msg.HostInfo(
        host_id="slo-h0", hostname="slo-n0", ip="10.9.9.1",
        host_type="super", idc="idc", location="na|z|r",
    )
    svc.register_peer(msg.RegisterPeerRequest(
        peer_id="slo-seed", task_id="slo-task", host=h,
        url="https://e.com/blob", content_length=4 * (4 << 20),
        total_piece_count=4,
    ))
    svc.peer_finished(msg.DownloadPeerFinishedRequest(
        peer_id="slo-seed", piece_count=4
    ))
    for i in range(6):
        hi = msg.HostInfo(
            host_id=f"slo-h{i+1}", hostname=f"slo-n{i+1}",
            ip=f"10.9.9.{i+2}", host_type="normal", idc="idc",
            location="na|z|r",
        )
        svc.register_peer(msg.RegisterPeerRequest(
            peer_id=f"slo-p{i}", task_id="slo-task", host=hi,
            url="https://e.com/blob", content_length=4 * (4 << 20),
            total_piece_count=4,
        ))
        svc.tick()
    d = svc.slo.dump()
    assert d["evaluations"]["tick_latency"]["events"] >= 6
    assert {"tick_latency", "shadow_regret", "breaker_health"} == set(
        svc.slo.specs
    )
    assert set(s.name for s in scheduler_slo_specs(250.0)) == set(svc.slo.specs)
    # healthy loop: nothing fires
    assert d["verdict"]["state"] == "ok"
    # the engine rides the service's flight dump behind the section knob
    dump = svc.flight_dump(sections=("slo",))
    assert "scheduler.slo" in dump["slo"]
    # config off-switch
    from dragonfly2_tpu.config.config import Config

    cfg = Config()
    cfg.scheduler.slo_enabled = False
    svc2 = SchedulerService(config=cfg, metrics_registry=m.Registry())
    assert svc2.slo is None
    svc2.tick()  # no SLO engine, no crash


# ------------------------------------------------------- offline judgment


def test_dfslo_judges_synthetic_artifacts(tmp_path):
    import tools.dfslo as dfslo

    clean = {
        "runs": [{
            "scenario": "planet", "hosts": 64, "minutes_per_round": 15.0,
            "timeline": [_clean_sample(t) for t in range(1, 20)],
        }],
    }
    rc, results = dfslo.judge(clean)
    assert rc == 0 and not results[0]["paged"]
    spiky = {
        "scenario": "soak", "hosts": 64, "minutes_per_round": 15.0,
        "timeline": [_clean_sample(t) for t in range(1, 20)],
    }
    spiky["timeline"][9]["reannounce_backlog"] = 40
    rc, results = dfslo.judge({"runs": [spiky]})
    assert rc == 2 and results[0]["paged"]
    # CLI contract: exit code rides out of main(), drift is detected
    path = tmp_path / "mega.json"
    path.write_text(json.dumps({"runs": [spiky]}))
    assert dfslo.main([str(path)]) == 2
    # a doctored recorded judgment is reported as drift (exit 2)
    doctored = dict(spiky)
    doctored["slo"] = {"pages_fired": 0, "tickets_fired": 0,
                       "verdict_final": "ok", "alert_log": []}
    rc, results = dfslo.judge({"runs": [doctored]})
    assert rc == 2 and results[0]["recorded_drift"]


def test_dfslo_reproduces_checked_in_bench_mega_verdicts():
    """THE acceptance gate (ISSUE 14): the checked-in BENCH_mega
    artifact replays offline to the same verdicts the runs recorded —
    the soak's scheduler kills paged, the clean planet day fired ZERO
    alerts (the alert-noise gate), and neither run drifts from its
    recorded judgment."""
    import tools.dfslo as dfslo

    doc = json.loads((ROOT / "BENCH_mega.json").read_text())
    rc, results = dfslo.judge(doc)
    by_scenario = {}
    for r in results:
        by_scenario[r["run"].rsplit("_", 1)[0]] = r
    assert "planet" in by_scenario and "soak" in by_scenario, by_scenario
    planet, soak = by_scenario["planet"], by_scenario["soak"]
    # alert-noise gate: a clean planet day fires NOTHING
    assert planet["pages_fired"] == 0 and planet["tickets_fired"] == 0, planet
    assert planet["verdict_final"] == "ok"
    # the soak's mid-day scheduler kills page
    assert soak["pages_fired"] > 0 and soak["paged"]
    assert rc == 2
    # offline replay == recorded judgment, bit for bit
    assert not planet["recorded_drift"], planet["recorded_drift"]
    assert not soak["recorded_drift"], soak["recorded_drift"]
