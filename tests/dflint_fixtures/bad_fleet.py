"""dflint red fixture: DET001 (process rng picking the crash victim) +
DET002 (wall clock deciding a replica's down window) + DET003 (set-ordered
iteration over the in-flight peers in a ring-rebalance sweep) — in a file
the test configures as a decision module, the way megascale/fleet.py is
in the real DET domain."""

import random
import time


class BadFleet:
    def __init__(self, k):
        self.k = k
        self.in_flight = set()
        self.down_until = {}

    def crash_victim(self):
        # a process-global rng makes the victim schedule differ between
        # paired-seed runs — the K=1 equivalence oracle breaks
        return random.randrange(self.k)  # <- DET001

    def shard_is_down(self, shard):
        # wall-clock down windows make the handoff stream depend on
        # machine load instead of the round counter
        return self.down_until.get(shard, 0) > time.time()  # <- DET002

    def rebalance(self, owner_of):
        moved = []
        for pid in self.in_flight:  # <- DET003 (order differs per process)
            moved.append((pid, owner_of(pid)))
        return moved
