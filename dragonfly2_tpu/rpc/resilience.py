"""Failure-domain resilience primitives for the RPC edge.

Two capabilities the reference gets from gRPC + pkg/retry and this codebase
previously lacked end to end:

- **Deadline budgets** (grpc-timeout semantics): a caller opens a
  ``deadline(budget_s)`` scope; every frame encoded inside it carries the
  *remaining* budget in the wire envelope (rpc/wire.py ``"dl"``), the
  receiving server re-anchors the budget on receipt and keeps decrementing
  while it holds the request — so a hop chain shares ONE budget instead of
  stacking per-hop timeouts. Clients enforce the budget per call
  (``DeadlineExceeded`` DFError before dialing work that cannot finish);
  servers shed work whose budget already expired instead of scheduling it.
  The budget rides as a RELATIVE duration, not an absolute timestamp:
  hosts do not share a clock, and monotonic clocks never cross processes.

- **Per-target circuit breakers** (closed → open → half-open): every dial
  site shares one implementation keyed by ``host:port``. A blackholed
  target costs `failure_threshold` dial timeouts, then the breaker opens
  and callers fail in microseconds until ``open_ttl`` elapses; the first
  caller after that runs as the half-open probe (dial + the existing
  HealthCheck request where the transport supports it) and its outcome
  closes or re-opens the breaker. This generalizes SyncSchedulerClient's
  old ad-hoc ``dial_failure_ttl`` cache to every client in the tree.

Breaker state and deadline outcomes export through
``telemetry.series.resilience_series`` (``dragonfly_<service>_rpc_breaker_*``
and ``dragonfly_<service>_rpc_deadline_*`` families).
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import threading
import time
import weakref

from dragonfly2_tpu.utils import dferrors

# ---------------------------------------------------------------- deadlines

# Absolute time.monotonic() deadline for the current logical call chain.
# Context-local, so concurrent asyncio tasks / threads (asyncio.to_thread
# copies the context) each see their own budget.
_DEADLINE: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "rpc_deadline", default=None
)


def current_deadline() -> float | None:
    """Absolute monotonic deadline of the ambient scope, or None."""
    return _DEADLINE.get()


def remaining() -> float | None:
    """Seconds of budget left in the ambient scope (may be <= 0), or None
    when no deadline scope is active."""
    dl = _DEADLINE.get()
    return None if dl is None else dl - time.monotonic()


def expired() -> bool:
    r = remaining()
    return r is not None and r <= 0


def check(what: str = "call") -> None:
    """Raise DeadlineExceeded if the ambient budget is already spent —
    the pre-flight guard clients run before dialing/sending."""
    r = remaining()
    if r is not None and r <= 0:
        raise dferrors.DeadlineExceeded(
            f"{what}: deadline budget exhausted ({r:.3f}s remaining)"
        )


def bound_timeout(timeout: float | None) -> float | None:
    """The effective per-call timeout: the caller's own cap bounded by the
    ambient budget. None stays None only when neither side bounds it."""
    r = remaining()
    if r is None:
        return timeout
    r = max(r, 0.0)
    return r if timeout is None else min(timeout, r)


@contextlib.contextmanager
def deadline(budget_s: float):
    """Open (or tighten) a deadline scope: the effective deadline is the
    MINIMUM of any enclosing scope and now+budget_s — a callee can only
    shrink the budget it was handed, never extend it."""
    yield from _enter(time.monotonic() + budget_s)


@contextlib.contextmanager
def deadline_at(deadline_monotonic: float):
    """Like deadline(), anchored at an absolute monotonic instant (the
    server side re-anchors a received relative budget here)."""
    yield from _enter(deadline_monotonic)


def _enter(candidate: float):
    current = _DEADLINE.get()
    effective = candidate if current is None else min(current, candidate)
    token = _DEADLINE.set(effective)
    try:
        yield effective
    finally:
        _DEADLINE.reset(token)


# ---------------------------------------------------------------- breakers

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# gauge encoding (dashboards alert on == 2)
_STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class BreakerOpen(dferrors.Unavailable, ConnectionError):
    """Raised by acquire() when the breaker short-circuits the call. A
    subclass of Unavailable (the retryable DFError code) AND of
    ConnectionError, so every existing except-clause that treats a dead
    target as a transport failure — the manager's job edge catches
    ConnectionError, the daemon's retry loop catches Unavailable — keeps
    working without enumerating a new type."""


class CircuitBreaker:
    """One target's closed/open/half-open dial breaker. Thread-safe (one
    plain lock, never held across IO) so the asyncio pool, the manager's
    REST worker threads, and the announcer cadence can share instances.

    - CLOSED: calls flow; `failure_threshold` consecutive failures open it.
    - OPEN: acquire() raises BreakerOpen until `open_ttl` elapses.
    - HALF_OPEN: exactly one caller wins acquire() as the probe (the rest
      keep fast-failing); its record_success()/record_failure() closes or
      re-opens the breaker.
    """

    def __init__(self, target: str, failure_threshold: int = 2,
                 open_ttl: float = 5.0, on_transition=None):
        self.target = target
        self.failure_threshold = max(int(failure_threshold), 1)
        self.open_ttl = open_ttl
        self._on_transition = on_transition  # (target, new_state) -> None
        self._mu = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._mu:
            return self._effective_state()

    def _effective_state(self) -> str:
        # lazily ripen OPEN -> HALF_OPEN once the ttl elapsed
        if self._state == OPEN and time.monotonic() - self._opened_at >= self.open_ttl:
            self._set_state(HALF_OPEN)
        return self._state

    def _set_state(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        if state != HALF_OPEN:
            self._probing = False
        if self._on_transition is not None:
            self._on_transition(self.target, state)

    def allows(self) -> bool:
        """Non-raising peek (the hashring failover asks 'should I even try
        this node' without consuming the half-open probe slot)."""
        with self._mu:
            state = self._effective_state()
            return state == CLOSED or (state == HALF_OPEN and not self._probing)

    def acquire(self) -> str:
        """Claim the right to dial. Returns the state the call runs under
        (CLOSED, or HALF_OPEN for the single probe); raises BreakerOpen
        when the target is short-circuited. Callers MUST follow up with
        record_success()/record_failure()."""
        with self._mu:
            state = self._effective_state()
            if state == CLOSED:
                return CLOSED
            if state == HALF_OPEN and not self._probing:
                self._probing = True
                return HALF_OPEN
            ttl_left = self.open_ttl - (time.monotonic() - self._opened_at)
            raise BreakerOpen(
                f"{self.target}: circuit open "
                f"({self._failures} consecutive failures; "
                f"probe in {max(ttl_left, 0.0):.1f}s)"
            )

    def record_success(self) -> None:
        with self._mu:
            self._failures = 0
            self._probing = False
            self._set_state(CLOSED)

    def record_failure(self) -> None:
        with self._mu:
            self._failures += 1
            if self._state == HALF_OPEN or self._failures >= self.failure_threshold:
                self._opened_at = time.monotonic()
                self._set_state(OPEN)

    def release(self) -> None:
        """Abandon an acquire() without a verdict (the caller was
        CANCELLED mid-dial, not refused by the target): frees the
        half-open probe slot so the next caller can probe — a cancelled
        dial says nothing about the target's health and must neither
        open the breaker nor wedge the probe."""
        with self._mu:
            self._probing = False


class BreakerBoard:
    """Per-service registry of per-target breakers, wired to the
    ``dragonfly_<service>_rpc_breaker_*`` telemetry families. One board per
    client object (pool / sync client), so tests and multi-cluster tools
    don't share failure state through a process-global."""

    def __init__(self, service: str, failure_threshold: int = 2,
                 open_ttl: float = 5.0, registry=None):
        from dragonfly2_tpu.telemetry import default_registry
        from dragonfly2_tpu.telemetry.series import resilience_series

        self.service = service
        self.failure_threshold = failure_threshold
        self.open_ttl = open_ttl
        self.metrics = resilience_series(registry or default_registry(), service)
        self._mu = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}
        _register_board(self)

    def open_count(self) -> int:
        """Breakers currently NOT closed (open or half-open probing) —
        the per-board contribution to the process-wide census the soak
        timeline samples (telemetry/timeline.py)."""
        with self._mu:
            breakers = list(self._breakers.values())
        return sum(1 for b in breakers if b.state != "closed")

    def get(self, target: str) -> CircuitBreaker:
        with self._mu:
            breaker = self._breakers.get(target)
            if breaker is None:
                breaker = self._breakers[target] = CircuitBreaker(
                    target,
                    failure_threshold=self.failure_threshold,
                    open_ttl=self.open_ttl,
                    on_transition=self._observe_transition,
                )
                self.metrics.breaker_state.labels(target).set(0.0)
            return breaker

    def _observe_transition(self, target: str, state: str) -> None:
        self.metrics.breaker_state.labels(target).set(_STATE_VALUE[state])
        self.metrics.breaker_transitions.labels(target, state).inc()

    def acquire(self, target: str) -> str:
        """get(target).acquire() + the fast-fail counter on BreakerOpen."""
        try:
            return self.get(target).acquire()
        except BreakerOpen:
            self.metrics.breaker_fast_fail.labels(target).inc()
            raise

    def allows(self, target: str) -> bool:
        return self.get(target).allows()

    def targets(self) -> list[str]:
        with self._mu:
            return list(self._breakers)

    def record_outcome(self, target: str, error: BaseException | None) -> None:
        """Single classification point for a dial/probe outcome, shared by
        every call site so the three dial paths cannot drift: None ->
        success; a transport failure (OSError incl. ConnectionError, or a
        timeout) -> failure; anything else (cancellation, a codec bug) is
        NOT evidence against the target -> release the probe slot without
        opening the breaker."""
        breaker = self.get(target)
        if error is None:
            breaker.record_success()
        elif isinstance(error, (OSError, TimeoutError, asyncio.TimeoutError)):
            breaker.record_failure()
        else:
            breaker.release()

    def drop(self, target: str) -> None:
        """Forget a decommissioned target (dynconfig removed it from the
        active set): its gauge resets to closed so dashboards don't alert
        forever on a scheduler that no longer exists."""
        with self._mu:
            if self._breakers.pop(target, None) is not None:
                self.metrics.breaker_state.labels(target).set(0.0)


# Weak census of live boards (boards stay per-client-object — no failure
# state is shared through this; it only answers "how many breakers are
# open anywhere in this process right now" for the soak timeline and the
# /debug/flight surface).
_BOARDS: "weakref.WeakSet[BreakerBoard]" = weakref.WeakSet()
_boards_mu = threading.Lock()


def _register_board(board: "BreakerBoard") -> None:
    with _boards_mu:
        _BOARDS.add(board)


def open_breaker_census() -> int:
    """Process-wide count of non-closed circuit breakers across every
    live BreakerBoard."""
    with _boards_mu:
        boards = list(_BOARDS)
    return sum(b.open_count() for b in boards)
