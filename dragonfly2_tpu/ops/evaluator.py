"""Batched parent-selection evaluator — the scheduler's hot path as one
jit-compiled array program.

Semantics parity (re-derived, not translated) with the reference's
evaluator family:

- base linear blend 0.2/0.2/0.15/0.15/0.15/0.15 over piece, upload-success,
  free-upload, host-type, IDC, location scores
  (scheduler/scheduling/evaluator/evaluator_base.go:28-46,71-188);
- network-topology blend with the extra 0.12 probe-RTT term
  `(1s - avgRTT)/1s` and 0.11 host-type/IDC/location weights
  (evaluator_network_topology.go:30-51,96-109,217-224);
- IsBadNode: bad states, then piece-cost outlier detection — 20x-mean rule
  under 30 samples, mean+3*sigma beyond (evaluator.go:93-129);
- candidate filtering: blocklist, same-host, rootless-normal-parent,
  bad-node, no-free-upload, DAG-cycle rules
  (scheduler/scheduling/scheduling.go:500-571).

Where the reference scores ONE child's parents per call behind a mutex,
this kernel scores (B tasks x K candidates) per device call with masked
vector ops and `lax.top_k` — BASELINE.json configs[2]'s 1k x 64 shape in a
single XLA program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# The packed-transport jits donate their H2D staging buffer (reused for
# outputs/scratch on devices that support donation). Backends without
# donation (CPU) warn once per compiled shape — an expected no-op the
# test conftest filters; no process-global filter here, other code's
# donation warnings are real findings.

from dragonfly2_tpu.config.constants import CONSTANTS
from dragonfly2_tpu.ops.topk import masked_top_k
from dragonfly2_tpu.state.fsm import BAD_NODE_STATES, PeerState

_BASE_WEIGHTS = dict(
    piece=CONSTANTS.W_FINISHED_PIECE,
    upload=CONSTANTS.W_UPLOAD_SUCCESS,
    free_upload=CONSTANTS.W_FREE_UPLOAD,
    host_type=CONSTANTS.W_HOST_TYPE,
    idc=CONSTANTS.W_IDC,
    location=CONSTANTS.W_LOCATION,
    probe=0.0,
)

_NT_WEIGHTS = dict(
    piece=CONSTANTS.NT_W_FINISHED_PIECE,
    upload=CONSTANTS.NT_W_UPLOAD_SUCCESS,
    free_upload=CONSTANTS.NT_W_FREE_UPLOAD,
    host_type=CONSTANTS.NT_W_HOST_TYPE,
    idc=CONSTANTS.NT_W_IDC,
    location=CONSTANTS.NT_W_LOCATION,
    probe=CONSTANTS.NT_W_PROBE,
)

MAX_SCORE = jnp.float32(CONSTANTS.MAX_SCORE)
MIN_SCORE = jnp.float32(CONSTANTS.MIN_SCORE)

# int8 codes of states where IsBadNode short-circuits true (evaluator.go:94-96);
# single source of truth lives in state/fsm.py.
_BAD_STATES = tuple(sorted(int(s) for s in BAD_NODE_STATES))


def piece_score(finished: jax.Array, child_finished: jax.Array,
                total: jax.Array) -> jax.Array:
    """finished/total when total is known, else raw finished-count delta
    (evaluator_base.go:86-99). Unbounded by design."""
    total_f = total.astype(jnp.float32)[..., None]
    known = total_f > 0
    normalized = finished.astype(jnp.float32) / jnp.maximum(total_f, 1.0)
    delta = finished.astype(jnp.float32) - child_finished.astype(jnp.float32)[..., None]
    return jnp.where(known, normalized, delta)


def upload_success_score(upload_count: jax.Array,
                         upload_failed: jax.Array) -> jax.Array:
    """(uc-ufc)/uc; never-scheduled hosts get max (evaluator_base.go:102-115)."""
    uc = upload_count.astype(jnp.float32)
    ufc = upload_failed.astype(jnp.float32)
    ratio = (uc - ufc) / jnp.maximum(uc, 1.0)
    score = jnp.where(uc < ufc, MIN_SCORE, ratio)
    return jnp.where((upload_count == 0) & (upload_failed == 0), MAX_SCORE, score)


def free_upload_score(upload_limit: jax.Array,
                      upload_used: jax.Array) -> jax.Array:
    free = (upload_limit - upload_used).astype(jnp.float32)
    limit = upload_limit.astype(jnp.float32)
    ok = (limit > 0) & (free > 0)
    return jnp.where(ok, free / jnp.maximum(limit, 1.0), MIN_SCORE)


def host_type_score(host_type: jax.Array, peer_state: jax.Array) -> jax.Array:
    """Seed peers max out while Received/Running, else 0; normal hosts 0.5
    (evaluator_base.go:129-143)."""
    is_normal = host_type == 0
    active = (peer_state == int(PeerState.RECEIVED_NORMAL)) | (
        peer_state == int(PeerState.RUNNING)
    )
    seed_score = jnp.where(active, MAX_SCORE, MIN_SCORE)
    return jnp.where(is_normal, MAX_SCORE * 0.5, seed_score)


def idc_affinity_score(parent_idc: jax.Array, child_idc: jax.Array) -> jax.Array:
    child = child_idc[..., None]
    both = (parent_idc != 0) & (child != 0)
    return jnp.where(both & (parent_idc == child), MAX_SCORE, MIN_SCORE).astype(jnp.float32)


def location_affinity_score(parent_loc: jax.Array,
                            child_loc: jax.Array) -> jax.Array:
    """Leading-element match depth / 5, exact match = 1.0, either side
    empty = 0 (evaluator_base.go:159-188). Operates on per-element hash
    codes; code 0 = absent element."""
    child = child_loc[:, None, :]  # (B,1,L)
    both_present = (parent_loc[..., 0] != 0) & (child[..., 0] != 0)
    exact = jnp.all(parent_loc == child, axis=-1)
    elem_eq = (parent_loc == child) & (parent_loc != 0) & (child != 0)
    # prefix length: cumulative AND of leading matches
    prefix = jnp.cumprod(elem_eq.astype(jnp.int32), axis=-1)
    depth = prefix.sum(axis=-1).astype(jnp.float32) / CONSTANTS.MAX_LOCATION_ELEMENTS
    score = jnp.where(exact, MAX_SCORE, depth)
    return jnp.where(both_present, score, MIN_SCORE)


def probe_score(avg_rtt_ns: jax.Array, has_rtt: jax.Array) -> jax.Array:
    """(pingTimeout - avgRTT) / pingTimeout, 0 when unprobed
    (evaluator_network_topology.go:217-224)."""
    timeout = jnp.float32(CONSTANTS.PING_TIMEOUT_NS)
    return jnp.where(has_rtt, (timeout - avg_rtt_ns) / timeout, MIN_SCORE)


def _blend(feats: dict, weights: dict) -> jax.Array:
    score = (
        weights["piece"]
        * piece_score(
            feats["finished_pieces"], feats["child_finished_pieces"], feats["total_piece_count"]
        )
        + weights["upload"]
        * upload_success_score(feats["upload_count"], feats["upload_failed_count"])
        + weights["free_upload"] * free_upload_score(feats["upload_limit"], feats["upload_used"])
        + weights["host_type"] * host_type_score(feats["host_type"], feats["peer_state"])
        + weights["idc"] * idc_affinity_score(feats["parent_idc"], feats["child_idc"])
        + weights["location"]
        * location_affinity_score(feats["parent_location"], feats["child_location"])
    )
    if weights["probe"]:
        score = score + weights["probe"] * probe_score(feats["avg_rtt_ns"], feats["has_rtt"])
    return score


def evaluate(feats: dict, algorithm: str = "default") -> jax.Array:
    """Scores (B, K) for every candidate. `algorithm` in {default, nt}."""
    weights = _NT_WEIGHTS if algorithm == "nt" else _BASE_WEIGHTS
    return _blend(feats, weights)


def is_bad_node(piece_costs: jax.Array, piece_cost_count: jax.Array,
                peer_state: jax.Array) -> jax.Array:
    """(B, K) bool — replicate IsBadNode's sampled-outlier rule on padded
    cost rings ordered oldest->newest (evaluator.go:93-129).

    Single fused pass over the (B, K, C) ring: masked sum + sum-of-squares
    give the previous-cost moments, and the newest element comes out of a
    select+sum rather than a gather, so XLA emits one reduction kernel
    instead of two serialized passes with a broadcast in between (the
    naive mean-then-(x-mean)^2 form cost ~0.86 ms at the 1024x64x32
    serving shape; this form costs ~0.07 ms).

    The moments are computed on SHIFTED values, d = x - x[0] (the oldest
    ring entry — a slice, not a reduction, so fusion survives):
    Var(x) = E[d^2] - E[d]^2 exactly, but with d centered near zero the
    float32 subtraction no longer catastrophically cancels. The raw form
    E[x^2] - mean^2 is unusable here: piece costs are nanoseconds (~1e9),
    E[x^2] ~ 1e18, and float32's ulp at that magnitude swamps any true
    variance below ~1e11 — empirically flipping a fifth of the bad-node
    verdicts vs the two-pass reference semantics.
    """
    count = piece_cost_count.astype(jnp.int32)
    idx = jnp.arange(piece_costs.shape[-1], dtype=jnp.int32)
    newest = idx == (count[..., None] - 1)
    prev = (idx < (count[..., None] - 1)).astype(jnp.float32)  # all but the newest

    shift = piece_costs[..., :1]  # oldest cost: same magnitude as the rest
    d = (piece_costs - shift) * prev
    prev_sum_d = d.sum(axis=-1)
    prev_sumsq_d = (d * (piece_costs - shift)).sum(axis=-1)
    last = jnp.where(newest, piece_costs, 0.0).sum(axis=-1)

    prev_n = jnp.maximum(count - 1, 1).astype(jnp.float32)
    mean_d = prev_sum_d / prev_n
    mean = mean_d + shift[..., 0]
    var = jnp.maximum(prev_sumsq_d / prev_n - mean_d * mean_d, 0.0)
    std = jnp.sqrt(var)

    small_sample = count < CONSTANTS.NORMAL_DISTRIBUTION_LEN
    outlier_small = last > mean * CONSTANTS.BAD_NODE_MEAN_MULTIPLIER
    outlier_normal = last > mean + CONSTANTS.BAD_NODE_SIGMA * std
    cost_bad = jnp.where(small_sample, outlier_small, outlier_normal)
    cost_bad = jnp.where(count < CONSTANTS.MIN_AVAILABLE_COST_LEN, False, cost_bad)

    state_bad = jnp.zeros(peer_state.shape, bool)
    for code in _BAD_STATES:
        state_bad = state_bad | (peer_state == code)
    return state_bad | cost_bad


def filter_candidates(
    feats: dict,
    blocklist: jax.Array | None = None,
    in_degree: jax.Array | None = None,
    can_add_edge: jax.Array | None = None,
) -> jax.Array:
    """(B, K) bool eligibility mask — scheduling.go:500-571 as vector ops.

    `in_degree`/`can_add_edge` come from the graph engine (graph/dag.py);
    None means "no DAG constraint" (trace replay mode).
    """
    mask = feats["valid"]
    if blocklist is not None:
        mask = mask & ~blocklist
    # Same host can't serve itself (scheduling.go:519-525).
    mask = mask & (feats["parent_host_id"] != feats["child_host_id"][..., None])
    # A normal-host parent must itself have a parent, or have finished /
    # gone back-to-source (scheduling.go:534-544).
    state = feats["peer_state"]
    rooted = (
        (state == int(PeerState.BACK_TO_SOURCE))
        | (state == int(PeerState.SUCCEEDED))
        | (feats["host_type"] != 0)
    )
    if in_degree is not None:
        rooted = rooted | (in_degree > 0)
    mask = mask & rooted
    # Bad nodes are skipped (scheduling.go:546-550).
    mask = mask & ~is_bad_node(feats["piece_costs"], feats["piece_cost_count"], state)
    # Saturated uploaders are skipped (scheduling.go:552-557).
    mask = mask & ((feats["upload_limit"] - feats["upload_used"]) > 0)
    # Edges that would create a cycle are skipped (scheduling.go:559-563).
    if can_add_edge is not None:
        mask = mask & can_add_edge
    return mask


def _filter_and_select(feats: dict, scores: jax.Array, blocklist, in_degree,
                       can_add_edge, limit: int) -> dict:
    """Shared contract of every scheduling path: eligibility mask + masked
    top-k over the provided scores."""
    mask = filter_candidates(feats, blocklist, in_degree, can_add_edge)
    values, indices, valid = masked_top_k(scores, mask, limit)
    return {
        "scores": scores,
        "mask": mask,
        "selected": indices,
        "selected_valid": valid,
        "selected_scores": values,
    }


def _pack_selection(values: jax.Array, indices: jax.Array,
                    valid: jax.Array) -> jax.Array:
    """Pack (indices, valid, scores) into ONE (B, limit, 2) float32 array:
    channel 0 = candidate index, or -1 for empty slots; channel 1 = score.
    Candidate indices are < 128 so float32 carries them exactly. One output
    buffer means the serving path pays a single D2H transfer per tick
    instead of three (each blocking host read pays a full link round-trip
    on a tunneled device)."""
    idx = jnp.where(valid, indices, -1).astype(jnp.float32)
    return jnp.stack([idx, values], axis=-1)


def unpack_selection(packed) -> tuple:
    """Host-side decode of `_pack_selection` output: (indices int32,
    valid bool, scores). Accepts np arrays (the tick's D2H read) or jax
    arrays (tests)."""
    idx = packed[..., 0]
    return idx.astype("int32"), idx >= 0, packed[..., 1]


@functools.partial(jax.jit, static_argnames=("algorithm", "limit"))
def schedule_candidate_parents_packed(
    feats: dict,
    blocklist: jax.Array | None = None,
    in_degree: jax.Array | None = None,
    can_add_edge: jax.Array | None = None,
    algorithm: str = "default",
    limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
):
    """Serving-path variant of `schedule_candidate_parents`: identical
    filter + score + select, but returns ONLY the packed (B, limit, 2)
    selection — no full (B, K) scores/mask outputs to materialize, one
    device output buffer, one D2H. This is the <1 ms p50 path; the dict
    variant below is the debug/replay surface."""
    scores = evaluate(feats, algorithm)
    mask = filter_candidates(feats, blocklist, in_degree, can_add_edge)
    values, indices, valid = masked_top_k(scores, mask, limit)
    return _pack_selection(values, indices, valid)


@functools.partial(jax.jit, static_argnames=("limit",))
def select_with_scores_packed(
    feats: dict,
    scores: jax.Array,
    blocklist: jax.Array | None = None,
    in_degree: jax.Array | None = None,
    can_add_edge: jax.Array | None = None,
    limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
):
    """Packed single-output twin of `select_with_scores` (plugin/ml path)."""
    mask = filter_candidates(feats, blocklist, in_degree, can_add_edge)
    values, indices, valid = masked_top_k(scores, mask, limit)
    return _pack_selection(values, indices, valid)


@functools.partial(jax.jit, static_argnames=("algorithm", "limit"))
def schedule_candidate_parents(
    feats: dict,
    blocklist: jax.Array | None = None,
    in_degree: jax.Array | None = None,
    can_add_edge: jax.Array | None = None,
    algorithm: str = "default",
    limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
):
    """Filter + score + select top-`limit` parents for B children at once.

    Returns dict with `scores` (B,K), `mask` (B,K), `selected` (B,limit)
    candidate indices, `selected_valid` (B,limit), `selected_scores`.
    One device call per scheduler tick — the <1ms p50 path.
    """
    scores = evaluate(feats, algorithm)
    return _filter_and_select(feats, scores, blocklist, in_degree, can_add_edge, limit)


@functools.partial(jax.jit, static_argnames=("limit",))
def select_with_scores(
    feats: dict,
    scores: jax.Array,
    blocklist: jax.Array | None = None,
    in_degree: jax.Array | None = None,
    can_add_edge: jax.Array | None = None,
    limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
):
    """Like schedule_candidate_parents but with externally supplied scores —
    the "ml" algorithm path where a served model (registry/serving.py)
    replaces the linear blend while every filter rule still applies
    (the wiring the reference leaves dead: evaluator.go:84-86)."""
    return _filter_and_select(feats, scores, blocklist, in_degree, can_add_edge, limit)


# ------------------------------------------------------------------------
# Single-buffer transport: the serving tick's features as ONE uint8 array.
#
# On the tunneled dev TPU every host->device transfer pays a full link
# round-trip (up to ~100 ms in degraded windows); the ~25-leaf feature
# dict therefore dominated full_loop_tick_p50 (BENCH_r03: 184.8 ms at
# 10k hosts — VERDICT r3 weak #5). Packing every field into one
# contiguous uint8 buffer host-side makes the whole tick cost exactly
# one H2D transfer + one dispatch + one D2H of the packed selection,
# independent of the field count. Inside the jit the buffer is sliced at
# static offsets and bitcast back to each field's dtype — a zero-FLOP
# reshuffle XLA folds into the consumers.
#
# int64 identity/count fields travel as int32: they are equality-only
# (or small counts), and the x32-mode dict path already truncated them
# to int32 at device_put time, so semantics are bit-identical.

_PACK_ONE_BYTE = (
    # (name, numpy dtype char) — 1-byte fields first so the 4-byte block
    # that follows stays aligned after a single pad.
    ("valid", "u1"),
    ("has_rtt", "u1"),
    ("blocklist", "u1"),
    ("can_add_edge", "u1"),
    ("host_type", "i1"),
    ("peer_state", "i1"),
)


def _packed_field_specs(
    b: int, k: int, c: int, l: int, n: int
) -> list[tuple[str, str, tuple[int, ...]]]:
    """Ordered (name, dtype_str, shape) for the packed transport."""
    shapes = {
        "valid": (b, k), "has_rtt": (b, k), "blocklist": (b, k),
        "can_add_edge": (b, k), "host_type": (b, k), "peer_state": (b, k),
        "finished_pieces": (b, k), "child_finished_pieces": (b,),
        "total_piece_count": (b,), "upload_count": (b, k),
        "upload_failed_count": (b, k), "upload_limit": (b, k),
        "upload_used": (b, k), "parent_idc": (b, k), "child_idc": (b,),
        "parent_location": (b, k, l), "child_location": (b, l),
        "parent_host_id": (b, k), "child_host_id": (b,),
        "piece_cost_count": (b, k), "in_degree": (b, k),
        "child_host_slot": (b,), "cand_host_slot": (b, k),
        "avg_rtt_ns": (b, k), "piece_costs": (b, k, c),
        "numeric": (b, k, n), "child_numeric": (b, n),
    }
    specs = [(name, dt, shapes[name]) for name, dt in _PACK_ONE_BYTE]
    for name in (
        "finished_pieces", "child_finished_pieces", "total_piece_count",
        "upload_count", "upload_failed_count", "upload_limit", "upload_used",
        "parent_idc", "child_idc", "parent_location", "child_location",
        "parent_host_id", "child_host_id", "piece_cost_count", "in_degree",
        "child_host_slot", "cand_host_slot",
    ):
        specs.append((name, "i4", shapes[name]))
    for name in ("avg_rtt_ns", "piece_costs", "numeric", "child_numeric"):
        specs.append((name, "f4", shapes[name]))
    return specs


def _packed_layout(b: int, k: int, c: int, l: int, n: int) -> tuple[list, int]:
    """[(name, dtype_str, shape, offset, nbytes)], total buffer size."""
    import numpy as np

    off = 0
    layout = []
    for name, dt, shape in _packed_field_specs(b, k, c, l, n):
        itemsize = np.dtype(dt).itemsize
        off = (off + itemsize - 1) // itemsize * itemsize
        nbytes = itemsize * int(np.prod(shape, dtype=np.int64)) if shape else itemsize
        layout.append((name, dt, shape, off, nbytes))
        off += nbytes
    return layout, (off + 3) // 4 * 4


def pack_eval_batch(
    feats: dict,
    blocklist=None,
    in_degree=None,
    can_add_edge=None,
    child_host_slot=None,
    cand_host_slot=None,
):  # -> np.uint8 buffer (numpy imported lazily to keep module load lean)
    """Host side: CandidateFeatures dict (+ filter aux + optional ml host
    slots) -> one contiguous np.uint8 buffer for `schedule_from_packed`."""
    import numpy as np

    b, k = feats["valid"].shape
    c = feats["piece_costs"].shape[-1]
    l = feats["parent_location"].shape[-1]
    n = feats["numeric"].shape[-1]
    extras = {
        "blocklist": blocklist, "in_degree": in_degree,
        "can_add_edge": can_add_edge if can_add_edge is not None
        else np.ones((b, k), bool),
        "child_host_slot": child_host_slot, "cand_host_slot": cand_host_slot,
    }
    layout, total = _packed_layout(b, k, c, l, n)
    buf = np.zeros(total, np.uint8)
    for name, dt, shape, off, nbytes in layout:
        src = feats.get(name)
        if src is None:
            src = extras.get(name)
        if src is None:
            continue  # stays zero (blocklist none = nothing blocked, etc.)
        a = np.ascontiguousarray(src).astype(np.dtype(dt), copy=False)
        buf[off : off + nbytes] = a.view(np.uint8).ravel()
    return buf


def unpack_eval_batch(buf, b: int, k: int, c: int, l: int, n: int) -> dict:
    """Traced inverse of `pack_eval_batch`: static-offset slices + bitcasts
    (free inside the jit — XLA folds them into the consuming ops)."""
    layout, _ = _packed_layout(b, k, c, l, n)
    out = {}
    for name, dt, shape, off, nbytes in layout:
        seg = jax.lax.slice(buf, (off,), (off + nbytes,))
        if dt == "u1":
            out[name] = seg.reshape(shape).astype(bool)
        elif dt == "i1":
            out[name] = jax.lax.bitcast_convert_type(seg, jnp.int8).reshape(shape)
        else:
            words = jax.lax.bitcast_convert_type(seg.reshape(-1, 4), jnp.int32)
            if dt == "f4":
                words = jax.lax.bitcast_convert_type(words, jnp.float32)
            out[name] = words.reshape(shape)
    return out


@functools.partial(
    jax.jit, static_argnames=("b", "k", "c", "l", "n", "algorithm", "limit"),
    # The packed H2D staging buffer is consumed exactly once (the tick
    # packs a fresh buffer per chunk; warmup and the MLEvaluator fallback
    # likewise pass a one-shot buffer), so XLA may reuse its device
    # allocation for outputs/scratch instead of allocating per chunk.
    # Callers always pass a host np.uint8 array, which donation leaves
    # untouched — only the transient device copy is donated.
    donate_argnums=(0,),
)
def schedule_from_packed(
    buf,
    b: int,
    k: int,
    c: int,
    l: int,
    n: int,
    algorithm: str = "default",
    limit: int = CONSTANTS.CANDIDATE_PARENT_LIMIT,
):
    """`schedule_candidate_parents_packed` over the single-buffer
    transport: one H2D (buf), one device program, one D2H (the packed
    (B, limit, 2) selection). The serving tick's whole device
    conversation is three link round-trips regardless of field count."""
    f = unpack_eval_batch(buf, b, k, c, l, n)
    scores = evaluate(f, algorithm)
    mask = filter_candidates(f, f["blocklist"], f["in_degree"], f["can_add_edge"])
    values, indices, valid = masked_top_k(scores, mask, limit)
    return _pack_selection(values, indices, valid)


# Flight-recorder instrumentation on the serving entry point: compile/
# retrace counts per (B, K, ...) signature (telemetry/flight.py). The
# wrapper forwards attributes, so `.lower()`/warmup callers are
# unaffected. block=False: the pipelined tick (cluster/scheduler.py)
# dispatches chunk i+1 BEFORE blocking on chunk i's D2H — a blocking
# wrapper would serialize the chunks again and erase exactly the overlap
# the pipeline buys; the dispatch/d2h_wait wall-time split now lives in
# the tick's own phase ring instead of the jit histogram.
from dragonfly2_tpu.telemetry.flight import instrument_jit as _instrument_jit  # noqa: E402

schedule_from_packed = _instrument_jit(
    schedule_from_packed, "evaluator.schedule_from_packed", service="scheduler",
    block=False,
    # costcards: first compile of each bucket signature queues an XLA
    # cost-card capture (telemetry/costcard.py) drained by warmup /
    # /debug/flight / the bench report — the measured flops/bytes basis
    # the perf-observatory verdicts are computed against
    costcards=True,
)


@functools.partial(jax.jit, static_argnames=("algorithm",))
def find_success_parent(
    feats: dict,
    blocklist: jax.Array | None = None,
    in_degree: jax.Array | None = None,
    can_add_edge: jax.Array | None = None,
    algorithm: str = "default",
):
    """Best already-Succeeded parent per child (FindSuccessParent,
    scheduling.go:442-497): the reference runs the full
    filterCandidateParents first (:478) and then keeps only Succeeded
    candidates (:484-489), so every filter rule applies here too."""
    mask = filter_candidates(feats, blocklist, in_degree, can_add_edge)
    mask = mask & (feats["peer_state"] == int(PeerState.SUCCEEDED))
    scores = evaluate(feats, algorithm)
    values, indices, valid = masked_top_k(scores, mask, 1)
    return {
        "parent": indices[..., 0],
        "found": valid[..., 0],
        "score": values[..., 0],
    }
