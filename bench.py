"""Headline benchmark: scheduler parent-selection p50 latency.

North star (BASELINE.md / BASELINE.json): p50 < 1 ms for batched parent
selection at the 1k-concurrent-tasks x 64-candidates shape on a cluster
with 10k+ peers — the workload the reference serves one-peer-at-a-time in
Go behind mutexes (scheduler/scheduling/scheduling.go), here ONE
jit-compiled device call (dragonfly2_tpu/ops/evaluator.py).

Prints exactly one JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
   "trainer": {...}, "loop": [...]}
vs_baseline = baseline_ms / measured_ms (>1 means faster than the 1 ms
target; the reference publishes no numbers of its own, BASELINE.md).

Sub-objects (second north star + the configs[3] end-to-end loop):
- "trainer": representative-scale GNN training (10k hosts, 100k records,
  hidden 256, batch 4096 — BASELINE.json configs[3] class, fixing the
  round-2 toy shape) with a LIVE torch-CPU baseline probe, plus flash-
  attention fwd and fwd+bwd MFU via chained in-jit timing.
- "loop": bounded bench_loop leg (10k hosts, 100k pieces, trained model
  served back on the ml path) so the full-loop numbers are
  driver-captured, not builder-claimed.

Robustness: the tunneled dev TPU has multi-minute "slow windows" where
EVERY dispatch — even a jitted x+1 — costs 60-110 ms of round-trip, then
recovers to ~0.04 ms (.claude/skills/verify/SKILL.md). Each trial is
paired with a trivial-dispatch control; only trials whose control stayed
sane count. If a good window never arrives before the deadline, fall back
to steady-state pipelined latency: issue K batches back-to-back and take
(T(K) - T(k0)) / (K - k0), which cancels the constant tunnel round-trip
and measures the sustained per-batch cost the persistent scheduler tick
actually pays (requests stream; the design batches one device call per
tick, SURVEY.md §7 hard part (b)).
"""

import json
import statistics
import sys
import time

import numpy as np

BASELINE_MS = 1.0
BATCH_TASKS = 1024
BATCH_CANDIDATES = 64
NUM_HOSTS = 10_000
CONTROL_THRESHOLD_MS = 5.0
GOOD_SAMPLES_WANTED = 60
DEADLINE_S = 300.0
RETRY_SLEEP_S = 15.0
PIPELINED_PROBES = 3

# Trainer sub-metrics (second north star, BASELINE.md: >=50x CPU
# samples/s/chip): a representative-scale GNN training run (VERDICT r2
# missing #1 — the r2 leg trained a 2k-host/8k-record toy at 0.016% MFU).
TRAINER_HOSTS = 10_000
TRAINER_RECORDS = 100_000
TRAINER_HIDDEN = 256
TRAINER_BATCH = 4096
# Three fused blocks of 8 epochs: block 1 carries the compile (excluded
# from block timing), blocks 2-3 each time 8 epochs in ONE device call so
# a tunnel round-trip amortizes ~200x — the PEAK block is the reported
# steady state (tunnel degradation only ever slows a block down).
TRAINER_EPOCHS = 24
TRAINER_FUSION = 8
# torch-CPU same-architecture fallback when the live probe fails
# (bench_trainer.py cpu_torch measured ~1.8k samples/s at the r2 shape on
# this image's CPU); the live probe at the representative shape is the
# number of record.
CPU_TORCH_SAMPLES_PER_SEC_FALLBACK = 1_840.0
CPU_PROBE_STEPS = 2
PEAK_TFLOPS_BF16 = 197.0  # TPU v5e per-chip peak
ATTN_SHAPE = (4, 8, 8192, 128)  # B, H, L, D for the MFU probes
ATTN_CHAIN = 8
# representative-scale good-window runs measure >100M samples/s
# (253M peak observed); anything far below means every fused block was
# tunnel-degraded, so retry within the deadline (raised from r2's 1M,
# which let the loop settle for a degraded window)
TRAINER_GOOD_SAMPLES_PER_SEC = 50_000_000.0
TRAINER_DEADLINE_S = 200.0

# Bounded configs[3] loop leg (VERDICT r2 next #7): enough pieces that
# the replay is service-GC-bounded and the trained model demonstrably
# serves, small enough to keep the whole bench under the driver window.
LOOP_HOSTS = 10_000
LOOP_PIECES = 100_000
LOOP_TASKS = 512


def _paired_trials(call, control, n):
    """Run n (control, kernel) timing pairs; return list of (ctl_ms, ker_ms)."""
    import jax

    out = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(control())
        ctl = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        jax.block_until_ready(call())
        ker = (time.perf_counter() - t0) * 1e3
        out.append((ctl, ker))
    return out


def _pipelined_per_call_ms(call, k0=8, k1=64):
    """Steady-state per-batch latency: marginal cost per extra in-flight
    dispatch between pipeline depths k0 and k1 (cancels tunnel RTT)."""
    import jax

    def run(depth):
        t0 = time.perf_counter()
        outs = [call() for _ in range(depth)]
        jax.block_until_ready(outs[-1])
        return (time.perf_counter() - t0) * 1e3

    run(k0)  # warm the pipeline path
    ests = []
    for _ in range(5):
        t_small = run(k0)
        t_big = run(k1)
        # Floor at 10 us: when the tunnel's dispatch stream fully overlaps
        # execution, t_big - t_small can measure ~0, which is an artifact
        # of the overlap, not a credible per-batch cost — 10 us is the
        # fastest per-dispatch marginal ever observed on this link.
        ests.append(max((t_big - t_small) / (k1 - k0), 1e-2))
    return statistics.median(ests)


def _attention_submetrics() -> dict:
    """Flash-attention fwd and fused fwd+bwd MFU via chained in-jit
    timing: N data-dependent steps in ONE jit (eps traced so XLA cannot
    fold the chain), a D2H fetch forcing completion, divided by N —
    per-dispatch timing would measure the tunnel, not the kernel."""
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.ops.flash import flash_attention

    out: dict = {}
    b, h, l, d = ATTN_SHAPE
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.bfloat16)

    @jax.jit
    def chain_f(q_, k_, v_, eps):
        for _ in range(ATTN_CHAIN):
            o = flash_attention(q_, k_, v_)
            q_ = q_ + eps * o.astype(q_.dtype)
        return q_[0, 0, :8, :4].astype(jnp.float32)

    grad_fn = jax.grad(
        lambda a, bb, c: flash_attention(a, bb, c).astype(jnp.float32).sum(),
        argnums=(0, 1, 2),
    )

    @jax.jit
    def chain_g(q_, k_, v_, eps):
        for _ in range(ATTN_CHAIN):
            dq, dk, dv = grad_fn(q_, k_, v_)
            q_ = q_ + eps * dq.astype(q_.dtype)
            k_ = k_ + eps * dk.astype(k_.dtype)
            v_ = v_ + eps * dv.astype(v_.dtype)
        return (q_[0, 0, :8, :4] + k_[0, 0, :8, :4] + v_[0, 0, :8, :4]).astype(jnp.float32)

    eps = jnp.bfloat16(0.0)
    for name, fn, mult in (("fwd", chain_f, 4), ("fwdbwd", chain_g, 12)):
        np.asarray(fn(q, k, v, eps))  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn(q, k, v, eps))
            best = min(best, time.perf_counter() - t0)
        ms = best / ATTN_CHAIN * 1e3
        tflops = mult * b * h * l * l * d / (ms / 1e3) / 1e12
        out[f"attention_{name}_ms_8k"] = round(ms, 3)
        out[f"attention_{name}_tflops"] = round(tflops, 1)
        out[f"attention_{name}_mfu_pct"] = round(100.0 * tflops / PEAK_TFLOPS_BF16, 1)
    # keep the r2 field name for the fwd number so round artifacts compare
    out["attention_mfu_pct"] = out["attention_fwd_mfu_pct"]
    return out


def _trainer_submetrics() -> dict:
    """Representative-scale GNN training throughput + live CPU baseline."""
    import jax

    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.training.train import train_gnn

    out: dict = {}
    cluster = synth.make_cluster(TRAINER_HOSTS, seed=0)
    ds, graph = synth.gen_ranking_dataset(cluster, TRAINER_RECORDS)
    out["shape"] = {
        "hosts": TRAINER_HOSTS, "records": TRAINER_RECORDS,
        "hidden": TRAINER_HIDDEN, "batch": TRAINER_BATCH,
        "graph_edges": int(graph.edge_src.shape[0]),
    }
    cfg = TrainerConfig(
        hidden_dim=TRAINER_HIDDEN, batch_size=TRAINER_BATCH,
        epochs=TRAINER_EPOCHS, epoch_fusion=TRAINER_FUSION,
    )
    control_in = jax.device_put(np.ones((8, 128), np.float32))
    control_fn = jax.jit(lambda x: x + 1)
    jax.block_until_ready(control_fn(control_in))

    def control_ok() -> bool:
        t0 = time.perf_counter()
        jax.block_until_ready(control_fn(control_in))
        return (time.perf_counter() - t0) * 1e3 < CONTROL_THRESHOLD_MS

    result = train_gnn(ds, graph, cfg)
    best = result.peak_samples_per_sec or result.samples_per_sec
    # Each retry pays a fresh trace+compile (the jitted epoch fn is built
    # per train_gnn call), so retries are a last resort — only on the
    # tunneled TPU (a slower backend legitimately measures slower and must
    # not burn the deadline re-training), and only until one block lands
    # in a good window.
    deadline = time.monotonic() + TRAINER_DEADLINE_S
    while (
        jax.devices()[0].platform == "tpu"
        and best < TRAINER_GOOD_SAMPLES_PER_SEC
        and time.monotonic() < deadline
    ):
        if not control_ok():
            time.sleep(RETRY_SLEEP_S)
            continue
        retry = train_gnn(ds, graph, cfg)
        best = max(best, retry.peak_samples_per_sec or retry.samples_per_sec)
        if retry.samples_per_sec > result.samples_per_sec:
            result = retry
    out["gnn_samples_per_sec"] = round(best, 1)
    if result.flops_per_sample:
        out["gnn_achieved_tflops"] = round(result.flops_per_sample * best / 1e12, 3)
        out["gnn_mfu_pct"] = round(
            100.0 * result.flops_per_sample * best / (PEAK_TFLOPS_BF16 * 1e12), 3
        )

    # LIVE torch-CPU baseline at the SAME shape (ADVICE r2: the pinned
    # constant made the ratio a paper number) — a few steps is enough,
    # each full step embeds the 10k-node graph like the TPU path does.
    try:
        from bench_trainer import torch_cpu_samples_per_sec

        cpu = torch_cpu_samples_per_sec(
            ds, graph, max_steps=CPU_PROBE_STEPS,
            hidden=TRAINER_HIDDEN, batch=TRAINER_BATCH,
        )
        out["cpu_baseline_source"] = "measured-live"
    except Exception as e:  # noqa: BLE001 - the ratio must survive
        cpu = CPU_TORCH_SAMPLES_PER_SEC_FALLBACK
        out["cpu_baseline_source"] = f"pinned-constant ({type(e).__name__})"
    out["cpu_torch_samples_per_sec"] = round(cpu, 1)
    out["gnn_vs_cpu_torch"] = round(best / cpu, 1)

    try:
        out.update(_attention_submetrics())
    except Exception as e:  # noqa: BLE001
        out["attention_error"] = f"{type(e).__name__}: {e}"
    return out


def _loop_submetrics() -> list:
    """Bounded configs[3] loop: replay -> train -> publish -> serve-ml."""
    from bench_loop import run

    return run(hosts=LOOP_HOSTS, pieces=LOOP_PIECES, tasks=LOOP_TASKS)


def main() -> int:
    import jax

    from dragonfly2_tpu.ops import evaluator as ev
    from dragonfly2_tpu.records import synth
    from dragonfly2_tpu.records.features import downloads_to_eval_batch

    # Build a 10k-host cluster and replay its traces as scoring requests.
    cluster = synth.make_cluster(NUM_HOSTS, seed=0)
    records = synth.gen_download_records(
        cluster, BATCH_TASKS, num_tasks=256, max_parents=20
    )
    feats = downloads_to_eval_batch(records, BATCH_TASKS, BATCH_CANDIDATES)
    rng = np.random.default_rng(0)
    # randomize states/rtt so every branch is live
    feats.peer_state = rng.integers(5, 8, feats.peer_state.shape).astype(np.int8)
    feats.has_rtt = rng.random(feats.has_rtt.shape) < 0.7
    feats.avg_rtt_ns = (rng.random(feats.avg_rtt_ns.shape) * 5e7).astype(np.float32)

    d = jax.device_put(feats.as_dict())
    control_in = jax.device_put(np.ones((8, 128), np.float32))
    control_fn = jax.jit(lambda x: x + 1)

    def call():
        # The packed single-output variant IS the serving path
        # (cluster/scheduler.py tick); the dict variant is debug/replay.
        return ev.schedule_candidate_parents_packed(d, algorithm="nt", limit=4)

    def control():
        return control_fn(control_in)

    # warmup / compile
    jax.block_until_ready(call())
    jax.block_until_ready(control())

    start = time.monotonic()
    good = []
    while len(good) < GOOD_SAMPLES_WANTED:
        pairs = _paired_trials(call, control, 30)
        good.extend(k for c, k in pairs if c < CONTROL_THRESHOLD_MS)
        if len(good) >= GOOD_SAMPLES_WANTED:
            break
        if time.monotonic() - start > DEADLINE_S:
            break
        if not any(c < CONTROL_THRESHOLD_MS for c, _ in pairs):
            # deep inside a slow window — wait it out rather than burn trials
            time.sleep(RETRY_SLEEP_S)

    if len(good) >= 10:
        p50 = statistics.median(good)
        method = "control_gated_p50"
        n_samples = len(good)
    else:
        # Never saw a good window: report sustained pipelined latency.
        # Tunnel degradation only ever INFLATES the marginal estimate, so
        # probe a few times spaced out and keep the best (closest to the
        # true steady-state per-batch cost the persistent tick pays).
        probes = []
        for i in range(PIPELINED_PROBES):
            probes.append(_pipelined_per_call_ms(call))
            if i + 1 < PIPELINED_PROBES:
                time.sleep(RETRY_SLEEP_S)
        # the published value is the BEST probe's median (degradation only
        # inflates); n_samples reflects that probe's 5 estimates, not 15
        p50 = min(probes)
        method = "pipelined_steady_state"
        n_samples = 5

    try:
        trainer = _trainer_submetrics()
    except Exception as e:  # noqa: BLE001 - the headline number must survive
        trainer = {"error": f"{type(e).__name__}: {e}"}

    try:
        loop = _loop_submetrics()
    except Exception as e:  # noqa: BLE001
        loop = [{"error": f"{type(e).__name__}: {e}"}]

    print(
        json.dumps(
            {
                "metric": "scheduler_parent_selection_p50_ms_1024x64",
                "value": round(p50, 4),
                "unit": "ms",
                "vs_baseline": round(BASELINE_MS / p50, 2),
                "method": method,
                "samples": n_samples,
                "trainer": trainer,
                "loop": loop,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
