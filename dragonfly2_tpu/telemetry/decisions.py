"""Decision provenance ledger + counterfactual shadow scoring.

The reference scheduler's whole reason for collecting download records is
to feed parent-ranking training (SURVEY §2.3), yet until this module the
observability stack stopped at *timings*: phase rings (PR 1), cost cards
and soak timelines (PR 12). Nothing recorded WHY a parent was chosen, or
what the inactive arm would have picked — so "ml beats rule" was judged
only by end-to-end A/B cost, and the trainer never saw the serving path's
own decisions as labeled data.

:class:`DecisionLedger` is a bounded columnar ring (struct-of-arrays, no
per-decision Python dicts on the hot path) recording, for every APPLIED
selection the scheduler emits:

- the candidate slot set (peer rows + host slots) and a compact
  per-candidate feature row (:data:`DECISION_FEATURES`);
- the active arm's ranked selection (candidate positions + device
  scores), which of those survived DAG legality, and the chosen parent;
- the shadow arm's ranking of the SAME candidate set (counterfactual:
  the rule blend when ml serves, the committed ml snapshot when the rule
  serves), recorded off the critical path from the tick's end-of-round
  drain valve;
- the joined outcome once the peer's terminal event lands
  (completed / failed / back-to-source, corruption attribution,
  failover re-announce) with decision→outcome join latency.

Per-tick divergence (top-1 disagreement rate, rank correlation of the
active top-``limit`` against the shadow ranking) and measured per-arm
regret (outcome deltas on disagreement decisions, estimated from the
joined per-host outcome table) are exported as
``dragonfly_scheduler_decision_*`` metrics, ride ``flight.dump()`` /
``/debug/flight`` under the ``decisions`` key, and feed ``tools/dfwhy.py``
("why did peer X get parent Y") plus the ledger→training-trace exporter
in :mod:`dragonfly2_tpu.training.data`.

Determinism contract: every column except the wall-clock ones
(``decided_at_ns``, ``outcome_ttc_ns``) is a pure function of the replay
— :meth:`DecisionLedger.deterministic_digest` is pinned identical across
paired-seed megascale runs (tests/test_megascale.py). The failure-rate
regret basis is likewise wall-free so it may ride deterministic timeline
samples; the TTC-ms basis is wall-derived and stays out of them.
"""

from __future__ import annotations

import threading
import time
import weakref

import numpy as np

# Compact per-candidate feature row recorded with every decision — the
# subset of the scoring features that (a) explains the rule blend's
# ordering (dfwhy) and (b) the trainer exporter needs (pair features).
DECISION_FEATURES = (
    "finished_pieces",
    "upload_count",
    "upload_failed_count",
    "free_upload",
    "host_type",
    "in_degree",
    "same_idc",
    "loc_match",
)
_F = len(DECISION_FEATURES)
_IDX = {name: i for i, name in enumerate(DECISION_FEATURES)}

ARM_CODES = {"default": 0, "nt": 1, "ml": 2, "plugin": 3}
ARM_NAMES = {v: k for k, v in ARM_CODES.items()}

OUTCOME_PENDING = 0
OUTCOME_COMPLETED = 1
OUTCOME_FAILED = 2
OUTCOME_BACK_TO_SOURCE = 3
OUTCOME_NAMES = {
    OUTCOME_PENDING: "pending",
    OUTCOME_COMPLETED: "completed",
    OUTCOME_FAILED: "failed",
    OUTCOME_BACK_TO_SOURCE: "back_to_source",
}


def compact_features(fd: dict, in_degree: np.ndarray,
                     max_location_elements: int = 5) -> np.ndarray:
    """(B, K, F) float32 ledger feature matrix from the tick's host-side
    feature dict (state.gather_candidates output) — one vectorised stack
    per tick, shared by every chunk's record."""
    child_idc = np.asarray(fd["child_idc"])[:, None]
    parent_idc = np.asarray(fd["parent_idc"])
    same_idc = ((parent_idc == child_idc) & (child_idc != 0)).astype(np.float32)
    ploc = np.asarray(fd["parent_location"])
    cloc = np.asarray(fd["child_location"])[:, None, :]
    elem_eq = (ploc == cloc) & (ploc != 0) & (cloc != 0)
    prefix = np.cumprod(elem_eq.astype(np.int32), axis=-1)
    loc_match = prefix.sum(axis=-1).astype(np.float32) / max_location_elements
    return np.stack(
        [
            np.asarray(fd["finished_pieces"], np.float32),
            np.asarray(fd["upload_count"], np.float32),
            np.asarray(fd["upload_failed_count"], np.float32),
            (np.asarray(fd["upload_limit"], np.float32)
             - np.asarray(fd["upload_used"], np.float32)),
            np.asarray(fd["host_type"], np.float32),
            np.asarray(in_degree, np.float32),
            same_idc,
            loc_match,
        ],
        axis=-1,
    )


def extract_dump_rows(doc) -> list[dict]:
    """Every decision-ledger row reachable in a dump document (a raw
    ledger dump, a flight dump, or a bench/megascale report embedding
    one), in seq order. THE one walker over the dump shape — shared by
    tools/dfwhy.py and the trainer exporter (training/data.py) so a
    dump-shape change cannot break one consumer silently."""
    rows: list[dict] = []

    def walk(node):
        if isinstance(node, dict):
            r = node.get("rows")
            if isinstance(r, list) and "counters" in node and "features" in node:
                rows.extend(x for x in r if isinstance(x, dict))
                return
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(doc)
    rows.sort(key=lambda r: r.get("seq", 0))
    return rows


# Weak named registry (mirrors flight.register_recorder / the timeline
# registry) so the process-wide /debug/flight dump finds the live
# scheduler's ledger without a handle on the service. Last wins.
_LEDGERS: dict[str, "weakref.ref[DecisionLedger]"] = {}
_ledgers_mu = threading.Lock()


def register_ledger(name: str, ledger: "DecisionLedger") -> None:
    with _ledgers_mu:
        _LEDGERS[name] = weakref.ref(ledger)


def live_ledgers() -> dict[str, "DecisionLedger"]:
    out = {}
    with _ledgers_mu:
        for name, ref in list(_LEDGERS.items()):
            led = ref()
            if led is None:
                del _LEDGERS[name]
            else:
                out[name] = led
    return out


class DecisionLedger:
    """Bounded SoA ring of applied scheduling decisions.

    The hot path touches it twice per tick: one ``record_batch`` per
    applied chunk (block column assigns, one lock acquisition) and one
    ``record_shadow`` at the tick's end-of-round shadow drain. Outcome
    joins are O(1) per terminal peer event via the bounded
    peer→slot map. Everything else (dump/regret/export) runs off the
    hot path.
    """

    def __init__(self, capacity: int = 4096, k: int = 15, limit: int = 4,
                 registry=None, name: str | None = None,
                 peer_resolver=None, host_resolver=None):
        cap = max(int(capacity), 8)
        self.capacity = cap
        self.k = int(k)
        self.limit = int(limit)
        self._peer_resolver = peer_resolver
        self._host_resolver = host_resolver
        # --- SoA columns. seq == 0 marks an empty slot.
        self.seq = np.zeros(cap, np.int64)
        self.tick = np.zeros(cap, np.int64)
        self.arm = np.full(cap, -1, np.int8)
        self.child_peer_row = np.full(cap, -1, np.int32)
        self.child_host_slot = np.full(cap, -1, np.int32)
        self.cand_rows = np.full((cap, k), -1, np.int32)
        self.cand_hosts = np.full((cap, k), -1, np.int32)
        self.cand_count = np.zeros(cap, np.int16)
        self.cand_feats = np.zeros((cap, k, _F), np.float32)
        self.sel_pos = np.full((cap, limit), -1, np.int16)
        self.sel_scores = np.full((cap, limit), np.nan, np.float32)
        self.sel_accepted = np.zeros((cap, limit), bool)
        self.chosen_pos = np.full(cap, -1, np.int16)
        self.shadow_arm = np.full(cap, -1, np.int8)
        self.shadow_pos = np.full((cap, limit), -1, np.int16)
        self.shadow_scores = np.full((cap, limit), np.nan, np.float32)
        self.outcome = np.zeros(cap, np.int8)
        self.outcome_bytes = np.zeros(cap, np.int64)
        # measured download cost from the peer's REPORTED piece costs
        # (virtual time in replays, measured transfer time in
        # production) — the replay-safe label basis; -1 = not joined
        self.outcome_cost_ns = np.full(cap, -1, np.int64)
        self.outcome_corruption = np.zeros(cap, bool)
        self.outcome_failover = np.zeros(cap, bool)
        # wall-clock columns — EXCLUDED from the determinism digest
        self.decided_at_ns = np.zeros(cap, np.int64)
        self.outcome_ttc_ns = np.full(cap, -1, np.int64)
        # identity strings for dfwhy / the trainer exporter: one store
        # per decision (object columns, not per-decision dicts)
        self.child_peer_id = np.empty(cap, object)
        self.task_id = np.empty(cap, object)
        self.chosen_parent_id = np.empty(cap, object)
        # peer -> slot of its latest pending decision (bounded by cap)
        self._by_peer: dict[str, int] = {}
        self._head = 0
        self._seq = 0
        self._mu = threading.Lock()
        # cumulative shadow counters (deterministic — counts only)
        self.shadow_compared = 0
        self.shadow_top1_disagree = 0
        self.joined = 0
        # per-tick divergence entries (plain data, bounded)
        from collections import deque

        self.divergence_ring: "deque[dict]" = deque(maxlen=512)
        from dragonfly2_tpu.telemetry import metrics as _metrics
        from dragonfly2_tpu.telemetry.series import decision_series

        reg = registry if registry is not None else _metrics.default_registry()
        self._series = decision_series(reg)
        if name is not None:
            register_ledger(name, self)

    # ------------------------------------------------------------ record

    def record_batch(
        self,
        tick_id: int,
        arm: int,
        child_rows: np.ndarray,
        child_hosts: np.ndarray,
        cand_rows: np.ndarray,
        cand_hosts: np.ndarray,
        cand_count: np.ndarray,
        feats: np.ndarray,
        sel_pos: np.ndarray,
        sel_scores: np.ndarray,
        sel_accepted: np.ndarray,
        chosen_pos: np.ndarray,
        peer_ids: list,
        task_ids: list,
        chosen_ids: list,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Record N applied decisions as block column assigns; returns
        (ring slots, their seq numbers) — the tick's later shadow join
        passes BOTH back so a mid-tick ring wrap (a single tick applying
        more decisions than the capacity) can never attach shadow data
        to a slot a later chunk already overwrote. All array args are
        already sliced to the applied rows."""
        n = len(peer_ids)
        if n == 0:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)
        drop = 0
        if n > self.capacity:
            # ONE batch larger than the whole ring: only the newest
            # `capacity` decisions can survive, and assigning duplicate
            # slots within a single call would leave earlier rows'
            # peer→slot mappings pointing at columns a later row owns —
            # a cross-peer outcome join. Drop the oldest overflow up
            # front; their returned slots stay -1 (the shadow join
            # skips them) and their peers never map.
            drop = n - self.capacity
            child_rows = np.asarray(child_rows)[drop:]
            child_hosts = np.asarray(child_hosts)[drop:]
            cand_rows = np.asarray(cand_rows)[drop:]
            cand_hosts = np.asarray(cand_hosts)[drop:]
            cand_count = np.asarray(cand_count)[drop:]
            feats = np.asarray(feats)[drop:]
            sel_pos = np.asarray(sel_pos)[drop:]
            sel_scores = np.asarray(sel_scores)[drop:]
            sel_accepted = np.asarray(sel_accepted)[drop:]
            chosen_pos = np.asarray(chosen_pos)[drop:]
            peer_ids = list(peer_ids)[drop:]
            task_ids = list(task_ids)[drop:]
            chosen_ids = list(chosen_ids)[drop:]
            n = self.capacity
        kk = min(self.k, cand_rows.shape[1])
        ll = min(self.limit, sel_pos.shape[1])
        with self._mu:
            slots = (self._head + np.arange(n, dtype=np.int64)) % self.capacity
            self._head = int((self._head + n) % self.capacity)
            # evict overwritten slots' peer map entries (ring reuse)
            for s in slots:
                old = self.child_peer_id[s]
                if old is not None and self._by_peer.get(old) == int(s):
                    del self._by_peer[old]
            self._reset_slots(slots)
            seqs = self._seq + 1 + np.arange(n)
            self.seq[slots] = seqs
            self._seq += n
            self.tick[slots] = tick_id
            self.arm[slots] = arm
            self.child_peer_row[slots] = np.asarray(child_rows, np.int32)
            self.child_host_slot[slots] = np.asarray(child_hosts, np.int32)
            self.cand_rows[slots[:, None], np.arange(kk)] = (
                np.asarray(cand_rows, np.int32)[:, :kk]
            )
            self.cand_hosts[slots[:, None], np.arange(kk)] = (
                np.asarray(cand_hosts, np.int32)[:, :kk]
            )
            self.cand_count[slots] = np.minimum(
                np.asarray(cand_count, np.int64), kk
            ).astype(np.int16)
            self.cand_feats[slots[:, None], np.arange(kk)] = (
                np.asarray(feats, np.float32)[:, :kk]
            )
            self.sel_pos[slots[:, None], np.arange(ll)] = (
                np.asarray(sel_pos, np.int64)[:, :ll].astype(np.int16)
            )
            self.sel_scores[slots[:, None], np.arange(ll)] = (
                np.asarray(sel_scores, np.float32)[:, :ll]
            )
            self.sel_accepted[slots[:, None], np.arange(ll)] = (
                np.asarray(sel_accepted, bool)[:, :ll]
            )
            self.chosen_pos[slots] = np.asarray(chosen_pos, np.int64).astype(np.int16)
            self.decided_at_ns[slots] = time.time_ns()
            for i, s in enumerate(slots):
                self.child_peer_id[s] = peer_ids[i]
                self.task_id[s] = task_ids[i]
                self.chosen_parent_id[s] = chosen_ids[i]
                self._by_peer[peer_ids[i]] = int(s)
            self._series.decisions.labels(ARM_NAMES.get(int(arm), "?")).inc(n)
            self._series.occupancy.labels().set(int((self.seq > 0).sum()))
        if drop:
            pad = np.full(drop, -1, np.int64)
            slots = np.concatenate([pad, slots])
            seqs = np.concatenate([pad, seqs])
        return slots, seqs

    def _reset_slots(self, slots: np.ndarray) -> None:
        """Clear reused ring slots so a short selection cannot inherit a
        previous occupant's tail columns (caller holds the lock)."""
        self.cand_rows[slots] = -1
        self.cand_hosts[slots] = -1
        self.cand_feats[slots] = 0.0
        self.sel_pos[slots] = -1
        self.sel_scores[slots] = np.nan
        self.sel_accepted[slots] = False
        self.shadow_arm[slots] = -1
        self.shadow_pos[slots] = -1
        self.shadow_scores[slots] = np.nan
        self.outcome[slots] = OUTCOME_PENDING
        self.outcome_bytes[slots] = 0
        self.outcome_cost_ns[slots] = -1
        self.outcome_corruption[slots] = False
        self.outcome_failover[slots] = False
        self.outcome_ttc_ns[slots] = -1
        self.chosen_parent_id[slots] = None

    # ------------------------------------------------------------ shadow

    def record_shadow(self, slots: np.ndarray, seqs: np.ndarray,
                      shadow_pos: np.ndarray, shadow_scores: np.ndarray,
                      shadow_arm: int, tick_id: int) -> dict | None:
        """Attach the inactive arm's ranking for this tick's recorded
        decisions and compute the tick's divergence. ``slots``/``seqs``
        align row-for-row with ``shadow_pos``/``shadow_scores``; slot -1
        rows (selections that never applied) and slots whose seq no
        longer matches (overwritten by a mid-tick ring wrap) are
        skipped. Returns the per-tick divergence entry, or None when
        nothing compared."""
        slots = np.asarray(slots, np.int64)
        seqs = np.asarray(seqs, np.int64)
        keep = slots >= 0
        if not keep.any():
            return None
        keep &= self.seq[np.clip(slots, 0, self.capacity - 1)] == seqs
        if not keep.any():
            return None
        s = slots[keep]
        ll = min(self.limit, shadow_pos.shape[1])
        with self._mu:
            self.shadow_arm[s] = shadow_arm
            self.shadow_pos[s[:, None], np.arange(ll)] = (
                np.asarray(shadow_pos, np.int64)[keep][:, :ll].astype(np.int16)
            )
            self.shadow_scores[s[:, None], np.arange(ll)] = (
                np.asarray(shadow_scores, np.float32)[keep][:, :ll]
            )
            active = self.sel_pos[s].astype(np.int64)
            shadow = self.shadow_pos[s].astype(np.int64)
            entry = self._divergence(active, shadow, tick_id)
            if entry is not None:
                self.divergence_ring.append(entry)
                self.shadow_compared += entry["compared"]
                self.shadow_top1_disagree += entry["top1_disagreements"]
                self._series.shadow_scored.labels().inc(int(keep.sum()))
                self._series.top1_disagreement.labels().set(
                    entry["top1_disagreement"]
                )
                if entry["rank_corr"] is not None:
                    self._series.rank_corr.labels().set(entry["rank_corr"])
            return entry

    @staticmethod
    def _divergence(active: np.ndarray, shadow: np.ndarray,
                    tick_id: int) -> dict | None:
        """Top-1 disagreement + mean Spearman rank correlation between
        the two arms' ranked candidate-position lists. Both arms rank
        the SAME candidate set, so position equality is candidate
        identity equality."""
        both = (active[:, 0] >= 0) & (shadow[:, 0] >= 0)
        n = int(both.sum())
        if n == 0:
            return None
        disagree = int((active[both, 0] != shadow[both, 0]).sum())
        # rank of each active pick in the shadow list (missing -> limit)
        a = active[both]
        sh = shadow[both]
        limit = a.shape[1]
        match = (a[:, :, None] == sh[:, None, :]) & (a[:, :, None] >= 0)
        found = match.any(axis=2)
        pos_in_shadow = np.where(found, match.argmax(axis=2), limit).astype(
            np.float64
        )
        valid = a >= 0
        counts = valid.sum(axis=1)
        rho_rows = []
        rank_a = np.arange(limit, dtype=np.float64)
        for i in np.flatnonzero(counts >= 2):
            m = valid[i]
            ra = rank_a[m]
            rb = pos_in_shadow[i][m]
            sa = ra.std()
            sb = rb.std()
            if sa == 0 or sb == 0:
                rho_rows.append(1.0 if np.array_equal(ra, rb) else 0.0)
                continue
            rho_rows.append(float(np.corrcoef(ra, rb)[0, 1]))
        return {
            "tick": int(tick_id),
            "compared": n,
            "top1_disagreements": disagree,
            "top1_disagreement": round(disagree / n, 4),
            "rank_corr": round(float(np.mean(rho_rows)), 4) if rho_rows else None,
        }

    # ----------------------------------------------------------- outcome

    def join_outcome(self, peer_id: str, outcome: int,
                     bytes_: int = 0, cost_ns: int = 0) -> bool:
        """Join a terminal peer event to its latest recorded decision.
        O(1); the join latency (decision→outcome wall time) feeds the
        histogram and the per-decision TTC column. ``cost_ns`` is the
        download's cost summed from the peer's REPORTED piece costs —
        virtual time in a replay, measured transfer time in production
        — and is the label basis the trainer exporter prefers (wall TTC
        would encode simulator host speed, not parent quality)."""
        with self._mu:
            slot = self._by_peer.pop(peer_id, None)
            if slot is None:
                return False
            self.outcome[slot] = outcome
            self.outcome_bytes[slot] = int(bytes_ or 0)
            if cost_ns and cost_ns > 0:
                self.outcome_cost_ns[slot] = int(cost_ns)
            ttc = time.time_ns() - int(self.decided_at_ns[slot])
            self.outcome_ttc_ns[slot] = max(ttc, 0)
            self.joined += 1
            self._series.outcomes.labels(
                OUTCOME_NAMES.get(outcome, "?")
            ).inc()
            self._series.join_latency.labels().observe(max(ttc, 0) / 1e9)
            return True

    def mark_corruption(self, peer_id: str) -> None:
        """The peer's decision led it to a digest-failing parent."""
        with self._mu:
            slot = self._by_peer.get(peer_id)
            if slot is not None:
                self.outcome_corruption[slot] = True

    def mark_failover(self, peer_id: str) -> None:
        """The peer re-announced with kept pieces (scheduler failover)."""
        with self._mu:
            slot = self._by_peer.get(peer_id)
            if slot is not None:
                self.outcome_failover[slot] = True

    def discard(self, peer_id: str) -> None:
        """Forget the pending-join mapping for a departing peer (the
        decision row itself stays until the ring recycles it)."""
        with self._mu:
            self._by_peer.pop(peer_id, None)

    # ------------------------------------------------------------ regret

    def regret(self) -> dict:
        """Measured per-arm regret on disagreement decisions.

        Estimator: the joined decisions give a per-HOST outcome table
        (mean TTC of completed downloads whose chosen parent lived on
        that host; failure rate = failed/back-to-source/corrupt share).
        For each decision where the arms' top-1 picks differ, the active
        arm's regret is ``est(active_host) − est(shadow_host)`` —
        positive means the shadow's pick historically did better. Both
        bases ride the report; ``fail_rate`` is wall-free (deterministic
        in a replay), ``ttc_ms`` uses the joined wall TTC."""
        with self._mu:
            live = self.seq > 0
            joined = live & (self.outcome != OUTCOME_PENDING)
            chosen_ok = joined & (self.chosen_pos >= 0)
            rows = np.flatnonzero(chosen_ok)
            host_of = lambda slot_idx, pos: self.cand_hosts[  # noqa: E731
                slot_idx, np.clip(pos, 0, self.k - 1)
            ]
            out: dict = {
                "n_joined": int(joined.sum()),
                "n_disagreements": 0,
                "by_arm": {},
            }
            if rows.size == 0:
                return out
            hosts = host_of(rows, self.chosen_pos[rows].astype(np.int64))
            hmax = int(hosts.max()) + 1 if hosts.size else 1
            cnt = np.zeros(hmax)
            done_cnt = np.zeros(hmax)
            ttc_sum = np.zeros(hmax)
            fail_sum = np.zeros(hmax)
            ok = hosts >= 0
            bad = (
                (self.outcome[rows] != OUTCOME_COMPLETED)
                | self.outcome_corruption[rows]
            ).astype(np.float64)
            ttc_ms = np.maximum(self.outcome_ttc_ns[rows], 0) / 1e6
            np.add.at(cnt, hosts[ok], 1.0)
            np.add.at(fail_sum, hosts[ok], bad[ok])
            # TTC means over COMPLETED downloads only: a fast failure's
            # tiny TTC would otherwise make an always-failing host look
            # like the quickest pick and invert the regret sign —
            # failures are what the fail-rate basis measures
            done = ok & (bad == 0.0)
            np.add.at(done_cnt, hosts[done], 1.0)
            np.add.at(ttc_sum, hosts[done], ttc_ms[done])
            mean_ttc = ttc_sum / np.maximum(done_cnt, 1.0)
            fail_rate = fail_sum / np.maximum(cnt, 1.0)
            dis = np.flatnonzero(
                live & (self.sel_pos[:, 0] >= 0) & (self.shadow_pos[:, 0] >= 0)
                & (self.sel_pos[:, 0] != self.shadow_pos[:, 0])
            )
            out["n_disagreements"] = int(dis.size)
            for arm_code in np.unique(self.arm[dis]) if dis.size else ():
                d = dis[self.arm[dis] == arm_code]
                ah = host_of(d, self.sel_pos[d, 0].astype(np.int64))
                sh = host_of(d, self.shadow_pos[d, 0].astype(np.int64))
                in_range = (
                    (ah >= 0) & (sh >= 0) & (ah < hmax) & (sh < hmax)
                )
                ah_c = np.clip(ah, 0, hmax - 1)
                sh_c = np.clip(sh, 0, hmax - 1)
                # fail basis: any joined outcome on both hosts; TTC
                # basis: a COMPLETED mean must exist on both hosts
                known_fail = in_range & (cnt[ah_c] > 0) & (cnt[sh_c] > 0)
                known_ttc = in_range & (done_cnt[ah_c] > 0) & (
                    done_cnt[sh_c] > 0
                )
                entry = {"n": int(known_fail.sum()),
                         "regret_ttc_ms": None, "regret_fail_rate": None}
                name = ARM_NAMES.get(int(arm_code), "?")
                if known_ttc.any():
                    entry["regret_ttc_ms"] = round(
                        float((mean_ttc[ah[known_ttc]]
                               - mean_ttc[sh[known_ttc]]).mean()),
                        3,
                    )
                    self._series.regret.labels(name).set(entry["regret_ttc_ms"])
                if known_fail.any():
                    entry["regret_fail_rate"] = round(
                        float((fail_rate[ah[known_fail]]
                               - fail_rate[sh[known_fail]]).mean()),
                        4,
                    )
                out["by_arm"][name] = entry
            return out

    # ----------------------------------------------------------- reading

    def counters(self) -> dict:
        """Deterministic cumulative counters (wall-free — safe for
        megascale timeline samples)."""
        with self._mu:
            return {
                "decisions": int(self._seq),
                "joined": int(self.joined),
                "shadow_compared": int(self.shadow_compared),
                "shadow_top1_disagree": int(self.shadow_top1_disagree),
            }

    def divergence_summary(self) -> dict:
        """Aggregate divergence over the retained per-tick entries plus
        the regret estimate — the bench artifact's decision block."""
        with self._mu:
            entries = list(self.divergence_ring)
        compared = sum(e["compared"] for e in entries)
        disagree = sum(e["top1_disagreements"] for e in entries)
        corrs = [e["rank_corr"] for e in entries if e["rank_corr"] is not None]
        return {
            "ticks_compared": len(entries),
            "compared": compared,
            "top1_disagreement": round(disagree / compared, 4) if compared else None,
            "rank_corr": round(float(np.mean(corrs)), 4) if corrs else None,
            "regret": self.regret(),
        }

    def report(self) -> dict:
        """THE flattened decision block for artifact writers (bench_loop
        / megascale soak / bench_megascale all consume this — one
        layout, so a key rename cannot silently drop a cell in one
        artifact): counters + aggregate divergence + both regret bases,
        per-arm and averaged. ``regret_ttc_ms`` and anything derived
        from wall TTC is NOT replay-deterministic; deterministic
        surfaces pick the fail-rate keys."""
        summary = self.divergence_summary()
        regret = summary.pop("regret")
        ttc = [e["regret_ttc_ms"] for e in regret["by_arm"].values()
               if e["regret_ttc_ms"] is not None]
        fail = [e["regret_fail_rate"] for e in regret["by_arm"].values()
                if e["regret_fail_rate"] is not None]
        return {
            **self.counters(),
            "top1_disagreement": summary["top1_disagreement"],
            "rank_corr": summary["rank_corr"],
            "n_disagreements": regret["n_disagreements"],
            "regret_ttc_ms": round(sum(ttc) / len(ttc), 3) if ttc else None,
            "regret_fail_rate": (
                round(sum(fail) / len(fail), 4) if fail else None
            ),
            "regret_by_arm": regret["by_arm"],
            "regret_fail_rate_by_arm": {
                arm: e["regret_fail_rate"]
                for arm, e in regret["by_arm"].items()
            },
        }

    def deterministic_columns(self) -> dict[str, np.ndarray]:
        """Every replay-determined column, in ring order — the megascale
        paired-seed determinism test compares these array-for-array.
        Wall-clock columns (decided_at_ns, outcome_ttc_ns) and the
        identity object columns (compared via the digest's string walk)
        are excluded."""
        with self._mu:
            order = np.argsort(self.seq, kind="stable")
            return {
                "seq": self.seq[order].copy(),
                "tick": self.tick[order].copy(),
                "arm": self.arm[order].copy(),
                "child_peer_row": self.child_peer_row[order].copy(),
                "child_host_slot": self.child_host_slot[order].copy(),
                "cand_rows": self.cand_rows[order].copy(),
                "cand_hosts": self.cand_hosts[order].copy(),
                "cand_count": self.cand_count[order].copy(),
                "cand_feats": self.cand_feats[order].copy(),
                "sel_pos": self.sel_pos[order].copy(),
                "sel_scores": self.sel_scores[order].copy(),
                "sel_accepted": self.sel_accepted[order].copy(),
                "chosen_pos": self.chosen_pos[order].copy(),
                "shadow_arm": self.shadow_arm[order].copy(),
                "shadow_pos": self.shadow_pos[order].copy(),
                "shadow_scores": self.shadow_scores[order].copy(),
                "outcome": self.outcome[order].copy(),
                "outcome_cost_ns": self.outcome_cost_ns[order].copy(),
                "outcome_corruption": self.outcome_corruption[order].copy(),
                "outcome_failover": self.outcome_failover[order].copy(),
            }

    def deterministic_digest(self) -> str:
        """Stable digest over the deterministic columns + the identity
        strings — two paired-seed replays must produce the same value."""
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        cols = self.deterministic_columns()
        for name in sorted(cols):
            h.update(name.encode())
            arr = cols[name]
            if arr.dtype == np.float32:
                # NaN payloads are stable within a platform; normalize
                # anyway so the digest never depends on NaN bit noise
                arr = np.nan_to_num(arr, nan=-1.0)
            h.update(np.ascontiguousarray(arr).tobytes())
        with self._mu:
            order = np.argsort(self.seq, kind="stable")
            for col in (self.child_peer_id, self.task_id, self.chosen_parent_id):
                for s in order:
                    v = col[s]
                    h.update(b"\x00" if v is None else str(v).encode())
        return h.hexdigest()

    def dump(self, last_n: int = 128) -> dict:
        """Plain-data snapshot for /debug/flight, bench artifacts, and
        dfwhy: the newest ``last_n`` decisions fully resolved (candidate
        peer/host ids via the attached resolvers — a recycled row
        resolves to its CURRENT occupant or None; the chosen parent's id
        was captured at decision time and cannot go stale)."""
        with self._mu:
            live = np.flatnonzero(self.seq > 0)
            order = live[np.argsort(self.seq[live], kind="stable")]
            # explicit zero guard: [-0:] is the WHOLE array in numpy/
            # python slicing, and last_n=0 is reachable from the HTTP
            # query surface — it must mean "no rows", not "all of them"
            order = order[-last_n:] if last_n > 0 else order[:0]
            rows = [self._row_dict(int(s)) for s in order]
        return {
            "config": {"capacity": self.capacity, "k": self.k,
                       "limit": self.limit},
            "counters": {
                "decisions": int(self._seq),
                "joined": int(self.joined),
                "shadow_compared": int(self.shadow_compared),
                "shadow_top1_disagree": int(self.shadow_top1_disagree),
            },
            "features": list(DECISION_FEATURES),
            "divergence": list(self.divergence_ring)[-32:],
            "rows": rows,
        }

    def _row_dict(self, s: int) -> dict:
        """One decision as plain data (caller holds the lock)."""
        count = int(self.cand_count[s])
        resolve_p = self._peer_resolver or (lambda _r: None)
        resolve_h = self._host_resolver or (lambda _h: None)
        cands = []
        rank_of = {int(p): j for j, p in enumerate(self.sel_pos[s]) if p >= 0}
        shadow_rank_of = {
            int(p): j for j, p in enumerate(self.shadow_pos[s]) if p >= 0
        }
        for pos in range(count):
            row = int(self.cand_rows[s, pos])
            entry = {
                "pos": pos,
                "peer_row": row,
                "peer": resolve_p(row),
                "host_slot": int(self.cand_hosts[s, pos]),
                "host": resolve_h(int(self.cand_hosts[s, pos])),
                "features": {
                    name: round(float(self.cand_feats[s, pos, i]), 4)
                    for name, i in _IDX.items()
                },
            }
            j = rank_of.get(pos)
            if j is not None:
                entry["rank"] = j
                entry["score"] = round(float(self.sel_scores[s, j]), 5)
                entry["accepted"] = bool(self.sel_accepted[s, j])
            sj = shadow_rank_of.get(pos)
            if sj is not None:
                entry["shadow_rank"] = sj
                entry["shadow_score"] = round(float(self.shadow_scores[s, sj]), 5)
            cands.append(entry)
        ttc = int(self.outcome_ttc_ns[s])
        cost = int(self.outcome_cost_ns[s])
        return {
            "seq": int(self.seq[s]),
            "tick": int(self.tick[s]),
            "arm": ARM_NAMES.get(int(self.arm[s]), None),
            "peer": self.child_peer_id[s],
            "task": self.task_id[s],
            "child_peer_row": int(self.child_peer_row[s]),
            "child_host_slot": int(self.child_host_slot[s]),
            "child_host": resolve_h(int(self.child_host_slot[s])),
            "candidates": cands,
            "chosen_pos": int(self.chosen_pos[s]),
            "chosen_parent": self.chosen_parent_id[s],
            "shadow_arm": ARM_NAMES.get(int(self.shadow_arm[s]), None),
            "shadow_top1_pos": int(self.shadow_pos[s, 0]),
            "shadow_agrees_top1": (
                bool(self.sel_pos[s, 0] == self.shadow_pos[s, 0])
                if self.sel_pos[s, 0] >= 0 and self.shadow_pos[s, 0] >= 0
                else None
            ),
            "outcome": {
                "state": OUTCOME_NAMES.get(int(self.outcome[s]), "?"),
                "ttc_ms": round(ttc / 1e6, 3) if ttc >= 0 else None,
                # replay-safe cost basis (reported piece costs): what
                # the trainer exporter labels from; ttc_ms is wall
                "cost_ms": round(cost / 1e6, 3) if cost >= 0 else None,
                "bytes": int(self.outcome_bytes[s]),
                "corruption": bool(self.outcome_corruption[s]),
                "failover": bool(self.outcome_failover[s]),
            },
        }
