"""Test harness: force an 8-device virtual CPU mesh before jax loads.

Mirrors the reference's approach of unit-testing "multi-node" logic without
a cluster (SURVEY.md §4): sharding/collective code paths run on
xla_force_host_platform_device_count=8 CPU devices; numeric kernels run on
the CPU backend with fixed seeds. No TPU needed in CI.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
