"""CLI: ``python -m tools.dflint [package-or-paths...]``.

Exit codes: 0 clean (waived findings allowed, but every waiver must
carry a reason), 1 unwaived findings or reason-less waivers (or, with
``--audit-waivers``, stale waivers), 2 usage.

``--list-waived`` prints the waived findings too — the audit view the
review wants when judging whether a waiver's argument still holds.

``--audit-waivers`` additionally fails on STALE waivers: a
``waive[RULE]`` comment whose rule no longer fires at that site. The
tier-1 static-analysis gate runs with this on, so an argued waiver is
deleted the moment its argument stops being needed instead of rotting
into a muzzle for the next unrelated finding.

``--json`` emits one machine-readable document (findings with stable
``rule@file:symbol`` ids, stale/reason-less waiver lists, scan stats)
for CI annotators; the human rendering is suppressed.

Wire-schema mode (the ``buf`` analog, tools/dflint/wireschema.py):
``--wire-schema`` prints the live extraction as JSON; ``--breaking``
diffs it against the checked-in ``tools/dfwire_schema.json`` and exits
1 on schema-breaking changes (add-field-with-default is the only
compatible evolution); ``--write`` (alone, or as the canonical
``--breaking --write`` spelling) regenerates the snapshot, bumping its
recorded ``schema_version`` when the change was breaking. These modes
run INSTEAD of the lint passes.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.dflint.core import run_dflint


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="dflint")
    parser.add_argument(
        "paths", nargs="*", default=["dragonfly2_tpu"],
        help="package dir (default: dragonfly2_tpu) or explicit .py files",
    )
    parser.add_argument("--root", default=".", help="repo root")
    parser.add_argument("--list-waived", action="store_true",
                        help="also print waived findings with their reasons")
    parser.add_argument("--audit-waivers", action="store_true",
                        help="fail on waivers whose rule no longer fires")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings on stdout")
    parser.add_argument("--wire-schema", action="store_true",
                        help="print the live wire-schema extraction as JSON")
    parser.add_argument("--breaking", action="store_true",
                        help="diff the live wire schema against the "
                             "checked-in snapshot; exit 1 on breaking "
                             "changes")
    parser.add_argument("--write", action="store_true",
                        help="regenerate the wire-schema snapshot "
                             "(records a schema_version bump on breaks; "
                             "usable alone or as --breaking --write)")
    args = parser.parse_args(argv)

    if args.wire_schema or args.breaking or args.write:
        from tools.dflint import wireschema

        if args.write:
            return wireschema.write_snapshot()
        if args.wire_schema:
            snapshot = wireschema.load_snapshot()
            version = (snapshot or {}).get("schema_version", 1)
            print(json.dumps(wireschema.extract(schema_version=version),
                             indent=1, sort_keys=True))
            return 0
        return wireschema.check_breaking()

    root = Path(args.root).resolve()
    files: list[Path] | None = None
    package = "dragonfly2_tpu"
    if args.paths != ["dragonfly2_tpu"]:
        explicit: list[Path] = []
        for p in args.paths:
            path = (root / p).resolve() if not Path(p).is_absolute() else Path(p)
            if path.is_dir():
                explicit.extend(sorted(path.rglob("*.py")))
            elif path.suffix == ".py":
                explicit.append(path)
            else:
                print(f"dflint: not a python file or dir: {p}", file=sys.stderr)
                return 2
        files = explicit
    report, contexts = run_dflint(root, package=package, files=files)
    reasonless = report.reasonless_waivers(contexts)
    # the stale list is always computed (nearly free once contexts are
    # parsed) so --json consumers can't mistake 'not audited' for
    # 'audited and clean'; --audit-waivers gates only the VERDICT
    stale = report.stale_waivers(contexts)
    failed = bool(
        report.unwaived() or reasonless or (stale and args.audit_waivers)
    )

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in report.findings],
            "reasonless_waivers": reasonless,
            "stale_waivers": stale,
            "waivers_audited": args.audit_waivers,
            "files_scanned": report.files_scanned,
            "duration_s": round(report.duration_s, 3),
            "ok": not failed,
        }, indent=2))
        return 1 if failed else 0

    print(report.render(include_waived=args.list_waived))
    for row in reasonless:
        print(f"REASONLESS WAIVER: {row}")
    if args.audit_waivers:
        for row in stale:
            print(f"STALE WAIVER: {row}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
