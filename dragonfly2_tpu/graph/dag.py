"""Per-task peer DAG — dual representation: mutable host-side adjacency +
batched device reachability kernels.

Capability parity with the reference's generic concurrent DAG
(pkg/graph/dag/dag.go:49-368: AddVertex/DeleteVertex/AddEdge with cycle
check `CanAddEdge`, DeleteEdge, in/out-degree, GetRandomVertices) used for
per-task peer graphs (scheduler/resource/task.go:155).

TPU-first split (SURVEY.md §7 stage 3): the *mutation* path (one edge at a
time, at announce-stream rate) stays host-side on dense-int adjacency — a
numpy bitset matrix per task, capacity-bounded — while the *query* path the
evaluator needs (per-tick `in_degree` and `can_add_edge` for B x K
candidates across many tasks) is a batched jitted kernel over stacked
bitset adjacency: reachability via bounded frontier expansion on bit-packed
rows (child reaches parent => adding parent->child closes a cycle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


class DAGError(Exception):
    pass


class TaskDAG:
    """Fixed-capacity DAG over peer slots 0..P-1 with uint64 bitset rows.

    `adj[u]` holds the bitset of direct children of u (edge u->v).
    """

    def __init__(self, capacity: int = 256):
        if capacity % 64 != 0:
            raise ValueError("capacity must be a multiple of 64")
        self.capacity = capacity
        self.words = capacity // 64
        self.adj = np.zeros((capacity, self.words), np.uint64)
        self.present = np.zeros(capacity, bool)
        self.in_degree = np.zeros(capacity, np.int32)
        self.out_degree = np.zeros(capacity, np.int32)

    # ------------------------------------------------------------ vertices

    def add_vertex(self, v: int) -> None:
        if self.present[v]:
            raise DAGError(f"vertex {v} already exists")
        self.present[v] = True

    def ensure_vertex(self, v: int) -> None:
        self.present[v] = True

    def delete_vertex(self, v: int) -> None:
        """Remove v and all incident edges (dag.go DeleteVertex)."""
        if not self.present[v]:
            return
        word, bit = divmod(v, 64)
        mask = np.uint64(1) << np.uint64(bit)
        # in-edges: every u with bit v set
        parents = np.nonzero(self.adj[:, word] & mask)[0]
        for u in parents:
            self.adj[u, word] &= ~mask
            self.out_degree[u] -= 1
        # out-edges of v
        children = self._children(v)
        self.in_degree[children] -= 1
        self.adj[v] = 0
        self.out_degree[v] = 0
        self.in_degree[v] = 0
        self.present[v] = False

    def _children(self, u: int) -> np.ndarray:
        bits = self.adj[u]
        out = []
        for w in range(self.words):
            word = int(bits[w])
            while word:
                b = word & -word
                out.append(w * 64 + b.bit_length() - 1)
                word ^= b
        return np.asarray(out, dtype=np.int64)

    # --------------------------------------------------------------- edges

    def has_edge(self, u: int, v: int) -> bool:
        word, bit = divmod(v, 64)
        return bool(self.adj[u, word] & (np.uint64(1) << np.uint64(bit)))

    def reachable(self, src: int, dst: int) -> bool:
        """BFS over bitset rows: can src reach dst? (dag.go DFS :84-86).
        Runs in native code when dfnative is built (cycle checks sit on
        the DAG-mutation hot path); the Python loop below is the
        fallback and the parity oracle for its tests."""
        if src == dst:
            return True
        from dragonfly2_tpu import native

        result = native.dag_reachable(self.adj, src, dst)
        if result is not None:
            return result
        frontier = np.zeros(self.words, np.uint64)
        word, bit = divmod(src, 64)
        frontier[word] = np.uint64(1) << np.uint64(bit)
        visited = frontier.copy()
        dw, db = divmod(dst, 64)
        dmask = np.uint64(1) << np.uint64(db)
        while frontier.any():
            nxt = np.zeros(self.words, np.uint64)
            for w in range(self.words):
                word_bits = int(frontier[w])
                while word_bits:
                    b = word_bits & -word_bits
                    u = w * 64 + b.bit_length() - 1
                    nxt |= self.adj[u]
                    word_bits ^= b
            nxt &= ~visited
            if nxt[dw] & dmask:
                return True
            visited |= nxt
            frontier = nxt
        return False

    def can_add_edge(self, u: int, v: int) -> bool:
        """Edge u->v is legal iff both exist, it's not a self-loop or
        duplicate, and v cannot already reach u (dag.go CanAddEdge)."""
        if u == v or not (self.present[u] and self.present[v]):
            return False
        if self.has_edge(u, v):
            return False
        return not self.reachable(v, u)

    def can_add_edges(self, parents: np.ndarray, child: int) -> np.ndarray:
        """Vectorized `can_add_edge(p, child)` over candidate parents —
        `can_add_edges_pairs` with one shared child (the legality rules
        live ONLY there so the two batch paths cannot diverge)."""
        parents = np.asarray(parents, np.int64)
        n = parents.shape[0]
        # child may be an unassigned dag_slot (-1): nothing is legal then
        if n == 0 or not (0 <= child < self.capacity) or not self.present[child]:
            return np.zeros(n, bool)
        return self.can_add_edges_pairs(parents, np.full(n, child, np.int64))

    def can_add_edges_pairs(self, parents: np.ndarray, children: np.ndarray) -> np.ndarray:
        """`can_add_edge(p, c)` over ALIGNED (parent, child) pairs in one
        native call — `can_add_edges` with the child varying per pair.
        The tick batches EVERY pending peer of a task through here, so a
        task with m peers x k candidates pays one ctypes round-trip
        instead of m (the per-call marshalling cost ~100 us dominated the
        host-side tick at scale)."""
        parents = np.asarray(parents, np.int64)
        children = np.asarray(children, np.int64)
        n = parents.shape[0]
        if n == 0:
            return np.zeros(0, bool)
        if n <= 32:
            # scalar twin of the vectorised checks below: the tick calls
            # this once per task with a handful of pairs (one pending peer
            # x k samples, or a few selected parents), where a dozen
            # whole-array numpy ops on 4-element arrays are pure call
            # overhead (~25 us/call, two call sites per task per tick)
            pl = parents.tolist()
            cl = children.tolist()
            cap = self.capacity
            adj = self.adj
            present = self.present
            out_deg = self.out_degree
            ok = np.zeros(n, bool)
            need_idx: list[int] = []
            for i in range(n):
                p = pl[i]
                c = cl[i]
                if (
                    p == c
                    or not (0 <= p < cap and 0 <= c < cap)
                    or not (present[p] and present[c])
                    or (int(adj[p, c >> 6]) >> (c & 63)) & 1
                ):
                    continue
                ok[i] = True
                if out_deg[c] > 0:
                    need_idx.append(i)
            if need_idx:
                from dragonfly2_tpu import native

                idx = np.asarray(need_idx, np.int64)
                batch = native.dag_reachable_batch(
                    self.adj, children[idx], parents[idx]
                )
                if batch is not None:
                    ok[idx] &= ~batch
                else:
                    for i in need_idx:
                        if self.reachable(cl[i], pl[i]):
                            ok[i] = False
            return ok
        p_in = (parents >= 0) & (parents < self.capacity)
        c_in = (children >= 0) & (children < self.capacity)
        safe_p = np.where(p_in, parents, 0)
        safe_c = np.where(c_in, children, 0)
        ok = (
            p_in & c_in
            & self.present[safe_p] & self.present[safe_c]
            & (parents != children)
        )
        word, bit = np.divmod(safe_c, 64)
        ok &= (self.adj[safe_p, word] & (np.uint64(1) << bit.astype(np.uint64))) == 0
        # Cycle check only where the child can reach ANYTHING: a child with
        # no out-edges (the common case — a fresh downloader serves nobody
        # yet) cannot reach the parent, so the edge is legal without a
        # reachability query. This drops the native round-trip from "every
        # scheduled peer" to "peers that already serve others".
        need = ok & (self.out_degree[safe_c] > 0)
        if not need.any():
            return ok
        from dragonfly2_tpu import native

        idx = np.nonzero(need)[0]
        batch = native.dag_reachable_batch(self.adj, children[idx], parents[idx])
        if batch is not None:
            ok[idx] &= ~batch
        else:  # native lib unavailable: per-query fallback
            for i in idx:
                if self.reachable(int(children[i]), int(parents[i])):
                    ok[i] = False
        return ok

    def add_edge(self, u: int, v: int) -> None:
        if not self.can_add_edge(u, v):
            raise DAGError(f"edge {u}->{v} rejected (missing vertex, duplicate, or cycle)")
        self._add_edge_unchecked(u, v)

    def _add_edge_unchecked(self, u: int, v: int) -> None:
        word, bit = divmod(v, 64)
        self.adj[u, word] |= np.uint64(1) << np.uint64(bit)
        self.out_degree[u] += 1
        self.in_degree[v] += 1

    def add_edges_from(self, parents: np.ndarray, child: int) -> np.ndarray:
        """Add every legal `p -> child` edge in ONE legality batch; returns
        the per-parent accepted mask. Equivalent to sequential add_edge
        over the same list: all the new edges END at `child`, so none can
        change reachability FROM `child` — each edge's cycle check against
        the pre-call graph is exactly the check sequential adds would
        make. One native reachability round-trip per scheduled peer
        instead of one per selected parent (scheduler _apply_selection)."""
        parents = np.asarray(parents, np.int64)
        ok = self.can_add_edges(parents, child)
        # a parent repeated IN THIS BATCH must only add once
        if ok.any():
            seen: set[int] = set()
            for i in np.nonzero(ok)[0]:
                p = int(parents[i])
                if p in seen:
                    ok[i] = False
                    continue
                seen.add(p)
                self._add_edge_unchecked(p, child)
        return ok

    def add_edges_grouped(
        self, parents_list: list[np.ndarray], children: np.ndarray
    ) -> list[np.ndarray]:
        """Batched `add_edges_from` over MANY children in ONE legality
        round-trip, with sequential-equivalent semantics.

        Children must be distinct (one scheduling decision per peer per
        tick). The legality of every (parent, child) pair is checked in a
        single `can_add_edges_pairs` batch against the pre-batch graph;
        groups are then applied in list order. A pre-batch answer can only
        go stale for a pair whose parent became reachable from a child
        that gained in-edges EARLIER in this batch (every new path
        traverses some new edge, and all new edges end at batch
        children), so the apply loop tracks `affected` — the union of
        {child} ∪ descendants(child) bitsets of already-edged children,
        computed against the then-current graph — and re-checks exactly
        the pairs whose parent bit is set. In the common case (children
        with no out-edges) `affected` stays one bit per child and no pair
        ever re-checks, so the whole batch costs one native call where
        the per-peer path paid one per child.

        Returns the per-group accepted masks, identical to what
        sequential `add_edges_from` calls would have returned."""
        children = np.asarray(children, np.int64)
        lens = [len(p) for p in parents_list]
        if not lens or sum(lens) == 0:
            return [np.zeros(n, bool) for n in lens]
        flat_p = np.concatenate(
            [np.asarray(p, np.int64) for p in parents_list if len(p)]
        )
        flat_c = np.repeat(children, lens)
        ok0 = self.can_add_edges_pairs(flat_p, flat_c)
        results: list[np.ndarray] = []
        affected = np.zeros(self.words, np.uint64)
        any_touched = False
        off = 0
        for parents, child in zip(parents_list, children):
            n = len(parents)
            ok = ok0[off : off + n].copy()
            off += n
            child = int(child)
            seen: set[int] = set()
            touched = False
            for i in range(n):
                if not ok[i]:
                    continue
                p = int(parents[i])
                if p in seen:
                    ok[i] = False
                    continue
                if any_touched:
                    w, b = divmod(p, 64)
                    if affected[w] & (np.uint64(1) << np.uint64(b)):
                        # p is (possibly) reachable from an earlier-edged
                        # child — the pre-batch legality answer may be
                        # stale; re-check against the CURRENT graph
                        if self.reachable(child, p):
                            ok[i] = False
                            continue
                seen.add(p)
                self._add_edge_unchecked(p, child)
                touched = True
            if touched:
                any_touched = True
                if self.out_degree[child] == 0:
                    # no descendants: affected gains exactly the child bit
                    w, b = divmod(child, 64)
                    affected[w] |= np.uint64(1) << np.uint64(b)
                else:
                    affected |= self._reach_bitset(child)
            results.append(ok)
        return results

    def add_edges_single(self, parents: list, child: int) -> list:
        """Python-int twin of a ONE-group ``add_edges_grouped`` call — the
        dominant shape on the batched apply path (~one scheduling decision
        per task per tick leaves most groups with a single child). Same
        accepted mask, no array construction or staleness bookkeeping:
        with a single child the batch's `affected` set is always empty at
        check time, and legality against the pre-call graph is sound for
        the same reason as ``add_edges_from`` (every new edge ends at
        `child`, so no add changes reachability FROM `child`).

        `parents` is a plain list of python ints; returns a list of bools
        aligned with it."""
        cap = self.capacity
        present = self.present
        adj = self.adj
        out_deg = self.out_degree
        c = int(child)
        n = len(parents)
        ok = [False] * n
        if not (0 <= c < cap and present[c]):
            return ok
        check_cycle = out_deg[c] > 0
        need: list[int] = []
        for i in range(n):
            p = parents[i]
            if (
                p == c
                or not (0 <= p < cap)
                or not present[p]
                or (int(adj[p, c >> 6]) >> (c & 63)) & 1
            ):
                continue
            ok[i] = True
            if check_cycle:
                need.append(i)
        if need:
            from dragonfly2_tpu import native

            idx = np.asarray(need, np.int64)
            pn = np.asarray([parents[i] for i in need], np.int64)
            batch = native.dag_reachable_batch(
                adj, np.full(len(need), c, np.int64), pn
            )
            if batch is not None:
                for j, i in enumerate(need):
                    if batch[j]:
                        ok[i] = False
            else:
                for i in need:
                    if self.reachable(c, parents[i]):
                        ok[i] = False
        seen: set[int] = set()
        for i in range(n):
            if not ok[i]:
                continue
            p = parents[i]
            if p in seen:
                ok[i] = False
                continue
            seen.add(p)
            self._add_edge_unchecked(p, c)
        return ok

    def _reach_bitset(self, src: int) -> np.ndarray:
        """{src} ∪ descendants(src) as a word-bitset (numpy BFS over
        adjacency rows; exits immediately for a vertex with no
        out-edges)."""
        out = np.zeros(self.words, np.uint64)
        w, b = divmod(src, 64)
        out[w] = np.uint64(1) << np.uint64(b)
        frontier = [src]
        while frontier:
            nxt = np.bitwise_or.reduce(self.adj[frontier], axis=0) & ~out
            if not nxt.any():
                break
            out |= nxt
            frontier = np.flatnonzero(
                np.unpackbits(nxt.view(np.uint8), bitorder="little")
            ).tolist()
        return out

    def delete_edge(self, u: int, v: int) -> None:
        if not self.has_edge(u, v):
            return
        word, bit = divmod(v, 64)
        self.adj[u, word] &= ~(np.uint64(1) << np.uint64(bit))
        self.out_degree[u] -= 1
        self.in_degree[v] -= 1

    def delete_in_edges(self, v: int) -> None:
        """Drop all parent->v edges (task.DeletePeerInEdges)."""
        word, bit = divmod(v, 64)
        mask = np.uint64(1) << np.uint64(bit)
        parents = np.nonzero(self.adj[:, word] & mask)[0]
        for u in parents:
            self.adj[u, word] &= ~mask
            self.out_degree[u] -= 1
        self.in_degree[v] = 0

    def delete_out_edges(self, u: int) -> None:
        children = self._children(u)
        self.in_degree[children] -= 1
        self.adj[u] = 0
        self.out_degree[u] = 0

    def random_vertices(self, n: int, rng: np.random.Generator) -> np.ndarray:
        """Uniform sample of up to n present vertices (dag.go GetRandomVertices
        — the LoadRandomPeers feed for candidate filtering)."""
        live = np.nonzero(self.present)[0]
        if live.size == 0:
            return live
        take = min(n, live.size)
        return rng.choice(live, size=take, replace=False)

    def vertex_count(self) -> int:
        return int(self.present.sum())

    def edge_count(self) -> int:
        return int(self.out_degree.sum())


# ----------------------------------------------------------------- device

@functools.partial(jax.jit, static_argnames=("max_depth",))
def batch_reachable(adj: jax.Array, src: jax.Array, dst: jax.Array,
                    max_depth: int = 0) -> jax.Array:
    """Batched reachability on stacked bool adjacency.

    adj:  (B, P, P) bool — adj[b, u, v] means edge u->v in graph b
    src:  (B, Q) int32 start vertices
    dst:  (B, Q) int32 targets
    Returns (B, Q) bool. Frontier expansion is a bool matmul per step —
    MXU-friendly — run P steps (or `max_depth`) under lax.fori_loop with
    early saturation via the visited mask.
    """
    b, p, _ = adj.shape
    q = src.shape[1]
    depth = max_depth or p
    adj_f = adj.astype(jnp.float32)

    frontier = jax.nn.one_hot(src, p, dtype=jnp.float32)  # (B, Q, P)
    visited = frontier

    def body(_, carry):
        frontier, visited = carry
        nxt = jnp.einsum("bqp,bpr->bqr", frontier, adj_f)
        nxt = jnp.where(nxt > 0, 1.0, 0.0) * (1.0 - visited)
        visited = jnp.clip(visited + nxt, 0.0, 1.0)
        return nxt, visited

    _, visited = jax.lax.fori_loop(0, depth, body, (frontier, visited))
    hit = jnp.take_along_axis(visited, dst[..., None], axis=-1)[..., 0]
    return hit > 0


@functools.partial(jax.jit, static_argnames=("max_depth",))
def batch_can_add_edge(
    adj: jax.Array,        # (B, P, P) bool
    present: jax.Array,    # (B, P) bool
    parent: jax.Array,     # (B, K) int32 proposed parent vertex
    child: jax.Array,      # (B,) int32 child vertex
    max_depth: int = 0,
) -> jax.Array:
    """(B, K) bool: adding parent->child keeps the graph acyclic and simple.

    Mirrors TaskDAG.can_add_edge for a whole evaluator batch in one call:
    illegal if self-loop, either vertex absent, duplicate edge, or child
    already reaches parent.
    """
    b, k = parent.shape
    child_b = jnp.broadcast_to(child[:, None], (b, k))
    cycle = batch_reachable(adj, child_b, parent, max_depth)
    parent_present = jnp.take_along_axis(present, parent, axis=1)
    child_present = jnp.take_along_axis(present, child[:, None], axis=1)
    dup = adj[jnp.arange(b)[:, None], parent, child_b]
    return (parent != child_b) & parent_present & child_present & ~dup & ~cycle
