"""Sharded training loops — making trainer/training/training.go:60-98 real.

The reference spells out the intended pipeline in TODO comments (load from
storage -> preprocess -> train -> upload model); here it exists:

- `train_mlp`: probe-RTT regressor over topology pairs.
- `train_gnn`: GraphSAGE ranker over download traces + host graph.

Parallelism: data-parallel over the mesh's `dp` axis — batches sharded on
their leading dim, params replicated, XLA inserts the gradient all-reduce
over ICI (the pjit recipe from the scaling playbook). For graphs too big
for one chip, `embed_graph_sharded` shards the EDGE set over the mesh and
combines partial segment-sums with `psum` under `shard_map` — the
"pkg/graph DAG ops lower to scatter/segment_sum with psum across chips"
north star (BASELINE.json).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from dragonfly2_tpu.config.config import TrainerConfig
from dragonfly2_tpu.models.graphsage import GraphSAGERanker, RankBatch, listwise_rank_loss
from dragonfly2_tpu.models.mlp import ProbeRTTRegressor
from dragonfly2_tpu.models import metrics as M
from dragonfly2_tpu.parallel.mesh import DP_AXIS, GRAPH_AXIS, replicated, shard_batch
from dragonfly2_tpu.records.features import HostGraph, RankingDataset
from dragonfly2_tpu.training import data as D


@dataclasses.dataclass
class TrainResult:
    params: dict
    losses: list[float]
    eval_metrics: dict[str, float]
    samples_per_sec: float
    steps: int


def _make_step(loss_fn: Callable, optimizer: optax.GradientTransformation):
    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def train_mlp(
    x: np.ndarray,
    y: np.ndarray,
    config: TrainerConfig | None = None,
    mesh=None,
    seed: int = 0,
    eval_fraction: float = 0.2,
) -> TrainResult:
    """Train the probe-RTT regressor; returns params + MSE/MAE on held-out
    pairs (the registry's evaluation fields)."""
    config = config or TrainerConfig()
    rng = np.random.default_rng(seed)
    n = x.shape[0]
    perm = rng.permutation(n)
    n_eval = max(1, int(n * eval_fraction))
    eval_idx, train_idx = perm[:n_eval], perm[n_eval:]

    model = ProbeRTTRegressor(hidden_dim=config.hidden_dim)
    params = model.init(jax.random.key(seed), jnp.zeros((1, x.shape[1]), jnp.float32))
    optimizer = optax.adamw(config.learning_rate)
    opt_state = optimizer.init(params)

    def loss_fn(params, batch):
        pred = model.apply(params, batch["x"])
        return ((pred - batch["y"]) ** 2 * batch["w"]).sum() / jnp.maximum(batch["w"].sum(), 1.0)

    step = _make_step(loss_fn, optimizer)
    if mesh is not None:
        params = jax.device_put(params, replicated(mesh))
        opt_state = jax.device_put(opt_state, replicated(mesh))

    losses = []
    t0 = time.perf_counter()
    n_samples = 0
    for _ in range(config.epochs):
        for idx in D.minibatches(len(train_idx), min(config.batch_size, len(train_idx)), rng):
            batch = {
                "x": x[train_idx[idx]],
                "y": y[train_idx[idx]],
                "w": np.ones(len(idx), np.float32),
            }
            batch = shard_batch(mesh, batch) if mesh is not None else jax.device_put(batch)
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
            n_samples += len(idx)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0

    pred = model.apply(params, jnp.asarray(x[eval_idx]))
    eval_metrics = M.regression_report(np.asarray(pred), y[eval_idx])
    return TrainResult(
        params=params,
        losses=losses,
        eval_metrics=eval_metrics,
        samples_per_sec=n_samples / max(dt, 1e-9),
        steps=len(losses),
    )


def train_gnn(
    ds: RankingDataset,
    graph: HostGraph,
    config: TrainerConfig | None = None,
    mesh=None,
    seed: int = 0,
    eval_fraction: float = 0.2,
) -> TrainResult:
    """Train the GraphSAGE parent ranker; eval = precision/recall/F1 of its
    top-1 parent picks on held-out downloads (manager/types/model.go:58-64)."""
    config = config or TrainerConfig()
    rng = np.random.default_rng(seed)
    n = ds.child.shape[0]
    perm = rng.permutation(n)
    n_eval = max(1, int(n * eval_fraction))
    eval_idx, train_idx = perm[:n_eval], perm[n_eval:]

    garrs = D.graph_arrays(graph, pad_edges_to=D.edge_bucket(graph.edge_src.shape[0]))
    model = GraphSAGERanker(hidden_dim=config.hidden_dim)
    sample = _take_rank_batch(ds, train_idx[: min(2, len(train_idx))])
    params = model.init(
        jax.random.key(seed), garrs, sample.child_idx, sample.parent_idx, sample.pair_feats
    )
    optimizer = optax.adamw(config.learning_rate)
    opt_state = optimizer.init(params)

    def loss_fn(params, batch: RankBatch):
        scores = model.apply(params, garrs_dev, batch.child_idx, batch.parent_idx, batch.pair_feats)
        return listwise_rank_loss(scores, batch.throughput, batch.mask)

    if mesh is not None:
        params = jax.device_put(params, replicated(mesh))
        opt_state = jax.device_put(opt_state, replicated(mesh))
        garrs_dev = jax.device_put(garrs, replicated(mesh))
    else:
        garrs_dev = jax.device_put(garrs)

    step = _make_step(loss_fn, optimizer)

    sub = _subset_rank_dataset(ds, train_idx)
    losses = []
    t0 = time.perf_counter()
    n_samples = 0
    batch_size = min(config.batch_size, len(train_idx))
    for _ in range(config.epochs):
        for batch in D.rank_batches(sub, batch_size, rng):
            batch = shard_batch(mesh, batch) if mesh is not None else jax.device_put(batch)
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
            n_samples += batch_size
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0

    eval_batch = _take_rank_batch(ds, eval_idx)
    scores = model.apply(
        params, garrs_dev, eval_batch.child_idx, eval_batch.parent_idx, eval_batch.pair_feats
    )
    stats = M.top1_selection_stats(
        np.asarray(scores), eval_batch.throughput, eval_batch.mask
    )
    eval_metrics = {k: float(v) for k, v in stats.items()}
    return TrainResult(
        params=params,
        losses=losses,
        eval_metrics=eval_metrics,
        samples_per_sec=n_samples / max(dt, 1e-9),
        steps=len(losses),
    )


def train_attention(
    ds: RankingDataset,
    config: TrainerConfig | None = None,
    mesh=None,
    seed: int = 0,
    eval_fraction: float = 0.2,
) -> TrainResult:
    """Train the set-transformer parent ranker (models/attention.py) on
    the same RankingDataset the GNN consumes — candidates attend to each
    other, no graph needed. With a mesh, batches shard over dp and the
    attention inner product can run as ring attention over sp."""
    import functools

    from dragonfly2_tpu.models.attention import AttentionRanker
    from dragonfly2_tpu.parallel.ring import sharded_ring_attention
    from dragonfly2_tpu.parallel.mesh import SP_AXIS

    config = config or TrainerConfig()
    rng = np.random.default_rng(seed)
    n = ds.child.shape[0]
    perm = rng.permutation(n)
    n_eval = max(1, int(n * eval_fraction))
    eval_idx, train_idx = perm[:n_eval], perm[n_eval:]

    model = AttentionRanker(hidden_dim=config.hidden_dim)
    attention_fn = None
    if mesh is not None and mesh.shape.get(SP_AXIS, 1) > 1:
        attention_fn = functools.partial(sharded_ring_attention, mesh)

    def apply(params, child, parents, pair, mask):
        if attention_fn is not None:
            return model.apply(params, child, parents, pair, mask, attention_fn=attention_fn)
        return model.apply(params, child, parents, pair, mask)

    def take(idx):
        return {
            "child": ds.child[idx],
            "parents": ds.parents[idx],
            "pair": _pair_feats(ds, idx),
            "mask": ds.mask[idx],
            "throughput": ds.throughput[idx],
        }

    sample = take(train_idx[: min(2, len(train_idx))])
    params = model.init(
        jax.random.key(seed), sample["child"], sample["parents"], sample["pair"], sample["mask"]
    )
    optimizer = optax.adamw(config.learning_rate)
    opt_state = optimizer.init(params)

    def loss_fn(params, batch):
        scores = apply(params, batch["child"], batch["parents"], batch["pair"], batch["mask"])
        return listwise_rank_loss(scores, batch["throughput"], batch["mask"])

    if mesh is not None:
        params = jax.device_put(params, replicated(mesh))
        opt_state = jax.device_put(opt_state, replicated(mesh))

    step = _make_step(loss_fn, optimizer)
    losses = []
    t0 = time.perf_counter()
    n_samples = 0
    batch_size = min(config.batch_size, len(train_idx))
    for _ in range(config.epochs):
        order = rng.permutation(len(train_idx))
        for start in range(0, len(order) - batch_size + 1, batch_size):
            batch = take(train_idx[order[start : start + batch_size]])
            batch = shard_batch(mesh, batch) if mesh is not None else jax.device_put(batch)
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
            n_samples += batch_size
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0

    eb = take(eval_idx)
    n_real = eb["mask"].shape[0]
    if mesh is not None:
        # The sharded attention path requires the batch dim to divide dp;
        # pad with masked-out rows and slice the scores back.
        dp = mesh.shape.get(DP_AXIS, 1)
        pad = (-n_real) % dp
        if pad:
            eb = {
                k: np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                for k, v in eb.items()
            }
    scores = apply(
        jax.device_put(params) if mesh is None else params,
        eb["child"], eb["parents"], eb["pair"], eb["mask"],
    )
    stats = M.top1_selection_stats(
        np.asarray(scores)[:n_real], eb["throughput"][:n_real], eb["mask"][:n_real]
    )
    return TrainResult(
        params=params,
        losses=losses,
        eval_metrics={k: float(v) for k, v in stats.items()},
        samples_per_sec=n_samples / max(dt, 1e-9),
        steps=len(losses),
    )


def _pair_feats(ds: RankingDataset, idx: np.ndarray) -> np.ndarray:
    """(B, P, 2) pair features — the single definition both the GNN and
    attention trainers consume, so the families can never drift apart."""
    return np.concatenate(
        [ds.same_idc[idx, :, None], ds.loc_match[idx, :, None]], axis=-1
    ).astype(np.float32)


def _take_rank_batch(ds: RankingDataset, idx: np.ndarray) -> RankBatch:
    return RankBatch(
        child_idx=ds.child_host_idx[idx],
        parent_idx=ds.parent_host_idx[idx],
        pair_feats=_pair_feats(ds, idx),
        throughput=ds.throughput[idx],
        mask=ds.mask[idx],
    )


def _subset_rank_dataset(ds: RankingDataset, idx: np.ndarray) -> RankingDataset:
    return RankingDataset(
        child=ds.child[idx],
        parents=ds.parents[idx],
        same_idc=ds.same_idc[idx],
        loc_match=ds.loc_match[idx],
        mask=ds.mask[idx],
        throughput=ds.throughput[idx],
        child_host_idx=ds.child_host_idx[idx],
        parent_host_idx=ds.parent_host_idx[idx],
    )


def embed_graph_sharded(model: GraphSAGERanker, params, graph_arrays: dict, mesh):
    """Host embeddings with the EDGE set sharded across the whole mesh.

    Each device owns an edge shard, computes partial neighbor sums via
    `segment_sum` into a full-size node accumulator, then `psum` over both
    mesh axes combines partials — ICI traffic is 2 x nodes x dim per layer
    instead of the whole edge list. This is the scale path for 1M-piece /
    10k-peer traces (BASELINE.json configs[3]).
    """
    n_nodes = graph_arrays["node_feats"].shape[0]
    axes = (DP_AXIS, GRAPH_AXIS)
    n_shards = mesh.size

    # Pad the edge set to a multiple of the shard count; pads carry weight 0
    # so their segment contributions vanish.
    e = graph_arrays["edge_src"].shape[0]
    pad = (-e) % n_shards
    edge_src = jnp.concatenate([jnp.asarray(graph_arrays["edge_src"]), jnp.zeros(pad, jnp.int32)])
    edge_dst = jnp.concatenate([jnp.asarray(graph_arrays["edge_dst"]), jnp.zeros(pad, jnp.int32)])
    edge_feats = jnp.concatenate(
        [jnp.asarray(graph_arrays["edge_feats"]),
         jnp.zeros((pad,) + graph_arrays["edge_feats"].shape[1:], jnp.float32)]
    )
    edge_weight = jnp.concatenate([jnp.ones(e, jnp.float32), jnp.zeros(pad, jnp.float32)])

    def shard_fn(node_feats, edge_src, edge_dst, edge_feats, edge_weight):
        h = node_feats
        w = edge_weight.astype(jnp.float32)[:, None]
        for i in range(model.num_layers):
            layer_params = params["params"][f"sage_{i}"]
            h_c = h.astype(model.compute_dtype)
            # float32 segment accumulation, matching SAGELayer exactly
            ef = edge_feats.astype(jnp.float32) * w
            msgs = h_c[edge_dst].astype(jnp.float32) * w
            agg = jax.ops.segment_sum(msgs, edge_src, num_segments=n_nodes)
            cnt = jax.ops.segment_sum(w, edge_src, num_segments=n_nodes)
            e_agg = jax.ops.segment_sum(ef, edge_src, num_segments=n_nodes)
            # combine partial sums from every edge shard over ICI
            agg = jax.lax.psum(agg, axes)
            cnt = jax.lax.psum(cnt, axes)
            e_agg = jax.lax.psum(e_agg, axes)
            agg = (agg / jnp.maximum(cnt, 1.0)).astype(model.compute_dtype)
            e_agg = (e_agg / jnp.maximum(cnt, 1.0)).astype(model.compute_dtype)
            out = (
                h_c @ layer_params["self"]["kernel"].astype(model.compute_dtype)
                + layer_params["self"]["bias"].astype(model.compute_dtype)
                + agg @ layer_params["neigh"]["kernel"].astype(model.compute_dtype)
                + e_agg @ layer_params["edge"]["kernel"].astype(model.compute_dtype)
            )
            h = jax.nn.gelu(out)
        return h

    edge_spec = P((DP_AXIS, GRAPH_AXIS))
    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), edge_spec, edge_spec, edge_spec, edge_spec),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)(
        jnp.asarray(graph_arrays["node_feats"]), edge_src, edge_dst, edge_feats, edge_weight
    )
