"""Build metadata — capability parity with version/version.go (Major/
Minor/GitVersion/GitCommit/Platform + the per-service `version` metric
gauge every reference service exports, e.g. scheduler/metrics/
metrics.go:273-280)."""

from __future__ import annotations

import platform as _platform

MAJOR = "2"
MINOR = "2"
GIT_VERSION = "v2.2.0-tpu"
GIT_COMMIT = "unknown"
BUILD_PLATFORM = f"{_platform.system().lower()}/{_platform.machine()}"


def version() -> str:
    return GIT_VERSION


def version_info() -> dict:
    return {
        "major": MAJOR,
        "minor": MINOR,
        "git_version": GIT_VERSION,
        "git_commit": GIT_COMMIT,
        "platform": BUILD_PLATFORM,
    }


def register_version_gauge(registry, service: str) -> None:
    """dragonfly_<service>_version{major,minor,git_version,git_commit,
    platform} = 1 — the reference's BuildInfo gauge."""
    gauge = registry.gauge(
        f"dragonfly_{service}_version",
        "build metadata",
        ("major", "minor", "git_version", "git_commit", "platform"),
    )
    gauge.labels(MAJOR, MINOR, GIT_VERSION, GIT_COMMIT, BUILD_PLATFORM).set(1)
