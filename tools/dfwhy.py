#!/usr/bin/env python
"""dfwhy — answer "why did peer X get parent Y" from a decision-ledger
dump.

Input: any JSON document carrying decision-ledger rows
(telemetry/decisions.DecisionLedger.dump): a raw ledger dump, a
``flight.dump()`` / ``/debug/flight`` body (rows under
``decisions.<name>.rows``), or a megascale/scenario report embedding a
ledger dump. For each matching decision it reconstructs the full
candidate-set explanation: every candidate's feature row, the active
arm's rank/score and DAG verdict, the shadow arm's counterfactual
ranking, the chosen parent, and the joined outcome.

Usage:
    python tools/dfwhy.py DUMP.json --peer PEER_ID [--parent PARENT_ID]
    python tools/dfwhy.py DUMP.json --peer PEER_ID --json   # machine form
    python tools/dfwhy.py DUMP.json --list                  # peers seen

Exit codes: 0 = explanation printed, 1 = no matching decision, 2 = the
input carries no ledger rows.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from dragonfly2_tpu.telemetry.decisions import (  # noqa: E402
    extract_dump_rows as extract_rows,
)


def matches(row: dict, peer: str, parent: str | None) -> bool:
    if row.get("peer") != peer:
        return False
    if parent is None:
        return True
    if row.get("chosen_parent") == parent:
        return True
    return any(c.get("peer") == parent for c in row.get("candidates", ()))


def _fmt_features(feats: dict) -> str:
    return " ".join(f"{k}={v:g}" for k, v in feats.items())


def explain(row: dict, out=sys.stdout) -> None:
    arm = row.get("arm") or "?"
    print(
        f"decision seq={row.get('seq')} tick={row.get('tick')} "
        f"arm={arm} peer={row.get('peer')} task={row.get('task')} "
        f"child_host={row.get('child_host') or row.get('child_host_slot')}",
        file=out,
    )
    chosen = row.get("chosen_pos")
    for c in row.get("candidates", ()):
        marks = []
        if c.get("pos") == chosen:
            marks.append("CHOSEN")
        if "rank" in c:
            acc = "accepted" if c.get("accepted") else "dag-rejected"
            marks.append(f"rank={c['rank']} score={c['score']} {acc}")
        else:
            marks.append("filtered/unranked")
        if "shadow_rank" in c:
            marks.append(
                f"shadow_rank={c['shadow_rank']} "
                f"shadow_score={c['shadow_score']}"
            )
        peer = c.get("peer") or f"row:{c.get('peer_row')}"
        host = c.get("host") or f"slot:{c.get('host_slot')}"
        print(
            f"  cand[{c.get('pos')}] {peer} @ {host}  "
            f"{_fmt_features(c.get('features', {}))}  "
            f"[{' | '.join(marks)}]",
            file=out,
        )
    print(
        f"  chosen_parent={row.get('chosen_parent')} "
        f"(pos={chosen})",
        file=out,
    )
    shadow_arm = row.get("shadow_arm")
    if shadow_arm:
        agrees = row.get("shadow_agrees_top1")
        verdict = (
            "agrees with the active top-1" if agrees
            else "DISAGREES with the active top-1" if agrees is not None
            else "no comparable top-1"
        )
        print(
            f"  shadow arm={shadow_arm} top1_pos={row.get('shadow_top1_pos')} "
            f"— {verdict}",
            file=out,
        )
    else:
        print("  shadow: not scored (no inactive arm available)", file=out)
    o = row.get("outcome") or {}
    extras = [k for k in ("corruption", "failover") if o.get(k)]
    print(
        f"  outcome={o.get('state')} ttc_ms={o.get('ttc_ms')} "
        f"bytes={o.get('bytes')}"
        + (f" [{', '.join(extras)}]" if extras else ""),
        file=out,
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="JSON file carrying decision-ledger rows")
    ap.add_argument("--peer", help="child peer id to explain")
    ap.add_argument("--parent", default=None,
                    help="restrict to decisions involving this parent")
    ap.add_argument("--last", action="store_true",
                    help="only the newest matching decision")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the matching rows as JSON")
    ap.add_argument("--list", action="store_true", dest="list_peers",
                    help="list peers with recorded decisions and exit")
    args = ap.parse_args(argv)

    try:
        doc = json.loads(open(args.dump).read())
    except (OSError, json.JSONDecodeError) as e:
        print(f"dfwhy: cannot read {args.dump}: {e}", file=sys.stderr)
        return 2
    rows = extract_rows(doc)
    if not rows:
        print(f"dfwhy: no decision-ledger rows in {args.dump}",
              file=sys.stderr)
        return 2
    if args.list_peers:
        peers = sorted({r.get("peer") for r in rows if r.get("peer")})
        for p in peers:
            print(p)
        return 0
    if not args.peer:
        print("dfwhy: --peer is required (or --list)", file=sys.stderr)
        return 2
    hits = [r for r in rows if matches(r, args.peer, args.parent)]
    if not hits:
        print(
            f"dfwhy: no decision for peer {args.peer!r}"
            + (f" with parent {args.parent!r}" if args.parent else "")
            + f" among {len(rows)} ledger rows",
            file=sys.stderr,
        )
        return 1
    if args.last:
        hits = hits[-1:]
    if args.as_json:
        print(json.dumps(hits, indent=1))
        return 0
    for row in hits:
        explain(row)
    return 0


if __name__ == "__main__":
    sys.exit(main())
