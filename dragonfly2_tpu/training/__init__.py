from dragonfly2_tpu.training.train import (
    TrainResult,
    train_mlp,
    train_gnn,
    embed_graph_sharded,
)
from dragonfly2_tpu.training.checkpoint import TrainCheckpointer

__all__ = [
    "TrainResult",
    "train_mlp",
    "train_gnn",
    "embed_graph_sharded",
    "TrainCheckpointer",
]
