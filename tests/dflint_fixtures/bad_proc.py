"""dflint red fixture: DET001 (unseeded rng picking the divergence
tolerance), DET002 (wall clock stamping a synthesized round), DET003
(set-ordered sweep into the timeline) — shaped like the procworld
replay path (sample synthesis + divergence judging)."""

import random
import time


class Synthesizer:
    def __init__(self):
        self.regions = set()

    def jitter_band(self, lo, hi):
        return lo + random.random() * (hi - lo)  # <- DET001 (global rng)

    def stamp_round(self, sample):
        sample["t"] = time.time()  # <- DET002 (wall clock in replay path)
        return sample

    def region_rows(self):
        rows = []
        for region in self.regions:  # <- DET003 (set order into output)
            rows.append({"region": region})
        return rows
