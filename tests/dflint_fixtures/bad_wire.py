"""Known-bad wire-contract idioms — every WIRE001-004 shape fires.

Expected findings (tests/test_static_analysis.py pins the counts):
WIRE001 x4  (unregistered send, consumer-less send, dead registered
             type, dispatch arm without a producer)
WIRE002 x4  (set field, multi-element tuple, dataclass union,
             dataclass inside a dict value)
WIRE003 x2  (serve loop drops the deadline budget AND the trace)
WIRE004 x3  (declared v1 type without an arm, unreachable arm,
             untranslated scheduling response)
"""

import dataclasses

from dragonfly2_tpu.rpc import wire


@dataclasses.dataclass
class GoodMsg:
    x: int = 0


@dataclasses.dataclass
class OrphanMsg:  # registered below, constructed nowhere: dead type
    y: int = 0


@dataclasses.dataclass
class UnregisteredMsg:  # sent below without ever being registered
    z: int = 0


@dataclasses.dataclass
class NoArmMsg:  # registered and sent, but nothing dispatches it
    q: int = 0


@dataclasses.dataclass
class GhostMsg:  # armed in _dispatch below, constructed nowhere
    g: int = 0


@dataclasses.dataclass
class AltA:
    a: int = 0


@dataclasses.dataclass
class AltB:
    b: int = 0


@dataclasses.dataclass
class BadFieldMsg:
    tags: set[str] = dataclasses.field(default_factory=set)
    pair: tuple[int, str] = (0, "")
    either: AltA | AltB | None = None
    lookup: dict[str, AltA] = dataclasses.field(default_factory=dict)


wire.register_messages(GoodMsg, OrphanMsg, NoArmMsg, BadFieldMsg)


def make_payload() -> BadFieldMsg:
    return BadFieldMsg()


def client_send(writer) -> None:
    wire.write_frame(writer, GoodMsg(x=1))
    wire.write_frame(writer, UnregisteredMsg(z=1))  # WIRE001: unregistered
    wire.write_frame(writer, NoArmMsg(q=2))  # WIRE001: nobody consumes it


def _dispatch(request):
    if isinstance(request, GoodMsg):
        return GoodMsg(x=request.x + 1)
    if isinstance(request, GhostMsg):  # WIRE001: no live producer
        return None
    return None


async def _serve_conn(reader, writer):  # WIRE003 x2: no budget, no trace
    while True:
        request = await wire.read_frame(reader)
        if request is None:
            return
        response = _dispatch(request)
        if response is not None:
            wire.write_frame(writer, response)


# ---------------------------------------------------------- v1 dialect


@dataclasses.dataclass
class V1AReq:
    task_id: str = ""


@dataclasses.dataclass
class V1BReq:
    task_id: str = ""


@dataclasses.dataclass
class V1CReq:
    task_id: str = ""


@dataclasses.dataclass
class NormalT:
    peer_id: str = ""


@dataclasses.dataclass
class FailT:
    peer_id: str = ""


V1_REQUEST_TYPES = (V1AReq, V1BReq)  # WIRE004: V1BReq has no arm below


def v1_producer():
    return [V1AReq(task_id="t"), V1CReq(task_id="t")]


def _dispatch_v1(request):
    if isinstance(request, V1AReq):
        return NormalT(peer_id="p")
    if isinstance(request, V1CReq):  # WIRE004: not in V1_REQUEST_TYPES
        return None
    return None


def to_peer_packet(response):  # WIRE004: FailT never translated
    if isinstance(response, NormalT):
        return {"src_pid": response.peer_id}
    return None
