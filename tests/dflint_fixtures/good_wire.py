"""Known-good wire-contract idioms — the dfwire pass must stay silent.

The closed loop: every registered type is produced, sent, and armed;
fields stay inside the codec lattice (scalars, Optional, list[T],
nested dataclass, enum, dict-of-scalars); the serve loop re-anchors the
propagated deadline budget and continues the trace; the v1 dialect's
request tuple, dispatch arms and response translations are exhaustive.
"""

import dataclasses
import enum

from dragonfly2_tpu.rpc import resilience, wire
from dragonfly2_tpu.telemetry.tracing import default_tracer


class Kind(enum.IntEnum):
    A = 0
    B = 1


@dataclasses.dataclass
class Inner:
    name: str = ""
    score: float = 0.0


@dataclasses.dataclass
class PingMsg:
    peer_id: str
    kind: Kind = Kind.A
    parents: list[Inner] = dataclasses.field(default_factory=list)
    note: str | None = None
    detail: dict = dataclasses.field(default_factory=dict)
    window: tuple[int, ...] = ()


@dataclasses.dataclass
class PongMsg:
    peer_id: str
    inner: Inner = dataclasses.field(default_factory=Inner)


wire.register_messages(PingMsg, PongMsg)


def client_send(writer) -> None:
    wire.write_frame(writer, PingMsg(peer_id="p"))


def client_consume(response) -> str:
    if isinstance(response, PongMsg):
        return response.peer_id
    return ""


def _dispatch(request):
    if isinstance(request, PingMsg):
        return PongMsg(peer_id=request.peer_id)
    return None


async def _serve_conn(reader, writer):
    while True:
        request = await wire.read_frame(reader)
        if request is None:
            return
        budget = getattr(request, "deadline_s", None)
        remote_ctx = getattr(request, "trace_context", None)
        with default_tracer().span("rpc", remote_parent=remote_ctx):
            if budget is not None:
                with resilience.deadline(budget):
                    response = _dispatch(request)
            else:
                response = _dispatch(request)
        if response is not None:
            wire.write_frame(writer, response)


# ---------------------------------------------------------- v1 dialect


@dataclasses.dataclass
class V1GoodReq:
    task_id: str = ""


@dataclasses.dataclass
class NormalT:
    peer_id: str = ""


@dataclasses.dataclass
class FailT:
    peer_id: str = ""


V1_REQUEST_TYPES = (V1GoodReq,)


def v1_producer() -> V1GoodReq:
    return V1GoodReq(task_id="t")


def _dispatch_v1(request):
    if isinstance(request, V1GoodReq):
        return NormalT(peer_id="p")
    return None


def to_peer_packet(response):
    if isinstance(response, NormalT):
        return {"src_pid": response.peer_id, "code": 200}
    if isinstance(response, FailT):
        return {"src_pid": response.peer_id, "code": 5000}
    return None
