"""Object-storage backends behind one interface.

Capability parity with pkg/objectstorage/objectstorage.go:206-211 — the
ObjectStorage interface (bucket CRUD, object CRUD, metadata, existence,
sign URLs) with per-vendor constructors (s3.go / oss.go / obs.go). The
filesystem backend is the real implementation (the model-registry bucket,
trace archives, and tests all ride it); the cloud vendors register as
gated stubs because their SDKs are not in the image — `new_backend`
raises `Unavailable` with the vendor name so callers can degrade the way
the reference degrades when a bucket is unreachable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pathlib
import shutil

from dragonfly2_tpu.utils import dferrors


@dataclasses.dataclass
class ObjectMetadata:
    """pkg/objectstorage ObjectMetadata: key, size, etag, content type,
    modified time."""

    key: str
    content_length: int
    etag: str = ""
    content_type: str = ""
    last_modified_at: float = 0.0
    storage_class: str = ""


@dataclasses.dataclass
class BucketMetadata:
    name: str
    created_at: float


class FilesystemBackend:
    """Buckets are directories, objects are files; etag is md5 (matching
    S3 single-part semantics the reference relies on for dfstore digests)."""

    name = "fs"

    def __init__(self, base_dir: str | pathlib.Path):
        self.base = pathlib.Path(base_dir).absolute()
        self.base.mkdir(parents=True, exist_ok=True)

    # ------------------------------------------------------------- buckets

    def create_bucket(self, bucket: str) -> None:
        self._bucket_dir(bucket).mkdir(parents=True, exist_ok=True)

    def delete_bucket(self, bucket: str) -> None:
        d = self._bucket_dir(bucket)
        if any(p.is_file() for p in d.rglob("*")):
            raise dferrors.InvalidArgument(f"bucket {bucket} not empty")
        shutil.rmtree(d, ignore_errors=True)

    def is_bucket_exist(self, bucket: str) -> bool:
        return self._bucket_dir(bucket).is_dir()

    def get_bucket_metadatas(self) -> list[BucketMetadata]:
        out = []
        for d in sorted(self.base.iterdir()):
            if d.is_dir():
                out.append(BucketMetadata(name=d.name, created_at=d.stat().st_mtime))
        return out

    # ------------------------------------------------------------- objects

    def put_object(self, bucket: str, key: str, data: bytes) -> ObjectMetadata:
        path = self._object_path(bucket, key)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_bytes(data)
        tmp.replace(path)
        # etag sidecar: listings must not re-hash every object's bytes
        _etag_path(path).write_text(hashlib.md5(data).hexdigest())
        return self.get_object_metadata(bucket, key)

    def put_object_if_absent(self, bucket: str, key: str, data: bytes) -> bool:
        """Atomic create-if-missing (the S3 `If-None-Match: *` conditional
        PUT): returns False, writing nothing, when the key already exists.
        The bytes are staged to a tmp file and os.link'd into place —
        link fails if the target exists (the CAS) and publishes the fully
        written file in one step, so a concurrent reader can never observe
        a half-written object (a direct O_EXCL open would expose empty/
        partial bytes between create and close)."""
        path = self._object_path(bucket, key)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f"{path.name}.{os.getpid()}.{id(data):x}.tmp")
        tmp.write_bytes(data)
        try:
            os.link(tmp, path)
        except FileExistsError:
            return False
        finally:
            tmp.unlink(missing_ok=True)
        _etag_path(path).write_text(hashlib.md5(data).hexdigest())
        return True

    def get_object(self, bucket: str, key: str, range_: tuple[int, int] | None = None) -> bytes:
        path = self._object_path(bucket, key)
        if not path.is_file():
            raise dferrors.NotFound(f"object {bucket}/{key} not found")
        data = path.read_bytes()
        if range_ is not None:
            start, end = range_
            data = data[start : end + 1]
        return data

    def get_object_metadata(self, bucket: str, key: str) -> ObjectMetadata:
        path = self._object_path(bucket, key)
        if not path.is_file():
            raise dferrors.NotFound(f"object {bucket}/{key} not found")
        return ObjectMetadata(
            key=key,
            content_length=path.stat().st_size,
            etag=_etag_of(path),
            last_modified_at=path.stat().st_mtime,
        )

    def get_object_metadatas(self, bucket: str, prefix: str = "", limit: int = 1000) -> list[ObjectMetadata]:
        bucket_dir = self._bucket_dir(bucket)
        if not bucket_dir.is_dir():
            raise dferrors.NotFound(f"bucket {bucket} not found")
        out = []
        for path in sorted(bucket_dir.rglob("*")):
            if not path.is_file() or path.name.endswith((".tmp", ".etag")):
                continue
            key = path.relative_to(bucket_dir).as_posix()
            if not key.startswith(prefix):
                continue
            out.append(
                ObjectMetadata(
                    key=key,
                    content_length=path.stat().st_size,
                    etag=_etag_of(path),
                    last_modified_at=path.stat().st_mtime,
                )
            )
            if len(out) >= limit:
                break
        return out

    def is_object_exist(self, bucket: str, key: str) -> bool:
        return self._object_path(bucket, key).is_file()

    def copy_object(self, bucket: str, src_key: str, dst_key: str) -> ObjectMetadata:
        data = self.get_object(bucket, src_key)
        return self.put_object(bucket, dst_key, data)

    def delete_object(self, bucket: str, key: str) -> None:
        path = self._object_path(bucket, key)
        if path.is_file():
            path.unlink()
        _etag_path(path).unlink(missing_ok=True)

    def get_sign_url(self, bucket: str, key: str, method: str = "GET", expire: float = 300.0) -> str:
        """Filesystem 'signed URL': a file:// URL (callers only need a
        fetchable address; the reference returns a presigned vendor URL)."""
        return f"file://{self._object_path(bucket, key)}"

    # ------------------------------------------------------------- helpers

    def _bucket_dir(self, bucket: str) -> pathlib.Path:
        if not bucket or "/" in bucket or bucket.startswith("."):
            raise dferrors.InvalidArgument(f"bad bucket name {bucket!r}")
        return self.base / bucket

    def _object_path(self, bucket: str, key: str) -> pathlib.Path:
        bucket_dir = self._bucket_dir(bucket)
        path = (bucket_dir / key).resolve()
        if not path.is_relative_to(bucket_dir.resolve()):
            raise dferrors.InvalidArgument(f"key escapes bucket: {key!r}")
        return path


def _etag_path(path: pathlib.Path) -> pathlib.Path:
    return path.with_name(path.name + ".etag")


def _etag_of(path: pathlib.Path) -> str:
    """Sidecar-cached md5; recomputed (and re-persisted) only when the
    sidecar is missing or older than the object."""
    side = _etag_path(path)
    try:
        if side.stat().st_mtime >= path.stat().st_mtime:
            return side.read_text().strip()
    except OSError:
        pass
    etag = hashlib.md5(path.read_bytes()).hexdigest()
    try:
        side.write_text(etag)
    except OSError:
        pass
    return etag


_VENDORS = ("s3", "oss", "obs")


def new_backend(name: str, base_dir: str | pathlib.Path | None = None, **options):
    """pkg/objectstorage New(): vendor dispatch (objectstorage.go:205-212).
    `fs` is the local store; `s3`/`oss`/`obs` speak the vendor HTTP dialect
    directly (signed with stdlib hmac — no SDKs in this image) and need
    endpoint + access_key + secret_key options."""
    if name == "fs":
        if base_dir is None:
            raise dferrors.InvalidArgument("fs backend needs base_dir")
        return FilesystemBackend(base_dir)
    if name in _VENDORS:
        if not options.get("endpoint"):
            raise dferrors.Unavailable(
                f"object-storage vendor {name!r} needs endpoint/access_key/"
                "secret_key options (no ambient cloud credentials here)"
            )
        from dragonfly2_tpu.objectstorage.remote import new_remote_backend

        return new_remote_backend(name, **options)
    raise dferrors.InvalidArgument(f"unknown object storage name {name!r}")


def object_task_id(bucket: str, key: str) -> str:
    """Stable task id for sharing an object through the mesh (the
    reference derives urfs task ids from bucket+key, objectstorage.go)."""
    return hashlib.sha256(f"urfs://{bucket}/{key}".encode()).hexdigest()
