"""dflint green twin of bad_tail.py: counter-hashed sampling, a
caller-supplied clock (perf_counter only measures), and sorted tracer
iteration — zero findings."""

import time


def hash_u01(seed, seq):
    return ((seed * 0x9E3779B97F4A7C15 + seq) & ((1 << 64) - 1)) / 2.0**64


class GoodTailLedger:
    def __init__(self, seed=0):
        self.seed = seed
        self.tracers = set()

    def observe(self, seq, ttc_ns):
        # the keep decision hashes the download's own sequence number:
        # pure function of (seed, seq), identical across paired runs
        keep = hash_u01(self.seed, seq) < 1 / 64
        # perf_counter is the one exempt clock (measuring, never
        # deciding); the recorded value is the caller's ttc_ns
        wall = time.perf_counter()
        return {"seq": seq, "ttc_ns": ttc_ns, "kept": keep,
                "observe_wall_s": wall}

    def dump(self):
        out = []
        for name in sorted(self.tracers):
            out.append({"tracer": name})
        return out
