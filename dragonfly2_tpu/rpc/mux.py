"""Single-port multiplexing of the wire protocol and plain HTTP.

Capability parity with pkg/rpc's mux listener (mux.go — one TCP port
serving both gRPC and HTTP health/debug traffic) and pkg/rpc/health (the
grpc health-checking protocol every service registers): the first bytes
of a connection decide the protocol. HTTP methods are ASCII ("GET ",
"POST"...), while a wire frame starts with a 4-byte big-endian length
whose first byte is 0x00 for any frame under 16 MiB — the two are
disjoint, so a 4-byte peek routes with no ambiguity (frames ≥16 MiB only
occur on the trainer upload path, which never fronts a mux).

HTTP side serves `/healthz` (liveness — the health RPC's HTTP twin),
`/metrics` (Prometheus text), and — when a `flight_source` is wired —
`/debug/flight` (the flight-recorder dump, telemetry/flight.py). The wire
side also answers `HealthCheckRequest` → SERVING on every server that
registers it.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging

from dragonfly2_tpu.rpc import resilience, wire
from dragonfly2_tpu.telemetry.tracing import default_tracer
from dragonfly2_tpu.utils.conntrack import ConnTracker

logger = logging.getLogger(__name__)

_HTTP_PREFIXES = (b"GET ", b"POST", b"HEAD", b"PUT ", b"DELE", b"OPTI", b"PATC")

# The mux enforces its own frame ceiling, far below wire.MAX_FRAME
# (256 MiB, sized for trainer dataset chunks that never front a mux): the
# relay is frame-aware, so an oversized length prefix is rejected loudly
# instead of either deadlocking (a back-pressure bound below the frame
# size starves read_frame's readexactly) or letting every untrusted
# connection buffer a quarter-gigabyte.
MUX_MAX_FRAME = 16 << 20
_RELAY_HIGH_WATER = 2 * MUX_MAX_FRAME

SERVING = "SERVING"
NOT_SERVING = "NOT_SERVING"


class _CountedReader(asyncio.StreamReader):
    """Detached StreamReader that tracks its own buffered byte count.

    A detached reader has no transport, so feed_data never back-pressures;
    the relay bounds memory by polling `buffered` instead of probing
    CPython's private `_buffer` (which a future CPython could rename,
    silently turning the high-water check into a no-op)."""

    def __init__(self):
        super().__init__()
        self.buffered = 0

    def feed_data(self, data):
        self.buffered += len(data)
        super().feed_data(data)

    async def read(self, n=-1):
        if n < 0:
            # StreamReader.read(-1) loops over self.read(limit) — those
            # inner calls hit this override and already decrement; doing
            # it again here would double-count and wedge `buffered`
            # negative, silently disabling the high-water check.
            return await super().read(n)
        data = await super().read(n)
        self.buffered -= len(data)
        return data

    async def readexactly(self, n):
        data = await super().readexactly(n)
        self.buffered -= len(data)
        return data

    async def readuntil(self, separator=b"\n"):
        data = await super().readuntil(separator)
        self.buffered -= len(data)
        return data

    async def readline(self):
        data = await super().readline()
        self.buffered -= len(data)
        return data


@dataclasses.dataclass
class HealthCheckRequest:
    """pkg/rpc/health: the standard health v1 Check, per-service."""

    service: str = ""


@dataclasses.dataclass
class HealthCheckResponse:
    status: str = SERVING


wire.register_messages(HealthCheckRequest, HealthCheckResponse)


class MuxServer:
    """Accepts on one port; routes each connection to `rpc_handler`
    (an `async (reader, writer)` — e.g. SchedulerRPCServer._serve_conn)
    or to the built-in HTTP handler by protocol sniffing."""

    def __init__(
        self,
        rpc_handler,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics_registry=None,
        health_check=None,  # () -> bool; liveness beyond "process is up"
        ssl_context=None,
        flight_source=None,  # () -> dict; /debug/flight JSON body
    ):
        self.rpc_handler = rpc_handler
        self.ssl_context = ssl_context
        self.host = host
        self.port = port
        self.metrics_registry = metrics_registry
        self.health_check = health_check
        # Flight-recorder dump for the same port daemons already scrape:
        # an explicit source (e.g. SchedulerService.flight_dump) wins;
        # otherwise the process-global dump serves, matching the
        # --metrics-port monitor endpoint (telemetry/metrics.py).
        if flight_source is None:
            from dragonfly2_tpu.telemetry import flight

            flight_source = flight.dump
        self.flight_source = flight_source
        self._server: asyncio.AbstractServer | None = None
        self._tracker = ConnTracker()

    def _healthy(self) -> bool:
        return True if self.health_check is None else bool(self.health_check())

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._tracker.tracked(self._handle), self.host, self.port,
            ssl=self.ssl_context,
        )
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            # long-lived wire streams would hang 3.12's wait_closed()
            await self._tracker.cancel_all()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            peek = await reader.readexactly(4)
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        if peek in _HTTP_PREFIXES:
            await self._handle_http(peek, reader, writer)
            return
        # Wire protocol: hand the consumed prefix back through a fresh
        # reader fed by a frame-aware relay task (StreamReader has no
        # un-read).
        relayed = _CountedReader()

        async def relay():
            prefix = peek
            try:
                while True:
                    # Pause on a high-water mark (above the frame ceiling,
                    # so readexactly always completes).
                    while relayed.buffered > _RELAY_HIGH_WATER:
                        await asyncio.sleep(0.01)
                    if prefix is None:
                        prefix = await reader.readexactly(4)
                    frame_len = int.from_bytes(prefix, "big")
                    if frame_len > MUX_MAX_FRAME:
                        logger.warning(
                            "mux: rejecting %d-byte frame (> %d ceiling)",
                            frame_len, MUX_MAX_FRAME,
                        )
                        relayed.feed_eof()
                        return
                    payload = await reader.readexactly(frame_len)
                    relayed.feed_data(prefix + payload)
                    prefix = None
            except (ConnectionError, asyncio.IncompleteReadError,
                    asyncio.CancelledError):
                relayed.feed_eof()

        relay_task = asyncio.create_task(relay())
        try:
            await self.rpc_handler(relayed, writer)
        finally:
            relay_task.cancel()

    async def _handle_http(self, peek: bytes, reader, writer):
        try:
            try:
                # readline converts LimitOverrunError into ValueError for
                # over-long request lines/headers — drop those quietly
                # WITHOUT catching ValueError around the handler bodies
                # below (a real bug in expose() must stay loud)
                line = peek + await asyncio.wait_for(reader.readline(), 10)
                parts = line.decode("latin1").split()
                path = parts[1] if len(parts) > 1 else "/"
                # drain headers
                while True:
                    header = await asyncio.wait_for(reader.readline(), 10)
                    if header in (b"\r\n", b"\n", b""):
                        break
            except ValueError:
                return
            path, _, query = path.partition("?")
            path = path.rstrip("/") or "/"
            if path == "/healthz":
                ok = self._healthy()
                status, body = (200, b"ok") if ok else (503, b"not serving")
            elif path == "/metrics" and self.metrics_registry is not None:
                status, body = 200, self.metrics_registry.expose().encode()
            elif path == "/debug/flight":
                import json

                from dragonfly2_tpu.telemetry.flight import parse_flight_query

                try:
                    kwargs = parse_flight_query(query)
                except ValueError as e:
                    status, body = 400, str(e).encode()
                else:
                    if kwargs:
                        try:
                            doc = self.flight_source(**kwargs)
                        except TypeError:
                            # explicit flight_source without the kwargs
                            # surface: serve its whole body unchanged
                            doc = self.flight_source()
                    else:
                        doc = self.flight_source()
                    # compact separators: the dump's max_bytes cap is
                    # measured against compact JSON — default separators
                    # would overshoot the promised bound by ~20%
                    status, body = 200, json.dumps(
                        doc, separators=(",", ":"), default=str
                    ).encode()
            elif path == "/debug/health":
                import json

                from dragonfly2_tpu.telemetry import slo as _slo

                try:
                    kwargs = _slo.parse_health_query(query)
                except ValueError as e:
                    status, body = 400, str(e).encode()
                else:
                    # the machine-readable health verdict plane
                    # (telemetry/slo.health_verdict): every live SLO
                    # engine merged worst-wins. 503 on `critical` so a
                    # load balancer can act on the same answer an
                    # operator reads; compact JSON — the max_bytes cap
                    # is measured against the bytes actually shipped.
                    doc = _slo.health_verdict(**kwargs)
                    status = (
                        503 if doc["state"] == _slo.VERDICT_CRITICAL else 200
                    )
                    body = json.dumps(
                        doc, separators=(",", ":"), default=str
                    ).encode()
            else:
                status, body = 404, b"not found"
            reason = {
                200: "OK", 400: "Bad Request", 404: "Not Found",
                503: "Service Unavailable",
            }[status]
            writer.write(
                f"HTTP/1.1 {status} {reason}\r\nContent-Length: {len(body)}\r\n"
                "Content-Type: text/plain\r\nConnection: close\r\n\r\n".encode() + body
            )
            await writer.drain()
        except (ConnectionError, asyncio.TimeoutError, UnicodeDecodeError,
                asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()


def dispatch_anchored(dispatch, request, span_prefix: str):
    """Run one decoded frame through ``dispatch`` with the wire envelope
    re-anchored (the PR-3 "dl" contract, dflint WIRE003): the frame's
    remaining deadline budget restarts on this host's clock so onward
    frames carry what is left, and the caller's trace context continues
    through a ``{span_prefix}.<Type>`` span. The ONE implementation
    every request/response serve loop shares — the dfwire pass blesses
    call sites of this helper as satisfying both halves, so a new RPC
    server routes through here instead of hand-rolling the scopes."""
    budget = getattr(request, "deadline_s", None)
    remote_ctx = getattr(request, "trace_context", None)
    with contextlib.ExitStack() as stack:
        if remote_ctx is not None:
            stack.enter_context(default_tracer().span(
                f"{span_prefix}.{type(request).__name__}",
                remote_parent=remote_ctx,
            ))
        if budget is not None:
            stack.enter_context(resilience.deadline(budget))
        return dispatch(request)


def handle_health_request(request, health_check=None):
    """Shared wire-side health answer — servers call this first in their
    dispatch: returns a response for HealthCheckRequest, else None. The
    optional `health_check` callable (the server's own) decides
    SERVING/NOT_SERVING — a draining server must not tell its load
    balancer SERVING. The null-check lives HERE so the four dispatch
    sites can all pass `self.health_check` verbatim."""
    if isinstance(request, HealthCheckRequest):
        healthy = True if health_check is None else bool(health_check())
        return HealthCheckResponse(status=SERVING if healthy else NOT_SERVING)
    return None
