"""ProcessPlanet — a supervised real-process service topology.

The multi-process tests each hand-rolled the same three helpers
(``_spawn`` reading one READY line off a pipe, ``_stop`` with an
unbounded-ish wait, ``_Origin``); none of them captured service logs,
probed liveness, or counted how often a SIGTERM had to escalate. This
module is the generalization the real-process planet harness
(tools/dfproc.py) and those tests share:

- :class:`ManagedProc` launches ``python -m dragonfly2_tpu.cmd <role>``
  with stdout/stderr teed to a per-process log file by a reader thread
  (no pipe-buffer deadlock, full log capture), parses the launcher
  READY-line contract (``READY host port [KEY value]...``), and owns the
  bounded SIGTERM -> grace -> SIGKILL escalation ladder plus the
  process-level chaos verbs the simulator cannot express: ``kill()``
  (SIGKILL), ``pause()``/``resume()`` (SIGSTOP/SIGCONT partitions).
- :class:`ProcessPlanet` supervises a named set of ManagedProcs
  (schedulers behind the client hashring, dfdaemons, a manager), with
  TCP liveness probes, role-aware restart (same port, same data dir —
  the rolling-upgrade / crash-recovery shape), and ``dragonfly_proc_*``
  metrics for every supervision event.

Wall clocks are legitimate here — supervising OS processes IS a
wall-clock job. The deterministic replay-facing surface lives in
``procworld/sample.py`` + ``procworld/divergence.py`` (dflint DET
domain), which only ever consume observations this module recorded.
"""

from __future__ import annotations

import os
import pathlib
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time

from dragonfly2_tpu.telemetry import default_registry
from dragonfly2_tpu.telemetry.series import proc_series

REPO = pathlib.Path(__file__).resolve().parents[2]

READY_TIMEOUT_S = 120.0  # first READY waits on a cold jax import
STOP_GRACE_S = 10.0


def base_env() -> dict:
    """The launcher environment every spawned service shares: CPU jax,
    two forced host devices (the launchers assert multi-device), and the
    repo on PYTHONPATH so ``-m dragonfly2_tpu.cmd`` resolves from any
    cwd."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
    env["PYTHONPATH"] = str(REPO)
    return env


class ManagedProc:
    """One supervised service process with log capture and the
    escalation ladder. Popen surface (``send_signal``/``wait``/``poll``/
    ``kill``/``pid``/``returncode``/``ready_line``) is delegated so call
    sites written against a raw Popen keep working."""

    def __init__(self, args: list[str], popen: subprocess.Popen,
                 log_path: pathlib.Path | None, *, role: str = "",
                 name: str = "", metrics=None):
        self.args = list(args)
        self.popen = popen
        self.log_path = log_path
        self.role = role or (args[0] if args else "")
        self.name = name or self.role
        self.ready_line: str | None = None
        self.host: str | None = None
        self.port: int | None = None
        self.ports: dict[str, int] = {}
        self.escalations = 0
        self._metrics = metrics
        self._lines: list[str] = []
        self._ready = threading.Event()
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()

    # ------------------------------------------------------------ spawn

    @classmethod
    def spawn(cls, args: list[str], cwd, *, log_path=None, env=None,
              name: str = "", metrics=None,
              ready_timeout: float = READY_TIMEOUT_S) -> "ManagedProc":
        popen = subprocess.Popen(
            [sys.executable, "-m", "dragonfly2_tpu.cmd", *args],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=cwd,
            env=env or base_env(),
        )
        proc = cls(args, popen, pathlib.Path(log_path) if log_path else None,
                   name=name, metrics=metrics)
        proc.wait_ready(ready_timeout)
        return proc

    def _pump(self) -> None:
        log = open(self.log_path, "a") if self.log_path else None
        try:
            for line in self.popen.stdout:
                self._lines.append(line.rstrip("\n"))
                if log is not None:
                    log.write(line)
                    log.flush()
                if not self._ready.is_set() and line.startswith("READY "):
                    self._parse_ready(line.strip())
                    self._ready.set()
        finally:
            if log is not None:
                log.close()
            self._ready.set()  # EOF before READY: unblock the waiter

    def _parse_ready(self, line: str) -> None:
        # "READY host port [KEY value]..." — every launcher's contract
        self.ready_line = line
        parts = line.split()
        self.host, self.port = parts[1], int(parts[2])
        rest = parts[3:]
        for key, value in zip(rest[::2], rest[1::2]):
            try:
                self.ports[key] = int(value)
            except ValueError:
                self.ports[key] = value  # INFER carries "host port" pair

    def wait_ready(self, timeout: float = READY_TIMEOUT_S) -> str:
        if not self._ready.wait(timeout) or self.ready_line is None:
            tail = "\n".join(self._lines[-20:])
            self.popen.kill()
            raise RuntimeError(
                f"{self.name or self.args}: no READY line "
                f"(rc={self.popen.poll()}); log tail:\n{tail}"
            )
        return self.ready_line

    # ----------------------------------------------------- supervision

    def alive(self) -> bool:
        return self.popen.poll() is None

    def probe(self, timeout: float = 1.0) -> bool:
        """TCP liveness: can the advertised primary port still accept?"""
        if self.host is None or self.port is None or not self.alive():
            return False
        try:
            with socket.create_connection((self.host, self.port), timeout):
                return True
        except OSError:
            return False

    def stop(self, grace: float = STOP_GRACE_S) -> int:
        """Bounded SIGTERM -> SIGKILL escalation ladder. Returns the exit
        code; an escalation is counted when graceful shutdown blew the
        grace window (the unbounded-wait bug the old ``_stop`` had)."""
        if self.popen.poll() is None:
            self.popen.send_signal(signal.SIGTERM)
            try:
                self.popen.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                self.escalations += 1
                if self._metrics is not None:
                    self._metrics.stop_escalations.labels("SIGKILL").inc()
                self.popen.kill()
                self.popen.wait(timeout=grace)
        self._reader.join(timeout=5.0)
        return self.popen.returncode

    def kill(self) -> None:
        """Process-level chaos: SIGKILL, no grace — the crash the
        simulator models as ``scheduler_crashed``."""
        if self.popen.poll() is None:
            self.popen.send_signal(signal.SIGKILL)
        self.popen.wait(timeout=STOP_GRACE_S)
        self._reader.join(timeout=5.0)

    def pause(self) -> None:
        """SIGSTOP: the silent-partition shape — the process holds its
        sockets but answers nothing (no FIN, requests just hang)."""
        self.popen.send_signal(signal.SIGSTOP)

    def resume(self) -> None:
        self.popen.send_signal(signal.SIGCONT)

    def log_text(self) -> str:
        return "\n".join(self._lines)

    # Popen delegation so migrated tests keep their call shapes
    def send_signal(self, sig):
        self.popen.send_signal(sig)

    def wait(self, timeout=None):
        return self.popen.wait(timeout=timeout)

    def poll(self):
        return self.popen.poll()

    def terminate(self):
        self.popen.terminate()

    @property
    def pid(self):
        return self.popen.pid

    @property
    def returncode(self):
        return self.popen.returncode

    @property
    def stdout(self):
        return self.popen.stdout


# ------------------------------------------------- functional test shims


def spawn_cmd(args: list[str], cwd) -> tuple[ManagedProc, str, int]:
    """Drop-in for the tests' hand-rolled ``_spawn(args, tmp_path)``:
    same (proc, host, port) contract, with log capture and the READY
    parser upgraded to the ManagedProc versions."""
    proc = ManagedProc.spawn(
        args, cwd, log_path=pathlib.Path(cwd) / f"{args[0]}-{os.getpid()}.log"
    )
    return proc, proc.host, proc.port


def stop_proc(proc, grace: float = STOP_GRACE_S) -> None:
    """Drop-in for the tests' ``_stop``: the bounded escalation ladder,
    accepting either a ManagedProc or a raw Popen."""
    if isinstance(proc, ManagedProc):
        proc.stop(grace)
        return
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=grace)


# ------------------------------------------------------------ the planet


class ProcessPlanet:
    """A supervised topology of real service processes: K schedulers
    (the client hashring's node set), M dfdaemons, optionally a manager.
    Knows how to restart any member on its original port/data-dir (the
    crash-recovery and rolling-upgrade shapes) and counts every
    supervision event into the ``dragonfly_proc_*`` families."""

    def __init__(self, workdir, *, registry=None):
        self.workdir = pathlib.Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.logdir = self.workdir / "logs"
        self.logdir.mkdir(exist_ok=True)
        self.metrics = proc_series(registry or default_registry())
        self.procs: dict[str, ManagedProc] = {}
        self.restarts: dict[str, int] = {}
        self.chaos_ops: dict[str, int] = {}
        self.liveness_failures = 0

    # ------------------------------------------------------------ spawn

    def _spawn(self, name: str, role: str, args: list[str]) -> ManagedProc:
        proc = ManagedProc.spawn(
            [role, *args], self.workdir,
            log_path=self.logdir / f"{name}.log",
            name=name, metrics=self.metrics,
        )
        proc.role = role
        self.procs[name] = proc
        self.metrics.processes.labels(role).inc()
        return proc

    def spawn_scheduler(self, name: str, *, port: int = 0,
                        manager: str = "", extra: tuple = ()) -> ManagedProc:
        args = [
            "--host", "127.0.0.1", "--port", str(port),
            "--data-dir", str(self.workdir / f"{name}-data"),
            "--metrics-port", "0",
        ]
        if manager:
            args += ["--manager", manager, "--keepalive-interval", "0.5"]
        proc = self._spawn(name, "scheduler", [*args, *extra])
        self._pin_port(proc)
        return proc

    def spawn_manager(self, name: str = "manager", *,
                      extra: tuple = ()) -> ManagedProc:
        args = [
            "--host", "127.0.0.1",
            "--db", str(self.workdir / f"{name}.db"),
            "--metrics-port", "0",
        ]
        proc = self._spawn(name, "manager", [*args, *extra])
        self._pin_port(proc)
        return proc

    def spawn_daemon(self, name: str, schedulers: list[str], *,
                     proxy_rules: tuple = (r"127\.0\.0\.1.*\.bin",),
                     idc: str = "", location: str = "",
                     host_type: str = "normal",
                     scenario: str = "", scenario_seed: int = 0,
                     extra: tuple = ()) -> ManagedProc:
        # distinct --hostname per daemon: host-id-v2 keys on (ip,
        # hostname), and every planet member shares 127.0.0.1
        args = ["--data-dir", str(self.workdir / f"{name}-data"),
                "--hostname", name,
                "--host-type", host_type, "--metrics-port", "0", "--proxy"]
        for addr in schedulers:
            args += ["--scheduler", addr]
        for rule in proxy_rules:
            args += ["--proxy-rule", rule]
        if idc:
            args += ["--idc", idc]
        if location:
            args += ["--location", location]
        if scenario:
            args += ["--scenario", scenario,
                     "--scenario-seed", str(scenario_seed)]
        return self._spawn(name, "dfdaemon", [*args, *extra])

    def _pin_port(self, proc: ManagedProc) -> None:
        """Rewrite ``--port 0`` to the bound port in the saved args so a
        restart comes back on the SAME address (clients redial it)."""
        args = proc.args
        for i, a in enumerate(args[:-1]):
            if a == "--port" and args[i + 1] == "0":
                args[i + 1] = str(proc.port)

    # ------------------------------------------------------ supervision

    def scheduler_addresses(self) -> list[str]:
        return [f"{p.host}:{p.port}" for n, p in sorted(self.procs.items())
                if p.role == "scheduler"]

    def daemons(self) -> list[ManagedProc]:
        return [p for _, p in sorted(self.procs.items())
                if p.role == "dfdaemon"]

    def kill(self, name: str) -> None:
        proc = self.procs[name]
        proc.kill()
        self.metrics.processes.labels(proc.role).dec()
        self.chaos_ops["sigkill"] = self.chaos_ops.get("sigkill", 0) + 1
        self.metrics.chaos_ops.labels("sigkill").inc()

    def pause(self, name: str) -> None:
        self.procs[name].pause()
        self.chaos_ops["sigstop"] = self.chaos_ops.get("sigstop", 0) + 1
        self.metrics.chaos_ops.labels("sigstop").inc()

    def resume(self, name: str) -> None:
        self.procs[name].resume()
        self.metrics.chaos_ops.labels("sigcont").inc()

    def restart(self, name: str, *, grace: float = STOP_GRACE_S,
                ready_timeout: float = READY_TIMEOUT_S) -> ManagedProc:
        """Stop (ladder) then respawn with the original args — a
        rolling-upgrade restart. A process that already died (e.g. via
        ``kill``) respawns directly; data dir and pinned port are kept,
        so a restarted scheduler adopts re-announced pieces and a
        restarted daemon reloads its kept pieces from disk."""
        old = self.procs[name]
        if old.alive():
            old.stop(grace)
            self.metrics.processes.labels(old.role).dec()
        proc = ManagedProc.spawn(
            old.args, self.workdir,
            log_path=self.logdir / f"{name}.log",
            name=name, metrics=self.metrics, ready_timeout=ready_timeout,
        )
        proc.role = old.role
        self.procs[name] = proc
        self.restarts[name] = self.restarts.get(name, 0) + 1
        self.metrics.restarts.labels(proc.role).inc()
        self.metrics.processes.labels(proc.role).inc()
        return proc

    def liveness_sweep(self, timeout: float = 1.0) -> dict[str, bool]:
        """Probe every member's advertised port; count failures of
        processes that should be alive."""
        out = {}
        for name, proc in sorted(self.procs.items()):
            ok = proc.probe(timeout)
            out[name] = ok
            if not ok and proc.alive():
                self.liveness_failures += 1
                self.metrics.liveness_failures.labels(proc.role).inc()
        return out

    def stop_all(self, grace: float = STOP_GRACE_S) -> dict[str, int]:
        """Stop daemons, then schedulers, then the manager (reverse
        dependency order); returns exit codes by name."""
        order = {"dfdaemon": 0, "trainer": 1, "scheduler": 2, "manager": 3}
        codes = {}
        for name, proc in sorted(
            self.procs.items(), key=lambda kv: order.get(kv[1].role, 9)
        ):
            was_alive = proc.alive()
            codes[name] = proc.stop(grace)
            if was_alive:
                self.metrics.processes.labels(proc.role).dec()
        return codes

    def escalations_total(self) -> int:
        return sum(p.escalations for p in self.procs.values())

    def describe(self) -> dict:
        """The artifact's topology block — how the planet was wired."""
        return {
            "processes": {
                name: {
                    "role": p.role,
                    "address": f"{p.host}:{p.port}",
                    "ports": dict(p.ports),
                    "cmd": shlex.join(p.args),
                }
                for name, p in sorted(self.procs.items())
            },
            "restarts": dict(sorted(self.restarts.items())),
            "chaos_ops": dict(sorted(self.chaos_ops.items())),
            "stop_escalations": self.escalations_total(),
            "liveness_failures": self.liveness_failures,
        }

    # context manager

    def __enter__(self) -> "ProcessPlanet":
        return self

    def __exit__(self, *exc) -> None:
        self.stop_all()


def wait_for(predicate, timeout: float, interval: float = 0.02,
             what: str = "condition") -> None:
    """Poll until ``predicate()`` is truthy or raise after ``timeout``."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise TimeoutError(f"timed out after {timeout}s waiting for {what}")
