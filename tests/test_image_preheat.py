"""Image-type preheat: registry manifest walk -> layer blobs warmed
through a seed daemon.

Mirrors the reference's flagship use case (manager/job/preheat.go:90-315
+ test/e2e/manager/preheat.go): a fake OCI registry (local HTTP) serves a
token challenge, a manifest list, per-platform manifests, and blobs; the
preheat job must resolve the right platform's layers and the seed daemon
must download every blob byte-for-byte.
"""

import asyncio
import hashlib
import http.server
import json
import threading

import pytest

from dragonfly2_tpu.client.daemon import Daemon
from dragonfly2_tpu.cluster import image_preheat
from dragonfly2_tpu.cluster.jobs import JobManager, JobState, PreheatRequest
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.rpc.server import SchedulerRPCServer
from dragonfly2_tpu.utils import dferrors


class FakeRegistry:
    """Minimal OCI distribution server: bearer-token challenge, manifest
    list -> per-platform manifests -> blobs (config + 2 layers)."""

    TOKEN = "test-token-123"

    def __init__(self, require_auth: bool = True):
        self.require_auth = require_auth
        self.layer_a = b"layer-a " + bytes(range(256)) * 200
        self.layer_b = b"layer-b " + bytes(reversed(range(256))) * 300
        self.config_blob = json.dumps({"architecture": "amd64"}).encode()
        self.blobs = {
            "sha256:" + hashlib.sha256(b).hexdigest(): b
            for b in (self.layer_a, self.layer_b, self.config_blob)
        }
        self.digest_a = "sha256:" + hashlib.sha256(self.layer_a).hexdigest()
        self.digest_b = "sha256:" + hashlib.sha256(self.layer_b).hexdigest()
        self.digest_cfg = "sha256:" + hashlib.sha256(self.config_blob).hexdigest()

        self.amd64_manifest = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.docker.distribution.manifest.v2+json",
            "config": {
                "mediaType": "application/vnd.docker.container.image.v1+json",
                "digest": self.digest_cfg,
                "size": len(self.config_blob),
            },
            "layers": [
                {
                    "mediaType": "application/vnd.docker.image.rootfs.diff.tar.gzip",
                    "digest": self.digest_a,
                    "size": len(self.layer_a),
                },
                {
                    "mediaType": "application/vnd.docker.image.rootfs.diff.tar.gzip",
                    "digest": self.digest_b,
                    "size": len(self.layer_b),
                },
            ],
        }
        self.amd64_digest = "sha256:" + hashlib.sha256(
            json.dumps(self.amd64_manifest).encode()
        ).hexdigest()
        # a second platform entry that must be filtered OUT
        self.manifest_list = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.docker.distribution.manifest.list.v2+json",
            "manifests": [
                {
                    "mediaType": "application/vnd.docker.distribution.manifest.v2+json",
                    "digest": self.amd64_digest,
                    "platform": {"architecture": "amd64", "os": "linux"},
                },
                {
                    "mediaType": "application/vnd.docker.distribution.manifest.v2+json",
                    "digest": "sha256:" + "0" * 64,
                    "platform": {"architecture": "s390x", "os": "linux"},
                },
            ],
        }
        self.token_requests = []
        self.manifest_requests = []

        registry = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _authed(self) -> bool:
                if not registry.require_auth:
                    return True
                return self.headers.get("Authorization") == f"Bearer {registry.TOKEN}"

            def _challenge(self):
                self.send_response(401)
                self.send_header(
                    "WWW-Authenticate",
                    f'Bearer realm="http://127.0.0.1:{registry.port}/token",'
                    f'service="registry",scope="repository:testrepo:pull"',
                )
                self.send_header("Content-Length", "0")
                self.end_headers()

            def _json(self, obj, content_type="application/json"):
                body = json.dumps(obj).encode()
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/token"):
                    registry.token_requests.append(self.headers.get("Authorization"))
                    self._json({"token": registry.TOKEN})
                    return
                if not self._authed():
                    self._challenge()
                    return
                if self.path == "/v2/testrepo/manifests/latest":
                    registry.manifest_requests.append(self.path)
                    self._json(
                        registry.manifest_list,
                        registry.manifest_list["mediaType"],
                    )
                    return
                if self.path == f"/v2/testrepo/manifests/{registry.amd64_digest}":
                    registry.manifest_requests.append(self.path)
                    self._json(
                        registry.amd64_manifest, registry.amd64_manifest["mediaType"]
                    )
                    return
                if self.path.startswith("/v2/testrepo/blobs/"):
                    digest = self.path.rsplit("/", 1)[1]
                    blob = registry.blobs.get(digest)
                    if blob is None:
                        self.send_error(404)
                        return
                    start, end = 0, len(blob) - 1
                    rng = self.headers.get("Range")
                    status = 200
                    if rng and rng.startswith("bytes="):
                        lo, _, hi = rng[len("bytes="):].partition("-")
                        start = int(lo or 0)
                        end = int(hi) if hi else len(blob) - 1
                        status = 206
                    chunk = blob[start : end + 1]
                    self.send_response(status)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(len(chunk)))
                    if status == 206:
                        self.send_header(
                            "Content-Range", f"bytes {start}-{end}/{len(blob)}"
                        )
                    self.end_headers()
                    self.wfile.write(chunk)
                    return
                self.send_error(404)

            def do_HEAD(self):
                if not self._authed():
                    self._challenge()
                    return
                if self.path.startswith("/v2/testrepo/blobs/"):
                    digest = self.path.rsplit("/", 1)[1]
                    blob = registry.blobs.get(digest)
                    if blob is None:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    return
                self.send_error(404)

        self._server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def manifest_url(self) -> str:
        return f"http://127.0.0.1:{self.port}/v2/testrepo/manifests/latest"

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture
def registry():
    r = FakeRegistry()
    yield r
    r.stop()


def test_resolver_walks_list_to_platform_layers(registry):
    layers = image_preheat.resolve_image_layers(
        registry.manifest_url(), username="u", password="p", platform="linux/amd64"
    )
    # config + 2 layers, in manifest order (config first: References())
    assert [l.digest for l in layers] == [
        registry.digest_cfg,
        registry.digest_a,
        registry.digest_b,
    ]
    for l in layers:
        assert l.url.endswith("/v2/testrepo/blobs/" + l.digest)
        assert l.headers["Authorization"] == f"Bearer {registry.TOKEN}"
    # basic auth reached the token endpoint
    assert registry.token_requests and registry.token_requests[0].startswith("Basic ")
    # walked the list AND the amd64 manifest, not the s390x one
    assert len(registry.manifest_requests) == 2


def test_resolver_no_platform_match(registry):
    with pytest.raises(dferrors.NotFound, match="no matching manifest"):
        image_preheat.resolve_image_layers(
            registry.manifest_url(), platform="linux/riscv64"
        )


def test_resolver_rejects_non_image_url():
    assert not image_preheat.is_image_url("http://example.com/some/file.bin")
    with pytest.raises(dferrors.InvalidArgument):
        image_preheat.resolve_image_layers("http://example.com/some/file.bin")


def test_image_preheat_e2e_through_seed(tmp_path, registry):
    """Fake registry -> preheat(image) -> seed daemon warms config+layers;
    bytes match sha256 (VERDICT r1 item 2 'done' criterion)."""

    async def run():
        cfg = Config()
        cfg.scheduler.max_hosts = 16
        cfg.scheduler.max_tasks = 16
        service = SchedulerService(config=cfg)
        server = SchedulerRPCServer(service, tick_interval=0.01)
        host, port = await server.start()
        try:
            seed = Daemon(
                tmp_path / "seed", [(host, port)], hostname="seed-1", host_type="super"
            )
            await seed.start()
            for _ in range(100):
                if service._seed_hosts:
                    break
                await asyncio.sleep(0.05)
            assert service._seed_hosts == [seed.host_id]

            jm = JobManager({"s1": service}, seed_hosts=[])
            # seed hosts are discovered from the scheduler's announces
            from dragonfly2_tpu.cluster import messages as msg

            jm.seed_hosts = [msg.HostInfo(host_id=seed.host_id, hostname="seed-1")]
            result = jm.create_preheat(
                PreheatRequest(
                    urls=[registry.manifest_url()],
                    preheat_type="image",
                    username="u",
                    password="p",
                    platform="linux/amd64",
                    piece_length=64 * 1024,
                )
            )
            assert result.state == JobState.PENDING, result.detail
            assert len(result.task_ids) == 3  # config + 2 layers

            # poll the JOB STATE until the seed finished every blob — the
            # reference's preheat e2e polls the machinery group the same way
            for _ in range(200):
                if jm.get(result.job_id).state == JobState.SUCCESS:
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError(
                    f"preheat job never reached SUCCESS: {jm.get(result.job_id)}"
                )
            assert all(
                seed.storage.find_completed_task(tid) for tid in result.task_ids
            )

            for tid, blob in zip(
                result.task_ids,
                (registry.config_blob, registry.layer_a, registry.layer_b),
            ):
                ts = seed.storage.find_completed_task(tid)
                with open(ts.data_path, "rb") as f:
                    got = f.read()
                assert hashlib.sha256(got).hexdigest() == hashlib.sha256(blob).hexdigest()
            await seed.stop()
        finally:
            await server.stop()

    asyncio.run(run())
