"""Megascale scenario lab: event-batch engine vs per-peer oracle
equivalence, WAN/traffic model determinism, bulk scheduler APIs, and the
soak smoke.

The equivalence contract (the subsystem's acceptance gate): at small
scale, a paired-seed `EventBatchEngine` replay produces IDENTICAL
aggregate outcomes to the per-peer `ClusterSimulator` oracle — every
SimStats counter (completions, back-to-source, injected-fault counters,
piece costs) and the scheduler's final piece columns — across the
scenario-less replay, bandwidth_skew, and chaos builtins. Both engines
drive a real SchedulerService through the same protocol; the engine only
replaces the per-piece wave loop with vectorized event batches, so any
divergence is a bug in the batch machinery.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np
import pytest

from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.cluster.simulator import ClusterSimulator
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.megascale import EventBatchEngine, hash_u01, make_region_cluster
from dragonfly2_tpu.megascale.soak import deterministic_view, run_megascale
from dragonfly2_tpu.megascale.topology import (
    FAULT_CORRUPT,
    FAULT_ERROR,
    WanCostModel,
    lognorm_vec,
    norm_ppf,
)
from dragonfly2_tpu.scenarios import builtin_scenarios, megascale_scenarios

# ----------------------------------------------------- oracle equivalence


def _run(sim_cls, scenario, seed, rounds=10, hosts=60, tasks=6, arrivals=6):
    svc = SchedulerService(config=Config(), seed=seed + 100)
    if sim_cls is ClusterSimulator:
        sim = sim_cls(svc, num_hosts=hosts, num_tasks=tasks, seed=seed,
                      scenario=scenario, deterministic_peer_ids=True)
    else:
        sim = sim_cls(svc, num_hosts=hosts, num_tasks=tasks, seed=seed,
                      scenario=scenario)
    for _ in range(rounds):
        sim.run_round(arrivals)
    svc.flush_piece_reports()
    columns = {
        pid: (
            int(svc.state.peer_finished_count[idx]),
            svc.state.peer_finished_bitset[idx].tobytes(),
            int(svc.state.peer_state[idx]),
        )
        for pid, idx in svc.state._peer_by_id.items()
    }
    return sim, columns, svc.counts()


@pytest.mark.parametrize("topology", [None, "bandwidth_skew", "chaos"])
def test_event_batch_matches_oracle(topology):
    """Paired seeds, three builtin scenarios: identical SimStats (every
    counter, including injected-fault families) and identical final
    piece columns (finished bitsets, counts, FSM states) in the
    scheduler's SoA state."""
    scenario = builtin_scenarios()[topology] if topology else None
    for seed in (3, 17):
        oracle, o_cols, o_counts = _run(ClusterSimulator, scenario, seed)
        batch, b_cols, b_counts = _run(EventBatchEngine, scenario, seed)
        assert oracle.stats.pieces > 0
        assert dataclasses.asdict(oracle.stats) == dataclasses.asdict(batch.stats), (
            f"SimStats divergence (topology={topology}, seed={seed})"
        )
        assert o_cols == b_cols, (
            f"final piece-column divergence (topology={topology}, seed={seed})"
        )
        assert o_counts == b_counts
        if topology == "chaos":
            # the chaos replay must actually exercise the fault paths the
            # equivalence claim covers
            st = oracle.stats
            assert st.injected_piece_failures > 0
            assert st.retry_waves > 0


def test_event_batch_is_actually_batching():
    """The engine must not fall back to per-piece oracle processing on a
    scenario path: its event counter covers every simulated piece."""
    spec = builtin_scenarios()["bandwidth_skew"]
    sim, _, _ = _run(EventBatchEngine, spec, seed=5)
    assert sim.mega.piece_events == sim.stats.pieces  # no faults in skew


# ----------------------------------------------------------- determinism


def _mega_run(seed=7, hosts=1500):
    return run_megascale(
        "soak", num_hosts=hosts, num_tasks=32, seed=seed,
        arrivals_per_round=24, retire_after_rounds=24,
    )


def test_megascale_determinism_same_seed():
    """Same seed + same megascale spec (region/WAN + diurnal traffic +
    flash crowds + upgrades + every fault family) → identical SimStats,
    MegaStats, per-region aggregates, and fault schedules across runs."""
    r1, r2 = _mega_run(), _mega_run()
    assert deterministic_view(r1) == deterministic_view(r2)
    assert r1["fault_schedule_digest"] == r2["fault_schedule_digest"]
    assert r1["stats"]["pieces"] > 0
    # paired-seed timeline determinism (perf observatory): the
    # per-round sampled gauge ring is IDENTICAL array-for-array — every
    # sample is a pure function of the event clock, no wall reads
    assert r1["timeline"] == r2["timeline"]
    assert r1["timeline_events"] == r2["timeline_events"]
    assert r1["recovery"] == r2["recovery"]
    assert len(r1["timeline"]) == r1["rounds"]
    # decision-provenance determinism (ISSUE 13): paired-seed runs
    # produce IDENTICAL ledger columns — the digest covers every
    # replay-determined column (candidate sets, feature rows, ranked
    # scores, shadow rankings, outcome codes) and excludes only the
    # wall-clock ones by construction
    assert r1["decisions"]["decisions"] > 0
    assert r1["decisions"]["columns_digest"] == r2["decisions"]["columns_digest"]
    assert r1["decisions"] == r2["decisions"]
    # the timeline carries the divergence/regret columns on every sample
    assert all(
        "decisions" in s and "shadow_divergence" in s
        and "decision_regret_fail" in s
        for s in r1["timeline"]
    )
    # SLO verdict plane (ISSUE 14): the slo block (alert log, verdict,
    # budget burn) and the per-sample verdict columns are paired-seed
    # IDENTICAL — the alert timeline is a pure function of the replay
    assert r1["slo"] == r2["slo"]
    assert r1["slo"]["pages_fired"] > 0  # the kills paged (see below)
    assert all(
        "slo_verdict" in s and "slo_alerts_firing" in s
        and "slo_pages_fired" in s and "ttc_ms_p95" in s
        for s in r1["timeline"]
    )
    # tail-attribution plane (ISSUE 16): the whole tail block — regions,
    # windows, exemplars, round matrices, AND the blake2b digest over
    # every ledger column and sketch — is paired-seed IDENTICAL
    assert r1["tail"]["digest"] == r2["tail"]["digest"]
    assert r1["tail"] == r2["tail"]
    assert r1["tail"]["completions"] > 0
    assert all("tail_dominant_phase" in s for s in r1["timeline"])


def test_megascale_seed_sensitivity():
    r1, r2 = _mega_run(seed=7), _mega_run(seed=8)
    assert r1["fault_schedule_digest"] != r2["fault_schedule_digest"]


# -------------------------------------------------------- soak (tier-1)


def test_soak_exercises_all_fault_families():
    """The soak builtin runs chaos (scheduler crashes + partitions),
    corruption, churn (+ rolling upgrades), and flash crowds in ONE
    compressed-day replay, each with nonzero injected-event counters —
    the acceptance gate for the 24h-in-production trace."""
    r = _mega_run()
    fam = r["fault_families"]
    assert fam["chaos"] > 0, fam
    assert fam["corruption"] > 0, fam
    assert fam["churn"] > 0, fam
    assert fam["flash_crowds"] > 0, fam
    assert r["mega"]["upgrade_host_restarts"] > 0
    assert r["stats"]["injected_scheduler_crashes"] > 0
    assert r["stats"]["crash_reannounced_peers"] > 0
    # quarantine reacted to the corrupt parents
    assert r["quarantine"]["corruption_reports"] > 0
    # the WAN hierarchy produced per-region completions
    assert sum(v["completed"] for v in r["regions"].values()) > 0


def test_soak_timeline_shows_scheduler_kill_and_measured_recovery():
    """The perf-observatory soak gate: 'recovers after a scheduler kill'
    is MEASURED from the timeline, not asserted from end aggregates.
    Every kill round is marked in the timeline (and matches the
    deterministic schedule preview), the kill is visible in the sampled
    series (the re-announce backlog spikes as wiped peers re-register),
    and every mid-day kill's pieces-per-round rate recovers to >=90% of
    its pre-kill baseline within 2 simulated hours. (Late-day kills sit
    on the diurnal downslope + drain tail, where a pre-kill baseline is
    not a meaningful recovery target — excluded by design.)"""
    r = _mega_run()
    tl = r["timeline"]
    by_t = {s["t"]: s for s in tl}
    kills = [e["t"] for e in r["timeline_events"]
             if e["event"] == "scheduler_crash"]
    assert kills, "soak spec produced no scheduler kill"
    assert kills == r["expected_crash_rounds"], (
        "timeline kill marks drifted from the deterministic schedule"
    )
    assert all(by_t[k]["scheduler_crash"] == 1 for k in kills)
    assert any(by_t[k]["reannounce_backlog"] > 0 for k in kills), (
        "no kill round shows the re-announce spike"
    )
    day = 96  # the soak builtin's compressed-day rounds
    mid_day = [e for e in r["recovery"] if e["round"] <= int(day * 0.75)]
    assert mid_day, r["recovery"]
    for e in mid_day:
        assert e["recovered"], e
        assert e["recovery_sim_minutes"] <= 120.0, e
    # per-region TTC percentiles ride every sample via the bounded
    # streaming sketches
    last = tl[-1]
    assert set(last["ttc_ms_p50"]) == set(r["regions"])
    assert all(v is not None for v in last["ttc_ms_p50"].values())
    # corruption + quarantine population are visible over time, not
    # just as a final count
    assert any(s["quarantine_active"] > 0 for s in tl)
    assert any(s["corruptions"] > 0 for s in tl)


def test_soak_scheduler_kill_pages_and_clears_from_slo_output():
    """THE SLO soak gate (ISSUE 14): every mid-day scheduler kill fires
    a page-severity burn-rate alert (announce_stability: the kill's
    re-announce wave burns the error budget on both alert windows) AT
    the kill round, and the page clears within the measured recovery
    window plus one short-window drain — asserted from SLO output, not
    hand-picked aggregate counters."""
    r = _mega_run()
    kills = r["expected_crash_rounds"]
    assert kills, "soak spec produced no scheduler kill"
    log = r["slo"]["alert_log"]
    pages = [e for e in log
             if e["severity"] == "page" and e["event"] == "fired"]
    page_rounds = {e["t"] for e in pages}
    day = 96
    mid_day_kills = [k for k in kills if k <= int(day * 0.75)]
    assert mid_day_kills
    for k in mid_day_kills:
        assert float(k) in page_rounds, (
            f"kill at round {k} fired no page; pages at {sorted(page_rounds)}"
        )
    # each page clears within (measured recovery + short-window drain +
    # one interval); recovery for these kills measured 0 simulated
    # minutes (same-round re-announce adoption), so the bound is tight
    recovery_by_round = {e["round"]: e for e in r["recovery"]}
    mpr = r["minutes_per_round"]
    for e in pages:
        clear = next(
            (c for c in log
             if c["event"] == "cleared" and c["slo"] == e["slo"]
             and c["rule"] == e["rule"] and c["t"] > e["t"]),
            None,
        )
        assert clear is not None, f"page at t={e['t']} never cleared"
        rec = recovery_by_round.get(int(e["t"]))
        rec_minutes = (
            rec["recovery_sim_minutes"]
            if rec and rec.get("recovery_sim_minutes") is not None else 0.0
        )
        clear_minutes = (clear["t"] - e["t"]) * mpr
        # short window (5m) drains within one 15-minute interval
        assert clear_minutes <= rec_minutes + mpr + 5.0, (e, clear)
    # the in-run judgment is reproducible offline from the timeline
    # (the dfslo contract; the checked-in-artifact gate lives in
    # tests/test_slo.py)
    from dragonfly2_tpu.telemetry.slo import replay_timeline

    replay = replay_timeline(r["timeline"], mpr)
    assert replay["pages_fired"] == r["slo"]["pages_fired"]
    assert replay["alert_log"][-len(r["slo"]["alert_log"]):] == \
        r["slo"]["alert_log"]


def test_planet_clean_day_fires_zero_alerts():
    """The alert-noise gate (ISSUE 14): a clean planet day — WAN scale,
    diurnal arrivals, flash crowds, NO fault injection — fires ZERO
    burn-rate alerts of any severity. An SLO plane that pages on a
    healthy day is worse than none."""
    r = run_megascale(
        "planet", num_hosts=1500, num_tasks=32, seed=7,
        arrivals_per_round=24, retire_after_rounds=24,
    )
    assert r["slo"]["pages_fired"] == 0, r["slo"]["alert_log"]
    assert r["slo"]["tickets_fired"] == 0, r["slo"]["alert_log"]
    assert r["slo"]["alert_log"] == []
    assert r["slo"]["verdict_final"] == "ok"
    assert all(s["slo_verdict"] == 0 for s in r["timeline"])


@pytest.mark.soak
def test_soak_smoke_50k_hosts():
    """Tier-1 time-budgeted smoke at megascale: >=50k hosts, a few
    engine steps of the soak spec, completing in a small fraction of the
    tier-1 wall (the full day lives behind `slow`/bench_megascale)."""
    t0 = time.perf_counter()
    r = run_megascale(
        "soak", num_hosts=50_000, num_tasks=64, seed=7,
        rounds=8, drain_rounds=2, arrivals_per_round=600,
    )
    wall = time.perf_counter() - t0
    assert r["stats"]["pieces"] > 10_000
    assert r["stats"]["completed"] > 500
    assert len(r["regions"]) == 4
    # budget: a fraction of the 870 s tier-1 wall, generous for slow CI
    assert wall < 240, f"soak smoke took {wall:.1f}s"


# ------------------------------------------------------------- slow tier


@pytest.mark.slow
def test_planet_100k_under_five_minutes():
    """The acceptance criterion: a 100k-host megascale scenario (regions
    + diurnal Zipf + flash crowd) completes on CPU in <= 5 minutes."""
    t0 = time.perf_counter()
    r = run_megascale("planet", num_hosts=100_000, num_tasks=128, seed=11)
    wall = time.perf_counter() - t0
    assert wall < 300, f"100k-host planet run took {wall:.1f}s"
    assert r["stats"]["completed"] == r["stats"]["registered"]
    assert r["stats"]["pieces"] > 1_000_000


@pytest.mark.slow
def test_megascale_one_million_hosts():
    """A 10^6-host scenario completes within the slow-tier budget (a
    reduced-rounds day slice — the point is the scale, exercised end to
    end: 1M announced hosts, WAN regions, diurnal arrivals)."""
    r = run_megascale(
        "planet", num_hosts=1_000_000, num_tasks=128, seed=11,
        rounds=20, drain_rounds=6, arrivals_per_round=8_000,
    )
    # the slice starts at the diurnal trough, so arrivals run well below
    # the configured base (measured ~51k registrations, ~63 s end to end
    # on one CPU core incl. announcing 10^6 hosts, ~3.3 GB peak RSS)
    assert r["stats"]["registered"] > 40_000
    assert r["stats"]["completed"] == r["stats"]["registered"]
    assert r["timing"]["peak_rss_mb"] is None or r["timing"]["peak_rss_mb"] < 64_000


# ------------------------------------------------------ topology + model


def test_region_cluster_layout():
    spec = megascale_scenarios()["planet"]
    cluster = make_region_cluster(400, spec, seed=3)
    regions = {}
    for h in cluster.hosts:
        regions.setdefault(h.location.split("|")[0], []).append(h)
    assert len(regions) == spec.wan.regions
    for hosts in regions.values():
        assert sum(h.is_seed for h in hosts) == spec.wan.seeds_per_region
    # contiguous region blocks in host order (the rolling-upgrade sweep
    # relies on it)
    seen = []
    for h in cluster.hosts:
        r = h.location.split("|")[0]
        if not seen or seen[-1] != r:
            seen.append(r)
    assert len(seen) == spec.wan.regions


def test_hash_u01_deterministic_and_uniform():
    a = hash_u01(7, "kind", np.arange(10_000), np.full(10_000, 3))
    b = hash_u01(7, "kind", np.arange(10_000), np.full(10_000, 3))
    assert np.array_equal(a, b)
    assert ((a >= 0) & (a < 1)).all()
    assert abs(a.mean() - 0.5) < 0.02
    c = hash_u01(8, "kind", np.arange(10_000), np.full(10_000, 3))
    assert not np.array_equal(a, c)
    d = hash_u01(7, "other", np.arange(10_000), np.full(10_000, 3))
    assert not np.array_equal(a, d)


def test_norm_ppf_matches_stdlib():
    from statistics import NormalDist

    nd = NormalDist()
    u = np.linspace(1e-6, 1 - 1e-6, 513)
    got = norm_ppf(u)
    want = np.asarray([nd.inv_cdf(float(x)) for x in u])
    assert np.allclose(got, want, atol=1e-6)
    assert np.allclose(lognorm_vec(u, 0.3), np.exp(0.3 * want), atol=1e-5)


def _wan_model(flaky_all=False, **flaky_kw):
    from dragonfly2_tpu.scenarios.engine import ScenarioEngine
    from dragonfly2_tpu.scenarios.spec import FlakySpec

    spec = megascale_scenarios()["planet"]
    if flaky_all:
        spec.flaky = FlakySpec(parent_fraction=1.0, **flaky_kw)
    cluster = make_region_cluster(256, spec, seed=3)
    engine = ScenarioEngine(spec, cluster.hosts, seed=3)
    return spec, WanCostModel.from_engine(spec, cluster.hosts, engine, seed=3)


def test_wan_cost_tiers():
    """Cross-region transfers pay the WAN tier: higher RTT and the WAN
    bandwidth cap, so they cost strictly more on average than same-rack
    transfers of the same piece."""
    spec, model = _wan_model()
    n = 2000
    task = np.zeros(n, np.int64)
    piece = np.arange(n) % 32
    wave = np.ones(n, np.int64)
    # child 0 lives in region 0; pick a same-region and cross-region parent
    same_region = np.flatnonzero(model.region == model.region[0])[1:]
    cross_region = np.flatnonzero(model.region != model.region[0])
    child = np.zeros(n, np.int64)
    c_same, _ = model.piece_costs(
        child, np.resize(same_region, n), 4 << 20, task, piece, wave)
    c_cross, _ = model.piece_costs(
        child, np.resize(cross_region, n), 4 << 20, task, piece, wave)
    assert c_cross.mean() > c_same.mean() * 1.5
    # determinism
    c_again, _ = model.piece_costs(
        child, np.resize(cross_region, n), 4 << 20, task, piece, wave)
    assert np.array_equal(c_cross, c_again)


def test_wan_fault_rolls_follow_rates():
    spec, model = _wan_model(
        flaky_all=True, piece_error_rate=0.3, piece_corrupt_rate=0.3
    )
    n = 4000
    child = np.zeros(n, np.int64)
    parent = 1 + (np.arange(n) % 200)
    _, fault = model.piece_costs(
        child, parent, 4 << 20,
        np.zeros(n, np.int64), np.arange(n) % 32, np.ones(n, np.int64),
    )
    err = (fault == FAULT_ERROR).mean()
    corrupt = (fault == FAULT_CORRUPT).mean()
    assert 0.25 < err < 0.35
    assert 0.25 < corrupt < 0.35


# ------------------------------------------------------- bulk scheduler


def test_leave_hosts_batch_matches_sequential():
    """leave_hosts_batch == sequential leave_host: same peers dropped,
    same host tables, same upload accounting."""
    def build(seed=5):
        svc = SchedulerService(config=Config(), seed=seed)
        sim = ClusterSimulator(svc, num_hosts=40, num_tasks=4, seed=seed,
                               deterministic_peer_ids=True)
        for _ in range(6):
            sim.run_round(6)
        return svc, sim

    svc_a, sim_a = build()
    svc_b, sim_b = build()
    victims = sorted(h.id for h in sim_a.cluster.hosts[:10])
    for host_id in victims:
        svc_a.leave_host(host_id)
    dropped = svc_b.leave_hosts_batch(victims)
    assert dropped == len(victims)
    assert svc_a.counts() == svc_b.counts()
    assert set(svc_a._host_info) == set(svc_b._host_info)
    assert set(svc_a._peer_meta) == set(svc_b._peer_meta)
    assert np.array_equal(
        svc_a.state.host_upload_used, svc_b.state.host_upload_used
    )
    # idempotent on unknown hosts
    assert svc_b.leave_hosts_batch(victims) == 0


def test_register_peers_batch_matches_sequential():
    from dragonfly2_tpu.cluster import messages as msg
    from dragonfly2_tpu.records import synth

    def build(batch: bool):
        svc = SchedulerService(config=Config(), seed=2)
        cluster = synth.make_cluster(8, seed=2)
        for h in cluster.hosts:
            svc.announce_host(msg.HostInfo(
                host_id=h.id, hostname=h.hostname, ip=h.ip,
                host_type="super" if h.is_seed else "normal",
                idc=h.idc, location=h.location,
            ))
        reqs = [
            msg.RegisterPeerRequest(
                peer_id=f"p-{i}",
                task_id=f"task-{i % 3}",
                host=svc._host_info[cluster.hosts[i % 8].id],
                url=f"https://o.example.com/{i % 3}",
                content_length=8 << 20,
                piece_length=4 << 20,
                total_piece_count=2,
            )
            for i in range(16)
        ]
        if batch:
            out = svc.register_peers_batch(reqs)
        else:
            out = [svc.register_peer(r) for r in reqs]
        return svc, out

    svc_a, out_a = build(batch=False)
    svc_b, out_b = build(batch=True)
    assert out_a == out_b
    assert svc_a.counts() == svc_b.counts()
    assert list(svc_a._pending) == list(svc_b._pending)
    assert len(svc_b.seed_triggers) == len(svc_a.seed_triggers)


def test_region_aware_seed_triggers():
    """With scheduler.region_aware_seeds, a cold task's trigger lands on
    a seed in the requester's region when one exists."""
    from dragonfly2_tpu.cluster import messages as msg

    cfg = Config()
    cfg.scheduler.region_aware_seeds = True
    svc = SchedulerService(config=cfg, seed=0)
    for r in range(2):
        for s in range(2):
            svc.announce_host(msg.HostInfo(
                host_id=f"seed-r{r}-{s}", hostname=f"seed-r{r}-{s}",
                ip="10.0.0.1", host_type="super",
                idc=f"idc-r{r}", location=f"region-{r}|zone-0|rack-0",
            ))
    svc.announce_host(msg.HostInfo(
        host_id="normal-r1", hostname="normal-r1", ip="10.0.0.9",
        host_type="normal", idc="idc-r1", location="region-1|zone-1|rack-3",
    ))
    svc.register_peer(msg.RegisterPeerRequest(
        peer_id="child-1", task_id="task-x", host=svc._host_info["normal-r1"],
        url="https://o.example.com/x", content_length=8 << 20,
        piece_length=4 << 20, total_piece_count=2,
    ))
    assert len(svc.seed_triggers) == 1
    assert svc.seed_triggers[0].host_id.startswith("seed-r1")


def test_peer_finished_pieces_decode():
    from dragonfly2_tpu.state.cluster import ClusterState

    st = ClusterState(max_hosts=4, max_tasks=4, max_peers=4)
    st.upsert_host("h", id_hash=1)
    st.upsert_task("t")
    idx = st.add_peer("p", 0, 0)
    pieces = [0, 1, 5, 63, 64, 130]
    st.adopt_pieces(idx, pieces)
    assert st.peer_finished_pieces(idx).tolist() == pieces
