"""ProbeStore tests (reference: scheduler/networktopology behaviors)."""

import numpy as np

import jax

from dragonfly2_tpu.cluster.probes import ProbeStore


def python_fold(samples, w=0.1):
    avg = samples[0]
    for s in samples[1:]:
        avg = w * avg + (1 - w) * s
    return avg


def test_enqueue_and_average():
    store = ProbeStore(max_pairs=16, max_hosts=8, queue_length=5)
    history = []
    for rtt in [10.0, 20.0, 30.0]:
        history.append(rtt)
        store.enqueue(np.array([0]), np.array([1]), np.array([rtt], np.float32))
    got = store.average_rtt(0, 1)
    assert got is not None
    assert np.isclose(got, python_fold(history), rtol=1e-5)
    assert store.average_rtt(1, 0) is None  # direction matters
    assert store.average_rtt(0, 5) is None  # never probed


def test_queue_bounded_drop_oldest():
    store = ProbeStore(max_pairs=16, max_hosts=8, queue_length=3)
    samples = [1.0, 2.0, 3.0, 4.0, 5.0]
    for s in samples:
        store.enqueue(np.array([2]), np.array([3]), np.array([s], np.float32))
    assert np.isclose(store.average_rtt(2, 3), python_fold(samples[-3:]), rtol=1e-5)


def test_gather_candidate_rtt_direction():
    """Evaluator scores parent->child probes (evaluator_network_topology
    .go:217: Probes(parent.ID, child.ID))."""
    store = ProbeStore(max_pairs=16, max_hosts=8)
    store.enqueue(np.array([4]), np.array([7]), np.array([5e6], np.float32))
    child = np.array([7])
    cands = np.array([[4, 5]])
    avg, has = store.gather_candidate_rtt(child, cands)
    assert has[0, 0] and not has[0, 1]
    assert avg[0, 0] == np.float32(5e6)


def test_probed_count_and_find():
    store = ProbeStore(max_pairs=64, max_hosts=8)
    # host 1 probed 3x, host 2 once
    for _ in range(3):
        store.enqueue(np.array([0]), np.array([1]), np.array([1e6], np.float32))
    store.enqueue(np.array([0]), np.array([2]), np.array([1e6], np.float32))
    alive = np.zeros(8, bool)
    alive[[1, 2, 3]] = True
    picked = store.find_probed_hosts(alive, jax.random.key(0), k=2)
    assert set(picked.tolist()) == {2, 3}  # least-probed alive


def test_snapshot_records():
    store = ProbeStore(max_pairs=64, max_hosts=8)
    store.enqueue(np.array([0, 0, 1]), np.array([1, 2, 2]), np.array([1e6, 2e6, 3e6], np.float32))
    info = {
        0: {"id": "h0", "hostname": "a", "ip": "10.0.0.0", "port": 1},
        1: {"id": "h1", "hostname": "b", "ip": "10.0.0.1", "port": 1},
        2: {"id": "h2", "hostname": "c", "ip": "10.0.0.2", "port": 1},
    }
    records = store.snapshot(info, now_ns=123)
    assert {r.host.id for r in records} == {"h0", "h1"}
    h0 = next(r for r in records if r.host.id == "h0")
    assert {d.id for d in h0.dest_hosts} == {"h1", "h2"}
    assert all(d.probes.average_rtt > 0 for d in h0.dest_hosts)
    assert h0.created_at == 123


def test_gather_candidate_rtt_batch_matches_scalar():
    """The vectorized searchsorted lookup must agree with per-pair
    average_rtt across hits, misses, and unprobed pairs."""
    import numpy as np

    store = ProbeStore(max_pairs=256, max_hosts=64)
    rng = np.random.default_rng(0)
    srcs = rng.integers(0, 40, 60)
    dsts = rng.integers(0, 40, 60)
    keep = srcs != dsts
    srcs, dsts = srcs[keep], dsts[keep]
    store.enqueue(srcs, dsts, rng.random(srcs.size).astype(np.float32) * 1e7 + 1)

    child = rng.integers(0, 48, 16).astype(np.int32)
    cand = rng.integers(0, 48, (16, 7)).astype(np.int32)
    avg, has = store.gather_candidate_rtt(child, cand)
    for i in range(16):
        for j in range(7):
            want = store.average_rtt(int(cand[i, j]), int(child[i]))
            if want is None:
                assert not has[i, j]
            else:
                assert has[i, j] and abs(avg[i, j] - want) < 1e-3
