"""dflint red fixture: one finding per jit-hygiene rule.

JIT001 x2 (``.item()`` + ``float(tracer)``), JIT002 (``if`` on a
tracer), JIT003 (un-allowlisted host sync in a hot function — the test
configures ``hot_tick`` as hot), JIT004 (dynamic slice into a jit call).
"""

import functools

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("limit",))
def score(batch, limit):
    peak = batch.max().item()  # <- JIT001 (.item() host sync)
    scale = float(batch[0, 0])  # <- JIT001 (cast concretizes tracer)
    if batch.sum() > 0:  # <- JIT002 (python branch on tracer)
        peak = peak + scale
    return batch * peak


def hot_tick(packed):
    out = np.asarray(packed)  # <- JIT003 (not on the d2h allowlist)
    return out


def caller(rows, n):
    return score(rows[:n], 4)  # <- JIT004 (runtime-length slice into jit)
