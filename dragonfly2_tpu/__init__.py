"""dragonfly2_tpu — a TPU-native framework with the capabilities of Dragonfly2.

A from-scratch rebuild of the capability surface of the reference
(RandySun01/Dragonfly2, a Go P2P file-distribution system): peer scheduling
with a batched XLA-compiled parent-selection evaluator, a *real* trainer
(GraphSAGE parent ranker + MLP probe-RTT regressor — left as TODO stubs in the
reference, trainer/training/training.go:82-98), network-topology probing with
EWMA RTT tracking, download/topology trace recording, a versioned model
registry with native serving, and a host-side control plane.

Design stance (see SURVEY.md §7): cluster state is struct-of-arrays, the
per-task peer DAG is edge-index/adjacency tensors, candidate filtering and
scoring are masked batched array programs under `jax.jit`, training is
`shard_map` data-parallel with `psum` gradients over a `jax.sharding.Mesh`.
Host-side Python keeps only what must touch the network.
"""

__version__ = "0.1.0"

from dragonfly2_tpu.config.constants import Constants  # noqa: F401
