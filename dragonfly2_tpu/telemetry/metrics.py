"""Prometheus-compatible metrics: counters, gauges, histograms.

Capability parity with the reference's per-service metrics packages
(scheduler/metrics/metrics.go:44-454 — ~40 collectors under
`dragonfly_scheduler_*` with label sets like traffic_type/task_type/tag/
app/host_type; client/daemon/metrics; manager/trainer metrics) and the
`/metrics` HTTP endpoint each service serves. Text exposition format v0.0.4
so a real Prometheus can scrape it; no external client library.
"""

from __future__ import annotations

import http.server
import threading
import time
from typing import Iterable

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()

    def labels(self, *values: str):
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: got {len(values)} label values, want {len(self.label_names)}"
            )
        return self._child(tuple(str(v) for v in values))

    def _help_lines(self) -> Iterable[str]:
        help_text = self.help.replace("\\", "\\\\").replace("\n", "\\n")
        yield f"# HELP {self.name} {help_text}"
        yield f"# TYPE {self.name} {self.TYPE}"


class _ScalarMetric(_Metric):
    """Shared storage + exposition for single-value-per-labelset metrics."""

    def __init__(self, name: str, help_: str = "", label_names: tuple[str, ...] = ()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple[str, ...], float] = {}

    def value(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(map(str, label_values)), 0.0)

    def expose(self) -> Iterable[str]:
        yield from self._help_lines()
        with self._lock:
            items = list(self._values.items())
        for key, v in items:
            yield f"{self.name}{_fmt_labels(self.label_names, key)} {v}"


class Counter(_ScalarMetric):
    TYPE = "counter"

    def _child(self, key: tuple[str, ...]) -> "_CounterChild":
        return _CounterChild(self, key)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)


class _CounterChild:
    def __init__(self, parent: Counter, key: tuple[str, ...]):
        self._parent = parent
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._parent._lock:
            self._parent._values[self._key] = self._parent._values.get(self._key, 0.0) + amount


class Gauge(_ScalarMetric):
    TYPE = "gauge"

    def _child(self, key: tuple[str, ...]) -> "_GaugeChild":
        return _GaugeChild(self, key)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().inc(-amount)


class _GaugeChild:
    def __init__(self, parent: Gauge, key: tuple[str, ...]):
        self._parent = parent
        self._key = key

    def set(self, value: float) -> None:
        with self._parent._lock:
            self._parent._values[self._key] = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._parent._lock:
            self._parent._values[self._key] = self._parent._values.get(self._key, 0.0) + amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(
        self,
        name: str,
        help_: str = "",
        label_names: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        self._totals: dict[tuple[str, ...], int] = {}

    def _child(self, key: tuple[str, ...]) -> "_HistogramChild":
        return _HistogramChild(self, key)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def expose(self) -> Iterable[str]:
        yield from self._help_lines()
        with self._lock:
            keys = list(self._counts)
            counts = {k: list(v) for k, v in self._counts.items()}
            sums = dict(self._sums)
            totals = dict(self._totals)
        for key in keys:
            cumulative = 0
            for bound, c in zip(self.buckets, counts[key]):
                cumulative += c
                labels = _fmt_labels(self.label_names + ("le",), key + (repr(bound),))
                yield f"{self.name}_bucket{labels} {cumulative}"
            labels = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
            yield f"{self.name}_bucket{labels} {totals[key]}"
            yield f"{self.name}_sum{_fmt_labels(self.label_names, key)} {sums[key]}"
            yield f"{self.name}_count{_fmt_labels(self.label_names, key)} {totals[key]}"


class _HistogramChild:
    def __init__(self, parent: Histogram, key: tuple[str, ...]):
        self._parent = parent
        self._key = key

    def observe(self, value: float) -> None:
        p = self._parent
        with p._lock:
            counts = p._counts.setdefault(self._key, [0] * len(p.buckets))
            for i, bound in enumerate(p.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            p._sums[self._key] = p._sums.get(self._key, 0.0) + value
            p._totals[self._key] = p._totals.get(self._key, 0) + 1


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric) or existing.label_names != metric.label_names:
                    raise ValueError(
                        f"metric {metric.name} already registered as "
                        f"{type(existing).__name__}{existing.label_names}"
                    )
                if (
                    isinstance(existing, Histogram)
                    and isinstance(metric, Histogram)
                    and existing.buckets != metric.buckets
                ):
                    raise ValueError(
                        f"metric {metric.name} already registered with buckets "
                        f"{existing.buckets}"
                    )
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Counter:
        return self.register(Counter(name, help_, labels))  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Gauge:
        return self.register(Gauge(name, help_, labels))  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help_: str = "",
        labels: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self.register(Histogram(name, help_, labels, buckets))  # type: ignore[return-value]

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


_DEFAULT = Registry()


def default_registry() -> Registry:
    return _DEFAULT


def serve_metrics(registry: Registry | None = None, port: int = 0) -> http.server.ThreadingHTTPServer:
    """Serve `/metrics` on a background thread; returns the server (use
    .server_address for the bound port, .shutdown() to stop)."""
    reg = registry or _DEFAULT

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib API
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            body = reg.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr noise
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


class Timer:
    """Context manager observing elapsed seconds into a histogram child."""

    def __init__(self, histogram_child):
        self._h = histogram_child

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._h.observe(time.perf_counter() - self._t0)
        return False
