"""Wire codec: dataclass<->msgpack roundtrips and stream framing."""

import asyncio

import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.rpc import wire

wire.register_module(msg)


def test_roundtrip_nested():
    req = msg.RegisterPeerRequest(
        peer_id="p1",
        task_id="t1",
        host=msg.HostInfo(host_id="h1", ip="10.0.0.1", idc="idc-a"),
        content_length=1234,
    )
    out = wire.decode(wire.encode(req)[4:])
    assert out == req
    assert isinstance(out.host, msg.HostInfo)


def test_roundtrip_lists_and_bytes():
    resp = msg.NormalTaskResponse(
        peer_id="p1",
        candidate_parents=[
            msg.CandidateParent("pp", "hh", "1.2.3.4", 80, 81, "Running", 0.9)
        ],
    )
    out = wire.decode(wire.encode(resp)[4:])
    assert out.candidate_parents[0].download_port == 81

    train = msg.TrainRequest(
        host_id="h", ip="i", hostname="n", dataset="download", chunk=b"\x00\xffdata"
    )
    out = wire.decode(wire.encode(train)[4:])
    assert out.chunk == b"\x00\xffdata"


def test_unknown_type_rejected():
    class NotRegistered:
        pass

    with pytest.raises(TypeError):
        wire.encode(NotRegistered())


def test_stream_framing():
    async def run():
        reader = asyncio.StreamReader()
        messages = [
            msg.ProbeStartedRequest(host_id="h", count=3),
            msg.ProbeFinishedRequest(
                host_id="h", results=[msg.ProbeResult(host_id="d", rtt_ns=5)]
            ),
        ]
        for item in messages:
            reader.feed_data(wire.encode(item))
        reader.feed_eof()
        got = []
        while True:
            item = await wire.read_frame(reader)
            if item is None:
                break
            got.append(item)
        return messages, got

    messages, got = asyncio.run(run())
    assert got == messages


def test_trainer_rpc_stream_trains_and_publishes(tmp_path):
    """Socket Train stream end to end (trainer_server_v1.go + announcer
    upload): chunked download/networktopology uploads over a real socket,
    EOF triggers training, the registry gets the published versions."""
    from dragonfly2_tpu.cluster.probes import ProbeStore
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.cluster.simulator import ClusterSimulator
    from dragonfly2_tpu.cluster.trainer_service import GNN_MODEL_NAME, TrainerService
    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.records.storage import HostTraceStorage, TraceStorage
    from dragonfly2_tpu.registry import ModelRegistry
    from dragonfly2_tpu.rpc.client import TrainerClient
    from dragonfly2_tpu.rpc.server import TrainerRPCServer

    storage = TraceStorage(tmp_path / "sched-data")
    svc = SchedulerService(storage=storage, probes=ProbeStore(max_pairs=1024, max_hosts=128))
    sim = ClusterSimulator(svc, num_hosts=24, num_tasks=4, seed=11)
    for _ in range(8):
        sim.run_round(new_downloads=6)
        sim.run_probe_round(sources=4)
    host_info = {
        svc.state.host_index(h.id): {
            "id": h.id, "hostname": h.hostname, "ip": h.ip, "port": 8002,
            "type": "super" if h.is_seed else "normal",
        }
        for h in sim.cluster.hosts
        if svc.state.host_index(h.id) is not None
    }
    for rec in svc.probes.snapshot(host_info, now_ns=1):
        storage.create_network_topology(rec)
    assert storage.list_downloads()

    registry = ModelRegistry(tmp_path / "registry")
    trainer = TrainerService(
        HostTraceStorage(tmp_path / "trainer-data"), registry,
        TrainerConfig(epochs=2, batch_size=32, hidden_dim=16),
    )

    async def run():
        server = TrainerRPCServer(trainer)
        host, port = await server.start()
        try:
            client = TrainerClient(host, port)
            return await client.train(
                "sched-1", "10.0.0.1", "sched-node",
                datasets={
                    "download": storage.open_download(),
                    "networktopology": storage.open_network_topology(),
                },
                chunk_size=4096,  # force multi-chunk framing
            )
        finally:
            await server.stop()

    response = asyncio.run(run())
    assert response.ok, response.description
    assert "gnn" in response.description
    models = registry.list_models()
    assert any(m["type"] == "gnn" for m in models)
    gnn_id = registry.model_id(GNN_MODEL_NAME, "sched-1")
    assert registry.active_version(gnn_id) is not None


def test_trainer_rpc_bad_dataset_aborts(tmp_path):
    from dragonfly2_tpu.cluster.trainer_service import TrainerService
    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.records.storage import HostTraceStorage
    from dragonfly2_tpu.registry import ModelRegistry
    from dragonfly2_tpu.rpc.client import TrainerClient
    from dragonfly2_tpu.rpc.server import TrainerRPCServer

    trainer = TrainerService(
        HostTraceStorage(tmp_path / "trainer-data"),
        ModelRegistry(tmp_path / "registry"),
        TrainerConfig(epochs=1, batch_size=8, hidden_dim=8),
    )

    async def run():
        server = TrainerRPCServer(trainer)
        host, port = await server.start()
        try:
            client = TrainerClient(host, port)
            return await client.train(
                "sched-1", "10.0.0.1", "sched-node",
                datasets={"bogus": b"xyz"},
            )
        finally:
            await server.stop()

    response = asyncio.run(run())
    assert not response.ok
    assert "bogus" in response.description
    # the failing host's partial files were cleared
    assert not trainer.storage.list_downloads()


def test_trainer_rpc_torn_connection_aborts(tmp_path):
    """Dropping the connection before the TrainEndRequest commit marker
    must abort the upload — no training on truncated datasets, and the
    host's partial files are cleared."""
    from dragonfly2_tpu.cluster.trainer_service import TrainerService
    from dragonfly2_tpu.config.config import TrainerConfig
    from dragonfly2_tpu.records.storage import HostTraceStorage
    from dragonfly2_tpu.registry import ModelRegistry
    from dragonfly2_tpu.rpc.server import TrainerRPCServer

    registry = ModelRegistry(tmp_path / "registry")
    trainer = TrainerService(
        HostTraceStorage(tmp_path / "trainer-data"), registry,
        TrainerConfig(epochs=1, batch_size=8, hidden_dim=8),
    )

    async def run():
        server = TrainerRPCServer(trainer)
        host, port = await server.start()
        try:
            reader, writer = await asyncio.open_connection(host, port)
            wire.write_frame(
                writer,
                msg.TrainRequest(
                    host_id="sched-torn", ip="1.2.3.4", hostname="n",
                    dataset="download", chunk=b"id,tag\n",
                ),
            )
            await writer.drain()
            writer.close()  # die mid-upload: no TrainEndRequest
            await writer.wait_closed()
            await asyncio.sleep(0.2)  # let the server observe EOF
        finally:
            await server.stop()

    asyncio.run(run())
    assert not trainer.storage.list_downloads()
    assert not registry.list_models()


def test_wire_decode_is_version_tolerant():
    """Cross-version compatibility contract (the reference pins previous
    released images against current code in compatibility-e2e): a peer
    speaking an OLDER schema (fields missing) or a NEWER one (extra
    fields) must still decode — missing fields take dataclass defaults,
    unknown fields are ignored."""
    import dataclasses

    import msgpack

    from dragonfly2_tpu.rpc import wire

    @dataclasses.dataclass
    class CompatProbe:
        host_id: str
        rtt_ms: float = 0.0
        new_field: str = "default"

    wire.register_messages(CompatProbe)

    # older sender: new_field absent
    old = msgpack.packb(
        {"t": "CompatProbe", "d": {"host_id": "h1", "rtt_ms": 1.5}},
        use_bin_type=True,
    )
    decoded = wire.decode(old)
    assert decoded == CompatProbe(host_id="h1", rtt_ms=1.5, new_field="default")

    # newer sender: unknown field present
    new = msgpack.packb(
        {"t": "CompatProbe",
         "d": {"host_id": "h2", "rtt_ms": 2.0, "new_field": "x",
               "field_from_the_future": [1, 2, 3]}},
        use_bin_type=True,
    )
    decoded = wire.decode(new)
    assert decoded == CompatProbe(host_id="h2", rtt_ms=2.0, new_field="x")

    # a REQUIRED field missing is a hard error, not a silent default
    broken = msgpack.packb({"t": "CompatProbe", "d": {"rtt_ms": 3.0}}, use_bin_type=True)
    with pytest.raises(TypeError):
        wire.decode(broken)


def test_vsock_target_parsing():
    """pkg/rpc/vsock.go IsVsock + VsockDialer's target parse."""
    from dragonfly2_tpu.utils import vsock

    assert vsock.is_vsock("vsock://2:8002")
    assert not vsock.is_vsock("10.0.0.1:8002")
    assert vsock.parse_target("vsock://2:8002") == (2, 8002)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        vsock.parse_target("tcp://1:2")
    with _pytest.raises(ValueError):
        vsock.parse_target("vsock://nocid")


def test_vsock_wire_roundtrip_if_supported():
    """Full wire exchange over AF_VSOCK loopback. Skipped where the kernel
    lacks vsock support (most CI containers)."""
    import asyncio
    import socket as _socket

    import pytest as _pytest

    from dragonfly2_tpu.utils import vsock

    if not vsock.available():
        _pytest.skip("AF_VSOCK not supported on this platform")

    async def run():
        from dragonfly2_tpu.rpc import wire
        from dragonfly2_tpu.rpc.mux import HealthCheckRequest, HealthCheckResponse

        async def handler(reader, writer):
            request = await wire.read_frame(reader)
            assert isinstance(request, HealthCheckRequest)
            wire.write_frame(writer, HealthCheckResponse())
            await writer.drain()
            writer.close()

        port = 51000 + (id(handler) % 1000)
        try:
            server = await vsock.start_server(handler, port, cid=vsock.VMADDR_CID_LOCAL)
        except OSError as e:
            _pytest.skip(f"vsock loopback unavailable: {e}")
        try:
            reader, writer = await vsock.open_connection(
                f"vsock://{vsock.VMADDR_CID_LOCAL}:{port}"
            )
            wire.write_frame(writer, HealthCheckRequest())
            await writer.drain()
            response = await wire.read_frame(reader)
            assert isinstance(response, HealthCheckResponse)
            writer.close()
        finally:
            server.close()
            await server.wait_closed()

    try:
        asyncio.run(run())
    except OSError as e:
        _pytest.skip(f"vsock loopback unavailable: {e}")


def test_vsock_target_allows_32bit_ports():
    """AF_VSOCK ports are 32-bit; the TCP 0-65535 range must not apply."""
    from dragonfly2_tpu.utils import vsock

    assert vsock.parse_target("vsock://2:1000000") == (2, 1000000)


def test_wire_server_survives_garbage_bytes():
    """Robustness: random garbage, oversized length prefixes, truncated
    frames, and unknown message types must never kill the scheduler RPC
    server — the next legitimate connection still works."""
    import asyncio
    import os
    import struct

    from dragonfly2_tpu.cluster import messages as msgmod
    from dragonfly2_tpu.cluster.scheduler import SchedulerService
    from dragonfly2_tpu.rpc import wire
    from dragonfly2_tpu.rpc.server import SchedulerRPCServer

    async def run():
        server = SchedulerRPCServer(SchedulerService(), tick_interval=0.01)
        host, port = await server.start()
        try:
            import msgpack

            unknown_type = msgpack.packb(
                {"t": "NoSuchMessage", "d": {}}, use_bin_type=True
            )
            payloads = [
                os.urandom(64),                         # pure noise
                struct.pack(">I", 0xFFFFFFF0),          # absurd length prefix
                struct.pack(">I", 100) + b"short",      # truncated frame
                wire.encode(msgmod.StatTaskRequest(task_id="x"))[:7],  # cut mid-frame
                struct.pack(">I", len(unknown_type)) + unknown_type,  # unregistered type
            ]
            for payload in payloads:
                writer = None
                try:
                    reader, writer = await asyncio.open_connection(host, port)
                    writer.write(payload)
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass  # server resetting us IS a valid outcome
                finally:
                    if writer is not None:
                        writer.close()
            await asyncio.sleep(0.05)
            # the server must still answer a well-formed request
            reader, writer = await asyncio.open_connection(host, port)
            wire.write_frame(writer, msgmod.StatTaskRequest(task_id="nope"))
            await writer.drain()
            response = await asyncio.wait_for(wire.read_frame(reader), timeout=5)
            assert isinstance(response, msgmod.StatResponse) and not response.found
            writer.close()
        finally:
            await server.stop()

    asyncio.run(run())
