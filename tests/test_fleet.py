"""Sharded control plane (megascale/fleet.py): SchedulerFleet routing,
cross-scheduler peer handoff, and the fleet-routed event-batch engine.

The two contracts this file pins are the ISSUE-17 acceptance gates:

- **K=1 equivalence oracle**: a single-replica SchedulerFleet megascale
  run is bit-identical to the plain single-scheduler run on paired
  seeds — SimStats field for field, fault-schedule digest, tail digest,
  decision block, SLO block. The fleet layer must be a pure routing
  shim at K=1.
- **Kill recovery**: a mid-soak replica kill on a K=4 fleet loses zero
  downloads, keeps origin traffic a small fraction, fires the
  announce-stability page AT the kill round and clears it on recovery —
  reproducible offline from the recorded timeline alone.
"""

from __future__ import annotations

import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.megascale.fleet import (
    FleetDecisionView,
    SchedulerFleet,
    megascale_fleet,
)
from dragonfly2_tpu.megascale.soak import deterministic_view, run_megascale

# --------------------------------------------------- fleet unit plumbing


def _small_fleet(k=3, seed=3):
    return megascale_fleet(64, num_tasks=8, seed=seed, replicas=k)


def _host(hid="host-a"):
    return msg.HostInfo(host_id=hid, ip="10.0.0.1")


def _register(fleet, peer_id, task_id, pieces=None):
    fleet.announce_host(_host())
    return fleet.register_peer(msg.RegisterPeerRequest(
        peer_id=peer_id, task_id=task_id, host=_host(),
        url=f"http://o/{task_id}", content_length=8 << 20,
        total_piece_count=2, finished_pieces=pieces,
    ))


def test_register_routes_to_ring_owner_and_reports_follow():
    fleet = _small_fleet()
    resp = _register(fleet, "peer-1", "task-zzz")
    assert not isinstance(resp, msg.ScheduleFailure)
    owner = fleet.shard_of_task("task-zzz")
    assert fleet.shard_of_peer("peer-1") == owner
    # the peer exists on the owner replica and ONLY there
    by_shard = fleet.counts_by_shard()
    for shard, name in enumerate(fleet.names):
        expected = 1 if shard == owner else 0
        assert by_shard[name]["peers"] == expected, (shard, by_shard)
    # peer-keyed report follows the recorded shard, not the ring
    out = fleet.peer_finished(msg.DownloadPeerFinishedRequest(
        peer_id="peer-1"))
    assert not isinstance(out, msg.ScheduleFailure)
    # unknown peer -> typed failure, not a KeyError
    out = fleet.peer_finished(msg.DownloadPeerFinishedRequest(
        peer_id="peer-nope"))
    assert isinstance(out, msg.ScheduleFailure)
    assert out.code == "NotFound"


def test_batch_register_matches_sequential_routing():
    fleet = _small_fleet()
    fleet.announce_host(_host())
    reqs = [
        msg.RegisterPeerRequest(
            peer_id=f"peer-{i}", task_id=f"task-{i % 5}", host=_host(),
            url=f"http://o/{i % 5}", content_length=8 << 20,
            total_piece_count=2,
        )
        for i in range(20)
    ]
    out = fleet.register_peers_batch(reqs)
    assert len(out) == len(reqs)
    for i, req in enumerate(reqs):
        assert not isinstance(out[i], msg.ScheduleFailure)
        if out[i] is not None:  # None = queued pending, answered at tick
            assert out[i].peer_id == req.peer_id
        assert fleet.shard_of_peer(req.peer_id) \
            == fleet.shard_of_task(req.task_id)
    # fleet-wide census sums to the per-shard censuses
    total = fleet.counts()
    by_shard = fleet.counts_by_shard().values()
    assert total["peers"] == sum(c["peers"] for c in by_shard) == 20


def test_handoff_moves_peer_to_new_ring_owner_with_kept_pieces():
    fleet = _small_fleet()
    resp = _register(fleet, "peer-7", "task-move")
    assert not isinstance(resp, msg.ScheduleFailure)
    old_owner = fleet.shard_of_task("task-move")
    fleet.shard_down(old_owner)
    new_owner = fleet.shard_of_task("task-move")
    assert new_owner != old_owner
    out = fleet.handle(msg.PeerHandoffRequest(
        peer_id="peer-7", task_id="task-move", host=_host(),
        url="http://o/task-move", content_length=8 << 20,
        total_piece_count=2, finished_pieces=[0],
        from_scheduler=fleet.names[old_owner], reason="crash",
    ))
    assert not isinstance(out, msg.ScheduleFailure)
    assert fleet.shard_of_peer("peer-7") == new_owner
    assert fleet.handoffs["crash"] == 1
    # the new owner ADOPTED the kept piece (PR-3 adopt_pieces path):
    # its state shows the peer holding piece 0 already
    svc = fleet.replicas[new_owner]
    idx = svc.state._peer_by_id["peer-7"]
    assert int(svc.state.peer_finished_count[idx]) == 1
    # ring restore readmits the replica and counts the restart
    fleet.shard_up(old_owner)
    assert fleet.down_shards() == []
    assert fleet.restarts == 1


def test_ring_down_up_round_trips_membership():
    fleet = _small_fleet(k=4)
    assert len(fleet.ring) == 4
    fleet.shard_down(2)
    assert len(fleet.ring) == 3
    assert fleet.down_shards() == [2]
    # a K=1 fleet never leaves the ring (restart-in-place semantics)
    lone = _small_fleet(k=1)
    lone.shard_down(0)
    assert len(lone.ring) == 1


def test_seed_trigger_queue_view_routes_by_task():
    fleet = _small_fleet()
    t = msg.TriggerSeedRequest(host_id="h", task_id="task-s",
                               url="http://o/s")
    fleet.replicas[0].seed_triggers.append(t)
    assert fleet.seed_triggers == [t]
    # the simulator's drain swap-assign: clears everywhere, re-assign
    # routes to the owner
    fleet.seed_triggers = [t]
    owner = fleet.shard_of_task("task-s")
    for shard, replica in enumerate(fleet.replicas):
        assert len(replica.seed_triggers) == (1 if shard == owner else 0)
    fleet.seed_triggers = []
    assert fleet.seed_triggers == []


def test_k1_factory_builds_the_exact_single_service_config():
    from dragonfly2_tpu.megascale.engine import megascale_service

    fleet = megascale_fleet(5000, num_tasks=32, seed=9, replicas=1)
    single = megascale_service(5000, num_tasks=32, seed=9)
    assert dataclasses_equal(fleet.replicas[0].config, single.config)
    assert fleet.k == 1


def dataclasses_equal(a, b):
    import dataclasses

    return dataclasses.asdict(a) == dataclasses.asdict(b)


def test_decision_view_k1_is_verbatim_passthrough():
    fleet = _small_fleet(k=1)
    led = fleet.replicas[0].decisions
    if led is None:
        pytest.skip("no decision ledger in this configuration")
    view = FleetDecisionView(fleet)
    assert view.report() == led.report()
    assert view.deterministic_digest() == led.deterministic_digest()


# ----------------------------------------------- K=1 equivalence oracle

_EQ_KW = dict(scenario="soak", num_hosts=2000, num_tasks=24, seed=11,
              rounds=40)


@pytest.fixture(scope="module")
def eq_runs():
    return (run_megascale(**_EQ_KW),
            run_megascale(**_EQ_KW, fleet_replicas=1))


def test_k1_fleet_is_bit_identical_to_single_scheduler(eq_runs):
    """THE equivalence oracle: a 1-replica fleet run on a paired seed is
    the single-scheduler run — SimStats field for field, the fault
    digest, tail/decision digests, the SLO block, the whole timeline's
    shared columns."""
    base, one = eq_runs
    assert one["stats"] == base["stats"]
    assert one["fault_schedule_digest"] == base["fault_schedule_digest"]
    assert one["tail"]["digest"] == base["tail"]["digest"]
    assert one["decisions"] == base["decisions"]
    assert one["slo"] == base["slo"]
    assert one["scheduler_counts"] == base["scheduler_counts"]
    # timeline: identical except the fleet-plane columns K=1 adds
    assert len(one["timeline"]) == len(base["timeline"])
    fleet_cols = {"fleet_pieces", "fleet_handoffs", "shards_in_ring",
                  "shards_down"}
    for ours, theirs in zip(one["timeline"], base["timeline"]):
        shared = {k: v for k, v in ours.items() if k not in fleet_cols}
        assert shared == theirs
    # and the fleet block agrees it never touched the ring: no
    # restarts, no rebalance/upgrade moves — the only handoff frames
    # are the crash re-announces, which at K=1 are self-handoffs
    # carrying the oracle's exact re-register
    assert one["fleet"]["replicas"] == 1
    assert one["fleet"]["handoffs"]["rebalance"] == 0
    assert one["fleet"]["handoffs"]["upgrade"] == 0
    assert one["fleet"]["handoffs"]["crash"] \
        == base["stats"]["crash_reannounced_peers"]
    assert one["fleet"]["restarts"] == 0


def test_k1_fleet_crash_replay_matches_oracle_counters(eq_runs):
    base, one = eq_runs
    assert base["stats"]["injected_scheduler_crashes"] > 0
    assert one["failover"] == base["failover"]
    assert one["recovery"] == base["recovery"]


# ------------------------------------------------- K=4 fleet soak gates

_FLEET_KW = dict(scenario="fleet", num_hosts=2000, num_tasks=24, seed=11,
                 rounds=40, fleet_replicas=4)


@pytest.fixture(scope="module")
def fleet_run():
    return run_megascale(**_FLEET_KW)


def test_fleet_soak_paired_seed_deterministic(fleet_run):
    again = run_megascale(**_FLEET_KW)
    assert deterministic_view(again) == deterministic_view(fleet_run)


def test_replica_kill_recovers_with_zero_lost_downloads(fleet_run):
    """ISSUE-17 acceptance: the mid-soak kill loses nothing, stays off
    origin, and the fleet block records the victim schedule + measured
    per-victim recovery."""
    st = fleet_run["stats"]
    fl = fleet_run["fleet"]
    assert st["injected_scheduler_crashes"] >= 2
    assert st["failed"] == 0
    assert fleet_run["origin_traffic_fraction"] < 0.10
    assert st["crash_reannounced_peers"] > 0
    assert fl["handoffs"]["crash"] > 0
    # round-robin victims, one per crash, named by shard
    victims = [v["shard"] for v in fl["crash_victims"]]
    assert victims == [fleet_run["fleet"]["names"][i % 4]
                       for i in range(len(victims))]
    # every victim with room to recover before the run ended did
    horizon = fleet_run["rounds"] - 8
    for entry in fl["victim_recovery"]:
        if entry["round"] < horizon:
            assert entry["recovered"], entry


def test_announce_page_fires_at_kill_round_and_clears(fleet_run):
    kill_rounds = [v["round"] for v in fleet_run["fleet"]["crash_victims"]]
    log = fleet_run["slo"]["alert_log"]
    pages = [e for e in log if e["slo"] == "announce_stability"
             and e["severity"] == "page"]
    fired = [e["t"] for e in pages if e["event"] == "fired"]
    cleared = [e["t"] for e in pages if e["event"] == "cleared"]
    assert fired, log
    # every page fired AT a kill round, and cleared before the next one
    for t in fired:
        assert t in kill_rounds, (t, kill_rounds)
        assert any(c > t for c in cleared), (t, cleared)


def test_kill_page_reproducible_offline_from_timeline(fleet_run):
    """tools/dfslo.py contract: the announce page timeline replays
    bit-identically from the recorded samples alone — the shipped
    artifact is enough to re-judge a kill."""
    from dragonfly2_tpu.telemetry.slo import replay_timeline

    replay = replay_timeline(fleet_run["timeline"],
                             fleet_run["minutes_per_round"])
    assert replay["alert_log"] == fleet_run["slo"]["alert_log"]
    assert replay["pages_fired"] == fleet_run["slo"]["pages_fired"]


def test_fleet_block_attribution_is_per_shard(fleet_run):
    fl = fleet_run["fleet"]
    names = fl["names"]
    assert fl["replicas"] == 4 and len(names) == 4
    # piece routing actually spread across replicas
    assert sum(1 for v in fl["pieces_by_shard"].values() if v > 0) >= 3
    assert sum(fl["pieces_by_shard"].values()) \
        == fleet_run["stats"]["pieces"]
    # per-shard decision digests exist and differ (different ledgers)
    digests = fl["decision_digests_by_shard"]
    assert set(digests) == set(names)
    # per-shard tail attribution covers the shard axis
    assert set(fleet_run["fleet"]["tail_by_shard"]["regions"]) \
        == set(names) or fl["tail_by_shard"]
    # timeline grew the fleet columns
    sample = fleet_run["timeline"][-1]
    assert set(sample["fleet_pieces"]) == set(names)
    assert "shards_in_ring" in sample and "shards_down" in sample


def test_upgrade_wave_rolls_replicas_through_the_ring():
    """A full compressed day drives the UpgradeSpec wave across every
    replica: each one restarts (down one round, rejoin, rebalance back)
    and upgrade-reason handoffs are recorded."""
    report = run_megascale(scenario="fleet", num_hosts=2000, num_tasks=24,
                           seed=11, fleet_replicas=4)
    fl = report["fleet"]
    events = [e["event"] for e in report["timeline_events"]]
    for shard in range(4):
        assert f"fleet_restart:{shard}" in events, events
    assert fl["handoffs"]["upgrade"] > 0
    assert fl["handoffs"]["rebalance"] > 0
    assert fl["restarts"] >= 4
    assert report["stats"]["failed"] == 0


def test_checked_in_artifact_fleet_scaling_and_kill_recovery():
    """THE acceptance gate (ISSUE 17): the shipped BENCH_mega.json
    carries the 1M-host fleet pair — aggregate pieces/s scales >= 3x
    going 1 -> 4 replicas, the mid-soak replica kill lost zero
    downloads with origin traffic under 10%, and tools/dfslo.py
    replays the announce-stability pages offline from the artifact
    with zero drift from the recorded judgment."""
    import json
    import pathlib

    import tools.dfslo as dfslo

    p = pathlib.Path(__file__).resolve().parents[1] / "BENCH_mega.json"
    doc = json.loads(p.read_text())
    fleet_runs = {
        r["fleet"]["replicas"]: r
        for r in doc["runs"] if r.get("scenario") == "fleet"
    }
    assert set(fleet_runs) == {1, 4}, sorted(fleet_runs)
    r1, r4 = fleet_runs[1], fleet_runs[4]
    hosts = r4["hosts"]
    assert hosts >= 1_000_000 and r1["hosts"] == hosts
    assert f"fleet_{hosts}_r1" in doc["summary"]
    assert f"fleet_{hosts}_r4" in doc["summary"]
    # the scaling claim: 4 task-sharded replicas sustain >= 3x the
    # aggregate pieces/s of one (modeled parallel wall)
    agg1 = doc["summary"][f"fleet_{hosts}_r1"]["aggregate_pieces_per_sec"]
    agg4 = doc["summary"][f"fleet_{hosts}_r4"]["aggregate_pieces_per_sec"]
    assert agg4 >= 3.0 * agg1, (agg1, agg4)
    # kill recovery: zero lost downloads, origin stays a small fraction
    for r in (r1, r4):
        assert r["stats"]["failed"] == 0
        assert r["origin_traffic_fraction"] < 0.10
    assert r4["fleet"]["handoffs"]["crash"] > 0
    assert r4["fleet"]["crash_victims"], "no replica kill recorded"
    # offline replay from the shipped artifact: the kill rounds paged
    # and the replay matches the recorded judgment bit for bit
    rc, results = dfslo.judge(doc, f"fleet_{hosts}")
    assert len(results) == 2
    for res in results:
        assert res["pages_fired"] > 0 and res["paged"]
        assert not res["recorded_drift"], res["recorded_drift"]
    # the K=4 run's announce-stability page fired AT a kill round and
    # cleared on recovery
    kill_rounds = {v["round"] for v in r4["fleet"]["crash_victims"]}
    pages = [
        e for e in r4["slo"]["alert_log"]
        if e["slo"] == "announce_stability" and e["severity"] == "page"
    ]
    fired = [e["t"] for e in pages if e["event"] == "fired"]
    cleared = [e["t"] for e in pages if e["event"] == "cleared"]
    assert fired, r4["slo"]["alert_log"]
    assert any(t in kill_rounds for t in fired), (kill_rounds, fired)
    assert cleared and max(cleared) > min(fired), (fired, cleared)
