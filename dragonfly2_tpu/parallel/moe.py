"""Expert parallelism: Switch-style top-1 MoE with all_to_all dispatch.

No analogue in the reference; this is the TPU-native pattern for scaling
parameter count without scaling per-token FLOPs — here framed as a
mixture-of-expert *scorers* (different peer-ranking experts can
specialize per traffic class/IDC, routed per candidate).

The exchange is the canonical Switch construction:
  1. router: gate logits [T, E] -> top-1 expert + prob per token.
  2. capacity C per expert; position-in-queue via a cumsum over the
     one-hot assignment; overflowing tokens are dropped (combine weight 0
     -> they pass through as zeros, standard Switch behavior).
  3. dispatch einsum builds [E, C, F]; tiled all_to_all over `ep`
     re-shards E -> each device holds its E/ep experts' queues from every
     token shard: [E/ep, ep*C, F].
  4. local expert FFN (gelu two-matmul, batched einsum over the expert dim).
  5. inverse all_to_all + combine einsum restore [T, F], scaled by the
     gate prob.

Exactness contract (tested): with capacity >= tokens, the sharded output
equals the unsharded reference `moe_reference` bit-for-bit in f32.
"""

from __future__ import annotations

import functools

import jax

from dragonfly2_tpu.utils.jaxcompat import shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dragonfly2_tpu.parallel.mesh import EP_AXIS


def _top1_dispatch(x, gate_logits, num_experts: int, capacity: int):
    """Build dispatch/combine tensors for top-1 routing.

    Returns (dispatch [T, E, C] f32 one-hot, combine [T, E, C] f32 with
    gate probs, aux metadata dict)."""
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]  # [T]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)  # [T, E]
    # position of each token in its expert's queue (0-based)
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot  # [T, E]
    pos_t = pos.sum(-1)  # [T]
    keep = pos_t < capacity
    onehot = onehot * keep[:, None]
    pos_oh = jax.nn.one_hot(pos_t.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = onehot[:, :, None] * pos_oh[:, None, :]  # [T, E, C]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine, {"dropped": (~keep).sum(), "gate": gate}


def moe_ffn(
    x,
    gate_w,
    w1,
    b1,
    w2,
    b2,
    capacity: int,
    axis_name: str = EP_AXIS,
) -> jax.Array:
    """Inside shard_map: x [T, F] = this device's token shard; w1/b1/w2/b2
    carry a leading LOCAL expert dim [E/ep, ...]; gate_w [F, E] replicated
    (E = global expert count). Returns [T, F]."""
    ep = jax.lax.psum(1, axis_name)
    e_local = w1.shape[0]
    num_experts = e_local * ep

    gate_logits = jnp.dot(x, gate_w, preferred_element_type=jnp.float32)
    dispatch, combine, _ = _top1_dispatch(x, gate_logits, num_experts, capacity)

    # [T, E, C] x [T, F] -> [E, C, F] expert queues for every global expert
    expert_in = jnp.einsum("tec,tf->ecf", dispatch, x.astype(jnp.float32))
    # re-shard: E -> E/ep local experts, queues from all ep token shards
    expert_in = jax.lax.all_to_all(
        expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True
    )  # [E/ep, ep*C, F]

    h = jax.nn.gelu(
        jnp.einsum("ecf,efh->ech", expert_in, w1.astype(jnp.float32))
        + b1[:, None, :]
    )
    expert_out = (
        jnp.einsum("ech,ehf->ecf", h, w2.astype(jnp.float32)) + b2[:, None, :]
    )

    expert_out = jax.lax.all_to_all(
        expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True
    )  # [E, C, F]
    out = jnp.einsum("tec,ecf->tf", combine, expert_out)
    return out.astype(x.dtype)


def sharded_moe_ffn(mesh, x, gate_w, w1, b1, w2, b2, capacity: int) -> jax.Array:
    """shard_map wrapper: tokens over `ep` (the token shard IS the ep
    axis — dp composes on top via the leading batch dim), experts'
    weights sharded on their leading expert dim."""
    fn = shard_map(
        functools.partial(moe_ffn, capacity=capacity, axis_name=EP_AXIS),
        mesh=mesh,
        in_specs=(
            P(EP_AXIS),  # tokens
            P(),  # gate
            P(EP_AXIS), P(EP_AXIS), P(EP_AXIS), P(EP_AXIS),  # expert shards
        ),
        out_specs=P(EP_AXIS),
        check_vma=False,
    )
    return fn(x, gate_w, w1, b1, w2, b2)


def moe_reference(x, gate_w, w1, b1, w2, b2) -> jax.Array:
    """Unsharded top-1 MoE oracle (no capacity drops): every token through
    its argmax expert, scaled by the gate prob."""
    probs = jax.nn.softmax(
        jnp.dot(x, gate_w, preferred_element_type=jnp.float32), axis=-1
    )
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    h = jax.nn.gelu(
        jnp.einsum("tf,efh->teh", x.astype(jnp.float32), w1.astype(jnp.float32))
        + b1[None]
    )
    out_all = jnp.einsum("teh,ehf->tef", h, w2.astype(jnp.float32)) + b2[None]
    out = jnp.take_along_axis(out_all, expert[:, None, None], axis=1)[:, 0]
    return (out * gate[:, None]).astype(x.dtype)
